"""Temporal-blocked packed kernel: k Yee steps per HBM pass (k=2/3/4).

Round 8 built the hardcoded two-step/four-phase pipeline; round 12
generalizes it into a DEPTH-k BUILDER (ROADMAP item 1, the
communication-strategy paper's halo-depth-vs-bytes frontier made a free
variable). At grid iteration i the kernel runs 2k phases:

    phase E_g:  E(t+g) on tile i - 2(g-1)      (g = 1..k)
    phase H_g:  H(t+g) on tile i - (2g-1)

so the grid runs ntiles + 2k-1 iterations (2k-1 drain iterations) and
HBM field traffic is

    read E(3) + H(3); write E(3) + H(3)  =  12 volumes PER k STEPS
    = ~48/k B/cell/step f32 (24 k=2, 16 k=3, 12 k=4; half that bf16),

plus the fixed per-dispatch floor amortized over k steps. Intermediate
generations t+1..t+k-1 never touch HBM: they live in VMEM ring
buffers — per generation g < k a depth-2 E ring and a depth-2 H ring
(consumed at lag 1 by H_g / the next E phase's curl and at lag 2 as
the next phases' old fields), one depth-1 ring for E(t+k), and the
H(t) tile + halo plane — rotated at the end of each iteration. Ring
values a drain-phase consumer would read before their producer ran are
masked to the PEC zero ghost (or the exchanged generation ghosts under
sharding) exactly like the single-step kernel's pipeline edges.

**CPML runs k times in-kernel.** The y/z slab psi recursion and the
round-6 tile-aligned x-psi stacks advance k generations per pass:
every E/H phase below generation k computes psi(t+g) into small ring
scratch (never HBM; depth-2 rings per generation, like the fields),
and the generation-k phases write psi(t+k) at the lagged block
indices. The x stacks keep the round-6 layout
(``pallas_packed.x_block_maps``) with lag-2(k-1)/lag-(2k-1) output
maps; writes are masked to slab tiles.

**In-kernel sources (eligibility widening, round 12).** A mid-block
injection cannot be post-patched (it must propagate through the later
generations' curls), so every source rides IN-KERNEL at its
generation's lag:

* point source — all k E phases add ``amplitude * waveform(t+g-1)
  * mask`` before the ca/cb application (the ``srcpos`` traced-operand
  pattern under sharding; requires ``_sources_interior``).
* TFSF — the incident-line corrections are PLANE-VALUE operands: the
  step advances the 1D incident line k times in thin jnp, evaluates
  each face correction's transverse value plane per generation
  (``tfsf.corr_plane_term`` — the same zeta/interp/gate math the jnp
  step uses, minus the normal-axis onehot), and the kernel adds
  ``onehot(coord == plane) * value`` inside the matching phase. The
  corrections never enter the psi recursions (they are accumulator
  adds, exactly the jnp form), and ``_sources_interior`` keeps the
  fused-x argument intact. Under sharding the onehot masks compare
  LOCAL coordinates against the global face plane through a traced
  shard-offset operand (``tfofs``, the ``srcpos`` pattern), the value
  planes are already shard-local (corr_plane_term reads the SHARDED
  gx/gy/gz coordinate arrays), and the boundary-wedge pre-pass gets
  its own incident-line port (round 14, below).
* Drude ADE — the electric current J is one extra generation stack in
  the ring scratch: phase E_g computes J(t+g) = kj J(t+g-1) + bj
  E(t+g-1) alongside E, generation k lands in HBM at the E lag — so
  Drude runs get the same k-fold traffic saving on J. Magnetic Drude
  (K) stays out of scope. Sharded runs carry a J ring through the
  wedge pre-pass (round 14, below).
* material grids — spatially-varying ca/cb/kj/bj (da/db) stream as
  per-generation tiled operands at each phase's lag: each grid is
  read k times per PASS = once per step, the same per-step coefficient
  traffic as the single-step kernel (the k-fold saving is on fields;
  ring-buffering coefficients would buy nothing but VMEM). The wedge
  pre-pass gathers each grid's per-cell plane sub-blocks instead of
  assuming scalar coefficients (round 14, below), so sharded
  material-grid runs stay in scope too.

**VMEM-calibrated auto-depth picker.** ``pick_depth`` scores every
k in {4, 3, 2} against the central Mosaic-temporaries calibration
table (``config.vmem_temps("tb", k)``, ``FDTD3D_VMEM_TEMPS_TABLE``
overrides) through the shared tile picker and takes the DEEPEST k
whose budgeted tile stays viable (tile >= 2; tile == 1 only when no
depth affords 2 and the single-step kernel does not afford >= 4).
``FDTD3D_TB_DEPTH`` pins k. The decision (chosen k, per-k candidate
tiles, source) is recorded in ``step.diag`` — telemetry ``run_start``
and the ledger comm lane echo it — and ``plan.CommStrategy`` scores
``ghost_depth`` with the same host-math picker. The VMEM ladder
(sim._vmem_fallback) re-runs the pick under each shrunken budget, so
a failing compile downgrades k -> k-1 -> ... -> 2 -> ``pallas_packed``
before switching kernel families.

**Sharded: the depth-k halo pipeline.** k Yee steps per pass need k
ghost-plane generations per neighbor per axis; the exchange is a
2k-message schedule per sharded axis per pass, every message a full
component stack at field dtype:

  1..k.   ``gh[j]`` (j = 0..k-1) — H(t+j) boundary stacks, downstream:
          generation 0 slices the stored field; generations 1..k-1
          come from a THIN jnp boundary-wedge pre-pass that advances
          the outermost k-1 planes per side generation by generation
          (same arithmetic as the jnp step — CPML slab/fused-x psi
          terms included via a per-plane psi wedge, source term
          included; cross-axis halo lines slice from the other axes'
          already-received full ghost planes of the SAME generation,
          so NO corner messages exist). Phase E_{j+1} consumes gh[j]
          as its lo ghost. Round 14 widens the wedge to the three
          remaining operand classes, so sharded TFSF / electric-Drude
          / material-grid runs no longer fall back to the single-step
          kernel: (a) an INCIDENT-LINE PORT — each wedge generation
          applies the TFSF corrections whose face planes intersect
          its boundary planes, from per-generation ``corr_plane_term``
          value planes gated by the SHARDED gx/gy/gz coordinate
          arrays (shard-local recomputation of replicated incident
          values: zero extra ICI bytes, so the per-step exchange
          stays depth-invariant and byte-exact vs the traced ledger);
          (b) a J RING — the wedge carries J(t+j) = kj J(t+j-1) + bj
          E(t+j-1) plane by plane through the k generations, exactly
          like the in-kernel ring scratch; (c) TILED COEFFICIENTS —
          the wedge slices each 3D material grid's per-cell plane
          sub-block at its (axis, plane) instead of embedding a
          scalar.
  k+1..2k-1. ``hi_e[j]`` (j = 1..k-1) — E(t+j) first-plane stacks,
          upstream (from the same wedge); phase H_j consumes hi_e[j]
          as its hi ghost, making H(t+j) exact in-kernel including
          the shard edges.
  2k.     E(t+k) first-plane stack, upstream, AFTER the kernel: phase
          H_k keeps the zero ghost in-kernel and the missing
          -db*s*E/dx contribution lands as the single-step kernel's
          thin post-fix (``pallas_packed.hi_edge_h_fix``).

Per STEP that is (ne + nh) component planes per sharded axis — the
SAME ICI traffic as the single-step kernel, invariant in k, at 1/k-th
the HBM traffic; ``plan.Plan.halo_bytes_per_step_tb`` (and its
``halo_bytes_per_step_tb_at(k=)`` form) models it to the byte and the
ledger comm lane's sharded tb trace equals it for every k
(tests/test_comm_costs.py). Message split (fused stack vs per-plane)
and sync-vs-async scheduling follow ``plan.CommStrategy``
(``FDTD3D_COMM_STRATEGY`` overrides). The drain-edge ring reads mask
against this k-deep ghost region: phase E_g's i == 2(g-1) lo edge
reads gh[g-1] instead of the PEC zero, and phase H_g's i == ntiles-1+
2g-1 hi edge reads hi_e[g].

Scope (everything else falls back to ops/pallas_packed.py): 3D, real
f32/bf16 storage, sharded or not (sharded axes need mesh axis names),
slab-fitting CPML on any axes; point sources inside the CPML identity
region (sharded or not); TFSF / electric-Drude ADE / material grids
sharded or not (round 14); no magnetic Drude, no compensated mode, no
double-single. Every dispatch that falls OUTSIDE this scope is named:
``plan_tb`` is the single decision authority (eligibility + depth +
tile, consulted by the dispatch, the planner and the ledger alike)
and its machine-readable ``reason`` token is recorded as the
``tb_fallback`` field in telemetry run_start and the cost ledger so
the 2x-HBM downgrade is never silent. ``FDTD3D_NO_TEMPORAL=1`` is the
escape hatch that forces the round-6 kernel bit-for-bit.

The step object advances k steps per call: ``step.steps_per_call ==
k`` and ``step.tail_step`` is a single-step ``pallas_packed`` step
built at THE SAME tile (``force_tile=T``) so horizons not divisible by
k run ``n//k`` blocked passes plus ``n mod k`` trailing single steps
on the identical packed-carry layout inside ONE compiled chunk
(solver.make_chunk_runner).

Donation-safety: every aliased array's block j is read at iteration j
(E/H/psi_E/J at the tile map; psi_H/x-psi-H at lag 1) and written only
at iteration j+2(k-1) (E family) or j+2k-1 (H family) — reads always
precede writes, and each array enters the call exactly once. Out
blocks at pipeline edges are revisited with writes MASKED (``pl.when``)
under the same Mosaic revisiting-semantics argument as the depth-2
kernel. Structural gate: the ``donation-safety`` lint rule +
tests/test_pallas_packed_tb.py::test_tb_donation_fetch_before_write.
"""

from __future__ import annotations

import dataclasses as _dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fdtd3d_tpu.layout import CURL_TERMS, component_axis
from fdtd3d_tpu.ops import pallas_packed as _pk
from fdtd3d_tpu.ops.pallas3d import COMPILER_PARAMS
from fdtd3d_tpu.telemetry import named as _named

AXES = "xyz"

# supported pipeline depths (Yee steps per HBM pass): aliased from the
# config authority so plan/bench/env validation can never drift from
# what the builder accepts; deeper rings do not fit the VMEM model on
# any tile we have measured
from fdtd3d_tpu.config import TB_DEPTHS as DEPTHS  # noqa: E402


def _depth_fits_shards(static, geo, k: int) -> bool:
    """Whether the k-generation boundary wedge fits every sharded
    axis's LOCAL extent: generation 1 computes E planes [0, k-2] (and
    the mirrored hi side), so a shard must hold at least k-1 planes —
    a (1,8,1) split of a 16-cell axis (local extent 2) admits k<=3
    only. Deeper depths are simply not candidates there (the pick
    falls to the deepest fitting k, then to pallas_packed)."""
    return all(k - 1 <= geo["ldims"][a] for a in geo["sharded_axes"])


def _coeff_grids_static(static) -> bool:
    """Whether any material coefficient is a 3D grid — the STATIC
    inference (plan._coeff_grid_counts, asserted equal to the real
    allocation by tests/test_plan.py), so eligibility and the planner
    never build coefficient arrays just to decide scope."""
    from fdtd3d_tpu.plan import _coeff_grid_counts
    per_e, per_h = _coeff_grid_counts(static)
    return per_e > 0 or per_h > 0


def _reject_reason(static, mesh_axes=None):
    """Machine-readable scope-rejection token, or None when the config
    is inside the temporal-blocked kernel's scope (module docstring).
    THE eligibility decision ``plan_tb`` (and through it the dispatch,
    the planner and the fallback records) consumes — the dispatch
    falls back to ``pallas_packed`` outside it, so this must never
    admit a config the kernel cannot advance k exact steps for in one
    pass.

    Round-14 widening: TFSF (in-kernel plane-value corrections +
    the wedge incident-line port), electric-Drude ADE (J in the ring
    scratch + the wedge J ring) and material grids (per-generation
    tiled operands + wedge plane sub-blocks) are IN scope sharded and
    unsharded alike."""
    if getattr(static, "paired_complex", False):
        return "paired_complex"
    if static.cfg.ds_fields:
        return "ds_fields"
    if not _pk.eligible(static, mesh_axes):
        return "packed_ineligible"
    if static.cfg.compensated:
        return "compensated"  # Kahan residuals would double traffic
    if static.use_drude_m:
        return "magnetic_drude"  # ADE K rings: ROADMAP item 1(c)
    src_like = static.tfsf_setup is not None \
        or static.cfg.point_source.enabled
    if src_like and not _pk._sources_interior(static):
        return "source_in_absorber"  # in-absorber injection: legacy
    return None


def eligible(static, mesh_axes=None) -> bool:
    """Whether the config is inside the temporal-blocked kernel's
    SCOPE (``_reject_reason``); depth/tile viability is a separate
    question — ``plan_tb`` answers both and is what the dispatch and
    the planner consult."""
    return _reject_reason(static, mesh_axes) is None


# ---------------------------------------------------------------------------
# VMEM model + auto-depth picker
# ---------------------------------------------------------------------------


def _geometry(static):
    """Shared trace-static geometry for the VMEM models and builder."""
    from fdtd3d_tpu import solver as solver_mod
    slabs = solver_mod.slab_axes(static)
    for a in static.pml_axes:
        if a not in slabs:
            return None       # thin-grid full-length psi: not covered
    mode = static.mode
    topo = static.topology
    sharded_axes = tuple(a for a in range(3) if topo[a] > 1)
    n1, n2, n3 = (static.grid_shape[a] // topo[a] for a in range(3))
    e_comps = list(mode.e_components)
    h_comps = list(mode.h_components)
    fuse_x = 0 in static.pml_axes
    rows_x_e = [c for c in e_comps
                if any(t[0] == 0 for t in CURL_TERMS[component_axis(c)])
                ] if fuse_x else []
    rows_x_h = [c for c in h_comps
                if any(t[0] == 0 for t in CURL_TERMS[component_axis(c)])
                ] if fuse_x else []
    return {
        "slabs": slabs,
        "ldims": (n1, n2, n3),
        "e_comps": e_comps, "h_comps": h_comps,
        "ne": len(e_comps), "nh": len(h_comps),
        "rows_e": _pk.psi_rows(static, slabs, "E"),
        "rows_h": _pk.psi_rows(static, slabs, "H"),
        "fuse_x": fuse_x,
        "kxe": len(rows_x_e), "kxh": len(rows_x_h),
        "rows_x_e": rows_x_e, "rows_x_h": rows_x_h,
        "m0": slabs.get(0, 0),
        "sharded_axes": sharded_axes,
        "yz_sharded": tuple(a for a in sharded_axes if a != 0),
    }


def _tf_group_sizes(static) -> Dict[Tuple[str, int], int]:
    """(family, face axis) -> correction count, polarization-filtered
    (tfsf.POL_EPS — the shared threshold, so a record the value
    builder drops never reaches the kernel)."""
    from fdtd3d_tpu.ops import tfsf as tfsf_mod
    setup = static.tfsf_setup
    out: Dict[Tuple[str, int], int] = {}
    if setup is None:
        return out
    for corr in setup.corrections:
        pol = (setup.ehat if corr.src[0] == "E"
               else setup.hhat)[component_axis(corr.src)]
        if abs(pol) < tfsf_mod.POL_EPS:
            continue
        out[(corr.field, corr.axis)] = out.get((corr.field, corr.axis),
                                               0) + 1
    return out


def _vmem_models(static, geo, k: int, n_arr_e: int, n_arr_h: int):
    """(block_bytes_at, scratch_bytes_at) closures for depth k."""
    slabs = geo["slabs"]
    n1, n2, n3 = geo["ldims"]
    ne, nh = geo["ne"], geo["nh"]
    rows_e, rows_h = geo["rows_e"], geo["rows_h"]
    psi_axes_e, psi_axes_h = sorted(rows_e), sorted(rows_h)
    fuse_x, kxe, kxh = geo["fuse_x"], geo["kxe"], geo["kxh"]
    sharded_axes, yz_sharded = geo["sharded_axes"], geo["yz_sharded"]
    fbytes = np.dtype(static.field_dtype).itemsize
    drude = static.use_drude
    src_on = bool(static.cfg.point_source.enabled)
    tf_sizes = _tf_group_sizes(static)

    def _psi_cells(a: int, t: int) -> int:
        """Cells of one psi-stack row's block: (t, n2, n3) with axis a
        compacted to the 2m slab planes."""
        s = [t, n2, n3]
        s[a] = 2 * slabs[a]
        return s[0] * s[1] * s[2]

    def _block_bytes(t: int) -> int:
        plane = n2 * n3
        total = 0
        total += 2 * ne * t * plane * fbytes       # E in + out
        total += 2 * nh * t * plane * fbytes       # H in + out
        for (axes, rows) in ((psi_axes_e, rows_e), (psi_axes_h, rows_h)):
            for a in axes:                         # psi stacks in + out
                total += 2 * len(rows[a]) * _psi_cells(a, t) * 4
        if fuse_x:
            total += 2 * (kxe + kxh) * t * plane * 4   # x-psi in + out
            total += 2 * k * 3 * t * 4             # prof_ex/hx per gen
        for a in psi_axes_e + psi_axes_h:
            total += 3 * 2 * slabs[a] * 4          # y/z profile packs
        if drude:
            total += 2 * ne * t * plane * 4        # J in + out
        total += (n_arr_e + n_arr_h) * k * t * plane * 4   # coeff grids
        for (fam, ax), ncorr in tf_sizes.items():  # TFSF value planes
            gens = k
            if ax == 0:
                total += gens * ncorr * plane * 4
            else:
                total += gens * ncorr * t * (n3, n2)[ax - 1] * 4
        if tf_sizes and sharded_axes:
            total += 3 * 4                         # tfofs
        if 0 in sharded_axes:                      # xgh[0..k-1], xe[1..k-1]
            total += (k * nh + (k - 1) * ne) * plane * fbytes
        for a in yz_sharded:                       # ygh/ye thin blocks
            total += (k * nh + (k - 1) * ne) * t \
                * (plane // (n2, n3)[a - 1]) * fbytes
        total += (k * t + n2 + n3) * 4             # walls (x per gen)
        if src_on:
            total += k * 4                         # waveform stack
            if sharded_axes:
                total += 3 * 4                     # srcpos
        return total

    def _scratch_bytes(t: int) -> int:
        plane = n2 * n3
        total = 0
        total += (2 * (k - 1) + 1) * ne * t * plane * 4   # E rings + E(t+k)
        total += (2 * (k - 1) + 1) * nh * t * plane * 4   # H rings + H(t)
        total += nh * plane * 4                    # H(t) halo plane
        for (axes, rows) in ((psi_axes_e, rows_e), (psi_axes_h, rows_h)):
            for a in axes:                         # psi rings per gen
                total += 2 * (k - 1) * len(rows[a]) * _psi_cells(a, t) * 4
        if fuse_x:
            total += 2 * (k - 1) * (kxe + kxh) * t * plane * 4
        if drude:
            total += 2 * (k - 1) * ne * t * plane * 4     # J rings
        return total

    return _block_bytes, _scratch_bytes


def _arr_counts_static(static, geo) -> Tuple[int, int]:
    """Streamed-coefficient-grid operand counts per family (one per
    grid per component), from the static inference."""
    from fdtd3d_tpu.plan import _coeff_grid_counts
    per_e, per_h = _coeff_grid_counts(static)
    return per_e * geo["ne"], per_h * geo["nh"]


def _depth_pick(static, geo, batch: int = 0):
    """The VMEM-calibrated depth scan (host math only; no coeffs are
    built, no backend touched). -> ``(best or None, tiles, source)``
    with tiles = {k: budgeted tile} per allowed depth; the pick is the
    DEEPEST k with tile >= 2, else the deepest with tile == 1,
    honoring the ``FDTD3D_TB_DEPTH`` pin (source records it). Raises a
    NAMED config error for an unviable pin — never a silent 48 B/cell
    family switch (the registered-knob convention; a user A/B-ing
    depths would otherwise blame the kernel for the fallback's
    slowdown)."""
    from fdtd3d_tpu.config import tb_depth_env, vmem_temps
    pinned = tb_depth_env()
    cands = (pinned,) if pinned else tuple(sorted(DEPTHS, reverse=True))
    n1, n2, n3 = geo["ldims"]
    n_arr_e, n_arr_h = _arr_counts_static(static, geo)
    tiles: Dict[int, int] = {}
    for k in cands:
        if not _depth_fits_shards(static, geo, k):
            tiles[k] = 0      # wedge wider than a local shard extent
            continue
        bb, sb = _vmem_models(static, geo, k, n_arr_e, n_arr_h)
        tiles[k] = _pk._pick_tile_packed(
            n1, n2 * n3, bb, sb,
            temps_f32_per_cell=vmem_temps("tb", k), batch=batch)
    source = f"env:FDTD3D_TB_DEPTH={pinned}" if pinned else "auto"
    best = max((k for k, t in tiles.items() if t >= 2), default=None)
    if best is None:
        best = max((k for k, t in tiles.items() if t == 1),
                   default=None)
    if best is None and pinned:
        raise ValueError(
            f"FDTD3D_TB_DEPTH={pinned}: the pinned temporal-block "
            f"depth is not viable for this configuration — the "
            f"k-1-plane boundary wedge must fit every sharded "
            f"axis's local extent and the depth-{pinned} ring "
            f"scratch must fit a VMEM tile (candidates: {tiles}). "
            f"Unset the pin for the auto-depth pick, or force the "
            f"single-step kernel with FDTD3D_NO_TEMPORAL=1.")
    return best, tiles, source


@_dataclasses.dataclass(frozen=True)
class TbPlan:
    """THE temporal-blocking decision for one (config, mesh): made
    once, consumed everywhere — the dispatch (solver.make_step), the
    builder (make_packed_tb_step), the planner (plan._infer_step_kind
    / CommStrategy.ghost_depth) and the fallback records (telemetry
    run_start / cost-ledger ``tb_fallback``) all read the SAME object,
    so they can never disagree about whether/why/at-what-depth a run
    temporal-blocks (the round-13 bug: pick_depth was consulted after
    eligible() in two call sites, and the planner skipped the
    tile-too-thin bail the builder applied).

    ``reason`` is None when eligible, else one machine-readable token:
    scope tokens (paired_complex / ds_fields / packed_ineligible /
    compensated / magnetic_drude / source_in_absorber), geometry
    (thin_grid_psi), or viability (no_viable_depth / tile_too_thin).
    The dispatch layer adds its own env/contract tokens
    (env:FDTD3D_NO_TEMPORAL, pallas_disabled, ...) on top —
    solver.tb_fallback_reason."""

    eligible: bool
    depth: Optional[int]
    tile: int
    candidates: Dict[int, int]
    source: str
    reason: Optional[str]


def plan_tb(static, mesh_axes=None, batch: int = 0) -> TbPlan:
    """Scope + depth + tile in one deterministic host-math decision
    (no coefficient arrays are built, no backend touched — dry-run
    planning at pod scale stays allocation-free).

    ``batch=B`` plans the LANE-CAPABLE build: the depth scan and the
    tile-too-thin bail both charge the per-lane ``batch_lane`` VMEM
    surcharge, so a depth viable solo may legitimately shallow (the
    lanes -> fewer-lanes rung of the batch ladder) or bail entirely
    under a wide batch."""
    reason = _reject_reason(static, mesh_axes)
    if reason is not None:
        return TbPlan(False, None, 0, {}, "n/a", reason)
    geo = _geometry(static)
    if geo is None:
        return TbPlan(False, None, 0, {}, "n/a", "thin_grid_psi")
    best, tiles, source = _depth_pick(static, geo, batch=batch)
    if best is None:
        return TbPlan(False, None, 0, tiles, source, "no_viable_depth")
    if tiles[best] == 1 and source == "auto" \
            and _pk.packed_tile(static, batch=batch) >= 4:
        # too thin: the deep pipeline at T=1 multiplies per-iteration
        # setup cost and ring-rotation VPU work; if the single-step
        # kernel affords a healthy tile, take its 48 B/cell instead
        # (the measured fused-vs-two-pass tile>=4 heuristic). An
        # explicit FDTD3D_TB_DEPTH pin skips the bail.
        return TbPlan(False, None, 1, tiles, source, "tile_too_thin")
    return TbPlan(True, best, tiles[best], tiles, source, None)


def pick_depth(static, mesh_axes=None):
    """Back-compat view of ``plan_tb``: ``(k, tile, candidates,
    source)`` or None when the kernel is not viable (scope, geometry,
    depth or the tile-too-thin bail)."""
    tbp = plan_tb(static, mesh_axes)
    if not tbp.eligible:
        return None
    return tbp.depth, tbp.tile, tbp.candidates, tbp.source


def planned_depth(static) -> Optional[int]:
    """ghost_depth the planner records for the tb kind (plan.py's
    CommStrategy scoring) — the same deterministic pick the builder
    makes, or None when the kernel is not viable at any depth. Mesh
    axis names are derived from the static topology (the planner has
    no live mesh; eligibility only needs the NAMES to exist)."""
    from fdtd3d_tpu.parallel.mesh import mesh_axis_map
    return plan_tb(static, mesh_axis_map(static.topology)).depth


# ---------------------------------------------------------------------------
# the builder
# ---------------------------------------------------------------------------


def make_packed_tb_step(static, mesh_axes=None, mesh_shape=None,
                        depth: Optional[int] = None, batch: int = 0):
    """k-steps-per-pass pipelined step, or None if out of scope.
    ``depth`` pins k (tests / the bench k-sweep); default: pick_depth.
    ``batch=B`` builds the lane-capable variant (per-lane VMEM
    surcharge in every tile pick, threaded into the tail build) — see
    pallas_packed.make_packed_eh_step.
    """
    from fdtd3d_tpu import solver as solver_mod
    from fdtd3d_tpu.config import vmem_temps

    if not eligible(static, mesh_axes):
        return None
    geo = _geometry(static)
    if geo is None:
        return None
    np_coeffs = solver_mod.build_coeffs(static)
    interpret = jax.default_backend() not in ("tpu", "axon")

    mode = static.mode
    topo = static.topology
    mesh_axes = mesh_axes or {}
    mesh_shape = mesh_shape or {}
    slabs = geo["slabs"]
    sharded_axes = geo["sharded_axes"]
    yz_sharded = geo["yz_sharded"]
    n1, n2, n3 = geo["ldims"]
    ldims = (n1, n2, n3)
    inv_dx = np.float32(1.0 / static.dx)
    fdt = jnp.float32
    fst = static.field_dtype
    e_comps, h_comps = geo["e_comps"], geo["h_comps"]
    ne, nh = geo["ne"], geo["nh"]
    rows_e, rows_h = geo["rows_e"], geo["rows_h"]
    psi_axes_e = sorted(rows_e)
    psi_axes_h = sorted(rows_h)
    drude = static.use_drude
    setup = static.tfsf_setup

    # fused x-slab CPML is MANDATORY here whenever x has a PML: a
    # k-step pass admits no post-kernel psi recursion. Eligibility
    # already guarantees the fuse condition (sourceless or interior
    # sources), mirroring pallas_packed's fuse_x gate.
    ps = static.cfg.point_source
    src_on = bool(ps.enabled)
    fuse_x = geo["fuse_x"]
    m0 = geo["m0"]
    rows_x_e, rows_x_h = geo["rows_x_e"], geo["rows_x_h"]
    kxe, kxh = geo["kxe"], geo["kxh"]

    # spatially-varying material coefficients: streamed per-generation
    # tiled operands (module docstring); scalars embed as constants
    pairs_e = ["ca", "cb"] + (["kj", "bj"] if drude else [])
    pairs_h = ["da", "db"]
    coeff_is_array = {}
    for c in e_comps:
        for p_ in pairs_e:
            coeff_is_array[f"{p_}_{c}"] = \
                np.ndim(np_coeffs[f"{p_}_{c}"]) == 3
    for c in h_comps:
        for p_ in pairs_h:
            coeff_is_array[f"{p_}_{c}"] = \
                np.ndim(np_coeffs[f"{p_}_{c}"]) == 3
    arr_e = [key for key, v in coeff_is_array.items()
             if v and key.split("_")[0] in pairs_e]
    arr_h = [key for key, v in coeff_is_array.items()
             if v and key.split("_")[0] in pairs_h]

    # ---- depth + tile (plan_tb is the single decision authority) ---------
    if depth is not None:
        if depth not in DEPTHS:
            raise ValueError(f"temporal-block depth {depth} not in "
                             f"{DEPTHS}")
        if not _depth_fits_shards(static, geo, depth):
            return None       # wedge wider than a local shard extent
        bb, sb = _vmem_models(static, geo, depth, len(arr_e),
                              len(arr_h))
        T = _pk._pick_tile_packed(
            n1, n2 * n3, bb, sb,
            temps_f32_per_cell=vmem_temps("tb", depth), batch=batch)
        if T == 0:
            return None
        k = depth
        depth_diag = {"candidates": {depth: T}, "source": "arg"}
    else:
        tbp = plan_tb(static, mesh_axes, batch=batch)
        if not tbp.eligible:
            return None
        k, T = tbp.depth, tbp.tile
        depth_diag = {"candidates": tbp.candidates,
                      "source": tbp.source}
    bb_k, sb_k = _vmem_models(static, geo, k, len(arr_e), len(arr_h))

    # the planned communication strategy (module docstring): message
    # split + schedule for the depth-k exchange; deterministic per
    # (grid, topology, dtype, kind), FDTD3D_COMM_STRATEGY overrides
    if sharded_axes:
        import dataclasses as _dc

        from fdtd3d_tpu.plan import comm_strategy as _strategy_for
        _strat = _strategy_for(static.cfg, topo,
                               step_kind="pallas_packed_tb")
        if _strat.ghost_depth != k:
            # the step consumed a pinned/arg depth the planner did not
            # model — the record must describe THIS exchange
            _strat = _dc.replace(_strat, ghost_depth=k)
        split = _strat.split
        sync_sched = _strat.schedule == "sync"
    else:
        split, sync_sched = "fused", False

    # odd-horizon tail at the SAME tile => identical packed-carry
    # layout (the x-psi stacks are tile-aligned); it also supplies
    # pack/unpack and the chunk-entry prepare() for both kernels.
    tail = _pk.make_packed_eh_step(static, mesh_axes, mesh_shape,
                                   force_tile=T, batch=batch)
    if tail is None:
        return None
    tail.kind = "pallas_packed"

    ntiles = n1 // T
    if fuse_x:
        (Sx, Lx, x_two_region, xblk, xpsi_tile_imap,
         _) = _pk.x_block_maps(m0, n1, T)
    else:
        Sx, Lx, x_two_region, xblk = 0, 0, False, None

    src_pos = tuple(int(v) for v in ps.position) if src_on else None
    lagE = 2 * (k - 1)        # E-family output lag
    lagH = 2 * k - 1          # H-family output lag

    # TFSF in-kernel records (unsharded): per family, face-axis groups
    # of polarization-filtered corrections; per component the static
    # (axis, row, plane) triples the kernel masks with.
    from fdtd3d_tpu.ops import tfsf as tfsf_mod
    tf_groups: Dict[str, Dict[int, List]] = {"E": {}, "H": {}}
    tf_records: Dict[str, Dict[str, List[Tuple[int, int, int]]]] = \
        {"E": {}, "H": {}}
    if setup is not None:
        for corr in setup.corrections:
            pol = (setup.ehat if corr.src[0] == "E"
                   else setup.hhat)[component_axis(corr.src)]
            if abs(pol) < tfsf_mod.POL_EPS:
                continue
            grp = tf_groups[corr.field].setdefault(corr.axis, [])
            tf_records[corr.field].setdefault(corr.comp, []).append(
                (corr.axis, len(grp), corr.plane))
            grp.append(corr)

    # ---- operand plan (ONE ordered authority for take/specs/args) -------
    in_names: List[str] = []
    in_specs: List = []

    def add_in(name, spec):
        in_names.append(name)
        in_specs.append(spec)

    def stack_spec(kk, last2, imap):
        return pl.BlockSpec((kk, T, last2[0], last2[1]), imap,
                            memory_space=pltpu.VMEM)

    def lag_imap(lag):
        if lag >= lagH:
            return lambda i, _l=lag: (0, jnp.maximum(i - _l, 0), 0, 0)
        # clamped at BOTH ends: the tb grid runs ntiles + 2k-1
        # iterations, so an unclamped max(i-l, 0) would hand Mosaic
        # out-of-range block indices on the drain iterations. Pinning
        # to the last block keeps the window (no refetch) and the
        # phase consuming it is masked there.
        return lambda i, _l=lag: (
            0, jnp.minimum(jnp.maximum(i - _l, 0), ntiles - 1), 0, 0)

    tile_imap = lag_imap(0)

    def psi_last2(a):
        s = [1, n1, n2, n3]
        s[1 + a] = 2 * slabs[a]
        return (s[2], s[3])

    if fuse_x:
        def xpsi_lag_imap(lag):
            if lag >= lagH:
                return lambda i, _l=lag: (
                    0, xblk(jnp.maximum(i - _l, 0)), 0, 0)
            return lambda i, _l=lag: (
                0, xblk(jnp.minimum(jnp.maximum(i - _l, 0),
                                    ntiles - 1)), 0, 0)

    const4 = lambda i: (0, 0, 0, 0)  # noqa: E731
    const3 = lambda i: (0, 0, 0)     # noqa: E731

    add_in("e_in", stack_spec(ne, (n2, n3), tile_imap))
    add_in("h_in", stack_spec(nh, (n2, n3), tile_imap))
    for a in psi_axes_e:
        add_in(f"psE{a}", stack_spec(len(rows_e[a]), psi_last2(a),
                                     tile_imap))
    for a in psi_axes_h:
        add_in(f"psH{a}", stack_spec(len(rows_h[a]), psi_last2(a),
                                     lag_imap(1)))
    if fuse_x:
        add_in("psxE", pl.BlockSpec((kxe, T, n2, n3), xpsi_tile_imap,
                                    memory_space=pltpu.VMEM))
        add_in("psxH", pl.BlockSpec((kxh, T, n2, n3), xpsi_lag_imap(1),
                                    memory_space=pltpu.VMEM))
    if drude:
        add_in("j_in", stack_spec(ne, (n2, n3), tile_imap))
    for a in psi_axes_e:
        s = [3, 1, 1, 1]
        s[1 + a] = 2 * slabs[a]
        add_in(f"prof_e_{a}", pl.BlockSpec(tuple(s), const4,
                                           memory_space=pltpu.VMEM))
    for a in psi_axes_h:
        s = [3, 1, 1, 1]
        s[1 + a] = 2 * slabs[a]
        add_in(f"prof_h_{a}", pl.BlockSpec(tuple(s), const4,
                                           memory_space=pltpu.VMEM))
    if fuse_x:
        def prof_spec(lag):
            m4 = lag_imap(lag)
            return pl.BlockSpec(
                (3, T, 1, 1),
                lambda i, _m=m4: (0, _m(i)[1], 0, 0),
                memory_space=pltpu.VMEM)
        for g in range(1, k + 1):
            add_in(f"prof_ex{g}", prof_spec(2 * (g - 1)))
        for g in range(1, k + 1):
            add_in(f"prof_hx{g}", prof_spec(2 * g - 1))
    # depth-k generation ghosts: x ghosts are whole boundary planes
    # (constant block), y/z ghosts are thin per-tile blocks whose index
    # maps follow their consuming phase's lag
    if 0 in sharded_axes:
        for j in range(k):
            add_in(f"xgh{j}", pl.BlockSpec((nh, 1, n2, n3), const4,
                                           memory_space=pltpu.VMEM))
        for j in range(1, k):
            add_in(f"xe{j}", pl.BlockSpec((ne, 1, n2, n3), const4,
                                          memory_space=pltpu.VMEM))
    for a in yz_sharded:
        gh = [nh, T, n2, n3]
        gh[1 + a] = 1
        ge = [ne, T, n2, n3]
        ge[1 + a] = 1
        for j in range(k):
            add_in(f"ygh{j}{a}", pl.BlockSpec(tuple(gh), lag_imap(2 * j),
                                              memory_space=pltpu.VMEM))
        for j in range(1, k):
            add_in(f"ye{j}{a}", pl.BlockSpec(tuple(ge),
                                             lag_imap(2 * j - 1),
                                             memory_space=pltpu.VMEM))
    # streamed material-coefficient grids, once per consuming phase
    def coeff_spec(lag):
        m4 = lag_imap(lag)
        return pl.BlockSpec((T, n2, n3),
                            lambda i, _m=m4: (_m(i)[1], 0, 0),
                            memory_space=pltpu.VMEM)
    for g in range(1, k + 1):
        for key in arr_e:
            add_in(f"ce{g}_{key}", coeff_spec(2 * (g - 1)))
    for g in range(1, k + 1):
        for key in arr_h:
            add_in(f"ch{g}_{key}", coeff_spec(2 * g - 1))
    # TFSF correction value planes, one stacked operand per (family,
    # face axis, generation); x-face planes are constant blocks, y/z
    # faces stream at the consuming phase's tile lag
    for fam, tag in (("E", "tfe"), ("H", "tfh")):
        for g in range(1, k + 1):
            lag = 2 * (g - 1) if fam == "E" else 2 * g - 1
            for ax_, grp in sorted(tf_groups[fam].items()):
                ncorr = len(grp)
                if ax_ == 0:
                    add_in(f"{tag}{g}_{ax_}",
                           pl.BlockSpec((ncorr, 1, n2, n3), const4,
                                        memory_space=pltpu.VMEM))
                else:
                    bs = [ncorr, T, n2, n3]
                    bs[1 + ax_] = 1
                    add_in(f"{tag}{g}_{ax_}",
                           pl.BlockSpec(tuple(bs), lag_imap(lag),
                                        memory_space=pltpu.VMEM))
    if setup is not None and sharded_axes:
        # traced shard origin for the TFSF onehot masks (the srcpos
        # pattern): local coordinates + tfofs == the global face plane
        add_in("tfofs", pl.BlockSpec((3, 1, 1), const3,
                                     memory_space=pltpu.VMEM))
    if src_on:
        add_in("src", pl.BlockSpec((k, 1, 1), const3,
                                   memory_space=pltpu.VMEM))
        if sharded_axes:
            add_in("srcpos", pl.BlockSpec((3, 1, 1), const3,
                                          memory_space=pltpu.VMEM))
    for g in range(1, k + 1):
        m4 = lag_imap(2 * (g - 1))
        add_in(f"wall_x{g}",
               pl.BlockSpec((T, 1, 1),
                            lambda i, _m=m4: (_m(i)[1], 0, 0),
                            memory_space=pltpu.VMEM))
    add_in("wall_y", pl.BlockSpec((1, n2, 1), const3,
                                  memory_space=pltpu.VMEM))
    add_in("wall_z", pl.BlockSpec((1, 1, n3), const3,
                                  memory_space=pltpu.VMEM))

    # ---- outputs ---------------------------------------------------------
    def _stack_shape(a: int, kk: int):
        s = [kk, n1, n2, n3]
        s[1 + a] = 2 * slabs[a]
        return tuple(s)

    out_names: List[str] = ["e_out", "h_out"]
    out_specs: List = [stack_spec(ne, (n2, n3), lag_imap(lagE)),
                       stack_spec(nh, (n2, n3), lag_imap(lagH))]
    out_shape = [jax.ShapeDtypeStruct((ne, n1, n2, n3), fst),
                 jax.ShapeDtypeStruct((nh, n1, n2, n3), fst)]
    for a in psi_axes_e:
        out_names.append(f"psE{a}_out")
        out_specs.append(stack_spec(len(rows_e[a]), psi_last2(a),
                                    lag_imap(lagE)))
        out_shape.append(jax.ShapeDtypeStruct(
            _stack_shape(a, len(rows_e[a])), np.float32))
    for a in psi_axes_h:
        out_names.append(f"psH{a}_out")
        out_specs.append(stack_spec(len(rows_h[a]), psi_last2(a),
                                    lag_imap(lagH)))
        out_shape.append(jax.ShapeDtypeStruct(
            _stack_shape(a, len(rows_h[a])), np.float32))
    if fuse_x:
        out_names += ["psxE_out", "psxH_out"]
        out_specs += [pl.BlockSpec((kxe, T, n2, n3), xpsi_lag_imap(lagE),
                                   memory_space=pltpu.VMEM),
                      pl.BlockSpec((kxh, T, n2, n3), xpsi_lag_imap(lagH),
                                   memory_space=pltpu.VMEM)]
        out_shape += [jax.ShapeDtypeStruct((kxe, Sx, n2, n3), np.float32),
                      jax.ShapeDtypeStruct((kxh, Sx, n2, n3), np.float32)]
    if drude:
        out_names.append("j_out")
        out_specs.append(stack_spec(ne, (n2, n3), lag_imap(lagE)))
        out_shape.append(jax.ShapeDtypeStruct((ne, n1, n2, n3),
                                              np.float32))

    # Donation: module docstring — reads always precede the (lag-2(k-1)
    # / lag-(2k-1)) writes of the same block, every array enters once.
    aliases = {j: j for j in range(len(out_names))}

    # ---- scratch ---------------------------------------------------------
    scratch_names: List[str] = []
    scratch: List = []

    def add_scratch(name, shape):
        scratch_names.append(name)
        scratch.append(pltpu.VMEM(shape, jnp.float32))

    for g in range(1, k):
        add_scratch(f"se{g}a", (ne, T, n2, n3))
        add_scratch(f"se{g}b", (ne, T, n2, n3))
    add_scratch("sek", (ne, T, n2, n3))
    add_scratch("sh0", (nh, T, n2, n3))
    add_scratch("sh0h", (nh, 1, n2, n3))
    for g in range(1, k):
        add_scratch(f"sh{g}a", (nh, T, n2, n3))
        add_scratch(f"sh{g}b", (nh, T, n2, n3))
    for g in range(1, k):
        for a in psi_axes_e:
            s2, s3 = psi_last2(a)
            add_scratch(f"spe{g}a_{a}", (len(rows_e[a]), T, s2, s3))
            add_scratch(f"spe{g}b_{a}", (len(rows_e[a]), T, s2, s3))
        for a in psi_axes_h:
            s2, s3 = psi_last2(a)
            add_scratch(f"sph{g}a_{a}", (len(rows_h[a]), T, s2, s3))
            add_scratch(f"sph{g}b_{a}", (len(rows_h[a]), T, s2, s3))
    if fuse_x:
        for g in range(1, k):
            add_scratch(f"sxe{g}a", (kxe, T, n2, n3))
            add_scratch(f"sxe{g}b", (kxe, T, n2, n3))
            add_scratch(f"sxh{g}a", (kxh, T, n2, n3))
            add_scratch(f"sxh{g}b", (kxh, T, n2, n3))
    if drude:
        for g in range(1, k):
            add_scratch(f"sj{g}a", (ne, T, n2, n3))
            add_scratch(f"sj{g}b", (ne, T, n2, n3))

    # ---- the kernel ------------------------------------------------------
    def kernel(*refs):
        idx = {}
        pos = 0

        def take(names):
            nonlocal pos
            for nm in names:
                idx[nm] = refs[pos]
                pos += 1

        take(in_names)
        take(out_names)
        take(scratch_names)

        i = pl.program_id(0)

        def lagv(lag):
            v = jnp.maximum(i - lag, 0)
            return v if lag >= lagH else jnp.minimum(v, ntiles - 1)

        valid_e = {g: (i >= 2 * (g - 1))
                   & (i <= ntiles - 1 + 2 * (g - 1))
                   for g in range(1, k + 1)}
        valid_h = {g: (i >= 2 * g - 1) & (i <= ntiles - 1 + 2 * g - 1)
                   for g in range(1, k + 1)}
        if fuse_x:
            if x_two_region:
                def in_slab(tj):
                    return (tj < Lx) | (tj >= ntiles - Lx)
            else:
                def in_slab(tj):
                    return tj >= 0                 # every tile

        def yz_diff(f, axis, backward, ghost=None):
            # ghost: the sharded-axis neighbor plane (backward: the lo
            # ghost; forward: the hi ghost). None = the PEC zero ghost
            # (unsharded axes, and phase H_k's hi edge — post-fixed).
            if ghost is None:
                ghost = jnp.zeros_like(
                    lax.slice_in_dim(f, 0, 1, axis=axis))
            if backward:
                body = lax.slice_in_dim(f, 0, f.shape[axis] - 1,
                                        axis=axis)
                return (f - jnp.concatenate([ghost, body],
                                            axis=axis)) * inv_dx
            body = lax.slice_in_dim(f, 1, f.shape[axis], axis=axis)
            return (jnp.concatenate([body, ghost], axis=axis) - f) \
                * inv_dx

        def slab_term(dfa, psi, tag, a, s):
            """CPML slab recursion (ops/pallas_packed.py's form, value-
            returning): -> (new compact psi, full accumulator term)."""
            m = slabs[a]
            pr = idx[f"prof_{tag}_{a}"]
            b, cc, ik = pr[0], pr[1], pr[2]
            cut = lambda f, lo, hi: lax.slice_in_dim(f, lo, hi, axis=a)  # noqa: E731
            nloc = dfa.shape[a]
            d_lo, d_hi = cut(dfa, 0, m), cut(dfa, nloc - m, nloc)
            p_lo = (cut(b, 0, m) * cut(psi, 0, m)
                    + cut(cc, 0, m) * d_lo)
            p_hi = (cut(b, m, 2 * m) * cut(psi, m, 2 * m)
                    + cut(cc, m, 2 * m) * d_hi)
            dl = s * ((cut(ik, 0, m) - 1.0) * d_lo + p_lo)
            dh = s * ((cut(ik, m, 2 * m) - 1.0) * d_hi + p_hi)
            mid = list(dfa.shape)
            mid[a] = nloc - 2 * m
            delta = jnp.concatenate(
                [dl, jnp.zeros(mid, fdt), dh], axis=a)
            return jnp.concatenate([p_lo, p_hi], axis=a), s * dfa + delta

        def coef(fam, g, key):
            if coeff_is_array.get(key):
                tag = "ce" if fam == "e" else "ch"
                return idx[f"{tag}{g}_{key}"][:].astype(fdt)
            return fdt(float(np_coeffs[key]))

        def src_term(c, tile_lo, step_j):
            """In-kernel point source at generation step_j (0-based):
            amplitude*waveform at the right tile offset; zero
            off-component. Under sharding the LOCAL position rides as
            a traced srcpos operand (off-shard local coordinates fall
            outside the iota range, so the mask is identically zero
            there)."""
            if not src_on or c != ps.component:
                return None
            if sharded_axes:
                sp = idx["srcpos"]
                px, py, pz = sp[0, 0, 0], sp[1, 0, 0], sp[2, 0, 0]
            else:
                px, py, pz = src_pos
            gx = lax.broadcasted_iota(jnp.int32, (T, n2, n3), 0) \
                + tile_lo * T
            gy = lax.broadcasted_iota(jnp.int32, (T, n2, n3), 1)
            gz = lax.broadcasted_iota(jnp.int32, (T, n2, n3), 2)
            mask = ((gx == px) & (gy == py) & (gz == pz)).astype(fdt)
            return idx["src"][step_j:step_j + 1] * mask

        def tfsf_term(fam, c, g, tile_lo):
            """Sum of comp c's TFSF plane-value corrections at
            generation g: onehot(static face plane) x the traced value
            plane (module docstring). Under sharding the face plane is
            GLOBAL and the iota local, so the traced shard origin
            (tfofs) closes the gap — off-shard face planes mask to
            zero, one SPMD program."""
            recs = tf_records[fam].get(c) if setup is not None else None
            if not recs:
                return None
            tag = "tfe" if fam == "E" else "tfh"
            tot = None
            for (ax_, row, plane) in recs:
                blk = idx[f"{tag}{g}_{ax_}"]
                gi = lax.broadcasted_iota(jnp.int32, (T, n2, n3), ax_)
                if ax_ == 0:
                    gi = gi + tile_lo * T
                if sharded_axes:
                    gi = gi + idx["tfofs"][ax_, 0, 0]
                mask = (gi == plane).astype(fdt)
                term = mask * blk[row]
                tot = term if tot is None else tot + term
            return tot

        def wall_mask(e, c, wall_x_vals):
            ca_ax = component_axis(c)
            if ca_ax != 0:
                e = e * wall_x_vals
            for a2 in (1, 2):
                if a2 != ca_ax:
                    e = e * idx[f"wall_{AXES[a2]}"][:].astype(fdt)
            return e

        def e_update(g, h_tiles, h_ghosts, e_old, psi_get, psx_get,
                     tile_lo, j_old, yz_ghost=None):
            """Phase E_g over one tile. Returns (new e comps,
            {a: [new psi rows]}, [new x-psi rows], [new J comps])."""
            new_psi: Dict[int, list] = {a: [None] * len(rows_e[a])
                                        for a in psi_axes_e}
            new_psx = [None] * kxe
            new_j = [None] * ne if drude else None
            out = []
            for jc, c in enumerate(e_comps):
                acc = None
                for (a, jd, s) in CURL_TERMS[component_axis(c)]:
                    if a == 0:
                        full = jnp.concatenate(
                            [h_ghosts[jd], h_tiles[jd]], axis=0)
                        dfa = (full[1:] - full[:-1]) * inv_dx
                        if fuse_x:
                            row = rows_x_e.index(c)
                            pr = idx[f"prof_ex{g}"]
                            psi_new = pr[0] * psx_get(row) + pr[1] * dfa
                            new_psx[row] = psi_new
                            term = s * (pr[2] * dfa + psi_new)
                        else:
                            term = s * dfa
                    else:
                        dfa = yz_diff(
                            h_tiles[jd], a, backward=True,
                            ghost=(yz_ghost(a, jd)
                                   if yz_ghost is not None else None))
                        if a in slabs and a in static.pml_axes:
                            row = rows_e[a].index(c)
                            psi_new, term = slab_term(
                                dfa, psi_get(a, row), "e", a, s)
                            new_psi[a][row] = psi_new
                        else:
                            term = s * dfa
                    acc = term if acc is None else acc + term
                tv = tfsf_term("E", c, g, tile_lo)
                if tv is not None:
                    acc = acc + tv
                old = e_old[jc]
                if drude:
                    jn = coef("e", g, f"kj_{c}") * j_old[jc] \
                        + coef("e", g, f"bj_{c}") * old
                    new_j[jc] = jn
                    acc = acc - jn
                sv = src_term(c, tile_lo, g - 1)
                if sv is not None:
                    acc = acc + sv
                e = coef("e", g, f"ca_{c}") * old \
                    + coef("e", g, f"cb_{c}") * acc
                out.append(wall_mask(
                    e, c, idx[f"wall_x{g}"][:].astype(fdt)))
            return out, new_psi, new_psx, new_j

        def h_update(g, e_tiles, e_firsts, h_old, psi_get, psx_get,
                     tile_lo, yz_ghost=None):
            """Phase H_g over one tile (dual of e_update)."""
            new_psi: Dict[int, list] = {a: [None] * len(rows_h[a])
                                        for a in psi_axes_h}
            new_psx = [None] * kxh
            out = []
            for jc, c in enumerate(h_comps):
                acc = None
                for (a, jd, s) in CURL_TERMS[component_axis(c)]:
                    if a == 0:
                        ext = jnp.concatenate(
                            [e_tiles[jd], e_firsts[jd]], axis=0)
                        dfa = (ext[1:] - ext[:-1]) * inv_dx
                        if fuse_x:
                            row = rows_x_h.index(c)
                            pr = idx[f"prof_hx{g}"]
                            psi_new = pr[0] * psx_get(row) + pr[1] * dfa
                            new_psx[row] = psi_new
                            term = s * (pr[2] * dfa + psi_new)
                        else:
                            term = s * dfa
                    else:
                        dfa = yz_diff(
                            e_tiles[jd], a, backward=False,
                            ghost=(yz_ghost(a, jd)
                                   if yz_ghost is not None else None))
                        if a in slabs and a in static.pml_axes:
                            row = rows_h[a].index(c)
                            psi_new, term = slab_term(
                                dfa, psi_get(a, row), "h", a, s)
                            new_psi[a][row] = psi_new
                        else:
                            term = s * dfa
                    acc = term if acc is None else acc + term
                tv = tfsf_term("H", c, g, tile_lo)
                if tv is not None:
                    acc = acc + tv
                out.append(coef("h", g, f"da_{c}") * h_old[jc]
                           - coef("h", g, f"db_{c}") * acc)
            return out, new_psi, new_psx

        # sharded y/z ghost getters per generation (block index maps
        # already track each phase's tile lag)
        def ygh_for(j):
            if not yz_sharded:
                return None

            def f(a, jd, _j=j):
                return idx[f"ygh{_j}{a}"][jd].astype(fdt) \
                    if a in yz_sharded else None
            return f

        def ye_for(g):
            if not yz_sharded:
                return None

            def f(a, jd, _g=g):
                return idx[f"ye{_g}{a}"][jd].astype(fdt) \
                    if a in yz_sharded else None
            return f

        h0_vals = [idx["h_in"][j].astype(fdt) for j in range(nh)]
        e0_vals = [idx["e_in"][j].astype(fdt) for j in range(ne)]

        # per-generation results stashed for the ring rotation
        e_gen: Dict[int, list] = {}
        h_gen: Dict[int, list] = {}
        psiE_gen: Dict[int, Dict[int, list]] = {}
        psiH_gen: Dict[int, Dict[int, list]] = {}
        psxE_gen: Dict[int, list] = {}
        psxH_gen: Dict[int, list] = {}
        j_gen: Dict[int, list] = {}

        for g in range(1, k + 1):
            le = 2 * (g - 1)
            # ---- phase E_g: E(t+g) on tile i - 2(g-1) ----------------
            if g == 1:
                h_tiles = h0_vals
                e_old = e0_vals
                ring_last = [idx["sh0h"][j] for j in range(nh)]
                psi_get = lambda a, row: idx[f"psE{a}"][row].astype(fdt)  # noqa: E731
                psx_get = ((lambda row: idx["psxE"][row].astype(fdt))
                           if fuse_x else None)
                j_old = ([idx["j_in"][j].astype(fdt) for j in range(ne)]
                         if drude else None)
            else:
                h_tiles = [idx[f"sh{g - 1}a"][j] for j in range(nh)]
                e_old = [idx[f"se{g - 1}b"][j] for j in range(ne)]
                ring_last = [idx[f"sh{g - 1}b"][j][-1:]
                             for j in range(nh)]
                psi_get = (lambda a, row, _g=g:
                           idx[f"spe{_g - 1}b_{a}"][row])
                psx_get = ((lambda row, _g=g: idx[f"sxe{_g - 1}b"][row])
                           if fuse_x else None)
                j_old = ([idx[f"sj{g - 1}b"][j] for j in range(ne)]
                         if drude else None)
            # lo x ghost: ring last plane of H(t+g-1)[tile-1], or the
            # exchanged generation ghost at the drain edge (tile 0)
            gh_lo = [jnp.where(i > le, ring_last[j],
                               idx[f"xgh{g - 1}"][j].astype(fdt)
                               if 0 in sharded_axes
                               else jnp.zeros_like(ring_last[j]))
                     for j in range(nh)]
            tl_e = lagv(le)
            e_g, psiE_g, psxE_g, j_g = e_update(
                g, h_tiles, gh_lo, e_old, psi_get, psx_get, tl_e,
                j_old, yz_ghost=ygh_for(g - 1))
            e_gen[g], psiE_gen[g], psxE_gen[g] = e_g, psiE_g, psxE_g
            if drude:
                j_gen[g] = j_g
            if g == k:
                for jc in range(ne):
                    @pl.when(valid_e[k])
                    def _(jc=jc):
                        idx["e_out"][jc] = e_g[jc].astype(fst)
                for a in psi_axes_e:
                    for row in range(len(rows_e[a])):
                        @pl.when(valid_e[k])
                        def _(a=a, row=row):
                            idx[f"psE{a}_out"][row] = \
                                psiE_g[a][row].astype(fdt)
                if fuse_x:
                    for row in range(kxe):
                        @pl.when(valid_e[k] & in_slab(lagv(lagE)))
                        def _(row=row):
                            idx["psxE_out"][row] = \
                                psxE_g[row].astype(fdt)
                if drude:
                    for jc in range(ne):
                        @pl.when(valid_e[k])
                        def _(jc=jc):
                            idx["j_out"][jc] = j_g[jc].astype(fdt)

            # ---- phase H_g: H(t+g) on tile i - (2g-1) ----------------
            if g < k:
                e_tiles = [idx[f"se{g}a"][j] for j in range(ne)]
                firsts = [jnp.where(valid_e[g], e_g[j][0:1],
                                    idx[f"xe{g}"][j].astype(fdt)
                                    if 0 in sharded_axes
                                    else jnp.zeros_like(e_g[j][0:1]))
                          for j in range(ne)]
                yzg = ye_for(g)
            else:
                e_tiles = [idx["sek"][j] for j in range(ne)]
                # phase H_k's hi edge keeps the zero ghost in-kernel;
                # the missing neighbor contribution is the thin
                # post-fix (pallas_packed.hi_edge_h_fix)
                firsts = [jnp.where(valid_e[k], e_g[j][0:1],
                                    jnp.zeros_like(e_g[j][0:1]))
                          for j in range(ne)]
                yzg = None
            if g == 1:
                h_old = [idx["sh0"][j] for j in range(nh)]
                psi_get_h = lambda a, row: idx[f"psH{a}"][row].astype(fdt)  # noqa: E731
                psx_get_h = ((lambda row: idx["psxH"][row].astype(fdt))
                             if fuse_x else None)
            else:
                h_old = [idx[f"sh{g - 1}b"][j] for j in range(nh)]
                psi_get_h = (lambda a, row, _g=g:
                             idx[f"sph{_g - 1}b_{a}"][row])
                psx_get_h = ((lambda row, _g=g:
                              idx[f"sxh{_g - 1}b"][row])
                             if fuse_x else None)
            tl_h = lagv(2 * g - 1)
            h_g, psiH_g, psxH_g = h_update(
                g, e_tiles, firsts, h_old, psi_get_h, psx_get_h, tl_h,
                yz_ghost=yzg)
            h_gen[g], psiH_gen[g], psxH_gen[g] = h_g, psiH_g, psxH_g
            if g == k:
                for jc in range(nh):
                    @pl.when(valid_h[k])
                    def _(jc=jc):
                        idx["h_out"][jc] = h_g[jc].astype(fst)
                for a in psi_axes_h:
                    for row in range(len(rows_h[a])):
                        @pl.when(valid_h[k])
                        def _(a=a, row=row):
                            idx[f"psH{a}_out"][row] = \
                                psiH_g[a][row].astype(fdt)
                if fuse_x:
                    for row in range(kxh):
                        @pl.when(valid_h[k] & in_slab(lagv(lagH)))
                        def _(row=row):
                            idx["psxH_out"][row] = \
                                psxH_g[row].astype(fdt)

        # ---- phase R: rotate the rings for the next iteration --------
        # (a slots were read into values above, so the b <- a,
        # a <- fresh order is race-free)
        for g in range(1, k):
            prev = [idx[f"se{g}a"][j] for j in range(ne)]
            for j in range(ne):
                idx[f"se{g}b"][j] = prev[j]
                idx[f"se{g}a"][j] = e_gen[g][j]
        for j in range(ne):
            idx["sek"][j] = e_gen[k][j]
        for j in range(nh):
            idx["sh0"][j] = h0_vals[j]
            idx["sh0h"][j] = h0_vals[j][-1:]
        for g in range(1, k):
            prev = [idx[f"sh{g}a"][j] for j in range(nh)]
            for j in range(nh):
                idx[f"sh{g}b"][j] = prev[j]
                idx[f"sh{g}a"][j] = h_gen[g][j]
        for g in range(1, k):
            for a in psi_axes_e:
                prev = [idx[f"spe{g}a_{a}"][row]
                        for row in range(len(rows_e[a]))]
                for row in range(len(rows_e[a])):
                    idx[f"spe{g}b_{a}"][row] = prev[row]
                    idx[f"spe{g}a_{a}"][row] = psiE_gen[g][a][row]
            for a in psi_axes_h:
                prev = [idx[f"sph{g}a_{a}"][row]
                        for row in range(len(rows_h[a]))]
                for row in range(len(rows_h[a])):
                    idx[f"sph{g}b_{a}"][row] = prev[row]
                    idx[f"sph{g}a_{a}"][row] = psiH_gen[g][a][row]
        if fuse_x:
            for g in range(1, k):
                prev = [idx[f"sxe{g}a"][row] for row in range(kxe)]
                for row in range(kxe):
                    idx[f"sxe{g}b"][row] = prev[row]
                    idx[f"sxe{g}a"][row] = psxE_gen[g][row]
                prev = [idx[f"sxh{g}a"][row] for row in range(kxh)]
                for row in range(kxh):
                    idx[f"sxh{g}b"][row] = prev[row]
                    idx[f"sxh{g}a"][row] = psxH_gen[g][row]
        if drude:
            for g in range(1, k):
                prev = [idx[f"sj{g}a"][j] for j in range(ne)]
                for j in range(ne):
                    idx[f"sj{g}b"][j] = prev[j]
                    idx[f"sj{g}a"][j] = j_gen[g][j]

    call = pl.pallas_call(
        kernel,
        grid=(ntiles + 2 * k - 1,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        input_output_aliases=aliases,
        scratch_shapes=scratch,
        compiler_params=COMPILER_PARAMS(
            vmem_limit_bytes=_pk._VMEM_TOTAL),
        interpret=interpret,
    )

    # ---- the step (advances k steps) -------------------------------------
    from fdtd3d_tpu.ops import stencil as _stencil
    from fdtd3d_tpu.ops.sources import waveform

    prepare = tail.prepare

    def _coefv(key):
        return fdt(float(np_coeffs[key]))

    # ---- depth-k boundary-wedge pre-pass (sharded only) ------------------
    # Thin jnp computations of the boundary-plane generations the
    # kernel cannot reach: generation by generation, E(t+j) on each
    # sharded axis's outermost k-j planes per side and H(t+j) on the
    # outermost k-j (hi) / k-1-j (lo) planes, each exact — CPML slab
    # and fused-x psi terms included via a per-plane psi wedge, source
    # included, walls applied. The psi wedge is throwaway scratch: the
    # kernel recomputes every psi generation for the whole local
    # domain.

    def _slab_row(p: int, m: int, n_loc: int):
        """Field plane -> compact slab-psi row (None = identity
        region, psi identically zero)."""
        if p < m:
            return p
        if p >= n_loc - m:
            return 2 * m - (n_loc - p)
        return None

    def _psx_row(p: int):
        """Field x plane -> tile-aligned x-psi storage row (None =
        identity region)."""
        if p < m0:
            return p
        if p >= n1 - m0:
            return Sx - (n1 - p)
        return None

    def _psx_plane(stack4, row, a, p):
        """Full-length x-psi of one row at plane (a, p): the
        tile-aligned compact storage re-expanded (zeros — identity
        no-op — between the slab regions)."""
        st = lax.slice_in_dim(stack4[row], p, p + 1, axis=a).astype(fdt)
        if Sx == n1:
            return st
        lo = lax.slice_in_dim(st, 0, m0, axis=0)
        hi = lax.slice_in_dim(st, Sx - m0, Sx, axis=0)
        shape = list(st.shape)
        shape[0] = n1 - 2 * m0
        return jnp.concatenate([lo, jnp.zeros(shape, fdt), hi], axis=0)

    def _plane_slab_term(dfa, psi, pr, ax, s):
        """Kernel slab_term's form on a plane array -> (new compact
        psi, accumulator term)."""
        m = slabs[ax]
        b, cc_, ik = pr[0], pr[1], pr[2]
        cut = lambda f, lo, hi: lax.slice_in_dim(f, lo, hi, axis=ax)  # noqa: E731
        nloc = dfa.shape[ax]
        d_lo, d_hi = cut(dfa, 0, m), cut(dfa, nloc - m, nloc)
        p_lo = cut(b, 0, m) * cut(psi, 0, m) + cut(cc_, 0, m) * d_lo
        p_hi = (cut(b, m, 2 * m) * cut(psi, m, 2 * m)
                + cut(cc_, m, 2 * m) * d_hi)
        dl = s * ((cut(ik, 0, m) - 1.0) * d_lo + p_lo)
        dh = s * ((cut(ik, m, 2 * m) - 1.0) * d_hi + p_hi)
        mid = list(dfa.shape)
        mid[ax] = nloc - 2 * m
        delta = jnp.concatenate([dl, jnp.zeros(mid, fdt), dh], axis=ax)
        return (jnp.concatenate([p_lo, p_hi], axis=ax),
                s * dfa + delta)

    def _mk_psi_get(pstate, fam, a, p, store):
        """psi reader at plane (a, p): the packed state for generation
        1, the previous generation's wedge store after; None means an
        identity region (psi == 0 there, profiles identity)."""
        def get(c, ax):
            if store is not None:
                return store.get((c, ax))
            if ax == 0 and fuse_x:
                rows_x = rows_x_e if fam == "e" else rows_x_h
                row = rows_x.index(c)
                key = "psxE" if fam == "e" else "psxH"
                if a == 0:
                    srow = _psx_row(p)
                    if srow is None:
                        return None
                    return pstate[key][row, srow:srow + 1].astype(fdt)
                return _psx_plane(pstate[key], row, a, p)
            rows_fam = rows_e if fam == "e" else rows_h
            stk = ("psE" if fam == "e" else "psH") + str(ax)
            row = rows_fam[ax].index(c)
            if ax == a:
                rr = _slab_row(p, slabs[ax], ldims[ax])
                if rr is None:
                    return None
                return lax.slice_in_dim(pstate[stk][row], rr, rr + 1,
                                        axis=ax).astype(fdt)
            return lax.slice_in_dim(pstate[stk][row], p, p + 1,
                                    axis=a).astype(fdt)
        return get

    def _own_psi_term(cc, fam, c, a, p, dfa, s, psi_get, psi_set):
        """Plane-normal psi term at plane (a, p): the slab / fused-x
        recursion degenerates to one compact row."""
        if a == 0 and fuse_x:
            srow = _psx_row(p)
            prx = cc[f"_pk_prof_{fam}x"]
            cutp = lambda v: lax.slice_in_dim(v, p, p + 1, axis=0)  # noqa: E731
            if srow is None:
                return s * cutp(prx[2]) * dfa      # identity: ik == 1
            psi_old = psi_get(c, 0)
            if psi_old is None:
                psi_old = jnp.zeros_like(dfa)
            psi_new = cutp(prx[0]) * psi_old + cutp(prx[1]) * dfa
            psi_set(c, 0, psi_new)
            return s * (cutp(prx[2]) * dfa + psi_new)
        if a in slabs and a in static.pml_axes:
            rr = _slab_row(p, slabs[a], ldims[a])
            if rr is None:
                return s * dfa
            pr = cc[f"_pk_prof_{fam}{a}"]
            cutr = lambda v: lax.slice_in_dim(v, rr, rr + 1, axis=a)  # noqa: E731
            psi_old = psi_get(c, a)
            if psi_old is None:
                psi_old = jnp.zeros_like(dfa)
            psi_new = cutr(pr[0]) * psi_old + cutr(pr[1]) * dfa
            psi_set(c, a, psi_new)
            return s * (cutr(pr[2]) * dfa + psi_new)
        return s * dfa

    def _cross_psi_term(cc, fam, c, a, p, ax, dfa, s, psi_get,
                        psi_set):
        """Cross-axis psi term on a boundary plane of axis a."""
        if ax == 0 and fuse_x:
            psi_old = psi_get(c, 0)
            if psi_old is None:
                psi_old = jnp.zeros_like(dfa)
            prx = cc[f"_pk_prof_{fam}x"]
            psi_new = prx[0] * psi_old + prx[1] * dfa
            psi_set(c, 0, psi_new)
            return s * (prx[2] * dfa + psi_new)
        if ax in slabs and ax in static.pml_axes:
            psi_old = psi_get(c, ax)
            if psi_old is None:
                psi_old = jnp.zeros(
                    tuple(2 * slabs[ax] if d == ax else dfa.shape[d]
                          for d in range(3)), fdt)
            psi_new, term = _plane_slab_term(
                dfa, psi_old, cc[f"_pk_prof_{fam}{ax}"], ax, s)
            psi_set(c, ax, psi_new)
            return term
        return s * dfa

    def _shard_offsets():
        offs = []
        for a in range(3):
            if topo[a] > 1:
                offs.append(lax.axis_index(mesh_axes[a])
                            * jnp.int32(ldims[a]))
            else:
                offs.append(jnp.int32(0))
        return offs

    active_axes = mode.active_axes

    def _wedge_coef(cc, key, a, p):
        """Coefficient at wedge plane (a, p): a 3D material grid
        slices its per-cell plane sub-block (the round-14 widened-
        operand port — the wedge gathers the ghost planes' tiled
        coefficients instead of assuming scalars), a scalar embeds as
        a constant exactly like the kernel's ``coef``."""
        if coeff_is_array.get(key):
            return lax.slice_in_dim(cc[key], p, p + 1,
                                    axis=a).astype(fdt)
        return _coefv(key)

    def _wedge_tfsf_sum(cc, tf_terms, c, a, p):
        """Comp c's TFSF accumulator corrections restricted to wedge
        plane (a, p) — the incident-line port (round 14). ``tf_terms``
        is this generation+family's [(corr, plane term)] list
        (``tfsf.corr_plane_term`` on the per-generation incident
        line): the normal-axis onehot is applied here from the SHARDED
        gx/gy/gz coordinate arrays — a traced 0/1 scalar when the
        correction is normal to the wedge axis, a 1D line mask
        otherwise — so the same SPMD program is exact on every shard
        and face planes owned by other shards contribute zero.
        Incident values are shard-local recomputation (the line is
        replicated): the port adds ZERO ICI bytes."""
        tot = None
        for corr, term in tf_terms or ():
            if corr.comp != c or term is None:
                continue
            t3 = term.astype(fdt)
            if jnp.ndim(t3) == 3 and t3.shape[a] > 1:
                t3 = lax.slice_in_dim(t3, p, p + 1, axis=a)
            ga = cc["g" + AXES[corr.axis]]
            if corr.axis == a:
                oh = (ga[p] == corr.plane).astype(fdt)
            else:
                shp = [1, 1, 1]
                shp[corr.axis] = ga.shape[0]
                oh = (ga == corr.plane).reshape(shp).astype(fdt)
            tv = t3 * oh
            tot = tv if tot is None else tot + tv
        return tot

    def _wedge_e_plane(cc, a, p, h_at, gh_prev, e_old_pl, psi_get,
                       psi_set, offs, tstep, j_old_pl=None,
                       tf_terms=None):
        """E(t+j) comps on plane (a, p) of a sharded axis (f32).
        ``h_at(jd, q)`` returns H(t+j-1) comp jd at plane q (q == -1:
        the received downstream ghost); ``gh_prev[ax]`` the other
        sharded axes' generation-(j-1) ghost stacks (cross-axis lo
        ghost lines slice from them — no corner messages).
        ``j_old_pl``: the Drude J(t+j-1) planes (the wedge's J ring,
        round 14); ``tf_terms``: this generation's TFSF plane terms.
        Returns (new E comps, new J comps or None) — term order
        mirrors the kernel's e_update (curl, TFSF, Drude, source)."""
        out = []
        new_j = [] if drude else None
        for jc, c in enumerate(e_comps):
            acc = None
            for (ax, jd, s) in CURL_TERMS[component_axis(c)]:
                if ax == a:
                    dfa = (h_at(jd, p) - h_at(jd, p - 1)) * inv_dx
                    term = _own_psi_term(cc, "e", c, a, p, dfa, s,
                                         psi_get, psi_set)
                else:
                    f = h_at(jd, p)
                    if ax in sharded_axes:
                        gl = lax.slice_in_dim(gh_prev[ax][jd], p, p + 1,
                                              axis=a).astype(fdt)
                    else:
                        gl = jnp.zeros_like(
                            lax.slice_in_dim(f, 0, 1, axis=ax))
                    body = lax.slice_in_dim(f, 0, f.shape[ax] - 1,
                                            axis=ax)
                    dfa = (f - jnp.concatenate([gl, body], axis=ax)) \
                        * inv_dx
                    term = _cross_psi_term(cc, "e", c, a, p, ax, dfa,
                                           s, psi_get, psi_set)
                acc = term if acc is None else acc + term
            if tf_terms is not None:
                tv = _wedge_tfsf_sum(cc, tf_terms, c, a, p)
                if tv is not None:
                    acc = acc + tv
            if drude:
                jn = _wedge_coef(cc, f"kj_{c}", a, p) * j_old_pl[jc] \
                    + _wedge_coef(cc, f"bj_{c}", a, p) * e_old_pl[jc]
                new_j.append(jn)
                acc = acc - jn
            if src_on and c == ps.component:
                with _named("source"):
                    wf = waveform(ps.waveform, tstep, 0.5, static.omega,
                                  static.dt, np.float32)
                    m_ = None
                    for b in range(3):
                        gi = lax.broadcasted_iota(
                            jnp.int32, acc.shape, b) + offs[b] \
                            + jnp.int32(p if b == a else 0)
                        mb = gi == jnp.int32(ps.position[b])
                        m_ = mb if m_ is None else (m_ & mb)
                    # traced amplitude (coeffs ps_amp): per-lane drive
                    # strength under a vmap-batched executor; ps_amp is
                    # the f32 round of the config float, bit-identical
                    # to the static multiply it replaces
                    acc = acc + cc["ps_amp"] * wf \
                        * m_.astype(fdt)
            e = _wedge_coef(cc, f"ca_{c}", a, p) * e_old_pl[jc] \
                + _wedge_coef(cc, f"cb_{c}", a, p) * acc
            ca_ax = component_axis(c)
            for b in range(3):
                if b == ca_ax:
                    continue
                w = cc[f"_pk_wall_{AXES[b]}"].astype(fdt)
                if b == a:
                    w = lax.slice_in_dim(w, p, p + 1, axis=b)
                e = e * w
            out.append(e)
        return out, new_j

    def _wedge_h_plane(cc, a, p, e_at, hi_cross, h_old_pl, psi_get,
                       psi_set, tf_terms=None):
        """H(t+j) comps on plane (a, p): ``e_at(jd, q)`` returns the
        SAME generation's E at plane q (q == n_a: the received
        upstream ghost); ``hi_cross[ax]`` its cross-axis hi-ghost
        stacks; ``tf_terms`` the generation's H-side TFSF terms."""
        out = []
        for jc, c in enumerate(h_comps):
            acc = None
            for (ax, jd, s) in CURL_TERMS[component_axis(c)]:
                if ax == a:
                    dfa = (e_at(jd, p + 1) - e_at(jd, p)) * inv_dx
                    term = _own_psi_term(cc, "h", c, a, p, dfa, s,
                                         psi_get, psi_set)
                else:
                    f = e_at(jd, p)
                    if ax in sharded_axes:
                        gl = lax.slice_in_dim(hi_cross[ax][jd], p,
                                              p + 1,
                                              axis=a).astype(fdt)
                    else:
                        gl = jnp.zeros_like(
                            lax.slice_in_dim(f, 0, 1, axis=ax))
                    body = lax.slice_in_dim(f, 1, f.shape[ax], axis=ax)
                    dfa = (jnp.concatenate([body, gl], axis=ax) - f) \
                        * inv_dx
                    term = _cross_psi_term(cc, "h", c, a, p, ax, dfa,
                                           s, psi_get, psi_set)
                acc = term if acc is None else acc + term
            if tf_terms is not None:
                tv = _wedge_tfsf_sum(cc, tf_terms, c, a, p)
                if tv is not None:
                    acc = acc + tv
            out.append(_wedge_coef(cc, f"da_{c}", a, p) * h_old_pl[jc]
                       - _wedge_coef(cc, f"db_{c}", a, p) * acc)
        return out

    def _exchange_ghosts(pstate, cc, t, inc_gen=None):
        """The 2k-1-message depth-k exchange schedule (module
        docstring; message 2k is the post-kernel hi-edge fix): returns
        (gh, hi_e, offs) with gh[j][a] the H(t+j) downstream stacks
        and hi_e[j][a] (j >= 1) the E(t+j) upstream stacks.
        ``inc_gen``: the per-generation incident-line states
        [(after-E-advance, after-H-advance)] the step computed — the
        wedge's incident-line port evaluates each generation's TFSF
        corrections from them, shard-locally (zero extra ICI)."""
        E_arr, H_arr = pstate["E"], pstate["H"]
        J_arr = pstate["J"] if drude else None
        offs = _shard_offsets()

        # per-generation TFSF plane terms for the wedge (j = 1..k-1):
        # corr_plane_term is the SAME authority the kernel's value-
        # plane operands ride, so wedge and kernel cannot drift
        tf_wedge: Dict[str, Dict[int, list]] = {"E": {}, "H": {}}
        if setup is not None:
            with _named("tfsf"):
                for j in range(1, k):
                    inc_e, inc_h = inc_gen[j - 1]
                    tf_wedge["E"][j] = [
                        (corr, tfsf_mod.corr_plane_term(
                            corr, setup, cc, inc_e, active_axes,
                            static.dx))
                        for corr in setup.corrections
                        if corr.field == "E"]
                    tf_wedge["H"][j] = [
                        (corr, tfsf_mod.corr_plane_term(
                            corr, setup, cc, inc_h, active_axes,
                            static.dx))
                        for corr in setup.corrections
                        if corr.field == "H"]

        def _ex(stack, a, down):
            name = mesh_axes[a]
            return _stencil.exchange_stack(stack, name,
                                           mesh_shape[name],
                                           downstream=down, split=split)

        gh = [{a: _ex(lax.slice_in_dim(H_arr, ldims[a] - 1, ldims[a],
                                       axis=1 + a), a, True)
               for a in sharded_axes}]
        hi_e: List[Optional[Dict[int, jnp.ndarray]]] = [None]
        Ew: Dict[int, Dict[int, list]] = {a: {} for a in sharded_axes}
        Hw: Dict[int, Dict[int, list]] = {a: {} for a in sharded_axes}
        Jw: Dict[int, Dict[int, list]] = {a: {} for a in sharded_axes}
        psiwE: Dict[int, Dict[int, dict]] = {a: {} for a in sharded_axes}
        psiwH: Dict[int, Dict[int, dict]] = {a: {} for a in sharded_axes}
        for j in range(1, k):
            newE: Dict[int, Dict[int, list]] = {a: {}
                                                for a in sharded_axes}
            newJ: Dict[int, Dict[int, list]] = {a: {}
                                                for a in sharded_axes}
            newPsiE: Dict[int, Dict[int, dict]] = {a: {}
                                                   for a in sharded_axes}
            with _named("E-update"):
                for a in sharded_axes:
                    n_a = ldims[a]
                    planes = sorted(set(range(0, k - j))
                                    | set(range(max(n_a - (k - j), 0),
                                                n_a)))
                    for p in planes:
                        def h_at(jd, q, a=a, j=j):
                            if q < 0:
                                return gh[j - 1][a][jd].astype(fdt)
                            if j == 1:
                                return lax.slice_in_dim(
                                    H_arr[jd], q, q + 1,
                                    axis=a).astype(fdt)
                            return Hw[a][q][jd]
                        if j == 1:
                            e_old_pl = [lax.slice_in_dim(
                                E_arr[jc], p, p + 1,
                                axis=a).astype(fdt)
                                for jc in range(ne)]
                            j_old_pl = ([lax.slice_in_dim(
                                J_arr[jc], p, p + 1,
                                axis=a).astype(fdt)
                                for jc in range(ne)]
                                if drude else None)
                            store = None
                        else:
                            e_old_pl = Ew[a][p]
                            j_old_pl = Jw[a][p] if drude else None
                            store = psiwE[a][p]
                        new_store: dict = {}
                        pset = (lambda c, ax, v, _ns=new_store:
                                _ns.__setitem__((c, ax), v))
                        newE[a][p], j_new = _wedge_e_plane(
                            cc, a, p, h_at, gh[j - 1], e_old_pl,
                            _mk_psi_get(pstate, "e", a, p, store),
                            pset, offs, t + (j - 1),
                            j_old_pl=j_old_pl,
                            tf_terms=tf_wedge["E"].get(j))
                        if drude:
                            newJ[a][p] = j_new
                        newPsiE[a][p] = new_store
            Ew, psiwE, Jw = newE, newPsiE, newJ
            hi_e.append({a: _ex(jnp.stack(Ew[a][0]).astype(fst), a,
                                False)
                         for a in sharded_axes})
            newH: Dict[int, Dict[int, list]] = {a: {}
                                                for a in sharded_axes}
            newPsiH: Dict[int, Dict[int, dict]] = {a: {}
                                                   for a in sharded_axes}
            with _named("H-update"):
                for a in sharded_axes:
                    n_a = ldims[a]
                    planes = sorted(set(range(0, max(k - 1 - j, 0)))
                                    | set(range(max(n_a - (k - j), 0),
                                                n_a)))
                    for p in planes:
                        def e_at(jd, q, a=a, j=j, n_a=n_a):
                            if q >= n_a:
                                return hi_e[j][a][jd].astype(fdt)
                            return Ew[a][q][jd]
                        if j == 1:
                            h_old_pl = [lax.slice_in_dim(
                                H_arr[jc], p, p + 1,
                                axis=a).astype(fdt)
                                for jc in range(nh)]
                            store = None
                        else:
                            h_old_pl = Hw[a][p]
                            store = psiwH[a][p]
                        new_store = {}
                        pset = (lambda c, ax, v, _ns=new_store:
                                _ns.__setitem__((c, ax), v))
                        newH[a][p] = _wedge_h_plane(
                            cc, a, p, e_at, hi_e[j], h_old_pl,
                            _mk_psi_get(pstate, "h", a, p, store),
                            pset, tf_terms=tf_wedge["H"].get(j))
                        newPsiH[a][p] = new_store
            Hw, psiwH = newH, newPsiH
            gh.append({a: _ex(jnp.stack(Hw[a][ldims[a] - 1])
                              .astype(fst), a, True)
                       for a in sharded_axes})
        return gh, hi_e, offs

    # ---- TFSF value-plane builder (module docstring; shard-local:
    # corr_plane_term reads the SHARDED gx/gy/gz coordinate arrays) ---
    if setup is not None:
        def _tf_stacks(fam, inc_d, coeffs):
            out = {}
            for ax_, grp in sorted(tf_groups[fam].items()):
                rows = []
                shape = [n1, n2, n3]
                shape[ax_] = 1
                for corr in grp:
                    term = tfsf_mod.corr_plane_term(
                        corr, setup, coeffs, inc_d, active_axes,
                        static.dx)
                    rows.append(jnp.broadcast_to(
                        term.astype(fdt) if term is not None
                        else jnp.zeros(()), tuple(shape)).astype(fdt))
                out[f"{'tfe' if fam == 'E' else 'tfh'}"
                    f"{{g}}_{ax_}"] = jnp.stack(rows)
            return out

    def step(pstate, coeffs):
        if "_pk_wall_x" not in coeffs:
            # direct callers hand raw coeffs; the chunk runner hoists
            # prepare() outside the scan (round 6)
            coeffs = prepare(coeffs)
        t = pstate["t"]
        new_state = dict(pstate)
        # advance the 1D incident line through all k generations FIRST
        # (thin jnp): the wedge pre-pass and the kernel's value-plane
        # operands both read the per-generation states (the E side
        # samples Hinc at t+g-1/2 — before the Hinc advance — and the
        # H side Einc at t+g, mirroring the jnp ordering)
        inc_gen = None
        if setup is not None:
            with _named("tfsf"):
                inc_gen = []
                inc_d = pstate["inc"]
                for g in range(1, k + 1):
                    inc_d = tfsf_mod.advance_einc(
                        inc_d, coeffs, t + (g - 1), static.dt,
                        static.omega, setup)
                    inc_e_g = inc_d
                    inc_d = tfsf_mod.advance_hinc(inc_d, coeffs, setup)
                    inc_gen.append((inc_e_g, inc_d))
                new_state["inc"] = inc_d
        offs = None
        if sharded_axes:
            gh, hi_e, offs = _exchange_ghosts(pstate, coeffs, t,
                                              inc_gen)
        operands: Dict[str, jnp.ndarray] = {
            "e_in": pstate["E"], "h_in": pstate["H"],
            "wall_y": coeffs["_pk_wall_y"],
            "wall_z": coeffs["_pk_wall_z"],
        }
        for a in psi_axes_e:
            operands[f"psE{a}"] = pstate[f"psE{a}"]
        for a in psi_axes_h:
            operands[f"psH{a}"] = pstate[f"psH{a}"]
        if fuse_x:
            operands["psxE"] = pstate["psxE"]
            operands["psxH"] = pstate["psxH"]
        if drude:
            operands["j_in"] = pstate["J"]
        for a in psi_axes_e:
            operands[f"prof_e_{a}"] = coeffs[f"_pk_prof_e{a}"]
        for a in psi_axes_h:
            operands[f"prof_h_{a}"] = coeffs[f"_pk_prof_h{a}"]
        if fuse_x:
            for g in range(1, k + 1):
                operands[f"prof_ex{g}"] = coeffs["_pk_prof_ex"]
                operands[f"prof_hx{g}"] = coeffs["_pk_prof_hx"]
        if 0 in sharded_axes:
            for j in range(k):
                operands[f"xgh{j}"] = gh[j][0]
            for j in range(1, k):
                operands[f"xe{j}"] = hi_e[j][0]
        for a in yz_sharded:
            for j in range(k):
                operands[f"ygh{j}{a}"] = gh[j][a]
            for j in range(1, k):
                operands[f"ye{j}{a}"] = hi_e[j][a]
        for g in range(1, k + 1):
            for key in arr_e:
                operands[f"ce{g}_{key}"] = coeffs[key]
            for key in arr_h:
                operands[f"ch{g}_{key}"] = coeffs[key]
        if setup is not None:
            # the per-generation correction value planes ride as
            # traced operands, evaluated from the already-advanced
            # incident-line states
            with _named("tfsf"):
                for g in range(1, k + 1):
                    inc_e_g, inc_h_g = inc_gen[g - 1]
                    for nm, v in _tf_stacks("E", inc_e_g,
                                            coeffs).items():
                        operands[nm.format(g=g)] = v
                    for nm, v in _tf_stacks("H", inc_h_g,
                                            coeffs).items():
                        operands[nm.format(g=g)] = v
                if sharded_axes:
                    operands["tfofs"] = jnp.stack(
                        [jnp.int32(0) + offs[b]
                         for b in range(3)]).reshape(3, 1, 1)
        if src_on:
            with _named("source"):
                wf = jnp.stack([
                    waveform(ps.waveform, t + j, 0.5, static.omega,
                             static.dt, np.float32)
                    for j in range(k)])
                # traced amplitude (see the wedge source above): the
                # per-step drive vector rides the operand tree so a
                # vmap batch can give every lane its own strength
                operands["src"] = (coeffs["ps_amp"]
                                   * wf).reshape(k, 1, 1)
                if sharded_axes:
                    operands["srcpos"] = jnp.stack(
                        [jnp.int32(src_pos[b]) - offs[b]
                         for b in range(3)]).reshape(3, 1, 1)
        for g in range(1, k + 1):
            operands[f"wall_x{g}"] = coeffs["_pk_wall_x"]
        args = [operands[nm] for nm in in_names]
        if sync_sched:
            # planned "sync" schedule (plan.CommStrategy): pin the
            # exchange results before the kernel so the scheduler
            # cannot overlap them with compute — the measurement A/B
            # posture the sentinel's async-window gates compare
            args = list(lax.optimization_barrier(tuple(args)))
        with _named("packed-kernel-tb"):
            outs = call(*args)
        p = 0
        new_state["E"] = outs[p]; p += 1
        new_state["H"] = outs[p]; p += 1
        for a in psi_axes_e:
            new_state[f"psE{a}"] = outs[p]; p += 1
        for a in psi_axes_h:
            new_state[f"psH{a}"] = outs[p]; p += 1
        if fuse_x:
            new_state["psxE"] = outs[p]; p += 1
            new_state["psxH"] = outs[p]; p += 1
        if drude:
            new_state["J"] = outs[p]; p += 1
        if sharded_axes:
            # phase H_k kept the PEC zero hi ghost for E(t+k): add the
            # neighbor's first-plane contribution as the single-step
            # kernel's thin post-fix (the 2k-th exchange message)
            new_state["H"] = _pk.hi_edge_h_fix(
                new_state["E"], new_state["H"], static, coeffs,
                mesh_axes, mesh_shape, sharded_axes, ldims, e_comps,
                h_comps, inv_dx, split=split)
        new_state["t"] = t + k
        return new_state

    step.pack = tail.pack
    step.unpack = tail.unpack
    step.packed = True
    step.prepare = prepare
    step.steps_per_call = k
    step.tail_step = tail
    step.diag = {"tile": {"EH": T},
                 "fused_x": fuse_x,
                 "temporal_block": k,
                 "depth_pick": depth_diag,
                 "vmem_block_bytes": {"EH": bb_k(T)},
                 "vmem_scratch_bytes": sb_k(T)}
    if sharded_axes:
        step.diag["comm_strategy"] = _strat.as_record()
    return step
