"""Multi-process (multi-host) runtime: the reference's MPI-launch analog.

Reference parity: fdtd3d runs as `mpirun -n N ./fdtd3d ...` — one process
per rank, ranks meeting over MPI (SURVEY.md §2.9, §5.8). The TPU-native
equivalent is one process per host, meeting through JAX's distributed
runtime: collectives ride ICI inside a slice and DCN across slices, with
the SAME solver code — the device mesh simply spans all processes'
devices.

Usage (per process):

    from fdtd3d_tpu.parallel import distributed
    distributed.initialize(coordinator="host0:9955",
                           num_processes=4, process_id=rank)
    sim = Simulation(cfg)          # mesh spans the global device set
    sim.run()

or from the CLI: --coordinator-address host0:9955 --num-processes 4
--process-id $RANK (each falling back to the standard JAX env vars /
TPU pod auto-detection when omitted).

Tested end-to-end with real multi-process runs on the CPU backend
(tests/test_distributed.py), the same oversubscribed-single-host pattern
the reference uses for its MPI unit tests.
"""

from __future__ import annotations

from typing import Optional

import jax


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the distributed runtime (no-op when already initialized).

    With all arguments None on TPU pods, JAX auto-detects the topology
    from the TPU environment. Must run BEFORE any other jax call that
    initializes the backend.
    """
    if is_initialized():
        return
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def is_initialized() -> bool:
    # NB: must not touch jax.process_count()/jax.devices() here — those
    # initialize the XLA backend, after which joining is impossible.
    try:
        return jax._src.distributed.global_state.client is not None
    except Exception:
        return False


def gather_to_host(arr) -> "np.ndarray":
    """Global numpy value of a (possibly multi-host sharded) jax array.

    Single-process: a plain device_get. Multi-process: an allgather of
    the addressable shards over the distributed runtime, so EVERY process
    returns the full global array (the reference's gather-for-dump).
    """
    import numpy as np
    if jax.process_count() <= 1:
        return np.asarray(jax.device_get(arr))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
