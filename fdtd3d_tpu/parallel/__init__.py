"""Spatial domain decomposition over a TPU device mesh.

The ParallelGrid/BufferShare replacement (SURVEY.md §2): 1/2/3-axis meshes,
auto or manual topology, shard_map execution with ppermute halo exchange
(the exchange itself lives in ops/stencil.py next to the differences).
"""

from fdtd3d_tpu.parallel.mesh import (  # noqa: F401
    choose_topology, build_mesh, coeff_specs, state_specs, shard_tree)
