"""Device-mesh construction, topology selection, sharding specs.

Reference parity (SURVEY.md §2 ParallelGrid row, §2.9 parallelism list):

* 7 decomposition modes (x, y, z, xy, yz, xz, xyz) -> a 1/2/3-axis
  ``jax.sharding.Mesh`` with axis names "x"/"y"/"z"; only active scheme axes
  may be sharded.
* auto-optimal node grid (``ParallelGridCore``'s topology heuristic) ->
  ``choose_topology``: over all factorizations of n_devices onto the active
  axes, minimize total halo-exchange surface (the same surface/volume
  criterion the reference optimizes).
* ``--manual-topology`` -> ``ParallelConfig.manual_topology``.
* ghost/buffer exchange -> ``lax.ppermute`` inside the difference ops
  (ops/stencil.py); the E-share/H-share points per step match §3.2.
* ``DYNAMIC_GRID`` rebalancing is a deliberate non-goal (SPMD on homogeneous
  chips; SURVEY.md §2.9 item 4).

Sharding-spec conventions (inferred from coeffs/state key names + rank):
rank-3 field arrays shard as P(x?, y?, z?); 1D arrays whose key ends in
``_x``/``_y``/``_z`` (or equals gx/gy/gz) shard along that axis; everything
else (incident line, scalars) is replicated.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = "xyz"


def _factorizations(n: int, k: int):
    """All ordered k-tuples of positive ints with product n."""
    if k == 1:
        yield (n,)
        return
    for f in range(1, n + 1):
        if n % f == 0:
            for rest in _factorizations(n // f, k - 1):
                yield (f,) + rest


def choose_topology(n_devices: int, grid_shape: Tuple[int, int, int],
                    active_axes: Tuple[int, ...]) -> Tuple[int, int, int]:
    """Minimal-halo-surface factorization of n_devices onto active axes.

    Cost = per-device ghost-plane area exchanged per half-step
         = sum over sharded axes a of 2 * (local cells / local n_a) —
    the same surface-to-volume criterion the reference's auto topology
    minimizes. Ties prefer MORE sharded axes: on the TPU torus each mesh
    axis rides its own ICI links, so 3-axis halos move concurrently.
    Sharded axes must divide evenly.
    """
    act = list(active_axes)
    best, best_cost = None, None
    for fac in _factorizations(n_devices, len(act)):
        topo = [1, 1, 1]
        ok = True
        for a, f in zip(act, fac):
            if grid_shape[a] % f != 0:
                ok = False
                break
            topo[a] = f
        if not ok:
            continue
        local = [grid_shape[a] / topo[a] for a in range(3)]
        local_cells = float(np.prod([local[a] for a in act]))
        cost = sum(2.0 * local_cells / local[a] for a in act if topo[a] > 1)
        n_sharded = sum(1 for a in act if topo[a] > 1)
        key = (cost, -n_sharded)
        if best is None or key < best_cost:
            best, best_cost = tuple(topo), key
    if best is None:
        raise ValueError(
            f"cannot factor {n_devices} devices onto grid {grid_shape} "
            f"active axes {active_axes} with even division")
    return best


def resolve_topology(parallel_cfg, grid_shape: Tuple[int, int, int],
                     active_axes: Tuple[int, ...],
                     n_devices: Optional[int] = None
                     ) -> Tuple[int, int, int]:
    """(px, py, pz) from a ParallelConfig — THE topology authority.

    Shared by Simulation and the dry-run planner so both resolve (and
    reject) configurations identically: manual topologies must name only
    active axes and divide the grid; "auto" needs a device count.
    """
    if parallel_cfg.topology == "none":
        return (1, 1, 1)
    if parallel_cfg.topology == "manual":
        if parallel_cfg.manual_topology is None:
            raise ValueError("manual topology requires manual_topology")
        topo = tuple(parallel_cfg.manual_topology)
        for a in range(3):
            if topo[a] > 1 and a not in active_axes:
                raise ValueError(f"cannot shard inactive axis {a}")
            if grid_shape[a] % topo[a] != 0:
                raise ValueError(
                    f"axis {a} ({grid_shape[a]} cells) not divisible "
                    f"by topology {topo[a]}")
        return topo
    if parallel_cfg.topology == "auto":
        n = parallel_cfg.n_devices or n_devices
        if not n:
            raise ValueError("auto topology needs a device count")
        return choose_topology(n, grid_shape, active_axes)
    raise ValueError(f"unknown topology {parallel_cfg.topology!r}")


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map across jax API generations: top-level vs experimental
    import, check_vma vs check_rep kwarg. The one shim Simulation and
    the cost ledger's comm-lane trace both use."""
    try:  # jax >= 0.5 exposes shard_map at top level
        from jax import shard_map as _sm
    except ImportError:  # pragma: no cover - older jax layout
        from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_vma=False)
    except TypeError:  # older kwarg name
        return _sm(fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)


def build_mesh(topology: Tuple[int, int, int], devices=None) -> Mesh:
    """Mesh with axis names x/y/z from an (px, py, pz) topology."""
    n = int(np.prod(topology))
    devices = devices if devices is not None else jax.devices()[:n]
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    dev_array = np.asarray(devices[:n]).reshape(topology)
    return Mesh(dev_array, axis_names=("x", "y", "z"))


def mesh_axis_map(topology: Tuple[int, int, int]) -> Dict[int, Optional[str]]:
    """axis index -> mesh axis name for sharded axes (>1 shards) else None."""
    return {a: (AXES[a] if topology[a] > 1 else None) for a in range(3)}


def mesh_shape_map(topology: Tuple[int, int, int]) -> Dict[str, int]:
    """mesh axis name -> shard count, sharded axes only (shard_map shape)."""
    return {AXES[a]: topology[a] for a in range(3) if topology[a] > 1}


def _axis_suffix(key: str) -> Optional[str]:
    if key in ("gx", "gy", "gz"):
        return key[1]
    if len(key) > 2 and key[-2] == "_" and key[-1] in AXES:
        return key[-1]
    return None


def _rank3_spec(topology) -> P:
    return P(*[AXES[a] if topology[a] > 1 else None for a in range(3)])


def coeff_specs(coeffs: Dict, topology) -> Dict:
    """PartitionSpec tree for the coeffs pytree (see module docstring)."""
    specs = {}
    for k, v in coeffs.items():
        nd = getattr(v, "ndim", 0)
        if nd == 3:
            specs[k] = _rank3_spec(topology)
        elif nd == 1:
            ax = _axis_suffix(k)
            if ax is not None and topology[AXES.index(ax)] > 1:
                specs[k] = P(ax)
            else:
                specs[k] = P()
        else:
            specs[k] = P()
    return specs


def state_specs(state: Dict, topology) -> Dict:
    """PartitionSpec tree for the state pytree: fields sharded, rest repl."""
    r3 = _rank3_spec(topology)

    def spec_of(leaf):
        return r3 if getattr(leaf, "ndim", 0) == 3 else P()

    return jax.tree.map(spec_of, state)


def packed_specs(packed_shapes, topology) -> Dict:
    """PartitionSpec tree for the PACKED carry (ops/pallas_packed.py).

    Stacked component leaves are rank-4 (comp-leading): the comp dim
    replicates and the trailing three shard as a field; rank-3 leaves
    (psi compacts, boundary bands) shard as fields; vectors (the TFSF
    incident line) and scalars replicate.
    """
    r3 = _rank3_spec(topology)

    def spec_of(leaf):
        nd = getattr(leaf, "ndim", 0)
        if nd == 4:
            return P(None, *r3)
        return r3 if nd == 3 else P()

    return jax.tree.map(spec_of, packed_shapes)


def shard_tree(tree, specs, mesh: Mesh):
    """Shard a host pytree: each device receives ONLY its own slice.

    Built on make_array_from_callback rather than a whole-array
    device_put, so (a) no full-size staging allocation happens on any
    single device, and (b) the same call works when ``mesh`` spans
    multiple processes — each process materializes just its addressable
    shards (the reference's per-rank grid fill, SURVEY.md §3.1 initGrids
    under MPI).
    """
    return jax.tree.map(lambda v, s: shard_leaf(v, s, mesh), tree, specs)


def shard_leaf(v, spec: P, mesh: Mesh):
    """One host array -> sharded jax array (each device gets its slice)."""
    v = np.asarray(v)
    return jax.make_array_from_callback(
        v.shape, NamedSharding(mesh, spec), lambda idx: v[idx])


def sharded_zeros(shape_tree, specs, mesh: Mesh):
    """Zeros pytree created ALREADY sharded (from eval_shape structs).

    Allocating zeros unsharded and resharding would momentarily need the
    full array on one device — at 1024^3 that alone overflows a chip.
    """
    def mk(sd, s):
        sharding = NamedSharding(mesh, s)

        def cb(idx):
            local = tuple(
                (sl.stop if sl.stop is not None else n)
                - (sl.start if sl.start is not None else 0)
                for sl, n in zip(idx, sd.shape)) if sd.shape else ()
            return np.zeros(local, dtype=sd.dtype)

        return jax.make_array_from_callback(sd.shape, sharding, cb)

    return jax.tree.map(mk, shape_tree, specs)
