"""AOT executable cache: repeat scenarios skip compile (ROADMAP item 2a).

The production-service posture: for service-sized grids the compile
wall and the per-dispatch floor — not the kernels — dominate
time-to-first-field, so the compiled chunk executable is treated as a
first-class ARTIFACT, separable from the scenario spec it was compiled
for and from the state pytree it runs on (the three-object split of
``docs/SERVICE.md``; SNIPPETS.md's pjit shard/gather-fn pattern is the
template). Every chunk compile in the repo routes through
:func:`get_or_compile`, keyed by a canonical :class:`ExecKey`:

* **in-process layer** — a bounded digest -> ``jax.stages.Compiled``
  map: a second ``Simulation`` with an identical key performs ZERO
  traces (counter-asserted in tests/test_exec_cache.py);
* **on-disk layer** (``FDTD3D_AOT_CACHE_DIR``) — executables
  serialized via ``jax.experimental.serialize_executable`` (the AOT
  ``compile()`` product), published atomically (io.atomic_open), meta
  sidecar last so a half-written entry can never read as committed.
  A corrupt/truncated/stale-provenance entry is a NAMED miss, never a
  crash — the compile just happens again.

The key is deliberately WIDE: grid/scheme/dtype, the engaged step
kind + its tile + temporal-block ghost depth, topology + the planned
communication strategy, the health/per-chip telemetry lanes, the
donation posture, the batch width, argument avals, and jax+git
provenance, plus a hash of the full physics config. A collision would
silently reuse the wrong physics, so every axis that changes the
compiled graph is in the key; per-scenario VALUES (material
coefficient arrays, source amplitudes, the state itself) are traced
arguments and deliberately NOT in it — that separation is what makes
the cache useful.

Cache hit/miss counters surface in telemetry ``run_start``
(``aot_cache``) and ``run_end`` (``aot_cache`` + ``compile_ms``);
``FDTD3D_AOT_CACHE=0`` switches the whole layer off.

Trust note: the on-disk payload is a pickle (the same class of
artifact as jax's own persistent compilation cache) — point
``FDTD3D_AOT_CACHE_DIR`` only at directories you trust.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import time
from typing import Any, Callable, Dict, Optional, Tuple

from fdtd3d_tpu import log as _log

# bump when the on-disk payload layout changes: old entries then read
# as named stale-provenance misses instead of unpickling garbage
DISK_FORMAT = 1

# In-process layer bound: compiled executables are small (programs,
# not buffers) but a long test session builds hundreds of distinct
# keys; FIFO-evict beyond this. Sims keep their own reference
# (sim._compiled), so eviction never invalidates a live run.
MEM_CAP = 64


def enabled() -> bool:
    """The whole cache layer's off-switch: ``FDTD3D_AOT_CACHE=0`` (or
    ``off``/``no``) disables both layers — every compile then behaves
    exactly as the pre-cache build (still counted in the stats)."""
    return os.environ.get("FDTD3D_AOT_CACHE", "").lower() \
        not in ("0", "off", "no")


def cache_dir() -> Optional[str]:
    """On-disk layer root (``FDTD3D_AOT_CACHE_DIR``); None = memory
    only."""
    return os.environ.get("FDTD3D_AOT_CACHE_DIR") or None


# --------------------------------------------------------------------------
# the key
# --------------------------------------------------------------------------


def config_fingerprint(cfg) -> str:
    """Canonical hash of the PHYSICS configuration — everything that
    can change the traced graph except the axes the key carries
    explicitly. ``output`` (telemetry paths, cadences — the health/
    per-chip lanes are explicit key fields), ``time_steps`` (the chunk
    length ``n_steps`` is the compiled quantity) and ``require_pallas``
    (a constructor guard, not graph state) are excluded; everything
    else — sources, TFSF angles, PML grading, material STRUCTURE,
    courant factor — is in. Material/source VALUES that are traced
    arguments (coefficient arrays) still land in the fingerprint via
    cfg; that only narrows sharing, never corrupts it."""
    d = dataclasses.asdict(cfg)
    for k in ("output", "time_steps", "require_pallas"):
        d.pop(k, None)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def avals_fingerprint(*trees) -> str:
    """Hash of the (path-ordered) shapes+dtypes of the executable's
    argument pytrees — the defense-in-depth axis: a compiled artifact
    must never be invoked on avals it was not compiled for, even if
    every config-level key field collides."""
    import jax

    parts = []
    for tree in trees:
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            shape = tuple(getattr(leaf, "shape", ()) or ())
            dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
            parts.append(f"{jax.tree_util.keystr(path)}:{shape}:{dtype}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


@dataclasses.dataclass(frozen=True)
class ExecKey:
    """Canonical identity of one compiled chunk executable.

    Two runs with equal keys may share the artifact; ANY axis that
    changes the compiled graph must appear here (a collision = wrong
    physics silently reused — tests/test_exec_cache.py asserts the
    comm-strategy / ghost-depth / health-lane axes separate)."""

    scheme: str
    grid: Tuple[int, int, int]
    dtype: str
    step_kind: str
    tile: Optional[str]              # canonical json of step_diag tile
    ghost_depth: Optional[int]       # temporal-block pipeline depth k
    topology: Tuple[int, int, int]
    comm_strategy: Optional[str]     # canonical json of the record
    n_steps: int                     # compiled chunk length
    health: bool                     # in-graph health counters wired
    per_chip: bool                   # per-chip telemetry lane wired
    batch: int                       # vmap lanes (0 = unbatched)
    backend: str                     # jax backend / AOT topology tag
    donate: bool                     # carry-donation posture
    jax_version: str
    git_sha: str
    config_fp: str                   # config_fingerprint(cfg)
    avals_fp: str                    # avals_fingerprint(args)
    # The mesh's device ids, in mesh order (None = the backend's
    # default placement). A compiled executable is DEVICE-PINNED: two
    # sims on the same topology but different device subsets (a
    # fleet/supervisor factory avoiding a faulted chip) must never
    # share one.
    devices: Optional[Tuple[int, ...]] = None

    def record(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["grid"] = list(self.grid)
        d["topology"] = list(self.topology)
        d["devices"] = list(self.devices) if self.devices else None
        return d

    @property
    def digest(self) -> str:
        blob = json.dumps(self.record(), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()

    @property
    def comparable_digest(self) -> str:
        """Digest WITHOUT the jax/git provenance axes: equal across
        commits whenever nothing graph-shaping changed. A provenance
        bump legitimately invalidates the CACHE entry (the full
        digest), but must not excuse a compile-TIME regression —
        tools/perf_sentinel.py's compile lane gates cold compile_ms
        "at equal key" using this form."""
        rec = self.record()
        for k in ("jax_version", "git_sha"):
            rec.pop(k, None)
        blob = json.dumps(rec, sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()


def registry_identity(key: ExecKey) -> Dict[str, Any]:
    """The run registry's executable-identity block
    (fdtd3d_tpu/registry.py): the provenance-free
    :attr:`ExecKey.comparable_digest` (stable across commits when
    nothing graph-shaping changed — the axis fleet_report and the SLO
    compile-budget rule join runs on) plus the human-readable axes a
    fleet table prints. Computed at the ``n_steps=0`` sentinel so two
    runs of one scenario share the digest regardless of chunking."""
    return {
        "exec_key_comparable": key.comparable_digest,
        "config_fp": key.config_fp,
        "step_kind": key.step_kind,
        "ghost_depth": key.ghost_depth,
    }


def mesh_device_ids(mesh) -> Optional[Tuple[int, ...]]:
    """The key's device-identity axis from a Mesh (None for no mesh:
    unsharded runs use the backend's default placement)."""
    if mesh is None:
        return None
    import numpy as _np
    return tuple(int(d.id) for d in _np.asarray(mesh.devices).flat)


def make_key(cfg, *, step_kind: str, topology, n_steps: int,
             health: bool = False, per_chip: bool = False,
             step_diag: Optional[Dict] = None, batch: int = 0,
             backend: Optional[str] = None,
             donate: Optional[bool] = None,
             avals_fp: str = "",
             devices: Optional[Tuple[int, ...]] = None) -> ExecKey:
    """Build the canonical ExecKey for one chunk compile.

    The tile / ghost depth / comm strategy come from the ENGAGED
    step's ``step_diag`` when the caller has one (the record the
    kernel actually consumed at build wins — the telemetry run_start
    convention); otherwise they are derived deterministically from the
    planner (plan.comm_strategy re-scores for the pinned kind, so an
    ``FDTD3D_COMM_STRATEGY``/``FDTD3D_TB_DEPTH`` override lands in the
    key even before any kernel is built)."""
    import jax

    from fdtd3d_tpu import telemetry as _telemetry

    topology = tuple(int(p) for p in topology)
    diag = step_diag or {}
    tile = diag.get("tile")
    depth = diag.get("temporal_block")
    if depth is None and step_kind == "pallas_packed_tb":
        from fdtd3d_tpu import solver as _solver
        from fdtd3d_tpu.ops import pallas_packed_tb
        static = dataclasses.replace(_solver.build_static(cfg),
                                     topology=topology)
        depth = pallas_packed_tb.planned_depth(static)
    strat = diag.get("comm_strategy")
    if strat is None and any(p > 1 for p in topology):
        from fdtd3d_tpu import plan as _plan
        s = _plan.comm_strategy(cfg, topology, step_kind=step_kind)
        strat = s.as_record() if s is not None else None
    if backend is None:
        backend = jax.default_backend()
    if donate is None:
        donate = backend in ("tpu", "axon")
    return ExecKey(
        scheme=cfg.scheme, grid=tuple(cfg.grid_shape), dtype=cfg.dtype,
        step_kind=step_kind,
        tile=json.dumps(tile, sort_keys=True) if tile else None,
        ghost_depth=int(depth) if depth is not None else None,
        topology=topology,
        comm_strategy=json.dumps(strat, sort_keys=True)
        if strat else None,
        n_steps=int(n_steps), health=bool(health),
        per_chip=bool(per_chip), batch=int(batch), backend=str(backend),
        donate=bool(donate), jax_version=jax.__version__,
        git_sha=_telemetry.git_sha(),
        config_fp=config_fingerprint(cfg), avals_fp=avals_fp,
        devices=tuple(int(d) for d in devices) if devices else None)


# --------------------------------------------------------------------------
# stats (surfaced in telemetry run_start/run_end `aot_cache`)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class CacheStats:
    hits: int = 0            # in-process layer hits
    misses: int = 0          # neither layer had it
    disk_hits: int = 0       # deserialized from FDTD3D_AOT_CACHE_DIR
    disk_load_failures: int = 0   # corrupt/stale entries read as misses
    traces: int = 0          # lower() calls actually performed
    compiles: int = 0        # compile() calls actually performed
    compile_ms: float = 0.0  # wall spent in lower+compile


STATS = CacheStats()


def stats() -> Dict[str, Any]:
    """Process-wide counter snapshot (JSON-ready): the assertion
    surface for the zero-trace guarantee and the ``aot_cache`` record
    telemetry run_start/run_end carry."""
    d = dataclasses.asdict(STATS)
    d["compile_ms"] = round(d["compile_ms"], 3)
    d["mem_entries"] = len(_MEM)
    d["disk_dir"] = cache_dir()
    d["enabled"] = enabled()
    return d


_MEM: Dict[str, Any] = {}


def clear_memory() -> None:
    """Drop the in-process layer (tests / bench's cold-compile stage).
    Live sims keep their own references; the disk layer is untouched."""
    _MEM.clear()


# --------------------------------------------------------------------------
# disk layer
# --------------------------------------------------------------------------


def _entry_paths(key: ExecKey) -> Tuple[str, str]:
    d = cache_dir() or ""
    dig = key.digest
    return (os.path.join(d, f"{dig}.json"),
            os.path.join(d, f"{dig}.aotx"))


def _disk_load(key: ExecKey):
    """-> Compiled or None. EVERY failure mode — missing, truncated,
    unpicklable, stale provenance, backend mismatch — is a named miss
    (warned), never an exception: a damaged cache must cost one
    recompile, not a run."""
    if cache_dir() is None:
        return None
    meta_path, bin_path = _entry_paths(key)
    if not os.path.exists(meta_path):
        return None
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except Exception as exc:
        STATS.disk_load_failures += 1
        _log.warn(f"aot cache: unreadable meta {meta_path} ({exc}); "
                  f"treating as a miss")
        return None
    # Provenance double-check (defense in depth beyond the digest): a
    # hand-copied or forged entry from another build must read as a
    # stale miss, not execute.
    for field, want in (("format", DISK_FORMAT),
                        ("jax_version", key.jax_version),
                        ("git_sha", key.git_sha),
                        ("backend", key.backend)):
        if meta.get(field) != want:
            STATS.disk_load_failures += 1
            _log.warn(f"aot cache: stale entry {meta_path} "
                      f"({field}={meta.get(field)!r} != {want!r}); "
                      f"treating as a miss")
            return None
    try:
        from jax.experimental import serialize_executable as _se
        with open(bin_path, "rb") as f:
            payload, in_tree, out_tree = pickle.loads(f.read())
        return _se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception as exc:
        STATS.disk_load_failures += 1
        _log.warn(f"aot cache: entry {bin_path} failed to load "
                  f"({type(exc).__name__}: {exc}); treating as a miss")
        return None


def _disk_store(key: ExecKey, compiled) -> None:
    """Best-effort publish (rank 0): payload first, meta sidecar LAST
    — the meta is the commit marker, so a crash mid-publish leaves an
    orphan payload the loader never consults. Serialization support
    varies by backend (abstract-AOT executables serialize; some
    interpreters do not) — an unserializable executable is a logged
    skip, never an error."""
    d = cache_dir()
    if d is None:
        return
    try:
        import jax
        if jax.process_index() != 0:
            return
        from jax.experimental import serialize_executable as _se
        payload, in_tree, out_tree = _se.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree))
    except Exception as exc:
        _log.warn(f"aot cache: executable not serializable on this "
                  f"backend ({type(exc).__name__}: {exc}); entry not "
                  f"written")
        return
    from fdtd3d_tpu.io import atomic_open
    meta_path, bin_path = _entry_paths(key)
    try:
        os.makedirs(d, exist_ok=True)
        with atomic_open(bin_path, "wb") as f:
            f.write(blob)
        meta = dict(key.record(), format=DISK_FORMAT)
        with atomic_open(meta_path, "w") as f:
            f.write(json.dumps(meta, indent=1) + "\n")
    except OSError as exc:
        _log.warn(f"aot cache: could not publish {bin_path} ({exc})")


# --------------------------------------------------------------------------
# the cache
# --------------------------------------------------------------------------


def get_or_compile(key: ExecKey, lower_fn: Callable[[], Any]
                   ) -> Tuple[Any, Dict[str, Any]]:
    """The one compile gateway: ``lower_fn()`` -> ``jax.stages.Lowered``
    is invoked ONLY on a full miss (it is the trace). Returns
    ``(compiled, info)`` with ``info`` carrying ``source`` (one of
    ``memory``/``disk``/``compiled``) and ``compile_ms`` (0.0 on any
    hit). Compile/lower failures propagate untouched — the VMEM
    fallback ladder (sim._vmem_fallback) owns them — and are never
    cached."""
    if not enabled():
        t0 = time.perf_counter()
        lowered = lower_fn()
        STATS.traces += 1
        compiled = lowered.compile()
        STATS.compiles += 1
        ms = (time.perf_counter() - t0) * 1e3
        STATS.compile_ms += ms
        return compiled, {"source": "compiled", "compile_ms": ms,
                          "digest": key.digest}
    dig = key.digest
    hit = _MEM.get(dig)
    if hit is not None:
        STATS.hits += 1
        return hit, {"source": "memory", "compile_ms": 0.0,
                     "digest": dig}
    compiled = _disk_load(key)
    if compiled is not None:
        STATS.disk_hits += 1
        _remember(dig, compiled)
        return compiled, {"source": "disk", "compile_ms": 0.0,
                          "digest": dig}
    STATS.misses += 1
    t0 = time.perf_counter()
    lowered = lower_fn()
    STATS.traces += 1
    compiled = lowered.compile()
    STATS.compiles += 1
    ms = (time.perf_counter() - t0) * 1e3
    STATS.compile_ms += ms
    _remember(dig, compiled)
    _disk_store(key, compiled)
    return compiled, {"source": "compiled", "compile_ms": ms,
                      "digest": dig}


def jit_compile(key: ExecKey, fn, args_fn, donate: bool
                ) -> Tuple[Any, Dict[str, Any]]:
    """The ONE jit+lower+compile gateway both chunk executors use
    (Simulation._chunk_fn and BatchSimulation._chunk_fn): donate-jit
    ``fn`` (argument 0 when ``donate``), then compile through the
    cache. ``args_fn()`` supplies the lower-time arguments LAZILY —
    a sim's carry may be re-packed between VMEM-ladder attempts, so
    it must be re-read at lower time, not captured at call time.
    Keeping this in one place means a new ExecKey axis or donation
    rule cannot be threaded into one executor and missed in the
    other."""
    import jax
    jitted = jax.jit(fn, donate_argnums=0 if donate else ())
    return get_or_compile(key, lambda: jitted.lower(*args_fn()))


def _remember(dig: str, compiled) -> None:
    if len(_MEM) >= MEM_CAP:
        # FIFO eviction: drop the oldest insertion (dict preserves
        # insertion order); live sims hold their own references
        _MEM.pop(next(iter(_MEM)))
    _MEM[dig] = compiled


# --------------------------------------------------------------------------
# the shared AOT build (tools/aot_overlap.py + abstract-topology compiles)
# --------------------------------------------------------------------------


class WrongStepKind(RuntimeError):
    """The AOT build engaged a different kernel than the caller
    required (``aot_compile_sharded(require_kinds=...)``) — raised
    BEFORE any lowering, so a mis-scoped config costs nothing."""


def aot_compile_sharded(cfg, topo3: Tuple[int, int, int], mesh,
                        n_steps: int, backend_tag: str,
                        require_kinds: Optional[Tuple[str, ...]] = None):
    """Compile cfg's PRODUCTION chunk runner sharded over an explicit
    ``Mesh`` (possibly of abstract AOT devices) through the cache ->
    ``(runner, compiled, info)``.

    The one AOT build both tools/aot_overlap.py and abstract-topology
    warmers share: runner construction, packed-spec inference,
    shard_map + donate-jit, lower and cached compile all live here, so
    the overlap tool measures the executable production would run —
    and its compiles warm the on-disk layer for a later real window.
    ``backend_tag`` names the target (e.g. ``"aot:v5e:2x2"``) so an
    abstract-topology entry can never collide with a runnable one."""
    import numpy as np

    import jax
    from jax.sharding import NamedSharding

    from fdtd3d_tpu.parallel import mesh as pmesh
    from fdtd3d_tpu.solver import (build_coeffs, build_static,
                                   init_state, make_chunk_runner)
    import dataclasses as _dc
    import functools as _ft

    st = _dc.replace(build_static(cfg), topology=topo3)
    mesh_axes = pmesh.mesh_axis_map(topo3)
    mesh_shape = pmesh.mesh_shape_map(topo3)
    coeffs_np = build_coeffs(st)
    state_shapes = jax.eval_shape(lambda: init_state(st))
    runner = make_chunk_runner(st, mesh_axes, mesh_shape)
    if require_kinds is not None and runner.kind not in require_kinds:
        raise WrongStepKind(
            f"step_kind {runner.kind!r}, wanted one of "
            f"{tuple(require_kinds)}")
    packed = getattr(runner, "packed", False)
    shapes = jax.eval_shape(runner.pack, state_shapes) if packed \
        else state_shapes
    specs = pmesh.packed_specs(shapes, topo3) if packed \
        else pmesh.state_specs(state_shapes, topo3)
    coeff_specs = pmesh.coeff_specs(coeffs_np, topo3)

    fn = pmesh.shard_map_compat(_ft.partial(runner, n=n_steps),
                                mesh, in_specs=(specs, coeff_specs),
                                out_specs=specs)

    def sds(shape_tree, spec_tree):
        return jax.tree.map(
            lambda s, p: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=NamedSharding(mesh, p)),
            shape_tree, spec_tree)

    coeff_shapes = jax.tree.map(
        lambda v: jax.ShapeDtypeStruct(np.shape(v),
                                       np.asarray(v).dtype),
        coeffs_np)
    args = (sds(shapes, specs), sds(coeff_shapes, coeff_specs))
    key = make_key(cfg, step_kind=runner.kind, topology=topo3,
                   n_steps=n_steps, step_diag=getattr(runner, "diag",
                                                      None),
                   backend=backend_tag, donate=True,
                   avals_fp=avals_fingerprint(*args),
                   devices=mesh_device_ids(mesh))
    jitted = jax.jit(fn, donate_argnums=0)
    compiled, info = get_or_compile(key,
                                    lambda: jitted.lower(*args))
    return runner, compiled, info
