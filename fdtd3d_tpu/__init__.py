"""fdtd3d_tpu — a TPU-native FDTD Maxwell-equations framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of the reference
C++/CUDA/MPI solver ``xj361685640/fdtd3d`` (fork of ``zer011b/fdtd3d``):
1D/2D/3D Yee-grid leapfrog E/H updates across all 13 scheme modes, CPML
absorbing boundaries, TFSF plane-wave injection, dispersive (Drude) media,
near-to-far-field transform, dump/load tooling, and spatial domain
decomposition — here via ``shard_map`` over a TPU device mesh with
``lax.ppermute`` halo exchange in place of MPI ghost-cell buffers.

Reference parity map (see SURVEY.md §2; reference paths are path-level
citations — the mount was empty during the survey):

==========================  =============================================
Reference component          This package
==========================  =============================================
Source/Settings              fdtd3d_tpu.config (+ .txt cmd-file parser)
Source/Coordinate            implicit (jnp indexing + layout offsets)
Source/Kernels (FieldValue)  jnp dtypes (f32/f64/complex)
Source/Grid/Grid             state pytree of jnp arrays
Source/Grid/ParallelGrid     fdtd3d_tpu.parallel (mesh + ppermute halo)
Source/Grid/CudaGrid         XLA TPU backend (nothing to write)
Source/Layout/YeeGridLayout  fdtd3d_tpu.layout
Source/Scheme/InternalScheme fdtd3d_tpu.solver + fdtd3d_tpu.ops
Source/Scheme/Scheme         fdtd3d_tpu.sim.Simulation
Source/File                  fdtd3d_tpu.io
Source/Physics               fdtd3d_tpu.physics
NTFF (in Source/Scheme)      fdtd3d_tpu.ntff
CallBacks (exact solutions)  fdtd3d_tpu.exact
main.cpp CLI                 fdtd3d_tpu.cli (console entry `fdtd3d`)
==========================  =============================================

Beyond the reference (docs/SERVICE.md): fdtd3d_tpu.scenario
(ScenarioSpec — the separable scenario description),
fdtd3d_tpu.exec_cache (AOT executable cache: repeat scenarios skip
compile) and fdtd3d_tpu.batch (vmap-batched multi-tenant execution,
``Simulation.run_batch`` / CLI ``--batch``).
"""

__version__ = "0.1.0"

from fdtd3d_tpu import physics  # noqa: F401
from fdtd3d_tpu.layout import SCHEME_MODES, SchemeMode, get_mode  # noqa: F401
from fdtd3d_tpu.config import SimConfig  # noqa: F401
