"""Durable-run supervisor: bounded retry, rollback, degradation ladder.

The recovery half of the durable-run layer (docs/ROBUSTNESS.md; io.py's
atomic writer + checkpoint integrity is the persistence half). WarpX-
class production FDTD/PIC codes treat restart-safe recovery as core
infrastructure (PAPERS.md, "Porting WarpX to GPU-accelerated
platforms"): long CPML runs on shared accelerators are exactly the
workloads that get preempted or hit transient device errors mid-flight.

:class:`Supervisor` wraps the ``Simulation.advance`` loop:

* **transient dispatch/runtime errors** (``RuntimeError`` — which the
  jax runtime's errors subclass — and ``OSError``) get bounded retry
  with exponential backoff. The backoff clock is injectable
  (``RetryPolicy.sleep``) so tier-1 tests run without sleeping. Before
  each retry the state is rolled back to the last good snapshot — a
  failed dispatch may have left the carry unusable.
* **health trips** (the in-graph counters' ``FloatingPointError``) roll
  back to the last COMMITTED checkpoint (or the initial in-memory
  snapshot) and resume one rung down the kernel degradation ladder:
  ``pallas_packed_tb`` -> ``pallas_packed`` -> two-pass/jnp — forced
  through the kernels' documented escape hatches (FDTD3D_NO_TEMPORAL /
  FDTD3D_NO_PACKED / use_pallas=False), pinned for the remainder of the
  supervised run.
* **topology degrade** (below the kernel ladder, and when transient
  retries on the current topology are exhausted): roll back to the
  last committed snapshot and resume on the next SMALLER decomposition
  (plan.degrade_topology), restored through the reshard-on-resume
  checkpoint path — the recovery for a lost chip or a shrunken
  preemptible allocation. Only at the UNSHARDED bottom of BOTH ladders
  does a health trip re-raise: a blow-up the single-chip jnp reference
  path reproduces is physics (Courant/Drude stability), not a kernel
  or chip fault.
* **simulated preemptions** (``faults.SimulatedPreemption``, a
  ``BaseException``) propagate untouched — a kill is a kill; the
  committed checkpoints + CLI ``--resume auto`` are the recovery path.
  The supervisor PERSISTS its recovery state (ladder pins, retry
  counters, topology rung) into every cadence snapshot
  (``Simulation.extra_ckpt_meta``), so a supervised ``--resume``
  adopts it and a preemption mid-degrade resumes DEGRADED rather than
  re-tripping the same fault.

Every recovery emits a structured telemetry record (schema v5:
``retry`` / ``rollback`` / ``degrade`` / ``topology_change``, each
stamped with the chip/host the failure was attributed to when known)
through the run's existing sink, which follows the simulation across
ladder rebuilds — one run_start/run_end span per supervised run,
summarized by tools/telemetry_report.py.

:func:`run_with_retry` is the stage-shaped flavor of the same bounded
retry: bench.py wraps each measurement stage in it and embeds the
attempts/verdict record in the artifact, so one transient device error
no longer voids an entire bench window's JSON contract.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, Optional

from fdtd3d_tpu import faults as _faults
from fdtd3d_tpu import log as _log
from fdtd3d_tpu import telemetry as _telemetry

# Errors treated as transient (retryable): the jax runtime surfaces
# dispatch/device failures as RuntimeError subclasses (XlaRuntimeError)
# and the tunneled backends as OSError-class failures. NEVER includes
# FloatingPointError (a health trip has its own ladder path) or
# faults.SimulatedPreemption (BaseException: a kill is a kill).
TRANSIENT_ERRORS = (RuntimeError, OSError)


@dataclasses.dataclass
class RetryPolicy:
    """Bounded retry + exponential backoff, with an injectable clock.

    ``delay_s(attempt)`` for attempt = 0, 1, 2 ... is
    ``min(backoff_base_s * backoff_factor**attempt, backoff_max_s)``.
    Tier-1 fault-injection tests pass ``sleep=`` a fake so no test ever
    sleeps; production keeps ``time.sleep``."""

    max_retries: int = 3
    backoff_base_s: float = 1.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    sleep: Callable[[float], None] = time.sleep

    def delay_s(self, attempt: int) -> float:
        return min(self.backoff_base_s * self.backoff_factor ** attempt,
                   self.backoff_max_s)


def run_with_retry(fn, policy: Optional[RetryPolicy] = None,
                   label: str = "", record: Optional[Dict] = None,
                   transient=TRANSIENT_ERRORS):
    """Bounded retry around one stage-shaped callable.

    ``record`` (optional dict) is mutated IN PLACE with the verdict —
    ``{label, attempts, ok, errors}`` — so callers can embed it in an
    artifact even when the final attempt raises (bench.py does exactly
    that). Non-transient exceptions propagate immediately."""
    policy = policy or RetryPolicy()
    rec = record if record is not None else {}
    rec.update(label=label, attempts=0, ok=False, errors=[])
    while True:
        rec["attempts"] += 1
        try:
            out = fn()
            rec["ok"] = True
            return out
        except transient as exc:
            rec["errors"].append(
                f"{type(exc).__name__}: {str(exc)[:200]}")
            failed = rec["attempts"] - 1
            if failed >= policy.max_retries:
                raise
            delay = policy.delay_s(failed)
            _log.warn(f"retrying {label or 'stage'} in {delay:.1f}s "
                      f"(attempt {rec['attempts']} failed: "
                      f"{str(exc)[:120]})")
            policy.sleep(delay)


def _cfg_with_topology(cfg, topology):
    """cfg pinned to an explicit decomposition ((1,1,1) -> unsharded)."""
    from fdtd3d_tpu.config import ParallelConfig
    topo = tuple(int(p) for p in topology)
    if all(p == 1 for p in topo):
        par = ParallelConfig(topology="none")
    else:
        par = ParallelConfig(topology="manual", manual_topology=topo)
    return dataclasses.replace(cfg, parallel=par)


def degrade_plan(kind: str):
    """One rung down the kernel ladder for a sim at ``kind``.

    -> (env pins to set, cfg transform or None), or None at the bottom.
    The pins are the kernels' documented escape hatches — the same
    levers an operator would reach for by hand (docs/PERFORMANCE.md)."""
    if kind == "pallas_packed_tb":
        return {"FDTD3D_NO_TEMPORAL": "1"}, None
    if kind in ("pallas_packed", "pallas_packed_ds"):
        return {"FDTD3D_NO_PACKED": "1"}, None
    if kind == "pallas_fused":
        return {"FDTD3D_NO_FUSED": "1"}, None
    if kind == "pallas":
        return {}, lambda cfg: dataclasses.replace(cfg,
                                                   use_pallas=False)
    return None  # jnp / jnp_ds: the reference path IS the bottom


class Supervisor:
    """Owns a Simulation and drives its horizon durably.

    Either adopt a pre-built ``sim=`` (the CLI's ``--supervise`` path —
    its config must already have ``check_finite`` on, or a telemetry
    sink, so the in-graph tripwire is wired) or pass ``cfg=`` and the
    supervisor builds the sim itself with ``check_finite`` forced on.

    After :meth:`run` returns, ``self.sim`` is the CURRENT simulation —
    possibly a ladder-degraded replacement of the one it started with;
    callers must close/inspect that one, not a stale handle."""

    def __init__(self, cfg=None, policy: Optional[RetryPolicy] = None,
                 sim=None, sim_factory=None, devices=None,
                 resume_state: Optional[Dict] = None):
        if sim is None and cfg is None:
            raise ValueError("Supervisor needs a cfg or a pre-built sim")
        self.sim = sim
        self._cfg = sim.cfg if sim is not None else cfg
        if sim is None:
            # the supervisor consumes the in-graph tripwire: force it
            out = dataclasses.replace(self._cfg.output,
                                      check_finite=True)
            self._cfg = dataclasses.replace(self._cfg, output=out)
        self.policy = policy or RetryPolicy()
        self._devices = devices
        self._factory = sim_factory or self._default_factory
        self._saved_env: Dict[str, Optional[str]] = {}
        self._snapshot = None   # initial host-side state (no-ckpt runs)
        self._snapshot_topo = None  # topology it was captured under
        self.retries = 0
        self.rollbacks = 0
        self.degrades = 0
        self.topology_rung = 0
        self._heartbeat = None  # live-health emitter, built lazily
        if resume_state:
            if sim is not None:
                raise ValueError(
                    "resume_state applies before the Simulation is "
                    "built — pass cfg=, not a pre-built sim")
            self._adopt_resume_state(resume_state)

    def _default_factory(self, cfg):
        from fdtd3d_tpu.sim import Simulation
        return Simulation(cfg, self._devices)

    def _adopt_resume_state(self, rs: Dict):
        """Adopt the recovery state a previous supervised run persisted
        into its snapshots (io.read_checkpoint_meta -> "supervisor"):
        re-pin the kernel-ladder escape hatches, resume on the persisted
        (possibly degraded) topology — shrunk further if the current
        allocation is smaller — and seed the counters, so a preemption
        mid-degrade resumes degraded rather than re-tripping."""
        pins = {k: str(v) for k, v in (rs.get("env_pins") or {}).items()}
        if pins:
            self._pin_env(pins)
            _log.warn(f"supervisor: resuming with persisted "
                      f"kernel-ladder pins {sorted(pins)}")
        topo = rs.get("topology")
        if topo:
            import jax

            from fdtd3d_tpu import plan as _plan_mod
            want = tuple(int(p) for p in topo)
            have = _plan_mod.shrink_to_devices(want, jax.device_count())
            if have != want:
                _log.warn(
                    f"supervisor: persisted topology {want} does not "
                    f"fit the {jax.device_count()} available devices; "
                    f"resuming on {have} (shrunken allocation)")
            self._cfg = _cfg_with_topology(self._cfg, have)
        self.retries = int(rs.get("retries", 0))
        self.rollbacks = int(rs.get("rollbacks", 0))
        self.degrades = int(rs.get("degrades", 0))
        self.topology_rung = int(rs.get("topology_rung", 0))

    @property
    def cfg(self):
        """The EFFECTIVE config (check_finite forced; topology possibly
        overridden by a persisted resume state)."""
        return self._cfg

    def ensure_sim(self):
        """Build (once) and return the supervised Simulation — callers
        that need the sim before run() (the CLI restores checkpoints
        and wires NTFF against it) go through here so the persisted
        resume state is already applied."""
        if self.sim is None:
            self.sim = self._factory(self._cfg)
            self._persist()
        return self.sim

    # -- durable recovery state -------------------------------------------

    def state_dict(self) -> Dict:
        """The supervisor's durable recovery state: ladder pins, the
        current (possibly degraded) topology, counters. Persisted into
        every cadence snapshot via Simulation.extra_ckpt_meta so a
        supervised --resume can adopt it."""
        pins = {k: os.environ[k] for k in self._saved_env
                if k in os.environ}
        return {
            "env_pins": pins,
            "topology": (list(self.sim.topology)
                         if self.sim is not None else None),
            "step_kind": (self.sim.step_kind
                          if self.sim is not None else None),
            "retries": int(self.retries),
            "rollbacks": int(self.rollbacks),
            "degrades": int(self.degrades),
            "topology_rung": int(self.topology_rung),
        }

    def _persist(self):
        if self.sim is not None:
            self.sim.extra_ckpt_meta["supervisor"] = self.state_dict()

    # -- telemetry ---------------------------------------------------------

    def _emit(self, rec_type: str, **fields):
        sink = self.sim.telemetry if self.sim is not None else None
        if sink is not None:
            sink.emit(rec_type, **fields)

    def _beat(self):
        """Forced supervisor heartbeat (schema v10) at a recovery
        boundary: the watcher sees the run alive the moment it
        survives a retry/rollback/degrade, even when the run emitter's
        next chunk beat is a whole chunk away. Lazily bound to the
        CURRENT sim's telemetry path (a ladder swap replaces the sim
        but the stream path survives the swap); a strict no-op when
        FDTD3D_HEARTBEAT_S is unset or the run has no stream."""
        sink = self.sim.telemetry if self.sim is not None else None
        path = getattr(sink, "path", None)
        if self._heartbeat is None:
            self._heartbeat = _telemetry.Heartbeater.maybe(
                path, "supervisor")
        if self._heartbeat is not None:
            self._heartbeat.beat(
                t=int(self.sim._t_host),
                run_id=getattr(self.sim, "run_id", None),
                trace_id=getattr(self.sim, "trace_id", None),
                job_id=getattr(self.sim, "job_id", None), force=True)

    def _trace_span(self, name: str, t0: float,
                    attrs: Optional[Dict] = None):
        """Recovery-phase span (schema v9) beside the matching v5
        recovery record: rides the supervised sim's causal trace when
        the run belongs to a queue job (registry stamped
        sim.trace_id); a no-op everywhere else."""
        if self.sim is not None:
            _telemetry.emit_trace_span(self.sim, name, t0,
                                       float(time.time()), attrs=attrs)

    # -- recovery ----------------------------------------------------------

    def _pin_env(self, pins: Dict[str, str]):
        """Set kernel escape hatches for the REST of the supervised run
        (restored in run()'s finally) — a later VMEM-ladder rebuild of
        the degraded sim must not resurrect the kernel we just left."""
        for k, v in pins.items():
            if k not in self._saved_env:
                self._saved_env[k] = os.environ.get(k)
            os.environ[k] = v

    def _restore_env(self):
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        self._saved_env.clear()

    def _rollback(self, reason: str, t_max: int) -> str:
        """Restore the current sim to the last good state at or before
        step ``t_max`` (the failure step); returns the source (a
        checkpoint path, or 'initial-snapshot').

        The ``t_max`` guard matters when save_dir still holds
        snapshots from a PREVIOUS run: a stale ckpt at t > t_max would
        pass every metadata check (same scheme/size/topology/dtype)
        and fast-forward this run to the OLD run's state."""
        from fdtd3d_tpu import io
        sim = self.sim
        out = self._cfg.output
        if out.checkpoint_every:
            for t_ck, path in io.find_checkpoints(out.save_dir):
                if t_ck > t_max:
                    continue  # stale leftover from a previous run
                try:
                    sim.restore(path)
                    return path
                except (io.CheckpointCorrupt, ValueError) as exc:
                    _log.warn(f"supervisor: skipping unusable "
                              f"checkpoint {path}: {exc}")
        if self._snapshot is None:
            raise RuntimeError(
                f"supervisor: no rollback target for {reason} (no "
                f"committed checkpoint, no initial snapshot)")
        # the snapshot was captured under the topology of that moment;
        # adopt_state reshards it onto the CURRENT sim's plan when a
        # topology degrade happened in between
        sim.adopt_state(self._snapshot,
                        src_topology=self._snapshot_topo)
        return "initial-snapshot"

    def _host_of(self, chip: Optional[int]) -> Optional[int]:
        """Host attribution for a recovery record: the host owning the
        failing chip (contiguous chip->process mapping). None when no
        chip was implicated — an unattributed failure must read as
        null, not as 'host 0' (docs/OBSERVABILITY.md v5 semantics)."""
        if chip is None:
            return None
        try:
            import jax
            import numpy as np
            n_chips = max(int(np.prod(self.sim.topology)), 1)
            return int(chip) * int(jax.process_count()) // n_chips
        except (ImportError, RuntimeError, ValueError,
                TypeError):  # pragma: no cover - best-effort; named
            #               types so the exception-hygiene lint can
            #               prove no kill is ever swallowed here
            return None

    def _swap_sim(self, cfg):
        """Replace the supervised sim with one built on ``cfg``, moving
        the telemetry sink (ONE run_start/run_end span per supervised
        run), the run-registry handle and the metrics registry (one
        run_begin/run_final pair and one exposition per supervised
        run — the replacement is the SAME logical run), and stopping
        the old tracer. Returns the new sim; on a factory failure the
        sink is reattached to the surviving sim so the caller's
        close() still writes the run_end record."""
        from fdtd3d_tpu import registry as _registry
        old_sim = self.sim
        sink = old_sim.telemetry
        old_sim.telemetry = None
        if old_sim.tracer is not None:
            old_sim.tracer.stop()
        try:
            # suppressed: the rebuild must not append a second
            # run_begin row for the same logical run
            with _registry.suppress_registration():
                new_sim = self._factory(cfg)
        except BaseException:
            old_sim.telemetry = sink
            raise
        new_sim.telemetry = sink
        new_sim.metrics = old_sim.metrics
        _registry.transfer(old_sim, new_sim)
        return new_sim

    def _handle_trip(self, exc: FloatingPointError):
        """Health trip: rollback + one rung down the kernel ladder —
        or, below the kernel ladder, one rung down the TOPOLOGY ladder
        (a chip-attributed blow-up on the reference path points at the
        chip, not the physics, while any sharding remains to shed)."""
        old_sim = self.sim
        old_kind = old_sim.step_kind
        t_sp0 = float(time.time())
        chip = getattr(exc, "bad_chip", None)
        host = self._host_of(chip)
        plan = degrade_plan(old_kind)
        if plan is None:
            # bottom of the KERNEL ladder: next is the topology ladder
            # (raises exc at the unsharded bottom — that is physics)
            self._topology_degrade(exc, chip=chip, host=host)
            return
        pins, cfg_fn = plan
        t_failed = old_sim._t_host
        reason = f"{type(exc).__name__}: {str(exc)[:200]}"
        self._pin_env(pins)
        cfg = cfg_fn(self._cfg) if cfg_fn is not None else self._cfg
        out = dataclasses.replace(cfg.output, telemetry_path=None,
                                  metrics_path=None,
                                  profile_dir=None, check_finite=True)
        cfg = dataclasses.replace(cfg, output=out, require_pallas=False)
        # the sink follows the run across the rebuild: ONE
        # run_start/run_end span per supervised run
        new_sim = self._swap_sim(cfg)
        if new_sim.step_kind == old_kind:
            # the escape hatch had no effect (unexpected dispatch):
            # degrading again would loop at this rung forever
            old_sim.telemetry = new_sim.telemetry
            new_sim.telemetry = None
            self.sim = old_sim
            raise exc
        self._cfg = cfg
        self.sim = new_sim
        self.degrades += 1
        src = self._rollback(reason, t_failed)
        self.rollbacks += 1
        self._emit("rollback", t_failed=int(t_failed),
                   t_restored=int(self.sim._t_host), source=str(src),
                   reason=reason, chip=chip, host=host)
        self._emit("degrade", t=int(self.sim._t_host),
                   old_kind=old_kind, new_kind=new_sim.step_kind,
                   reason=reason, chip=chip, host=host)
        self._trace_span("rollback", t_sp0,
                         attrs={"t_failed": int(t_failed),
                                "t_restored": int(self.sim._t_host),
                                "source": str(src)})
        self._trace_span("degrade", t_sp0,
                         attrs={"old_kind": old_kind,
                                "new_kind": new_sim.step_kind})
        _log.warn(f"supervisor: health trip at t<={t_failed} "
                  f"({str(exc)[:120]}); rolled back to "
                  f"t={self.sim._t_host} ({src}) and degraded "
                  f"{old_kind} -> {new_sim.step_kind}")
        self._beat()
        self._persist()

    def _topology_degrade(self, exc, chip: Optional[int] = None,
                          host: Optional[int] = None):
        """Roll back and resume on the next smaller topology
        (plan.degrade_topology) via the reshard-on-resume restore path.
        Re-raises ``exc`` at the unsharded bottom."""
        from fdtd3d_tpu import plan as _plan_mod
        old_topo = tuple(self.sim.topology)
        new_topo = _plan_mod.degrade_topology(old_topo)
        if new_topo is None:
            raise exc  # unsharded bottom: nothing left to shed
        t_sp0 = float(time.time())
        t_failed = self.sim._t_host
        reason = f"{type(exc).__name__}: {str(exc)[:200]}"
        cfg = _cfg_with_topology(self._cfg, new_topo)
        out = dataclasses.replace(cfg.output, telemetry_path=None,
                                  metrics_path=None,
                                  profile_dir=None, check_finite=True)
        cfg = dataclasses.replace(cfg, output=out, require_pallas=False)
        new_sim = self._swap_sim(cfg)
        self._cfg = cfg
        self.sim = new_sim
        self.topology_rung += 1
        src = self._rollback(reason, t_failed)  # restore reshards
        self.rollbacks += 1
        self._emit("rollback", t_failed=int(t_failed),
                   t_restored=int(self.sim._t_host), source=str(src),
                   reason=reason, chip=chip, host=host)
        self._emit("topology_change", t=int(self.sim._t_host),
                   old_topology=list(old_topo),
                   new_topology=list(new_topo), reason=reason,
                   chip=chip, host=host)
        self._trace_span("rollback", t_sp0,
                         attrs={"t_failed": int(t_failed),
                                "t_restored": int(self.sim._t_host),
                                "source": str(src)})
        self._trace_span("topology_change", t_sp0,
                         attrs={"old_topology": list(old_topo),
                                "new_topology": list(new_topo)})
        _log.warn(f"supervisor: recovery exhausted on topology "
                  f"{old_topo} at t<={t_failed}"
                  + (f" (chip {chip} implicated)"
                     if chip is not None else "")
                  + f"; rolled back to t={self.sim._t_host} ({src}) "
                  f"and degraded the topology to {new_topo}")
        self._beat()
        self._persist()

    def _handle_transient(self, exc, consec: int) -> bool:
        """Transient error: bounded retry with backoff + rollback.

        Returns True when the retry budget on the current topology was
        exhausted and the supervisor degraded the topology instead
        (the caller resets its consecutive-failure counter); at the
        unsharded bottom the error re-raises."""
        host = self._host_of(None)
        if consec > self.policy.max_retries:
            # retries on THIS topology are exhausted: shed a rung
            self._topology_degrade(exc, chip=None, host=host)
            return True
        t = self.sim._t_host
        t_sp0 = float(time.time())
        delay = self.policy.delay_s(consec - 1)
        reason = f"{type(exc).__name__}: {str(exc)[:200]}"
        self._emit("retry", t=int(t), attempt=int(consec),
                   delay_s=float(delay), error=reason,
                   chip=None, host=host)
        _log.warn(f"supervisor: transient error at t={t} "
                  f"({str(exc)[:120]}); retry {consec}/"
                  f"{self.policy.max_retries} in {delay:.1f}s")
        self.policy.sleep(delay)
        self.retries += 1
        src = self._rollback(reason, t)
        self.rollbacks += 1
        self._emit("rollback", t_failed=int(t),
                   t_restored=int(self.sim._t_host), source=str(src),
                   reason=reason, chip=None, host=host)
        self._trace_span("retry", t_sp0,
                         attrs={"attempt": int(consec),
                                "delay_s": float(delay),
                                "t_restored": int(self.sim._t_host)})
        self._beat()
        self._persist()
        return False

    # -- the loop ----------------------------------------------------------

    def run(self, time_steps: Optional[int] = None, interval: int = 0,
            on_interval: Optional[Callable] = None):
        """Advance to the horizon durably; returns the CURRENT sim.

        ``interval``/``on_interval`` mirror ``Simulation.run`` (host
        work between compiled chunks). Recovery granularity is the
        chunk: with ``interval=0`` the whole horizon is one chunk and a
        late failure rolls back to the last committed checkpoint."""
        total = (time_steps if time_steps is not None
                 else self._cfg.time_steps)
        try:
            self.ensure_sim()
            self._seed_rollback_floor()
            self._persist()
            consec = 0
            # high-water mark of on_interval callbacks: each boundary's
            # callbacks fire EXACTLY once. A rollback re-advancing
            # through already-called boundaries must not re-fire them
            # (the NTFF DFT accumulator and metrics rows would double-
            # count), and a failure that fired AFTER a boundary's
            # cadence checkpoint committed but BEFORE its callbacks ran
            # still gets them — the restored state at that boundary is
            # bit-exact, so the callback below sees what the
            # uninterrupted run would have.
            done_t = self.sim._t_host
            while self.sim._t_host < total:
                n = total - self.sim._t_host
                if interval:
                    n = min(interval, n)
                try:
                    self.sim.advance(n)
                    consec = 0
                except FloatingPointError as exc:
                    self._handle_trip(exc)
                except TRANSIENT_ERRORS as exc:
                    consec += 1
                    if self._handle_transient(exc, consec):
                        consec = 0  # fresh budget on the new topology
                if on_interval is not None and \
                        self.sim._t_host > done_t:
                    on_interval(self.sim)
                done_t = max(done_t, self.sim._t_host)
            return self.sim
        finally:
            self._restore_env()

    def _seed_rollback_floor(self):
        """Guarantee a rollback target exists before the first chunk.

        Cadence runs get a COMMITTED cadence-style checkpoint at the
        starting step (unless one at t <= start already exists) — NOT a
        host-side copy of the state: gathering the global pytree on
        every host is exactly the large-run staging cost io.py's orbax
        docstring warns about (~30 GB at 1024^3). Cadence-less runs
        keep the in-memory snapshot; if the seeding write itself fails
        transiently, fall back to that snapshot too."""
        from fdtd3d_tpu import io
        out = self._cfg.output
        if out.checkpoint_every:
            t0 = self.sim._t_host
            if any(t <= t0 for t, _p in io.find_checkpoints(
                    out.save_dir)):
                return
            try:
                self.sim.checkpoint_now()
                return
            except TRANSIENT_ERRORS as exc:
                _log.warn(f"supervisor: seeding checkpoint failed "
                          f"({exc}); keeping an in-memory snapshot")
        import jax
        import numpy as np
        from fdtd3d_tpu.parallel import distributed as pdist
        self._snapshot = jax.tree.map(
            lambda x: np.array(pdist.gather_to_host(x)),
            self.sim.state)
        # remember the layout: a later topology degrade reshards the
        # snapshot's psi leaves onto the new plan at rollback time
        self._snapshot_topo = tuple(self.sim.topology)
