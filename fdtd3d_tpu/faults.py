"""Deterministic fault-injection harness (docs/ROBUSTNESS.md §fault-plan).

The durable-run layer (io.py atomic writes, the checkpoint cadence, and
the supervisor's rollback/degrade ladder) is only trustworthy if every
recovery path is *provable* end-to-end — so faults are injected as
deterministic functions of the run itself (step counters, write
counters), never wall clock or RNG. A **fault plan** is a small ordered
list of one-shot faults parsed from a compact spec string, installed
programmatically (``install``) or via ``FDTD3D_FAULT_PLAN`` in the
environment (picked up once per process by ``Simulation.__init__``):

    nan@t=8,field=Ez; preempt@t=16; fail_write@n=2; corrupt_ckpt@n=1

Fault kinds:

``nan@t=T[,field=COMP][,chip=C][,lane=L]``
    Inject a single NaN into COMP at the first chunk boundary with
    ``t >= T`` (between compiled chunks, after the auto-checkpoint
    cadence — the snapshot at the same ``t`` stays clean). The next
    chunk's in-graph health counters trip ``FloatingPointError``.
    ``chip=C`` places the NaN at the center of chip C's shard (chip
    index = the mesh-linearized position, telemetry.PER_CHIP_KEYS
    convention) — the deterministic stand-in for one diverging/faulty
    chip in a pod, so chip-scoped recovery paths are provable.
    ``lane=L`` scopes the NaN to vmap lane L of a batched simulation
    (fdtd3d_tpu/batch.py; REQUIRED there — lanes are tenants, and the
    per-lane health isolation must be proven against a named one).
``preempt@t=T``
    Raise :class:`SimulatedPreemption` at the first chunk boundary with
    ``t >= T`` — the stand-in for a preempted TPU window / SIGKILL.
    It subclasses ``BaseException`` on purpose: generic
    ``except Exception`` recovery paths must NOT swallow it, mirroring
    a real kill.
``error@t=T[,times=K]``
    Raise :class:`InjectedTransientError` (a ``RuntimeError``) at chunk
    boundaries with ``t >= T``, K times total — the deterministic
    stand-in for a transient dispatch/runtime error the supervisor's
    bounded retry must absorb.
``fail_write@n=N[,host=H]``
    The Nth write through the atomic writer (io.atomic_open /
    io.atomic_publish, counted process-wide while a plan is active)
    raises :class:`InjectedWriteError` BEFORE publish — proving the
    target file is never half-written. ``host=H`` scopes the counter
    to writes attributed to host H (``current_host()``: the simulated
    writer installed by :func:`simulated_host`, else the real
    ``jax.process_index()``) — the Nth write BY THAT HOST fails, so
    multi-host commit protocols can lose exactly one writer.
``host_lost@n=H``
    Simulated loss of host H during a coordinated multi-writer
    checkpoint: the next time host H participates in a two-phase
    publish (io.publish_host_marker), :class:`SimulatedHostLoss` — a
    ``SimulatedPreemption``, so a ``BaseException`` — fires before its
    marker lands, leaving a PARTIAL marker set that discovery must
    treat as uncommitted.
``corrupt_ckpt@n=N[,mode=truncate|zero]``
    After the Nth *committed* checkpoint, damage it on disk (truncate
    the file / zero bytes mid-file; for an orbax directory, delete its
    COMMIT marker) — proving the integrity checks catch it and resume
    falls back to an older snapshot.
``sched_crash@job=N`` / ``sched_crash@between=acquire,dispatch``
    Kill the job-queue SCHEDULER (fdtd3d_tpu/jobqueue.py) between its
    journal writes. ``job=N``: the Nth dispatched job's run finishes,
    and the :class:`SimulatedPreemption` fires BEFORE its post-run
    journal row lands — the stand-in for the scheduler process dying
    mid-commit. The journal then still reads the job as ``running``; a
    restarted scheduler must re-drive it to a terminal state from the
    append-only journal alone (the crash-safety contract
    docs/SERVICE.md proves). ``between=acquire,dispatch`` /
    ``between=renew,commit`` instead kill the scheduler at a LEASE
    boundary: immediately after its ``lease_acquire`` (resp. first
    ``lease_renew``) row lands and before the next dispatch commits —
    the two races the fenced-lease takeover protocol must survive (a
    held-but-idle lease expires; a renewed lease dies mid-tenure).
``lease_expire@job=N``
    Turn the scheduler into a deterministic ZOMBIE from its Nth
    dispatch onward: it stops renewing its lease and stops checking
    its own expiry, so (on the injectable clock) a peer's fenced
    takeover and the fold's stale-token rejection are provable without
    sleeping — the stand-in for a paused/partitioned scheduler that
    keeps writing after its lease lapsed.

All faults are one-shot (``times`` generalizes that for ``error``), so
a rolled-back run does not re-fire them — exactly the semantics of a
real single incident.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
from typing import Dict, List, Optional

from fdtd3d_tpu import log as _log


class SimulatedPreemption(BaseException):
    """Simulated kill between chunks (fault plan ``preempt@t=T``).

    BaseException, not Exception: recovery code that catches broad
    ``Exception`` must not accidentally absorb a simulated kill — the
    point of the fault is to END the process the way a preemption
    would, leaving only committed checkpoints behind."""


class SimulatedHostLoss(SimulatedPreemption):
    """One host of a multi-writer set died mid-commit
    (fault plan ``host_lost@n=H``) — same never-swallowed semantics as
    a whole-process preemption, scoped to the lost writer."""


class InjectedTransientError(RuntimeError):
    """Deterministic stand-in for a transient dispatch/runtime error."""


class InjectedWriteError(OSError):
    """The fault plan failed this write before it was published."""


_KINDS = ("nan", "preempt", "error", "fail_write", "corrupt_ckpt",
          "host_lost", "sched_crash", "lease_expire")

# Keys each kind actually reads: a key the kind would silently ignore
# (e.g. fail_write@...,chip=1 where host= was meant) is a plan that
# "proves" a scenario that never ran — rejected as loudly as a typo.
_KIND_KEYS = {
    "nan": ("t", "field", "chip", "lane"),
    "preempt": ("t",),
    "error": ("t", "times"),
    "fail_write": ("n", "host"),
    "corrupt_ckpt": ("n", "mode"),
    "host_lost": ("n",),
    "sched_crash": ("job", "between"),
    "lease_expire": ("job",),
}

# The lease-boundary windows sched_crash@between= accepts, mapped to
# the on_lease_boundary event that arms them (the kill fires right
# after that lease row lands, before the window's second half runs).
_BETWEEN_EVENTS = {"acquire,dispatch": "acquire",
                   "renew,commit": "renew"}


@dataclasses.dataclass
class Fault:
    kind: str
    t: int = 0            # step threshold (nan / preempt / error)
    field: str = "Ez"     # target component (nan)
    n: int = 0            # ordinal (fail_write: Nth write; corrupt_ckpt:
    #                       Nth committed checkpoint; host_lost: the
    #                       lost host's id)
    times: int = 1        # firings before the fault is spent (error)
    mode: str = "truncate"  # corrupt_ckpt damage mode: truncate | zero
    chip: Optional[int] = None  # chip scope (nan): mesh-linearized id
    host: Optional[int] = None  # host scope (fail_write)
    lane: Optional[int] = None  # batch-lane scope (nan): vmap lane id
    job: Optional[int] = None   # dispatch ordinal (sched_crash /
    #                             lease_expire): the Nth job the
    #                             scheduler dispatched
    between: Optional[str] = None  # lease-boundary window
    #                             (sched_crash): a _BETWEEN_EVENTS key
    fired: int = 0        # firings so far (one-shot bookkeeping)


class FaultPlan:
    """An ordered list of one-shot faults + the process-wide counters
    the ordinal faults key on."""

    def __init__(self, faults: List[Fault]):
        self.faults = list(faults)
        self.write_count = 0   # atomic writes seen (fail_write)
        # per-host write counters (fail_write@...,host=H scopes its
        # ordinal to writes attributed to that host)
        self.write_counts: Dict[int, int] = {}
        self.ckpt_count = 0    # committed checkpoints seen (corrupt_ckpt)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """``kind@k=v,k=v; kind@...`` -> FaultPlan (docs/ROBUSTNESS.md)."""
        faults = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            kind, _, rest = entry.partition("@")
            kind = kind.strip()
            if kind not in _KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} in plan entry "
                    f"{entry!r} (valid: {', '.join(_KINDS)})")
            f = Fault(kind=kind)
            tokens = [kv.strip() for kv in rest.split(",")]
            i = 0
            while i < len(tokens):
                kv = tokens[i]
                i += 1
                if not kv:
                    continue
                key, _, val = kv.partition("=")
                key, val = key.strip(), val.strip()
                if key == "between" and i < len(tokens) \
                        and "=" not in tokens[i]:
                    # the window pair's second half was split off by
                    # the comma (between=acquire,dispatch): rejoin it
                    val = f"{val},{tokens[i]}"
                    i += 1
                if key in ("t", "n", "times", "chip", "host", "lane",
                           "job", "field", "mode", "between") \
                        and key not in _KIND_KEYS[kind]:
                    raise ValueError(
                        f"fault-plan key {key!r} does not apply to "
                        f"kind {kind!r} in {entry!r} (valid for "
                        f"{kind}: {', '.join(_KIND_KEYS[kind])})")
                if key in ("t", "n", "times", "chip", "host", "lane",
                           "job"):
                    try:
                        setattr(f, key, int(val))
                    except ValueError:
                        raise ValueError(
                            f"fault plan entry {entry!r}: {key} must be "
                            f"an integer, got {val!r}")
                elif key == "between":
                    if val not in _BETWEEN_EVENTS:
                        raise ValueError(
                            f"fault plan entry {entry!r}: between must "
                            f"be one of "
                            f"{' | '.join(sorted(_BETWEEN_EVENTS))}, "
                            f"got {val!r}")
                    f.between = val
                elif key in ("field", "mode"):
                    setattr(f, key, val)
                else:
                    raise ValueError(
                        f"unknown fault-plan key {key!r} in {entry!r} "
                        f"(valid: t, n, times, field, mode, chip, "
                        f"host, lane, job, between)")
            if f.mode not in ("truncate", "zero"):
                raise ValueError(
                    f"fault plan entry {entry!r}: mode must be "
                    f"truncate|zero, got {f.mode!r}")
            if kind == "sched_crash" and (f.job is None) \
                    == (f.between is None):
                raise ValueError(
                    f"fault plan entry {entry!r}: sched_crash needs "
                    f"exactly one of job=N or between=<window>")
            if kind == "lease_expire" and f.job is None:
                raise ValueError(
                    f"fault plan entry {entry!r}: lease_expire needs "
                    f"job=N (the dispatch ordinal the zombie window "
                    f"opens at)")
            faults.append(f)
        return cls(faults)


_PLAN: Optional[FaultPlan] = None


def install(plan) -> FaultPlan:
    """Install a plan (spec string or FaultPlan) process-wide."""
    global _PLAN
    _PLAN = FaultPlan.parse(plan) if isinstance(plan, str) else plan
    return _PLAN


def clear() -> None:
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


def load_env() -> Optional[FaultPlan]:
    """Adopt ``FDTD3D_FAULT_PLAN`` once per process (Simulation calls
    this at construction). A plan already installed wins — its fired
    flags are the record that the incident already happened; re-parsing
    the env would re-arm every fault on each new Simulation."""
    spec = os.environ.get("FDTD3D_FAULT_PLAN")
    if spec and _PLAN is None:
        install(spec)
        _log.warn(f"fault plan active (FDTD3D_FAULT_PLAN): {spec}")
    return _PLAN


# --------------------------------------------------------------------------
# host attribution (multi-writer commit simulation + host-scoped faults)
# --------------------------------------------------------------------------

# the simulated writer id installed by simulated_host(); None = use the
# real process index
_SIM_HOST: Optional[int] = None


@contextlib.contextmanager
def simulated_host(host: int):
    """Attribute everything inside the block to writer ``host``.

    The CPU-deterministic stand-in for a multi-host writer set: tier-1
    drives the coordinated-commit protocol (io.publish_host_marker /
    commit_if_complete) once per simulated host, and host-scoped faults
    (``fail_write@...,host=H``, ``host_lost@n=H``) key on this id."""
    global _SIM_HOST
    old = _SIM_HOST
    _SIM_HOST = int(host)
    try:
        yield
    finally:
        _SIM_HOST = old


def current_host() -> int:
    """The writer id faults attribute work to: the simulated host when
    one is installed, else the real ``jax.process_index()`` (0 when jax
    was never imported — this module must not initialize a backend)."""
    if _SIM_HOST is not None:
        return _SIM_HOST
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except (RuntimeError, ValueError, TypeError):
            # backend not initialized / no distributed runtime; named
            # types (not Exception) so the exception-hygiene lint can
            # prove no simulated kill is ever swallowed in this module
            pass
    return 0


# --------------------------------------------------------------------------
# hooks (each a no-op when no plan is installed)
# --------------------------------------------------------------------------


def on_write(path: str) -> None:
    """From io's atomic writers, immediately BEFORE publish: a
    fail_write fault fires here, so the target is never touched.
    Host-scoped faults count only writes attributed to their host."""
    if _PLAN is None:
        return
    _PLAN.write_count += 1
    host = current_host()
    _PLAN.write_counts[host] = _PLAN.write_counts.get(host, 0) + 1
    for f in _PLAN.faults:
        if f.kind != "fail_write" or f.fired:
            continue
        count = (_PLAN.write_counts[host] if f.host is not None
                 else _PLAN.write_count)
        if (f.host is None or f.host == host) and count == f.n:
            f.fired = 1
            scope = f" by host {host}" if f.host is not None else ""
            raise InjectedWriteError(
                f"fault plan: atomic write #{f.n}{scope} ({path}) "
                f"failed (injected)")


def on_host_publish(host: int) -> None:
    """From io.publish_host_marker, BEFORE the marker write: a
    host_lost fault kills exactly that writer mid-commit, leaving the
    two-phase marker set partial."""
    if _PLAN is None:
        return
    for f in _PLAN.faults:
        if f.kind == "host_lost" and not f.fired and f.n == host:
            f.fired = 1
            raise SimulatedHostLoss(
                f"fault plan: host {host} lost during coordinated "
                f"commit (injected)")


def on_sched_journal(job_ordinal: int) -> None:
    """From the job-queue dispatcher (fdtd3d_tpu/jobqueue.py),
    immediately BEFORE the first post-run journal write of each
    dispatched job: a ``sched_crash@job=N`` fault kills the scheduler
    right there when ``job_ordinal`` (the dispatch counter since the
    scheduler process started, 1-based; a coalesced group is ONE
    dispatch, even when its constructor rejects it and the jobs fall
    back to solo — EVERY consumed ordinal is offered here, so fault
    targeting can never silently shift) matches. The job's run (or
    failed build) already finished — the journal is left one
    transition short, which is exactly the window the
    replay-on-restart contract must cover."""
    if _PLAN is None:
        return
    for f in _PLAN.faults:
        if f.kind == "sched_crash" and not f.fired \
                and f.job == job_ordinal:
            f.fired = 1
            raise SimulatedPreemption(
                f"fault plan: scheduler crashed after dispatch "
                f"#{job_ordinal}'s run, before its journal write "
                f"(injected)")


def on_lease_boundary(event: str) -> None:
    """From the scheduler's lease plane (fdtd3d_tpu/jobqueue.py),
    immediately AFTER a lease row of kind ``event`` ("acquire" /
    "renew") landed in the journal: a ``sched_crash@between=...``
    fault whose window opens at that event kills the scheduler right
    there — the lease row is durable, the window's second half
    (dispatch / cycle commit) never runs. The journal then shows a
    held lease with zero progress behind it, which is exactly the
    tenure a peer's deadline math must expire and fence out."""
    if _PLAN is None:
        return
    for f in _PLAN.faults:
        if f.kind == "sched_crash" and not f.fired \
                and f.between is not None \
                and _BETWEEN_EVENTS[f.between] == event:
            f.fired = 1
            a, b = f.between.split(",")
            raise SimulatedPreemption(
                f"fault plan: scheduler crashed between {a} and {b} "
                f"(after its lease_{event} row landed; injected)")


def lease_zombie(dispatch_ordinal: int) -> bool:
    """From the scheduler's lease plane, once per cycle: True exactly
    once, when a ``lease_expire@job=N`` fault's dispatch ordinal is
    reached. The scheduler then flips itself into ZOMBIE mode — it
    stops renewing its lease and stops honoring its own expiry — and
    keeps dispatching, so the fold's stale-token rejection (not the
    zombie's good behavior) is what the test proves. One-shot like
    every fault; the scheduler remembers the flip itself."""
    if _PLAN is None:
        return False
    for f in _PLAN.faults:
        if f.kind == "lease_expire" and not f.fired \
                and f.job is not None and dispatch_ordinal >= f.job:
            f.fired = 1
            return True
    return False


def on_checkpoint(path: str) -> None:
    """From Simulation.checkpoint, after a snapshot COMMITTED."""
    if _PLAN is None:
        return
    _PLAN.ckpt_count += 1
    for f in _PLAN.faults:
        if f.kind == "corrupt_ckpt" and not f.fired \
                and _PLAN.ckpt_count == f.n:
            f.fired = 1
            _damage(path, f.mode)


def _damage(path: str, mode: str) -> None:
    """Deliberately corrupt a committed checkpoint on disk."""
    if os.path.isdir(path):  # orbax: un-commit it
        from fdtd3d_tpu import io  # deferred: io imports this module
        marker = os.path.join(path, io.ORBAX_COMMIT_MARKER)
        if os.path.exists(marker):
            os.remove(marker)
        _log.warn(f"fault plan: removed COMMIT marker of {path}")
        return
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        if mode == "zero":
            fh.seek(size // 2)
            fh.write(b"\0" * min(64, size - size // 2))
        else:
            fh.truncate(max(1, size // 2))
    _log.warn(f"fault plan: corrupted checkpoint {path} ({mode})")


def on_chunk_boundary(sim) -> None:
    """From Simulation.advance, after each compiled chunk (and after
    the auto-checkpoint cadence, so a snapshot at the same ``t`` is
    clean): fires nan / error / preempt faults whose step threshold has
    been reached."""
    if _PLAN is None:
        return
    t = sim._t_host
    for f in _PLAN.faults:
        if f.kind == "nan" and not f.fired and t >= f.t:
            f.fired = 1
            _inject_nan(sim, f.field, chip=f.chip, lane=f.lane)
        elif f.kind == "error" and f.fired < f.times and t >= f.t:
            f.fired += 1
            raise InjectedTransientError(
                f"fault plan: injected transient error "
                f"#{f.fired}/{f.times} at t={t}")
        elif f.kind == "preempt" and not f.fired and t >= f.t:
            f.fired = 1
            raise SimulatedPreemption(
                f"fault plan: simulated preemption at t={t}")


def _chip_center(topology, shape, chip: int):
    """Cell index at the CENTER of chip ``chip``'s shard of a
    ``shape``-sized field (chip index = mesh-linearized row-major
    position over the (x, y, z) topology — telemetry.PER_CHIP_KEYS
    convention)."""
    import numpy as np
    topo = tuple(topology)
    n_chips = int(np.prod(topo))
    if not 0 <= chip < n_chips:
        raise ValueError(
            f"fault plan: chip={chip} out of range for topology "
            f"{topo} ({n_chips} chips)")
    pos = np.unravel_index(chip, topo)
    local = tuple(s // p for s, p in zip(shape, topo))
    return tuple(p * ln + ln // 2 for p, ln in zip(pos, local))


def _inject_nan(sim, comp: str, chip: Optional[int] = None,
                lane: Optional[int] = None) -> None:
    import numpy as np
    group = "E" if comp[:1] == "E" else "H"
    cur = np.array(sim.state[group][comp])
    batch = getattr(sim, "batch_size", None)
    if batch is not None:
        # vmap-batched executor (fdtd3d_tpu/batch.py): fields carry a
        # leading lane axis, and the fault must name the tenant it
        # damages — an unscoped nan on a batch would "prove" per-lane
        # isolation a fault never exercised
        if lane is None:
            raise ValueError(
                "fault plan: nan on a batched simulation needs an "
                "explicit lane= scope (lanes are tenants; pick one)")
        if not 0 <= lane < batch:
            raise ValueError(
                f"fault plan: lane={lane} out of range for batch "
                f"of {batch}")
        # chip= composes: the NaN lands at that chip's shard center
        # WITHIN the lane (a silently-ignored scope would "prove" a
        # chip-scoped scenario that never ran — the module contract)
        tail = _chip_center(sim.topology, cur.shape[1:], chip) \
            if chip is not None \
            else tuple(s // 2 for s in cur.shape[1:])
        idx = (lane,) + tail
    elif lane is not None:
        raise ValueError(
            "fault plan: lane= scope only applies to a batched "
            "simulation (Simulation.run_batch)")
    elif chip is None:
        idx = tuple(s // 2 for s in cur.shape)
    else:
        # chip-scoped: the NaN lands at the CENTER of chip `chip`'s
        # shard, so per-chip attribution can name the faulty chip.
        idx = _chip_center(sim.topology, cur.shape, chip)
    cur[idx] = np.nan
    sim.set_field(comp, cur)
    where = f" (chip {chip}, cell {idx})" if chip is not None else \
        (f" (lane {lane}, cell {idx[1:]})" if lane is not None else "")
    _log.warn(f"fault plan: injected NaN into {comp}{where} "
              f"at t={sim._t_host}")
