"""Deterministic fault-injection harness (docs/ROBUSTNESS.md §fault-plan).

The durable-run layer (io.py atomic writes, the checkpoint cadence, and
the supervisor's rollback/degrade ladder) is only trustworthy if every
recovery path is *provable* end-to-end — so faults are injected as
deterministic functions of the run itself (step counters, write
counters), never wall clock or RNG. A **fault plan** is a small ordered
list of one-shot faults parsed from a compact spec string, installed
programmatically (``install``) or via ``FDTD3D_FAULT_PLAN`` in the
environment (picked up once per process by ``Simulation.__init__``):

    nan@t=8,field=Ez; preempt@t=16; fail_write@n=2; corrupt_ckpt@n=1

Fault kinds:

``nan@t=T[,field=COMP]``
    Inject a single NaN into COMP at the first chunk boundary with
    ``t >= T`` (between compiled chunks, after the auto-checkpoint
    cadence — the snapshot at the same ``t`` stays clean). The next
    chunk's in-graph health counters trip ``FloatingPointError``.
``preempt@t=T``
    Raise :class:`SimulatedPreemption` at the first chunk boundary with
    ``t >= T`` — the stand-in for a preempted TPU window / SIGKILL.
    It subclasses ``BaseException`` on purpose: generic
    ``except Exception`` recovery paths must NOT swallow it, mirroring
    a real kill.
``error@t=T[,times=K]``
    Raise :class:`InjectedTransientError` (a ``RuntimeError``) at chunk
    boundaries with ``t >= T``, K times total — the deterministic
    stand-in for a transient dispatch/runtime error the supervisor's
    bounded retry must absorb.
``fail_write@n=N``
    The Nth write through the atomic writer (io.atomic_open /
    io.atomic_publish, counted process-wide while a plan is active)
    raises :class:`InjectedWriteError` BEFORE publish — proving the
    target file is never half-written.
``corrupt_ckpt@n=N[,mode=truncate|zero]``
    After the Nth *committed* checkpoint, damage it on disk (truncate
    the file / zero bytes mid-file; for an orbax directory, delete its
    COMMIT marker) — proving the integrity checks catch it and resume
    falls back to an older snapshot.

All faults are one-shot (``times`` generalizes that for ``error``), so
a rolled-back run does not re-fire them — exactly the semantics of a
real single incident.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional

from fdtd3d_tpu import log as _log


class SimulatedPreemption(BaseException):
    """Simulated kill between chunks (fault plan ``preempt@t=T``).

    BaseException, not Exception: recovery code that catches broad
    ``Exception`` must not accidentally absorb a simulated kill — the
    point of the fault is to END the process the way a preemption
    would, leaving only committed checkpoints behind."""


class InjectedTransientError(RuntimeError):
    """Deterministic stand-in for a transient dispatch/runtime error."""


class InjectedWriteError(OSError):
    """The fault plan failed this write before it was published."""


_KINDS = ("nan", "preempt", "error", "fail_write", "corrupt_ckpt")


@dataclasses.dataclass
class Fault:
    kind: str
    t: int = 0            # step threshold (nan / preempt / error)
    field: str = "Ez"     # target component (nan)
    n: int = 0            # ordinal (fail_write: Nth write; corrupt_ckpt:
    #                       Nth committed checkpoint)
    times: int = 1        # firings before the fault is spent (error)
    mode: str = "truncate"  # corrupt_ckpt damage mode: truncate | zero
    fired: int = 0        # firings so far (one-shot bookkeeping)


class FaultPlan:
    """An ordered list of one-shot faults + the process-wide counters
    the ordinal faults key on."""

    def __init__(self, faults: List[Fault]):
        self.faults = list(faults)
        self.write_count = 0   # atomic writes seen (fail_write)
        self.ckpt_count = 0    # committed checkpoints seen (corrupt_ckpt)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """``kind@k=v,k=v; kind@...`` -> FaultPlan (docs/ROBUSTNESS.md)."""
        faults = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            kind, _, rest = entry.partition("@")
            kind = kind.strip()
            if kind not in _KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} in plan entry "
                    f"{entry!r} (valid: {', '.join(_KINDS)})")
            f = Fault(kind=kind)
            for kv in rest.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                key, _, val = kv.partition("=")
                key, val = key.strip(), val.strip()
                if key in ("t", "n", "times"):
                    try:
                        setattr(f, key, int(val))
                    except ValueError:
                        raise ValueError(
                            f"fault plan entry {entry!r}: {key} must be "
                            f"an integer, got {val!r}")
                elif key in ("field", "mode"):
                    setattr(f, key, val)
                else:
                    raise ValueError(
                        f"unknown fault-plan key {key!r} in {entry!r} "
                        f"(valid: t, n, times, field, mode)")
            if f.mode not in ("truncate", "zero"):
                raise ValueError(
                    f"fault plan entry {entry!r}: mode must be "
                    f"truncate|zero, got {f.mode!r}")
            faults.append(f)
        return cls(faults)


_PLAN: Optional[FaultPlan] = None


def install(plan) -> FaultPlan:
    """Install a plan (spec string or FaultPlan) process-wide."""
    global _PLAN
    _PLAN = FaultPlan.parse(plan) if isinstance(plan, str) else plan
    return _PLAN


def clear() -> None:
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


def load_env() -> Optional[FaultPlan]:
    """Adopt ``FDTD3D_FAULT_PLAN`` once per process (Simulation calls
    this at construction). A plan already installed wins — its fired
    flags are the record that the incident already happened; re-parsing
    the env would re-arm every fault on each new Simulation."""
    spec = os.environ.get("FDTD3D_FAULT_PLAN")
    if spec and _PLAN is None:
        install(spec)
        _log.warn(f"fault plan active (FDTD3D_FAULT_PLAN): {spec}")
    return _PLAN


# --------------------------------------------------------------------------
# hooks (each a no-op when no plan is installed)
# --------------------------------------------------------------------------


def on_write(path: str) -> None:
    """From io's atomic writers, immediately BEFORE publish: a
    fail_write fault fires here, so the target is never touched."""
    if _PLAN is None:
        return
    _PLAN.write_count += 1
    for f in _PLAN.faults:
        if f.kind == "fail_write" and not f.fired \
                and _PLAN.write_count == f.n:
            f.fired = 1
            raise InjectedWriteError(
                f"fault plan: atomic write #{f.n} ({path}) failed "
                f"(injected)")


def on_checkpoint(path: str) -> None:
    """From Simulation.checkpoint, after a snapshot COMMITTED."""
    if _PLAN is None:
        return
    _PLAN.ckpt_count += 1
    for f in _PLAN.faults:
        if f.kind == "corrupt_ckpt" and not f.fired \
                and _PLAN.ckpt_count == f.n:
            f.fired = 1
            _damage(path, f.mode)


def _damage(path: str, mode: str) -> None:
    """Deliberately corrupt a committed checkpoint on disk."""
    if os.path.isdir(path):  # orbax: un-commit it
        from fdtd3d_tpu import io  # deferred: io imports this module
        marker = os.path.join(path, io.ORBAX_COMMIT_MARKER)
        if os.path.exists(marker):
            os.remove(marker)
        _log.warn(f"fault plan: removed COMMIT marker of {path}")
        return
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        if mode == "zero":
            fh.seek(size // 2)
            fh.write(b"\0" * min(64, size - size // 2))
        else:
            fh.truncate(max(1, size // 2))
    _log.warn(f"fault plan: corrupted checkpoint {path} ({mode})")


def on_chunk_boundary(sim) -> None:
    """From Simulation.advance, after each compiled chunk (and after
    the auto-checkpoint cadence, so a snapshot at the same ``t`` is
    clean): fires nan / error / preempt faults whose step threshold has
    been reached."""
    if _PLAN is None:
        return
    t = sim._t_host
    for f in _PLAN.faults:
        if f.kind == "nan" and not f.fired and t >= f.t:
            f.fired = 1
            _inject_nan(sim, f.field)
        elif f.kind == "error" and f.fired < f.times and t >= f.t:
            f.fired += 1
            raise InjectedTransientError(
                f"fault plan: injected transient error "
                f"#{f.fired}/{f.times} at t={t}")
        elif f.kind == "preempt" and not f.fired and t >= f.t:
            f.fired = 1
            raise SimulatedPreemption(
                f"fault plan: simulated preemption at t={t}")


def _inject_nan(sim, comp: str) -> None:
    import numpy as np
    group = "E" if comp[:1] == "E" else "H"
    cur = np.array(sim.state[group][comp])
    idx = tuple(s // 2 for s in cur.shape)
    cur[idx] = np.nan
    sim.set_field(comp, cur)
    _log.warn(f"fault plan: injected NaN into {comp} at t={sim._t_host}")
