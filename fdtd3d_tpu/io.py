"""Grid dump/load: DAT (raw binary), TXT, BMP; full-state checkpoints.

Reference parity: ``Source/File/`` dumper/loader hierarchy (SURVEY.md §2 —
BMPDumper/BMPLoader/DATDumper/DATLoader/TXTDumper/TXTLoader + BMPHelper)
and the DAT-as-checkpoint posture of §5.4:

* DAT — bare little-endian binary of the grid values (bit-exact roundtrip;
  doubles as the material/field exchange format). A ``.manifest.json``
  sidecar records shape/dtype/step so files are self-describing without
  breaking the bare-values layout.
* TXT — human-readable ``i j k value`` lines.
* BMP — colormapped 2D cut (central slice of the first two active axes),
  written by a dependency-free 24-bit BMP encoder (the reference vendors
  EasyBMP; we need ~40 lines, SURVEY.md §7 non-goals).
* checkpoint — one ``.npz`` of the ENTIRE solver state pytree (fields,
  CPML psi, Drude J, incident line, step counter), the orbax-free
  equivalent of the reference's save->load-from-DAT resume workflow.

Durability contract (docs/ROBUSTNESS.md): EVERY file this package
writes goes through the atomic writer (``atomic_open`` /
``atomic_publish``: tmp file + fsync + ``os.replace``), so a crash
mid-write can never leave a torn artifact under the final name —
asserted structurally by tests/test_lint_atomic_write.py. Append-only
JSONL sinks (telemetry, metrics) are the one sanctioned exception:
each record is a single flushed line, and a torn tail line is
tolerated by their readers. Checkpoints additionally carry a payload
checksum + per-array manifest; readers raise :class:`CheckpointCorrupt`
(naming the path and WHICH check failed) instead of a raw numpy/zip
traceback, and orbax checkpoint directories require a COMMIT marker
written only after the save fully finished.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import struct
import time
import zipfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from fdtd3d_tpu import _native
from fdtd3d_tpu import faults as _faults
from fdtd3d_tpu import log as _log


class CheckpointCorrupt(ValueError):
    """A checkpoint failed an integrity check.

    The message names the path and WHICH check failed (zip/npz
    structure, manifest, checksum, missing COMMIT marker). Resume paths
    (CLI ``--resume auto``, the supervisor's rollback) catch this and
    fall back to an older committed snapshot."""


# ---------------------------------------------------------------------------
# atomic writer — the one durable-write primitive
# ---------------------------------------------------------------------------


def _tmp_name(path: str) -> str:
    return f"{path}.tmp.{os.getpid()}"


def _fsync_dir(path: str) -> None:
    """fsync the parent directory so the rename itself is durable."""
    try:
        fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                     os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - platform without dir fsync
        pass


def _publish_tmp(path: str, tmp: str) -> None:
    """The shared publish epilogue of both atomic primitives: fire the
    fail-the-Nth-write fault hook BEFORE the rename (the final name
    must never have been touched on an injected failure), then rename
    into place and fsync the parent directory."""
    _faults.on_write(path)
    os.replace(tmp, path)
    _fsync_dir(path)


def atomic_append(path: str, data: str) -> None:
    """Whole-record append for shared JSONL indexes (the run registry,
    ``fdtd3d_tpu/registry.py``): ONE ``os.write`` of the complete
    record to an ``O_APPEND`` descriptor, then fsync. POSIX O_APPEND
    makes each such write land contiguously, so several concurrent
    runs appending to one ``runs.jsonl`` interleave whole lines —
    never torn ones — and a crash mid-append costs at most its own
    line. (``atomic_open`` is the whole-file flavor; append-mode
    sinks must not rewrite the file they share.)"""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    buf = data.encode()
    fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
    try:
        # os.write may write fewer bytes than asked (quota, RLIMIT,
        # network filesystems) — loop, or the no-torn-lines contract
        # above is fiction exactly when the disk is misbehaving
        while buf:
            n = os.write(fd, buf)
            buf = buf[n:]
        os.fsync(fd)
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_open(path: str, mode: str = "w"):
    """Crash-safe whole-file write: tmp + flush + fsync + ``os.replace``.

    The file appears under its final name fully written or not at all;
    a crash (or an injected ``fail_write`` fault) mid-write leaves the
    previous version intact and no debris under the final name. Modes:
    'w'/'wb'/'x'/'xb' only — append-mode sinks don't rewrite and read
    modes don't write."""
    if any(c in mode for c in "ra+"):
        raise ValueError(
            f"atomic_open is for whole-file writes ('w'/'wb'/'x'), "
            f"got mode {mode!r}")
    tmp = _tmp_name(path)
    try:
        with open(tmp, mode) as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        _publish_tmp(path, tmp)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_publish(path: str, write_fn) -> None:
    """Atomic publish for writers that need a real filesystem path
    (the native C++ dumpers, ``ndarray.tofile``): ``write_fn(tmp)``
    produces the complete file, which is then fsync'd and renamed into
    place. Same crash contract as :func:`atomic_open`."""
    tmp = _tmp_name(path)
    try:
        write_fn(tmp)
        with open(tmp, "rb") as fh:
            os.fsync(fh.fileno())
        _publish_tmp(path, tmp)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise

# ---------------------------------------------------------------------------
# DAT
# ---------------------------------------------------------------------------


def dump_dat(arr: np.ndarray, path: str, step: Optional[int] = None):
    """Bare binary dump (little-endian, C order) + .manifest.json sidecar.

    Writes through the native C++ backend (native/fdtd3d_io.cpp) when
    built, matching the reference's C++ DATDumper; Python fallback emits
    byte-identical files.
    """
    arr = np.asarray(arr)
    le = arr.astype(arr.dtype.newbyteorder("<"), copy=False)

    def _write(tmp):
        if not _native.write_raw(tmp, le):
            le.tofile(tmp)

    atomic_publish(path, _write)
    # record the dtype of the bytes actually written (little-endian) —
    # recording the source dtype breaks roundtrip for big-endian input.
    manifest = {"shape": list(arr.shape), "dtype": le.dtype.str,
                "order": "C", "endian": "little"}
    if step is not None:
        manifest["step"] = int(step)
    with atomic_open(path + ".manifest.json", "w") as f:
        json.dump(manifest, f)


def load_dat(path: str, shape: Optional[Tuple[int, ...]] = None,
             dtype=None) -> np.ndarray:
    """Load a DAT dump; shape/dtype from the sidecar when not given."""
    if shape is None or dtype is None:
        with open(path + ".manifest.json") as f:
            manifest = json.load(f)
        shape = shape or tuple(manifest["shape"])
        dtype = dtype or np.dtype(manifest["dtype"])
    native = _native.read_raw(path, shape, dtype)
    if native is not None:
        return native
    return np.fromfile(path, dtype=dtype).reshape(shape)


# ---------------------------------------------------------------------------
# TXT
# ---------------------------------------------------------------------------


def dump_txt(arr: np.ndarray, path: str):
    """Reference-style human-readable dump: one ``i j k value`` per line.

    Formatted by the native backend when built (the Python nditer loop is
    ~40x slower on 3D grids); formats are identical (%.9e).
    """
    arr = np.asarray(arr)

    def _write(tmp):
        if _native.dump_txt(tmp, arr):
            return
        with open(tmp, "w") as f:
            it = np.nditer(arr, flags=["multi_index"])
            for v in it:
                idx = " ".join(str(i) for i in it.multi_index)
                if np.iscomplexobj(arr):
                    f.write(f"{idx} {v.real:.9e} {v.imag:.9e}\n")
                else:
                    f.write(f"{idx} {float(v):.9e}\n")

    atomic_publish(path, _write)


def load_txt(path: str, shape: Tuple[int, ...],
             dtype=np.float64) -> np.ndarray:
    native = _native.load_txt(path, shape, dtype)
    if native is not None:
        return native
    out = np.zeros(shape, dtype=dtype)
    nd = len(shape)
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            idx = tuple(int(p) for p in parts[:nd])
            vals = [float(p) for p in parts[nd:]]
            out[idx] = vals[0] + 1j * vals[1] if np.iscomplexobj(out) \
                else vals[0]
    return out


# ---------------------------------------------------------------------------
# BMP (dependency-free 24-bit encoder + diverging colormap)
# ---------------------------------------------------------------------------


def _bmp_encode(rgb: np.ndarray) -> bytes:
    """uint8 (H, W, 3) RGB -> 24-bit uncompressed BMP bytes."""
    h, w, _ = rgb.shape
    row = w * 3
    pad = (4 - row % 4) % 4
    body = bytearray()
    for y in range(h - 1, -1, -1):  # BMP rows bottom-up, BGR
        body += rgb[y, :, ::-1].tobytes() + b"\x00" * pad
    size = 54 + len(body)
    header = struct.pack("<2sIHHI", b"BM", size, 0, 0, 54)
    info = struct.pack("<IiiHHIIiiII", 40, w, h, 1, 24, 0, len(body),
                       2835, 2835, 0, 0)
    return bytes(header + info + body)


def colormap_diverging(v: np.ndarray) -> np.ndarray:
    """Symmetric blue-white-red map on [-max|v|, +max|v|] -> uint8 RGB."""
    v = np.asarray(v, dtype=np.float64)
    scale = np.max(np.abs(v)) or 1.0
    x = np.clip(v / scale, -1.0, 1.0)
    rgb = np.empty(v.shape + (3,), dtype=np.uint8)
    up = np.clip(1.0 + x, 0.0, 1.0)     # 0 at -1 .. 1 at >=0
    dn = np.clip(1.0 - x, 0.0, 1.0)     # 1 at <=0 .. 0 at +1
    rgb[..., 0] = np.round(255 * np.where(x >= 0, 1.0, up))
    rgb[..., 1] = np.round(255 * np.minimum(up, dn))
    rgb[..., 2] = np.round(255 * np.where(x <= 0, 1.0, dn))
    return rgb


def dump_bmp(arr: np.ndarray, path: str, active_axes=(0, 1)):
    """Central 2D cut of a rank-3 grid -> colormapped BMP.

    The cut plane is spanned by the first two active axes (for 1D modes a
    horizontal strip is emitted). Real part is shown for complex fields.
    """
    arr = np.asarray(arr)
    if np.iscomplexobj(arr):
        arr = arr.real
    axes = list(active_axes)
    if len(axes) == 0:
        axes = [0, 1]
    if len(axes) == 1:
        a = axes[0]
        line = np.moveaxis(arr, a, 0).reshape(arr.shape[a], -1)[:, 0]
        img = np.tile(line[None, :], (24, 1))
    else:
        a, b = axes[0], axes[1]
        rest = [ax for ax in range(arr.ndim) if ax not in (a, b)]
        sl = [slice(None)] * arr.ndim
        for r in rest:
            sl[r] = arr.shape[r] // 2
        cut = arr[tuple(sl)]
        if a > b:  # keep (a, b) order as (rows, cols)
            cut = cut.T
        img = cut.T  # rows = axis b (vertical), cols = axis a
    rgb = colormap_diverging(img)

    def _write(tmp):
        if _native.encode_bmp(tmp, rgb):
            return
        with open(tmp, "wb") as f:
            f.write(_bmp_encode(rgb))

    atomic_publish(path, _write)


def load_bmp_size(path: str) -> Tuple[int, int]:
    """(width, height) of a BMP file (sanity-check helper)."""
    with open(path, "rb") as f:
        head = f.read(26)
    return struct.unpack_from("<ii", head, 18)


def load_bmp(path: str) -> np.ndarray:
    """Decode a 24-bit uncompressed BMP -> uint8 (H, W, 3) RGB.

    The loader half of the reference's BMPLoader (SURVEY.md §2 File I/O
    row). Handles the standard bottom-up row order (positive height) and
    top-down (negative height) variants; anything else (palettized, RLE)
    is out of scope — the reference vendors EasyBMP for those, we only
    need the interchange subset our own dumper and common tools write.
    """
    with open(path, "rb") as f:
        data = f.read()
    if data[:2] != b"BM":
        raise ValueError(f"{path}: not a BMP file")
    (offset,) = struct.unpack_from("<I", data, 10)
    w, h = struct.unpack_from("<ii", data, 18)
    (bpp,) = struct.unpack_from("<H", data, 28)
    (compression,) = struct.unpack_from("<I", data, 30)
    if bpp != 24 or compression != 0:
        raise ValueError(
            f"{path}: only 24-bit uncompressed BMP supported "
            f"(got {bpp}bpp, compression {compression})")
    top_down = h < 0
    h = abs(h)
    row = w * 3
    stride = row + (4 - row % 4) % 4
    # Validate the header against the actual file size BEFORE indexing:
    # a truncated/corrupt file should fail with a clear message, not an
    # opaque frombuffer error (ADVICE r2).
    if w <= 0 or h <= 0:
        raise ValueError(f"{path}: bad BMP dimensions {w}x{h}")
    if offset + (h - 1) * stride + row > len(data):
        raise ValueError(
            f"{path}: truncated BMP ({len(data)} bytes; header claims "
            f"{w}x{h} 24-bit rows ending at byte "
            f"{offset + (h - 1) * stride + row})")
    out = np.empty((h, w, 3), dtype=np.uint8)
    for y in range(h):
        src = offset + y * stride
        line = np.frombuffer(data, np.uint8, row, src).reshape(w, 3)
        out[y if top_down else h - 1 - y] = line[:, ::-1]  # BGR -> RGB
    return out


def load_bmp_gray(path: str) -> np.ndarray:
    """BMP -> float64 (H, W) luminance in [0, 1] (material-init input)."""
    return load_bmp(path).mean(axis=2) / 255.0


# ---------------------------------------------------------------------------
# checkpoints (full solver state pytree)
# ---------------------------------------------------------------------------


def _flatten(prefix: str, tree, out: Dict[str, np.ndarray]):
    if isinstance(tree, dict):
        for k, v in tree.items():
            _flatten(f"{prefix}/{k}" if prefix else k, v, out)
    else:
        arr = np.asarray(tree)
        if arr.dtype.kind == "V":
            # non-native dtypes (bfloat16) hit .npz as raw void bytes and
            # cannot be cast back on load; store widened to f32 instead —
            # bf16 -> f32 is exact, and restore()'s .astype(old.dtype)
            # returns the identical bf16 bits.
            arr = arr.astype(np.float32)
        out[prefix] = arr


def _state_checksum(flat: Dict[str, np.ndarray]) -> int:
    """crc32 over every array's name + raw bytes, in sorted-key order."""
    crc = 0
    for key in sorted(flat):
        crc = zlib.crc32(key.encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(flat[key]).tobytes(), crc)
    return crc


def save_checkpoint(state, path: str, extra: Optional[Dict] = None):
    """Bit-exact .npz snapshot of the whole state pytree.

    Crash-safe: written through :func:`atomic_open` (a crash mid-write
    leaves the previous snapshot intact — an .npz under its final name
    is COMMITTED by construction). The metadata blob carries a payload
    checksum (`_checksum`) and a per-array manifest (`_manifest`) that
    :func:`load_checkpoint` verifies."""
    flat: Dict[str, np.ndarray] = {}
    _flatten("", state, flat)
    meta = dict(extra or {})
    meta["_manifest"] = {k: [list(v.shape), v.dtype.str]
                         for k, v in flat.items()}
    meta["_checksum"] = _state_checksum(flat)
    blob = json.dumps(meta)
    with atomic_open(path, "wb") as f:
        # np.savez on a file OBJECT: no implicit ".npz" suffix games,
        # and the bytes land in the atomic writer's tmp file
        np.savez(f, __meta__=np.frombuffer(
            zlib.compress(blob.encode()), dtype=np.uint8), **flat)


def load_checkpoint(path: str, verify: bool = True) -> Tuple[Dict, Dict]:
    """-> (state pytree of numpy arrays, extra metadata dict).

    Integrity: a truncated/corrupt .npz, a manifest mismatch, or a
    payload-checksum failure raises :class:`CheckpointCorrupt` naming
    the path and the failed check — never a raw numpy/zipfile
    traceback. Checkpoints written before the checksum era (no
    `_checksum`/`_manifest` keys) load without those checks."""
    flat: Dict[str, np.ndarray] = {}
    extra: Dict = {}
    try:
        with np.load(path, allow_pickle=False) as z:
            for key in z.files:
                if key == "__meta__":
                    extra = json.loads(zlib.decompress(z[key].tobytes()))
                    continue
                flat[key] = z[key]
    except (zipfile.BadZipFile, ValueError, OSError, EOFError,
            KeyError, zlib.error, json.JSONDecodeError) as exc:
        raise CheckpointCorrupt(
            f"{path}: unreadable checkpoint (npz/zip structure check "
            f"failed: {type(exc).__name__}: {exc})") from exc
    manifest = extra.pop("_manifest", None)
    checksum = extra.pop("_checksum", None)
    if verify and manifest is not None:
        want = {k: (tuple(s), d) for k, (s, d) in manifest.items()}
        got = {k: (v.shape, v.dtype.str) for k, v in flat.items()}
        if want != got:
            missing = sorted(set(want) - set(got))
            extra_k = sorted(set(got) - set(want))
            changed = sorted(k for k in set(want) & set(got)
                             if want[k] != got[k])
            raise CheckpointCorrupt(
                f"{path}: manifest check failed (missing arrays: "
                f"{missing or 'none'}; unexpected: {extra_k or 'none'}; "
                f"shape/dtype changed: {changed or 'none'})")
    if verify and checksum is not None:
        actual = _state_checksum(flat)
        if actual != checksum:
            raise CheckpointCorrupt(
                f"{path}: payload checksum check failed (stored "
                f"{checksum:#010x}, computed {actual:#010x}) — the "
                f"snapshot was damaged after it was committed")
    state: Dict = {}
    for key, arr in flat.items():
        parts = key.split("/")
        node = state
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return state, extra


def read_checkpoint_meta(path: str) -> Dict:
    """Metadata of a snapshot WITHOUT loading its arrays.

    Works on both backends (an ``.npz`` reads just the ``__meta__``
    member; a directory goes through :func:`read_orbax_meta`). The
    cheap peek resume paths use to decide HOW to resume — supervisor
    state, source topology — before any state bytes move. Integrity of
    the payload is still load_checkpoint's job."""
    if os.path.isdir(path):
        return read_orbax_meta(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            if "__meta__" not in z.files:
                return {}
            extra = json.loads(zlib.decompress(z["__meta__"].tobytes()))
    except (zipfile.BadZipFile, ValueError, OSError, EOFError,
            KeyError, zlib.error, json.JSONDecodeError) as exc:
        raise CheckpointCorrupt(
            f"{path}: unreadable checkpoint metadata "
            f"({type(exc).__name__}: {exc})") from exc
    extra.pop("_manifest", None)
    extra.pop("_checksum", None)
    return extra


# ---------------------------------------------------------------------------
# topology reshard: CPML psi slab layout conversion (reshard-on-resume)
# ---------------------------------------------------------------------------
#
# Every leaf of the state pytree is a GLOBAL array with a topology-
# independent shape — except the CPML psi recursions, whose storage is
# slab-compacted PER SHARD (solver.slab_axes: each shard keeps only the
# 2*(npml+1) boundary planes of its own axis, or the full extent when a
# shard is too thin). A snapshot is therefore topology-portable once
# its psi leaves are converted: expand the source layout to the full
# axis, then compact onto the target layout. Both directions are exact
# data movement; the compact step VALIDATES that every dropped plane is
# zero (physically guaranteed — psi is identically zero outside the
# absorbing slabs — so a non-zero drop means the snapshot and its
# declared layout disagree).

_PSI_GROUPS = ("psi_E", "psi_H", "lopsi_E", "lopsi_H")
_AXES = "xyz"


def psi_slab_expand(arr: np.ndarray, axis: int, n_global: int,
                    topo_a: int, m: Optional[int],
                    key: str = "psi") -> np.ndarray:
    """Stored psi (slab-compact or full) -> full-length global axis.

    ``m`` is the per-side slab plane count of the SOURCE layout
    (solver.slab_axes value), or None for full storage. Shard ``i`` of
    ``topo_a`` holds planes ``[i*2m, i*2m+m)`` (its local lo edge) and
    ``[i*2m+m, (i+1)*2m)`` (its local hi edge)."""
    arr = np.asarray(arr)
    if m is None:
        if arr.shape[axis] != n_global:
            raise ValueError(
                f"reshard: {key} has {arr.shape[axis]} planes along "
                f"axis {_AXES[axis]} but the declared layout is full "
                f"storage of {n_global} — snapshot and layout disagree")
        return arr
    want = 2 * m * topo_a
    if arr.shape[axis] != want:
        raise ValueError(
            f"reshard: {key} has {arr.shape[axis]} planes along axis "
            f"{_AXES[axis]} but the declared slab layout "
            f"(m={m} x {topo_a} shards) stores {want} — snapshot and "
            f"layout disagree")
    shape = list(arr.shape)
    shape[axis] = n_global
    out = np.zeros(shape, dtype=arr.dtype)
    ln = n_global // topo_a

    def _take(a, lo, hi):
        sl = [slice(None)] * a.ndim
        sl[axis] = slice(lo, hi)
        return tuple(sl)

    for i in range(topo_a):
        out[_take(out, i * ln, i * ln + m)] = \
            arr[_take(arr, i * 2 * m, i * 2 * m + m)]
        out[_take(out, (i + 1) * ln - m, (i + 1) * ln)] = \
            arr[_take(arr, i * 2 * m + m, (i + 1) * 2 * m)]
    return out


def psi_slab_compact(full: np.ndarray, axis: int, topo_a: int,
                     m: Optional[int],
                     key: str = "psi") -> np.ndarray:
    """Full-length psi -> the target layout (slab-compact or full).

    VALIDATED: planes outside every target shard's kept slabs must be
    identically zero (they are, for any state a real run produced —
    psi lives only in the global absorbing slabs, which every layout
    keeps). A non-zero drop raises instead of silently losing state."""
    full = np.asarray(full)
    if m is None:
        return full
    n_global = full.shape[axis]
    ln = n_global // topo_a
    shape = list(full.shape)
    shape[axis] = 2 * m * topo_a
    out = np.zeros(shape, dtype=full.dtype)

    def _take(a, lo, hi):
        sl = [slice(None)] * a.ndim
        sl[axis] = slice(lo, hi)
        return tuple(sl)

    kept = np.zeros(n_global, dtype=bool)
    for i in range(topo_a):
        out[_take(out, i * 2 * m, i * 2 * m + m)] = \
            full[_take(full, i * ln, i * ln + m)]
        out[_take(out, i * 2 * m + m, (i + 1) * 2 * m)] = \
            full[_take(full, (i + 1) * ln - m, (i + 1) * ln)]
        kept[i * ln:i * ln + m] = True
        kept[(i + 1) * ln - m:(i + 1) * ln] = True
    dropped = np.where(~kept)[0]
    if dropped.size:
        probe = np.take(full, dropped, axis=axis)
        if np.any(probe != 0):
            raise ValueError(
                f"reshard would drop non-zero psi planes of {key} "
                f"(axis {_AXES[axis]}, planes outside the target slab "
                f"layout m={m} x {topo_a} shards hold non-zero "
                f"recursion state) — the snapshot does not match its "
                f"declared layout; refusing a lossy reshard")
    return out


def reshard_psi_tree(state: Dict, grid_shape: Tuple[int, int, int],
                     src_topology: Tuple[int, int, int],
                     src_slabs: Dict[int, int],
                     dst_topology: Tuple[int, int, int],
                     dst_slabs: Dict[int, int]) -> Dict:
    """Convert every psi leaf of a host-side state tree between
    topologies' slab layouts (everything else passes through).

    ``src_slabs``/``dst_slabs`` map axis index -> per-side plane count
    for axes using slab storage under that topology (solver.slab_axes
    of the respective static setups). Pure numpy; returns a new tree
    sharing the non-psi leaves."""
    for label, topo in (("source", src_topology),
                        ("target", dst_topology)):
        for a in range(3):
            if topo[a] < 1 or grid_shape[a] % topo[a]:
                raise ValueError(
                    f"reshard: {label} topology {tuple(topo)} does not "
                    f"divide grid {tuple(grid_shape)} evenly on axis "
                    f"{_AXES[a]}")
    out = dict(state)
    for group in _PSI_GROUPS:
        if group not in state:
            continue
        newg = {}
        for key, arr in state[group].items():
            ax_letter = key.rsplit("_", 1)[1]
            a = _AXES.index(ax_letter)
            full = psi_slab_expand(arr, a, grid_shape[a],
                                   src_topology[a], src_slabs.get(a),
                                   key=f"{group}/{key}")
            newg[key] = psi_slab_compact(full, a, dst_topology[a],
                                         dst_slabs.get(a),
                                         key=f"{group}/{key}")
        out[group] = newg
    return out


def _import_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError as exc:  # pragma: no cover
        raise ImportError(
            "the 'orbax' checkpoint backend needs the orbax-checkpoint "
            "package (pip install orbax-checkpoint), or use the default "
            "npz backend") from exc


# ---------------------------------------------------------------------------
# coordinated commit: two-phase marker protocol for multi-writer snapshots
# ---------------------------------------------------------------------------

# A committed directory-style checkpoint carries this marker, written by
# rank 0 only after EVERY participating writer's per-host marker landed
# (phase 2 of the two-phase protocol below): a preempted/crashed save —
# of any single writer — leaves a directory without it (or with a
# partial marker set), and readers refuse the un-committed snapshot.
ORBAX_COMMIT_MARKER = "COMMIT.fdtd3d"

# Phase 1: each participating process atomically publishes its shards
# plus one of these markers (host id + expected writer count). Phase 2:
# process 0 publishes ORBAX_COMMIT_MARKER only after observing the FULL
# marker set. Discovery (find_checkpoints / commit_status) treats any
# partial set as uncommitted — skipped with a warning, never a crash.
_HOST_MARKER_RE = re.compile(r"^HOST\.(\d+)\.fdtd3d$")


def host_marker_name(host: int) -> str:
    return f"HOST.{int(host):04d}.fdtd3d"


def publish_host_marker(dirpath: str, host: int, num_writers: int):
    """Phase 1 of the coordinated commit, called by EACH writer after
    its own shards are fully written: atomically publish this host's
    marker. The ``host_lost`` / host-scoped ``fail_write`` fault hooks
    fire here, so a lost writer leaves a provably partial set."""
    _faults.on_host_publish(int(host))
    os.makedirs(dirpath, exist_ok=True)
    with atomic_open(os.path.join(dirpath, host_marker_name(host)),
                     "w") as f:
        json.dump({"host": int(host),
                   "num_writers": int(num_writers)}, f)


def commit_status(dirpath: str) -> Dict[str, Any]:
    """Commit-marker completeness of a directory snapshot.

    -> ``{"committed": bool, "markers": [host ids], "num_writers":
    Optional[int], "missing": [host ids], "legacy": bool}``.
    ``legacy`` marks a pre-two-phase directory (COMMIT marker, no host
    markers) — still committed, single-writer era. A COMMIT marker over
    an INCOMPLETE marker set does not count as committed either: the
    partial set is authoritative (a damaged/hand-rolled directory must
    never resurrect as a resume source)."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return {"committed": False, "markers": [], "num_writers": None,
                "missing": [], "legacy": False}
    markers: List[int] = []
    num_writers: Optional[int] = None
    for name in names:
        m = _HOST_MARKER_RE.match(name)
        if not m:
            continue
        host = int(m.group(1))
        markers.append(host)
        try:
            with open(os.path.join(dirpath, name)) as f:
                nw = int(json.load(f).get("num_writers", 0))
            num_writers = max(num_writers or 0, nw)
        except (OSError, ValueError, json.JSONDecodeError):
            pass  # marker content is advisory; presence is the phase-1 fact
    markers.sort()
    commit = ORBAX_COMMIT_MARKER in names
    if not markers:
        # pre-two-phase directory: COMMIT alone was the whole protocol
        return {"committed": commit, "markers": [], "num_writers": None,
                "missing": [], "legacy": commit}
    authoritative = False
    if commit:
        # the COMMIT marker's recorded writer count is authoritative:
        # a stray marker from an earlier crashed wider attempt must
        # not inflate the expected set of a smaller committed save
        try:
            with open(os.path.join(dirpath, ORBAX_COMMIT_MARKER)) as f:
                num_writers = int(json.load(f)["num_writers"])
            authoritative = True
        except (OSError, ValueError, KeyError, TypeError,
                json.JSONDecodeError):
            pass  # legacy "committed" content: fall through
    if not authoritative and (num_writers is None
                              or num_writers < len(markers)):
        num_writers = max(markers) + 1
    missing = [h for h in range(num_writers) if h not in markers]
    return {"committed": commit and not missing, "markers": markers,
            "num_writers": num_writers, "missing": missing,
            "legacy": False}


def commit_if_complete(dirpath: str, num_writers: int) -> bool:
    """Phase 2, rank 0 only: publish the COMMIT marker iff EVERY
    writer's phase-1 marker is present (a stray marker from an earlier
    crashed attempt neither helps nor hurts). Returns whether it
    committed. Reads only the marker NAMES — one listdir, no per-file
    opens: this is the poll body of save_checkpoint_orbax and must
    stay cheap on the shared filesystems pod checkpoints live on."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return False
    present = {int(m.group(1)) for m in
               (_HOST_MARKER_RE.match(n) for n in names) if m}
    want = set(range(int(num_writers)))
    if not want <= present:
        return False
    with atomic_open(os.path.join(dirpath, ORBAX_COMMIT_MARKER),
                     "w") as f:
        json.dump({"num_writers": int(num_writers),
                   "hosts": sorted(want)}, f)
    return True


def save_checkpoint_orbax(state, path: str, extra: Optional[Dict] = None,
                          commit_timeout_s: float = 600.0):
    """Sharding-aware checkpoint: every host writes ITS OWN shards.

    The TPU-native alternative to the .npz snapshot for large/multi-host
    runs — no rank-0 gather of the global state (at 1024^3 the npz path
    stages ~30 GB on one host). `path` becomes a directory; metadata
    rides a REQUIRED .meta.json sidecar. Commit is the two-phase marker
    protocol: every process publishes its per-host marker after the
    save finished, and process 0 publishes the COMMIT marker only after
    observing the full set (polling the shared filesystem up to
    ``commit_timeout_s`` — single-process runs observe it immediately,
    so tier-1 never sleeps).
    """
    import jax
    ocp = _import_orbax()
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ck:
        ck.save(path, state, force=True)
        ck.wait_until_finished()
    n_writers = jax.process_count()
    publish_host_marker(path, jax.process_index(), n_writers)
    if jax.process_index() == 0:
        # atomic publish: a preemption between checkpoint completion and
        # the sidecar write must not strand (or half-write) the metadata
        with atomic_open(path + ".meta.json", "w") as f:
            json.dump(extra or {}, f)
        # COMMIT marker LAST: its presence asserts every writer's
        # shards + markers + the sidecar
        deadline = time.monotonic() + commit_timeout_s
        while not commit_if_complete(path, n_writers):
            if time.monotonic() >= deadline:
                st = commit_status(path)
                raise CheckpointCorrupt(
                    f"{path}: coordinated commit timed out after "
                    f"{commit_timeout_s:.0f}s — hosts {st['missing']} "
                    f"never published their markers (lost writers?); "
                    f"the snapshot stays uncommitted and discovery "
                    f"will skip it")
            time.sleep(0.05)  # pragma: no cover - multi-host only


def read_orbax_meta(path: str) -> Dict:
    """Metadata of a directory checkpoint — validate BEFORE restoring.

    Requires the two-phase commit to have COMPLETED: a missing COMMIT
    marker or a partial per-host marker set raises
    :class:`CheckpointCorrupt` naming the missing writers."""
    path = os.path.abspath(path)
    st = commit_status(path)
    if not st["committed"]:
        if st["markers"] and st["missing"]:
            raise CheckpointCorrupt(
                f"{path}: partial commit-marker set — hosts "
                f"{st['missing']} of {st['num_writers']} never "
                f"published (writer lost mid-commit?); the snapshot "
                f"was never committed; use an older committed one")
        raise CheckpointCorrupt(
            f"{path}: missing {ORBAX_COMMIT_MARKER} marker — the "
            f"checkpoint was never committed (crash or preemption "
            f"mid-save?); use an older committed snapshot")
    meta_path = path + ".meta.json"
    if not os.path.exists(meta_path):
        raise CheckpointCorrupt(
            f"{path}: missing {os.path.basename(meta_path)} sidecar — "
            f"the metadata guards (scheme/size/topology) cannot be "
            f"checked; keep the sidecar next to the checkpoint directory")
    with open(meta_path) as f:
        try:
            return json.load(f)
        except json.JSONDecodeError as exc:
            raise CheckpointCorrupt(
                f"{path}: corrupt metadata sidecar "
                f"({os.path.basename(meta_path)}): {exc}") from exc


def load_checkpoint_orbax(path: str, target) -> Dict:
    """State pytree restored WITH target's shardings.

    `target` is the live state pytree (or abstract equivalents): shapes,
    dtypes and shardings to restore into — each host reads only its own
    shards. Call read_orbax_meta first and validate.
    """
    import jax
    ocp = _import_orbax()
    path = os.path.abspath(path)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding",
                                                        None)), target)
    with ocp.StandardCheckpointer() as ck:
        return ck.restore(path, abstract)


# ---------------------------------------------------------------------------
# checkpoint discovery + keep-K rotation (resume/rollback both use these)
# ---------------------------------------------------------------------------

# the cadence writer's naming scheme: ckpt_t000123.npz (npz backend) or
# the directory ckpt_t000123 (orbax backend)
_CKPT_NAME_RE = re.compile(r"^ckpt_t(\d+)(\.npz)?$")


def find_checkpoints(save_dir: str) -> List[Tuple[int, str]]:
    """COMMITTED snapshots in ``save_dir`` -> [(t, path)], newest first.

    Committed means: an ``.npz`` under its final name (the atomic
    writer never publishes a partial file), or an orbax directory
    carrying the COMMIT marker. Integrity beyond commit (checksums) is
    verified at load time — resume paths try candidates newest-first
    and fall back past a :class:`CheckpointCorrupt` one."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(save_dir)
    except OSError:
        return []
    for name in names:
        m = _CKPT_NAME_RE.match(name)
        if not m:
            continue
        path = os.path.join(save_dir, name)
        if os.path.isdir(path):
            st = commit_status(path)
            if not st["committed"]:
                if st["markers"] and st["missing"]:
                    # phase 1 started but never completed: a writer
                    # died mid-commit. Loud skip — a pod operator
                    # should learn a host was lost, not just that an
                    # older snapshot was picked.
                    _log.warn(
                        f"skipping {path}: partial commit-marker set "
                        f"(hosts {st['missing']} of "
                        f"{st['num_writers']} missing) — a writer was "
                        f"lost mid-commit; treating as uncommitted")
                continue  # never committed: crash mid-save
        elif not m.group(2):
            continue  # a FILE without .npz is not one of ours
        out.append((int(m.group(1)), path))
    out.sort(key=lambda kv: (-kv[0], kv[1]))
    return out


def find_latest_checkpoint(save_dir: str) -> Optional[str]:
    """Path of the newest COMMITTED snapshot in save_dir, or None."""
    found = find_checkpoints(save_dir)
    return found[0][1] if found else None


def prune_checkpoints(save_dir: str, keep: int,
                      t_max: Optional[int] = None) -> List[str]:
    """Keep the newest ``keep`` committed snapshots, delete the rest
    (including orbax sidecars). Returns the pruned paths.

    ``t_max`` (the cadence writer passes the current step) restricts
    the rotation to snapshots at t <= t_max: leftovers a previous
    LONGER run left in the same save_dir sort newest and would
    otherwise crowd the live run's own snapshots out of the keep-K
    window — deleting exactly the state a resume needs."""
    import shutil
    pruned: List[str] = []
    if keep <= 0:
        return pruned
    found = find_checkpoints(save_dir)
    if t_max is not None:
        found = [(t, p) for t, p in found if t <= t_max]
    for _t, path in found[keep:]:
        try:
            if os.path.isdir(path):
                shutil.rmtree(path)
                side = path + ".meta.json"
                if os.path.exists(side):
                    os.remove(side)
            else:
                os.remove(path)
            pruned.append(path)
        except OSError:
            pass  # a prune failure must never kill the run
    return pruned


# ---------------------------------------------------------------------------
# periodic output hook (Scheme's dump cadence, SURVEY.md §3.1)
# ---------------------------------------------------------------------------


def write_outputs(sim, step: int):
    """Dump every stored field component in each configured format.

    Multi-process: the gather below is COLLECTIVE (all ranks must call
    it), the file writes happen on rank 0 only.
    """
    import jax
    out = sim.cfg.output
    fields = sim.fields()            # collective allgather
    if jax.process_index() != 0:
        return
    os.makedirs(out.save_dir, exist_ok=True)
    axes = sim.static.mode.active_axes
    for comp, arr in fields.items():
        base = os.path.join(out.save_dir, f"{comp}_t{step:06d}")
        if "dat" in out.formats:
            dump_dat(arr, base + ".dat", step=step)
        if "txt" in out.formats:
            dump_txt(arr, base + ".txt")
        if "bmp" in out.formats:
            dump_bmp(arr, base + ".bmp", axes)


def write_materials(sim):
    """One-time dump of EVERY material grid (reference --save-materials).

    eps at each E component's staggered positions, mu at each H
    component's, uniform sigma_e/sigma_m, and the Drude omega_p/gamma
    grids when dispersion is on — in every configured dump format.
    """
    import jax
    if jax.process_index() != 0:     # host-side only: rank 0 writes
        return
    from fdtd3d_tpu import materials as mats
    out = sim.cfg.output
    os.makedirs(out.save_dir, exist_ok=True)
    mode = sim.static.mode
    mat = sim.cfg.materials
    shape = sim.static.grid_shape

    grids: Dict[str, np.ndarray] = {}
    for comp in mode.e_components:
        grids[f"eps_{comp}"] = mats.scalar_or_grid(
            comp, shape, mode.active_axes, mat.eps, mat.eps_sphere,
            mat.eps_file)
        if mat.use_drude:
            wp, gamma, _ = mats.drude_params(comp, shape,
                                             mode.active_axes, mat)
            grids[f"omega_p_{comp}"] = wp
            grids[f"gamma_{comp}"] = gamma
    for comp in mode.h_components:
        grids[f"mu_{comp}"] = mats.scalar_or_grid(
            comp, shape, mode.active_axes, mat.mu, mat.mu_sphere,
            mat.mu_file)
        if mat.use_drude_m:
            wpm, gm, _ = mats.drude_params(comp, shape, mode.active_axes,
                                           mat, magnetic=True)
            grids[f"omega_pm_{comp}"] = wpm
            grids[f"gamma_m_{comp}"] = gm
    grids["sigma_e"] = mat.sigma_e
    grids["sigma_m"] = mat.sigma_m

    axes = mode.active_axes
    for name, val in grids.items():
        arr = np.broadcast_to(np.asarray(val, dtype=np.float64), shape)
        base = os.path.join(out.save_dir, name)
        if "dat" in out.formats:
            dump_dat(arr, base + ".dat")
        if "txt" in out.formats:
            dump_txt(arr, base + ".txt")
        if "bmp" in out.formats:
            dump_bmp(arr, base + ".bmp", axes)
