"""Grid dump/load: DAT (raw binary), TXT, BMP; full-state checkpoints.

Reference parity: ``Source/File/`` dumper/loader hierarchy (SURVEY.md §2 —
BMPDumper/BMPLoader/DATDumper/DATLoader/TXTDumper/TXTLoader + BMPHelper)
and the DAT-as-checkpoint posture of §5.4:

* DAT — bare little-endian binary of the grid values (bit-exact roundtrip;
  doubles as the material/field exchange format). A ``.manifest.json``
  sidecar records shape/dtype/step so files are self-describing without
  breaking the bare-values layout.
* TXT — human-readable ``i j k value`` lines.
* BMP — colormapped 2D cut (central slice of the first two active axes),
  written by a dependency-free 24-bit BMP encoder (the reference vendors
  EasyBMP; we need ~40 lines, SURVEY.md §7 non-goals).
* checkpoint — one ``.npz`` of the ENTIRE solver state pytree (fields,
  CPML psi, Drude J, incident line, step counter), the orbax-free
  equivalent of the reference's save->load-from-DAT resume workflow.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from fdtd3d_tpu import _native

# ---------------------------------------------------------------------------
# DAT
# ---------------------------------------------------------------------------


def dump_dat(arr: np.ndarray, path: str, step: Optional[int] = None):
    """Bare binary dump (little-endian, C order) + .manifest.json sidecar.

    Writes through the native C++ backend (native/fdtd3d_io.cpp) when
    built, matching the reference's C++ DATDumper; Python fallback emits
    byte-identical files.
    """
    arr = np.asarray(arr)
    le = arr.astype(arr.dtype.newbyteorder("<"), copy=False)
    if not _native.write_raw(path, le):
        le.tofile(path)
    # record the dtype of the bytes actually written (little-endian) —
    # recording the source dtype breaks roundtrip for big-endian input.
    manifest = {"shape": list(arr.shape), "dtype": le.dtype.str,
                "order": "C", "endian": "little"}
    if step is not None:
        manifest["step"] = int(step)
    with open(path + ".manifest.json", "w") as f:
        json.dump(manifest, f)


def load_dat(path: str, shape: Optional[Tuple[int, ...]] = None,
             dtype=None) -> np.ndarray:
    """Load a DAT dump; shape/dtype from the sidecar when not given."""
    if shape is None or dtype is None:
        with open(path + ".manifest.json") as f:
            manifest = json.load(f)
        shape = shape or tuple(manifest["shape"])
        dtype = dtype or np.dtype(manifest["dtype"])
    native = _native.read_raw(path, shape, dtype)
    if native is not None:
        return native
    return np.fromfile(path, dtype=dtype).reshape(shape)


# ---------------------------------------------------------------------------
# TXT
# ---------------------------------------------------------------------------


def dump_txt(arr: np.ndarray, path: str):
    """Reference-style human-readable dump: one ``i j k value`` per line.

    Formatted by the native backend when built (the Python nditer loop is
    ~40x slower on 3D grids); formats are identical (%.9e).
    """
    arr = np.asarray(arr)
    if _native.dump_txt(path, arr):
        return
    with open(path, "w") as f:
        it = np.nditer(arr, flags=["multi_index"])
        for v in it:
            idx = " ".join(str(i) for i in it.multi_index)
            if np.iscomplexobj(arr):
                f.write(f"{idx} {v.real:.9e} {v.imag:.9e}\n")
            else:
                f.write(f"{idx} {float(v):.9e}\n")


def load_txt(path: str, shape: Tuple[int, ...],
             dtype=np.float64) -> np.ndarray:
    native = _native.load_txt(path, shape, dtype)
    if native is not None:
        return native
    out = np.zeros(shape, dtype=dtype)
    nd = len(shape)
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            idx = tuple(int(p) for p in parts[:nd])
            vals = [float(p) for p in parts[nd:]]
            out[idx] = vals[0] + 1j * vals[1] if np.iscomplexobj(out) \
                else vals[0]
    return out


# ---------------------------------------------------------------------------
# BMP (dependency-free 24-bit encoder + diverging colormap)
# ---------------------------------------------------------------------------


def _bmp_encode(rgb: np.ndarray) -> bytes:
    """uint8 (H, W, 3) RGB -> 24-bit uncompressed BMP bytes."""
    h, w, _ = rgb.shape
    row = w * 3
    pad = (4 - row % 4) % 4
    body = bytearray()
    for y in range(h - 1, -1, -1):  # BMP rows bottom-up, BGR
        body += rgb[y, :, ::-1].tobytes() + b"\x00" * pad
    size = 54 + len(body)
    header = struct.pack("<2sIHHI", b"BM", size, 0, 0, 54)
    info = struct.pack("<IiiHHIIiiII", 40, w, h, 1, 24, 0, len(body),
                       2835, 2835, 0, 0)
    return bytes(header + info + body)


def colormap_diverging(v: np.ndarray) -> np.ndarray:
    """Symmetric blue-white-red map on [-max|v|, +max|v|] -> uint8 RGB."""
    v = np.asarray(v, dtype=np.float64)
    scale = np.max(np.abs(v)) or 1.0
    x = np.clip(v / scale, -1.0, 1.0)
    rgb = np.empty(v.shape + (3,), dtype=np.uint8)
    up = np.clip(1.0 + x, 0.0, 1.0)     # 0 at -1 .. 1 at >=0
    dn = np.clip(1.0 - x, 0.0, 1.0)     # 1 at <=0 .. 0 at +1
    rgb[..., 0] = np.round(255 * np.where(x >= 0, 1.0, up))
    rgb[..., 1] = np.round(255 * np.minimum(up, dn))
    rgb[..., 2] = np.round(255 * np.where(x <= 0, 1.0, dn))
    return rgb


def dump_bmp(arr: np.ndarray, path: str, active_axes=(0, 1)):
    """Central 2D cut of a rank-3 grid -> colormapped BMP.

    The cut plane is spanned by the first two active axes (for 1D modes a
    horizontal strip is emitted). Real part is shown for complex fields.
    """
    arr = np.asarray(arr)
    if np.iscomplexobj(arr):
        arr = arr.real
    axes = list(active_axes)
    if len(axes) == 0:
        axes = [0, 1]
    if len(axes) == 1:
        a = axes[0]
        line = np.moveaxis(arr, a, 0).reshape(arr.shape[a], -1)[:, 0]
        img = np.tile(line[None, :], (24, 1))
    else:
        a, b = axes[0], axes[1]
        rest = [ax for ax in range(arr.ndim) if ax not in (a, b)]
        sl = [slice(None)] * arr.ndim
        for r in rest:
            sl[r] = arr.shape[r] // 2
        cut = arr[tuple(sl)]
        if a > b:  # keep (a, b) order as (rows, cols)
            cut = cut.T
        img = cut.T  # rows = axis b (vertical), cols = axis a
    rgb = colormap_diverging(img)
    if _native.encode_bmp(path, rgb):
        return
    with open(path, "wb") as f:
        f.write(_bmp_encode(rgb))


def load_bmp_size(path: str) -> Tuple[int, int]:
    """(width, height) of a BMP file (sanity-check helper)."""
    with open(path, "rb") as f:
        head = f.read(26)
    return struct.unpack_from("<ii", head, 18)


def load_bmp(path: str) -> np.ndarray:
    """Decode a 24-bit uncompressed BMP -> uint8 (H, W, 3) RGB.

    The loader half of the reference's BMPLoader (SURVEY.md §2 File I/O
    row). Handles the standard bottom-up row order (positive height) and
    top-down (negative height) variants; anything else (palettized, RLE)
    is out of scope — the reference vendors EasyBMP for those, we only
    need the interchange subset our own dumper and common tools write.
    """
    with open(path, "rb") as f:
        data = f.read()
    if data[:2] != b"BM":
        raise ValueError(f"{path}: not a BMP file")
    (offset,) = struct.unpack_from("<I", data, 10)
    w, h = struct.unpack_from("<ii", data, 18)
    (bpp,) = struct.unpack_from("<H", data, 28)
    (compression,) = struct.unpack_from("<I", data, 30)
    if bpp != 24 or compression != 0:
        raise ValueError(
            f"{path}: only 24-bit uncompressed BMP supported "
            f"(got {bpp}bpp, compression {compression})")
    top_down = h < 0
    h = abs(h)
    row = w * 3
    stride = row + (4 - row % 4) % 4
    # Validate the header against the actual file size BEFORE indexing:
    # a truncated/corrupt file should fail with a clear message, not an
    # opaque frombuffer error (ADVICE r2).
    if w <= 0 or h <= 0:
        raise ValueError(f"{path}: bad BMP dimensions {w}x{h}")
    if offset + (h - 1) * stride + row > len(data):
        raise ValueError(
            f"{path}: truncated BMP ({len(data)} bytes; header claims "
            f"{w}x{h} 24-bit rows ending at byte "
            f"{offset + (h - 1) * stride + row})")
    out = np.empty((h, w, 3), dtype=np.uint8)
    for y in range(h):
        src = offset + y * stride
        line = np.frombuffer(data, np.uint8, row, src).reshape(w, 3)
        out[y if top_down else h - 1 - y] = line[:, ::-1]  # BGR -> RGB
    return out


def load_bmp_gray(path: str) -> np.ndarray:
    """BMP -> float64 (H, W) luminance in [0, 1] (material-init input)."""
    return load_bmp(path).mean(axis=2) / 255.0


# ---------------------------------------------------------------------------
# checkpoints (full solver state pytree)
# ---------------------------------------------------------------------------


def _flatten(prefix: str, tree, out: Dict[str, np.ndarray]):
    if isinstance(tree, dict):
        for k, v in tree.items():
            _flatten(f"{prefix}/{k}" if prefix else k, v, out)
    else:
        arr = np.asarray(tree)
        if arr.dtype.kind == "V":
            # non-native dtypes (bfloat16) hit .npz as raw void bytes and
            # cannot be cast back on load; store widened to f32 instead —
            # bf16 -> f32 is exact, and restore()'s .astype(old.dtype)
            # returns the identical bf16 bits.
            arr = arr.astype(np.float32)
        out[prefix] = arr


def save_checkpoint(state, path: str, extra: Optional[Dict] = None):
    """Bit-exact .npz snapshot of the whole state pytree."""
    flat: Dict[str, np.ndarray] = {}
    _flatten("", state, flat)
    meta = json.dumps(extra or {})
    np.savez(path, __meta__=np.frombuffer(
        zlib.compress(meta.encode()), dtype=np.uint8), **flat)


def load_checkpoint(path: str) -> Tuple[Dict, Dict]:
    """-> (state pytree of numpy arrays, extra metadata dict)."""
    with np.load(path, allow_pickle=False) as z:
        extra = {}
        state: Dict = {}
        for key in z.files:
            if key == "__meta__":
                extra = json.loads(zlib.decompress(z[key].tobytes()))
                continue
            parts = key.split("/")
            node = state
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = z[key]
    return state, extra


def _import_orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError as exc:  # pragma: no cover
        raise ImportError(
            "the 'orbax' checkpoint backend needs the orbax-checkpoint "
            "package (pip install orbax-checkpoint), or use the default "
            "npz backend") from exc


def save_checkpoint_orbax(state, path: str, extra: Optional[Dict] = None):
    """Sharding-aware checkpoint: every host writes ITS OWN shards.

    The TPU-native alternative to the .npz snapshot for large/multi-host
    runs — no rank-0 gather of the global state (at 1024^3 the npz path
    stages ~30 GB on one host). `path` becomes a directory; metadata
    rides a REQUIRED .meta.json sidecar written by rank 0 (restore
    refuses a checkpoint separated from it).
    """
    import jax
    ocp = _import_orbax()
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ck:
        ck.save(path, state, force=True)
        ck.wait_until_finished()
    if jax.process_index() == 0:
        # atomic publish: a preemption between checkpoint completion and
        # the sidecar write must not strand (or half-write) the metadata
        tmp = path + ".meta.json.tmp"
        with open(tmp, "w") as f:
            json.dump(extra or {}, f)
        os.replace(tmp, path + ".meta.json")


def read_orbax_meta(path: str) -> Dict:
    """Metadata of an orbax checkpoint — validate BEFORE restoring."""
    meta_path = os.path.abspath(path) + ".meta.json"
    if not os.path.exists(meta_path):
        raise ValueError(
            f"{path}: missing {os.path.basename(meta_path)} sidecar — "
            f"the metadata guards (scheme/size/topology) cannot be "
            f"checked; keep the sidecar next to the checkpoint directory")
    with open(meta_path) as f:
        try:
            return json.load(f)
        except json.JSONDecodeError as exc:
            raise ValueError(
                f"{path}: corrupt metadata sidecar "
                f"({os.path.basename(meta_path)}): {exc}") from exc


def load_checkpoint_orbax(path: str, target) -> Dict:
    """State pytree restored WITH target's shardings.

    `target` is the live state pytree (or abstract equivalents): shapes,
    dtypes and shardings to restore into — each host reads only its own
    shards. Call read_orbax_meta first and validate.
    """
    import jax
    ocp = _import_orbax()
    path = os.path.abspath(path)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding",
                                                        None)), target)
    with ocp.StandardCheckpointer() as ck:
        return ck.restore(path, abstract)


# ---------------------------------------------------------------------------
# periodic output hook (Scheme's dump cadence, SURVEY.md §3.1)
# ---------------------------------------------------------------------------


def write_outputs(sim, step: int):
    """Dump every stored field component in each configured format.

    Multi-process: the gather below is COLLECTIVE (all ranks must call
    it), the file writes happen on rank 0 only.
    """
    import jax
    out = sim.cfg.output
    fields = sim.fields()            # collective allgather
    if jax.process_index() != 0:
        return
    os.makedirs(out.save_dir, exist_ok=True)
    axes = sim.static.mode.active_axes
    for comp, arr in fields.items():
        base = os.path.join(out.save_dir, f"{comp}_t{step:06d}")
        if "dat" in out.formats:
            dump_dat(arr, base + ".dat", step=step)
        if "txt" in out.formats:
            dump_txt(arr, base + ".txt")
        if "bmp" in out.formats:
            dump_bmp(arr, base + ".bmp", axes)


def write_materials(sim):
    """One-time dump of EVERY material grid (reference --save-materials).

    eps at each E component's staggered positions, mu at each H
    component's, uniform sigma_e/sigma_m, and the Drude omega_p/gamma
    grids when dispersion is on — in every configured dump format.
    """
    import jax
    if jax.process_index() != 0:     # host-side only: rank 0 writes
        return
    from fdtd3d_tpu import materials as mats
    out = sim.cfg.output
    os.makedirs(out.save_dir, exist_ok=True)
    mode = sim.static.mode
    mat = sim.cfg.materials
    shape = sim.static.grid_shape

    grids: Dict[str, np.ndarray] = {}
    for comp in mode.e_components:
        grids[f"eps_{comp}"] = mats.scalar_or_grid(
            comp, shape, mode.active_axes, mat.eps, mat.eps_sphere,
            mat.eps_file)
        if mat.use_drude:
            wp, gamma, _ = mats.drude_params(comp, shape,
                                             mode.active_axes, mat)
            grids[f"omega_p_{comp}"] = wp
            grids[f"gamma_{comp}"] = gamma
    for comp in mode.h_components:
        grids[f"mu_{comp}"] = mats.scalar_or_grid(
            comp, shape, mode.active_axes, mat.mu, mat.mu_sphere,
            mat.mu_file)
        if mat.use_drude_m:
            wpm, gm, _ = mats.drude_params(comp, shape, mode.active_axes,
                                           mat, magnetic=True)
            grids[f"omega_pm_{comp}"] = wpm
            grids[f"gamma_m_{comp}"] = gm
    grids["sigma_e"] = mat.sigma_e
    grids["sigma_m"] = mat.sigma_m

    axes = mode.active_axes
    for name, val in grids.items():
        arr = np.broadcast_to(np.asarray(val, dtype=np.float64), shape)
        base = os.path.join(out.save_dir, name)
        if "dat" in out.formats:
            dump_dat(arr, base + ".dat")
        if "txt" in out.formats:
            dump_txt(arr, base + ".txt")
        if "bmp" in out.formats:
            dump_bmp(arr, base + ".bmp", axes)
