"""ctypes bridge to the native C++ I/O backend (native/fdtd3d_io.cpp).

The reference's file subsystem is C++ (Source/File + EasyBMP); ours is
too — this module loads ``libfdtd3d_io.so``, building it on first use
with the in-image toolchain if needed. Every entry point returns None
gracefully when the native library is unavailable (no compiler, build
failure), and fdtd3d_tpu.io falls back to pure Python with identical
file formats.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libfdtd3d_io.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.f3d_write_raw.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                      ctypes.c_uint64]
        lib.f3d_write_raw.restype = ctypes.c_int
        lib.f3d_read_raw.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                     ctypes.c_uint64]
        lib.f3d_read_raw.restype = ctypes.c_int
        lib.f3d_dump_txt_f64.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_int]
        lib.f3d_dump_txt_f64.restype = ctypes.c_int
        lib.f3d_load_txt_f64.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
        lib.f3d_load_txt_f64.restype = ctypes.c_longlong
        lib.f3d_encode_bmp.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                       ctypes.c_int, ctypes.c_int]
        lib.f3d_encode_bmp.restype = ctypes.c_int
        _lib = lib
        return _lib


def write_raw(path: str, arr: np.ndarray) -> bool:
    lib = load()
    if lib is None:
        return False
    arr = np.ascontiguousarray(arr)
    rc = lib.f3d_write_raw(path.encode(), arr.ctypes.data,
                           arr.nbytes)
    return rc == 0


def read_raw(path: str, shape, dtype) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    out = np.empty(shape, dtype=dtype)
    rc = lib.f3d_read_raw(path.encode(), out.ctypes.data, out.nbytes)
    return out if rc == 0 else None


def dump_txt(path: str, arr: np.ndarray) -> bool:
    lib = load()
    if lib is None:
        return False
    is_complex = int(np.iscomplexobj(arr))
    data = np.ascontiguousarray(
        arr, dtype=np.complex128 if is_complex else np.float64)
    view = data.view(np.float64) if is_complex else data
    shape = (ctypes.c_uint64 * arr.ndim)(*arr.shape)
    rc = lib.f3d_dump_txt_f64(
        path.encode(), view.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double)),
        shape, arr.ndim, is_complex)
    return rc == 0


def load_txt(path: str, shape, dtype) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    is_complex = int(np.issubdtype(np.dtype(dtype), np.complexfloating))
    total = int(np.prod(shape))
    buf = np.zeros(total * (2 if is_complex else 1), dtype=np.float64)
    got = lib.f3d_load_txt_f64(
        path.encode(), buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        total, len(shape), is_complex)
    if got != total:
        return None
    if is_complex:
        return buf.view(np.complex128).reshape(shape).astype(dtype)
    return buf.reshape(shape).astype(dtype)


def encode_bmp(path: str, rgb: np.ndarray) -> bool:
    lib = load()
    if lib is None:
        return False
    rgb = np.ascontiguousarray(rgb, dtype=np.uint8)
    h, w, _ = rgb.shape
    rc = lib.f3d_encode_bmp(path.encode(),
                            rgb.ctypes.data_as(ctypes.c_char_p), h, w)
    return rc == 0
