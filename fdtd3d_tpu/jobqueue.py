"""Durable multi-tenant job queue + scheduler (ROADMAP item 2c).

The scheduling layer of the simulation service: tenants submit
scenario specs (CLI command files — the same format ``--cmd-from-file``
and ``--batch`` lanes consume), and the scheduler drives every job to
a terminal state against the substrate the previous rounds built —
the AOT executable cache + vmap batch executor (docs/SERVICE.md), the
PR 5/7 durable-run supervisor, and the run registry / OpenMetrics /
SLO observability stack (docs/OBSERVABILITY.md). The queue SCHEDULES
against that substrate; it does not rebuild any of it.

**Crash-safe journal.** All queue state is ONE append-only JSONL
journal (``<queue_dir>/journal.jsonl``), written exclusively through
:func:`fdtd3d_tpu.io.atomic_append` (one O_APPEND write per row) and
validated against the telemetry schema (v8 ``job_submit`` /
``job_state`` record types — the journal can never drift from the
toolchain that reads it). Restart = replay: :meth:`JobQueue.jobs`
folds the rows by ``job_id`` with the last status winning, so killing
the scheduler between writes (the ``sched_crash@job=N`` fault) loses
at most the transition that was about to land — the job then still
reads ``running``, and :meth:`Scheduler.serve` re-queues any job that
is ``running`` with no live dispatcher and drives it to a terminal
state (``completed`` / ``failed`` / ``cancelled``).

**Quota-aware admission.** :meth:`JobQueue.submit` enforces the
per-tenant :class:`QuotaPolicy`: ``max_queued`` bounds a tenant's
queued backlog at admission (a named :class:`QuotaError`, never a
silent drop), ``max_concurrent_cells`` bounds the device-cell
footprint a tenant may occupy at once (checked at dispatch — an
oversubscribed job defers and AGES; a job that can never fit fails
with the cap named). Priority aging: a job's effective priority is
``priority + aging x (terminal transitions recorded since it was
submitted)`` — journal-derived, so aging survives restarts and a
starved low-priority tenant eventually outranks a chatty one.

**Coalescing.** Queued jobs whose
:meth:`~fdtd3d_tpu.scenario.ScenarioSpec.batch_fingerprint` match are
dispatched as ONE ``BatchSimulation`` (vmap) group: same-shape
tenants share a single trace, one compiled executable and one halo
exchange per step — the PR 11 executor as a scheduling win. The
coalesce key is the canonical fingerprint digest; groups are capped
by ``FDTD3D_BATCH_MAX`` and the per-tenant cell quota, and a group
the batch constructor still rejects (structure divergence shapes
cannot see) falls back to solo dispatches with the reason logged.

**Placement scoring.** Jobs that ask for an automatic decomposition
(``--topology auto``) are placed by scoring every
factorization of the available device set with
``costs.halo_topology_table`` (modeled halo bytes/chip/step) and
breaking byte-ties toward the factorization whose
``plan.comm_strategy`` schedules async (overlappable exchange) —
POLAR-PIC's co-designed layout/communication framing applied at the
fleet level. Chips the run registry's straggler leaderboard keeps
convicting (the per-chunk imbalance argmax, PR 6/13) are EXCLUDED
from the pool before factorizing, and the filtered device list is
threaded into the dispatch's mesh build so a convicted chip really
hosts no shard (not merely a smaller mesh over the default devices).

**Durability of the jobs themselves.** Every solo job runs under the
:class:`~fdtd3d_tpu.supervisor.Supervisor` with a per-job
``save_dir``: a preemption (``faults.SimulatedPreemption`` — the
stand-in for a killed TPU window) re-queues the job rather than
failing it, and the re-dispatch restores the newest committed
checkpoint exactly like CLI ``--resume auto`` (adopting persisted
supervisor recovery state first), so the resumed job's final state is
bit-identical to an uninterrupted run. Coalesced groups are durable
too (round 16): every chunk boundary commits ONE whole-group snapshot
under ``<queue>/groups/<gid>/ckpt_t*.npz`` (atomic writer, newest two
kept), and a preempted group's re-dispatch restores every lane from
the newest committed one — bit-identical to an uninterrupted run,
with the resume t journaled on the re-dispatch's ``running`` rows as
``resumed_from`` (docs/SERVICE.md's recovery matrix).

**Fenced multi-scheduler leases (schema v11).** N scheduler processes
may share ONE journal: dispatch right is a single-holder lease
journaled as ``lease_acquire`` / ``lease_renew`` / ``lease_release``
rows with a monotonic fencing ``token`` (max token ever granted + 1 at
each acquire). Every ``job_state`` row a leased scheduler writes
carries its token (``fence``) and identity (``sched``:
host:pid:start — the same stamps its heartbeats carry), and the
:func:`fold` REJECTS a job_state row whose fence is staler than the
newest ``lease_acquire`` that precedes it in the journal — the classic
fenced-lock rule. The soundness argument rides the append-only order:
a new holder's acquire row necessarily lands before any of its
dispatch rows, so a zombie's write is either harmless (it landed
before any takeover — no conflicting dispatcher existed yet) or
provably stale (it landed after, bearing a smaller token). Leases
expire by deadline math (``unix + ttl_s``, FDTD3D_LEASE_TTL_S) on an
injectable clock — no sleeps anywhere in tier-1 — and are renewed once
per scheduling cycle. A dead holder's jobs are recovered by TAKEOVER:
the next acquire (a restarted peer, or ``fleet_watch --evict`` driven
by the watcher's lost verdict) carries ``takeover_from`` naming the
expired holder, and the new holder requeues its orphaned
running/preempted jobs; the per-job checkpoints and per-group
snapshots make the re-dispatch bit-identical.

**Journal compaction.** :meth:`JobQueue.compact` folds the journal
into a snapshot row-set (one submit row + one current-state row per
job, the lease lineage, live jobs' spans) published atomically as a
NEW file via ``io.atomic_open`` — ``tail.py`` consumers observe a
named rotation (inode change), never silent truncation, and re-fold to
the identical state: ``fold(compacted) == fold(original)`` is asserted
before publish (jobs, ages, lease, max fencing token all survive).
Each submit row's ``age_base`` key re-bases the priority-aging clock
so aging survives the fold. Compaction refuses while a live unexpired
lease is held by anyone — the holder is mid-tenure and O_APPEND rows
racing the rename would be lost.

Every dispatch runs inside :func:`fdtd3d_tpu.registry.job_context`,
so the run-registry row and the telemetry run_start carry the
``job_id`` — ``tools/fleet_report.py`` / ``tools/slo_gate.py`` /
``tools/telemetry_report.py`` observe the queue for free, joined by
``run_id``. The journal feeds the metrics facade (queue depth,
wait-time histogram, ``jobs_total{status,tenant}``) and the SLO
``queue-wait-p95`` rule. Operator CLI: ``tools/fdtd_queue.py``
(submit / serve / status / cancel; runbook in docs/SERVICE.md).

NOTE on catching ``SimulatedPreemption`` here: faults.py's contract is
that recovery paths must not swallow a kill. The dispatcher is not the
killed party — the JOB is (in production it runs on a different slice;
in-process the exception is the slice dying). The scheduler observing
a dead job and re-queuing it is the design, not a swallow; the
scheduler's OWN death is ``sched_crash``, raised outside any handler
here so it always propagates.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from fdtd3d_tpu import faults as _faults
from fdtd3d_tpu import log as _log
from fdtd3d_tpu import telemetry as _telemetry

QUEUE_DIR_KNOB = "FDTD3D_JOB_QUEUE_DIR"
TENANT_KNOB = "FDTD3D_QUEUE_TENANT"
LEASE_TTL_KNOB = "FDTD3D_LEASE_TTL_S"
JOURNAL_NAME = "journal.jsonl"

# the job lifecycle (journal `status` values). queued -> running ->
# {completed | failed | preempted -> queued ...}; cancel is legal from
# any non-terminal state. Every job must END in a terminal state —
# the crash-safety acceptance bar (tests/test_queue_e2e.py).
TERMINAL_STATES = ("completed", "failed", "cancelled")
JOB_STATES = ("queued", "running", "preempted") + TERMINAL_STATES


def queue_dir_env() -> Optional[str]:
    """The default queue directory (``FDTD3D_JOB_QUEUE_DIR``), or
    None — tools/fdtd_queue.py falls back to it when ``--queue-dir``
    is not passed."""
    return os.environ.get(QUEUE_DIR_KNOB) or None


def default_tenant() -> str:
    """The submitting tenant (``FDTD3D_QUEUE_TENANT``; default
    "default") — multi-tenant CI lanes export it once instead of
    passing ``--tenant`` on every submit."""
    return os.environ.get(TENANT_KNOB) or "default"


def lease_ttl_s() -> float:
    """The scheduler-lease time-to-live (``FDTD3D_LEASE_TTL_S``;
    default 30 s): a lease whose last acquire/renew row is older than
    this — on the INJECTABLE clock, never the wall clock in tier-1 —
    is expired, and a peer may take it over with a higher fencing
    token."""
    raw = os.environ.get(LEASE_TTL_KNOB, "").strip()
    if not raw:
        return 30.0
    try:
        ttl = float(raw)
    except ValueError:
        raise ValueError(
            f"{LEASE_TTL_KNOB}={raw!r}: lease TTL must be a number "
            f"of seconds") from None
    if ttl <= 0:
        raise ValueError(
            f"{LEASE_TTL_KNOB}={raw!r}: lease TTL must be > 0 (an "
            f"instantly-expired lease fences nobody)")
    return ttl


class LeaseHeld(RuntimeError):
    """Lease acquisition refused: another scheduler's lease is live
    (unreleased and unexpired on the caller's clock). Always NAMES the
    holder and its deadline — a silent wait would be a sleep, and a
    silent steal would break the fencing argument."""


@dataclasses.dataclass(frozen=True)
class SchedIdentity:
    """One scheduler process's lease identity: pid + host + start
    (the clock reading at construction) — the same stamps its
    heartbeats carry, so lease rows join liveness verdicts without a
    side table. ``sched`` is the canonical identity string every
    lease row and fenced job_state row carries."""

    pid: int
    host: str
    start: float

    @property
    def sched(self) -> str:
        return f"{self.host}:{self.pid}:{self.start:g}"

    @classmethod
    def mine(cls, now: Optional[float] = None) -> "SchedIdentity":
        return cls(pid=os.getpid(), host=socket.gethostname(),
                   start=float(time.time() if now is None else now))


class QuotaError(ValueError):
    """Admission/dispatch refused by a tenant quota — always NAMES the
    tenant and the violated bound (a silent drop would read as a lost
    job, the one thing a durable queue must never do)."""


@dataclasses.dataclass
class QuotaPolicy:
    """Per-tenant quotas + the priority-aging rate.

    ``max_queued``: queued-job cap per tenant, enforced at submit.
    ``max_concurrent_cells``: device-cell cap per tenant, enforced at
    dispatch (bounds the lanes a tenant packs into one coalesced
    batch; a solo job must fit it alone or it FAILS, named). ``aging``:
    effective-priority points per terminal transition recorded after a
    job's submit — journal-derived, so it survives restarts."""

    max_queued: int = 16
    max_concurrent_cells: Optional[float] = None
    aging: float = 1.0


def job_cells(cfg) -> float:
    """Device-cell footprint of one scenario (active-axis grid cells)
    — the quota accounting's unit, recorded on the submit row."""
    cells = 1.0
    for a in cfg.mode.active_axes:
        cells *= cfg.grid_shape[a]
    return float(cells)


def load_spec(spec_path: str):
    """Parse one scenario spec (a CLI command file) into a SimConfig.

    A malformed spec is a named ValueError at SUBMIT time — admission
    must reject what dispatch could never run, not journal it."""
    from fdtd3d_tpu import cli
    if not os.path.exists(spec_path):
        raise ValueError(f"job spec {spec_path!r}: no such file")
    parser = cli.build_parser()
    try:
        args = parser.parse_args(cli.read_cmd_file(spec_path))
    except SystemExit:
        raise ValueError(
            f"job spec {spec_path!r} does not parse as a CLI command "
            f"file (see --save-cmd-to-file)") from None
    if args.batch:
        raise ValueError(
            f"job spec {spec_path!r} contains --batch: submit each "
            f"scenario as its own job — the queue coalesces "
            f"same-shape jobs itself")
    return cli.args_to_config(args)


def coalesce_key(cfg) -> Optional[str]:
    """The coalesce-group digest: canonical JSON of the batch
    fingerprint (every graph-shaping cfg field). Equal keys = the jobs
    can share one vmap executable. None = not batchable at all (the
    documented executor limits: float32x2 / complex scenarios run
    solo, docs/SERVICE.md)."""
    if cfg.ds_fields or cfg.complex_fields:
        return None
    from fdtd3d_tpu.scenario import ScenarioSpec
    fp = ScenarioSpec(cfg).batch_fingerprint()
    blob = json.dumps(fp, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _cfg_with_topology(cfg, topology: Tuple[int, int, int]):
    """cfg pinned to an explicit decomposition ((1,1,1) -> unsharded)
    — the placement decision made executable. ONE transform for the
    whole stack: this is the supervisor's topology-degrade rung
    helper, so queue placement and degrade pinning cannot drift."""
    from fdtd3d_tpu.supervisor import _cfg_with_topology as _pin
    return _pin(cfg, topology)


# --------------------------------------------------------------------------
# placement scoring (ROADMAP item 3's first concrete step)
# --------------------------------------------------------------------------


def straggler_chips(registry_path: Optional[str],
                    threshold: int = 3) -> List[int]:
    """Chip ids the fleet keeps convicting: per-chunk imbalance-argmax
    tallies across every telemetry stream the run registry points at,
    thresholded (a chip crowned worst in >= ``threshold`` chunks).
    Empty without a registry — placement must work on day one."""
    if not registry_path or not os.path.exists(registry_path):
        return []
    from fdtd3d_tpu import registry as _registry
    tally: Dict[int, int] = {}
    try:
        runs = _registry.fold(_registry.read(registry_path))
    except (OSError, ValueError) as exc:
        _log.warn(f"jobqueue: registry {registry_path} unreadable "
                  f"({exc}); placing without straggler exclusion")
        return []
    for row in runs.values():
        tpath = _registry.resolve_artifact(registry_path,
                                           row.get("telemetry_path"))
        if tpath is None:
            continue
        try:
            recs = _telemetry.read_jsonl(tpath)
        except (OSError, ValueError):
            continue
        for rec in recs:
            if rec.get("type") == "imbalance":
                chip = int(rec["argmax"])
                tally[chip] = tally.get(chip, 0) + 1
    return sorted(c for c, n in tally.items() if n >= threshold)


def score_topology(cfg, n_devices: int,
                   exclude_chips: Tuple[int, ...] = ()
                   ) -> Tuple[Tuple[int, int, int],
                              Optional[Dict[str, Any]]]:
    """The placement decision for one job: the cheapest valid
    factorization of the usable device pool.

    Scans ``costs.halo_topology_table`` (modeled halo bytes/chip/step
    for every valid factorization) for the LARGEST device count <=
    ``n_devices - len(exclude_chips)`` that factors at all, picks the
    minimum-byte factorization, and breaks byte-ties toward the one
    whose ``plan.comm_strategy`` schedules async (an overlappable
    exchange beats an equal-byte synchronous one). Returns
    ``(topology, record)`` — record None when the pool degenerates to
    one chip (unsharded)."""
    from fdtd3d_tpu import costs as _costs
    from fdtd3d_tpu import plan as _plan
    usable = max(1, int(n_devices) - len(exclude_chips))
    for m in range(usable, 1, -1):
        table = _costs.halo_topology_table(cfg, m)
        if not table:
            continue
        best_bytes = min(table.values())
        ties = sorted(k for k, v in table.items() if v == best_bytes)
        chosen = ties[0]
        sched = None
        if len(ties) > 1:
            for key in ties:
                topo = tuple(int(x) for x in key.split("."))
                strat = _plan.comm_strategy(cfg, topo)
                if strat is not None and strat.schedule == "async":
                    chosen, sched = key, strat.schedule
                    break
        topo = tuple(int(x) for x in chosen.split("."))
        if sched is None:
            strat = _plan.comm_strategy(cfg, topo)
            sched = strat.schedule if strat is not None else None
        return topo, {
            "halo_bytes_per_chip_step": int(best_bytes),
            "n_candidates": len(table),
            "schedule": sched,
            "excluded_chips": [int(c) for c in exclude_chips],
        }
    return (1, 1, 1), None


# --------------------------------------------------------------------------
# the journal
# --------------------------------------------------------------------------


def fold(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Replay a journal's rows into its current state — THE fold every
    consumer shares (JobQueue.jobs/lease_state, compaction's identity
    assertion, the watcher's retirement rule, the status CLI).

    Returns ``{"jobs", "lease", "max_token", "stale_rejected"}``:

    * ``jobs``: job_id -> current row (the submit row's fields
      overlaid by every ACCEPTED later transition; last status wins).
      Each row carries ``age`` — the priority-aging clock: the count
      of terminal transitions journaled after its submit row, plus the
      submit row's ``age_base`` when compaction re-based it.
    * ``lease``: the current lease dict (holder identity, token,
      last acquire/renew ``unix``, ``ttl_s``, ``released``) or None
      when the journal has no lease rows. Expiry is the CALLER's
      deadline math (``unix + ttl_s`` vs its injectable clock) — the
      fold never reads a clock.
    * ``max_token``: the highest fencing token any lease_acquire ever
      granted — the threshold a new acquire must exceed.
    * ``stale_rejected``: the job_state rows the fencing rule THREW
      OUT — rows whose ``fence`` was staler than the newest
      lease_acquire preceding them (a zombie scheduler writing after
      its lease was taken over). Rows with no fence (pre-v11
      journals, or schedulers driven without serve()'s lease) are
      always accepted. Rejected rows neither change job state nor
      tick the aging clock — a double-dispatch provably cannot be
      journaled into existence.
    """
    jobs: Dict[str, Dict[str, Any]] = {}
    terminal_idx: List[int] = []
    lease: Optional[Dict[str, Any]] = None
    max_token = 0
    stale: List[Dict[str, Any]] = []
    for i, rec in enumerate(records):
        rtype = rec.get("type")
        if rtype == "lease_acquire":
            max_token = max(max_token, int(rec["token"]))
            lease = {"sched": rec["sched"], "pid": rec["pid"],
                     "host": rec["host"], "start": rec["start"],
                     "token": int(rec["token"]),
                     "unix": float(rec["unix"]),
                     "ttl_s": float(rec["ttl_s"]),
                     "released": False,
                     "takeover_from": rec.get("takeover_from")}
        elif rtype == "lease_renew":
            # a renew bearing anything but the CURRENT token is a
            # zombie's — ignored, exactly like its job_state rows
            if lease is not None and not lease["released"] \
                    and int(rec["token"]) == lease["token"]:
                lease["unix"] = float(rec["unix"])
                lease["ttl_s"] = float(rec["ttl_s"])
        elif rtype == "lease_release":
            if lease is not None \
                    and int(rec["token"]) == lease["token"]:
                lease["released"] = True
                lease["unix"] = float(rec["unix"])
        elif rtype == "job_submit":
            row = {k: v for k, v in rec.items()
                   if k not in ("v", "type")}
            row["submit_idx"] = i
            jobs[rec["job_id"]] = row
        elif rtype == "job_state":
            fence = rec.get("fence")
            if fence is not None and int(fence) < max_token:
                # the fenced-lock rule: a newer acquire precedes this
                # row in the append-only order, so its writer's lease
                # was already taken over when the row landed
                stale.append(rec)
                continue
            row = jobs.setdefault(rec["job_id"],
                                  {"job_id": rec["job_id"],
                                   "submit_idx": i})
            # `reason` rides ONE transition: a completed job must
            # not keep wearing its requeue explanation
            row.pop("reason", None)
            row.update({k: v for k, v in rec.items()
                        if k not in ("v", "type")})
            if rec["status"] in TERMINAL_STATES:
                terminal_idx.append(i)
    for row in jobs.values():
        row["age"] = int(row.get("age_base", 0)) \
            + sum(1 for i in terminal_idx
                  if i > row.get("submit_idx", 0))
    return {"jobs": jobs, "lease": lease, "max_token": max_token,
            "stale_rejected": stale}


def lease_deadline(lease: Optional[Dict[str, Any]]
                   ) -> Optional[float]:
    """The epoch second a folded lease expires at (None when the
    journal has no lease) — callers compare against THEIR clock."""
    if lease is None:
        return None
    return float(lease["unix"]) + float(lease["ttl_s"])


class JobQueue:
    """The durable queue: one directory, one append-only journal.

    ``metrics`` (a :class:`fdtd3d_tpu.metrics.MetricsRegistry`)
    observes every journal row AFTER validation — the exposition's
    queue-depth gauge / wait histogram / jobs_total counters can never
    see a row the journal contract would reject. An existing journal
    is replayed into it at construction, so a restarted scheduler's
    exposition carries the cumulative fleet state."""

    def __init__(self, dirpath: str, metrics=None):
        self.dirpath = os.path.abspath(dirpath)
        self.journal = os.path.join(self.dirpath, JOURNAL_NAME)
        self.metrics = metrics
        if metrics is not None and os.path.exists(self.journal):
            for rec in self.read():
                metrics.observe_record(rec)

    # -- rows ---------------------------------------------------------------

    def _emit(self, rec_type: str, **fields) -> Dict[str, Any]:
        from fdtd3d_tpu import io as _io
        rec = {"v": _telemetry.SCHEMA_VERSION, "type": rec_type,
               **fields}
        _telemetry.validate_record(rec)
        _io.atomic_append(self.journal, json.dumps(rec) + "\n")
        if self.metrics is not None:
            self.metrics.observe_record(rec)
        return rec

    def read(self) -> List[Dict[str, Any]]:
        """Parse + validate the journal ([] when none exists yet)."""
        if not os.path.exists(self.journal):
            return []
        return _telemetry.read_jsonl(self.journal)

    def jobs(self) -> Dict[str, Dict[str, Any]]:
        """Replay the journal -> job_id -> current row (the shared
        :func:`fold`'s ``jobs`` view: submit fields overlaid by every
        ACCEPTED transition, last status wins, ``age`` = the
        priority-aging clock, stale-fenced zombie rows rejected)."""
        return fold(self.read())["jobs"]

    # -- the lease plane (schema v11) ---------------------------------------

    def lease_state(self) -> Optional[Dict[str, Any]]:
        """The journal's current lease (:func:`fold`'s ``lease``
        view), or None when no scheduler ever leased it."""
        return fold(self.read())["lease"]

    def acquire_lease(self, ident: SchedIdentity, now: float,
                      ttl_s: Optional[float] = None) -> int:
        """Acquire the journal's dispatch lease as ``ident`` at clock
        reading ``now`` -> the granted fencing token (max token ever
        granted + 1 — monotonic even across takeovers and re-acquires,
        so a stale holder's rows are rejectable forever).

        Legal when the journal has no lease, the lease was released,
        the holder's deadline passed on ``now`` (a TAKEOVER — the
        acquire row names the expired holder in ``takeover_from``), or
        ``ident`` already holds it (re-acquire bumps the token: the
        holder noticed its own lapse and re-fences itself forward).
        A live peer's lease raises :class:`LeaseHeld`, named."""
        st = fold(self.read())
        lease, token = st["lease"], st["max_token"] + 1
        takeover_from = None
        if lease is not None and not lease["released"]:
            if lease["sched"] != ident.sched \
                    and float(now) < lease_deadline(lease):
                raise LeaseHeld(
                    f"journal {self.journal} is leased to "
                    f"{lease['sched']} (token {lease['token']}) "
                    f"until unix {lease_deadline(lease):g}; now is "
                    f"{float(now):g} — wait for expiry or let the "
                    f"watcher evict it")
            if lease["sched"] != ident.sched:
                takeover_from = str(lease["sched"])
        self._emit("lease_acquire", **_telemetry.lease_fields(
            ident.sched, ident.pid, ident.host, ident.start,
            token, float(now),
            float(lease_ttl_s() if ttl_s is None else ttl_s),
            takeover_from=takeover_from))
        if takeover_from:
            _log.warn(f"jobqueue: lease TAKEOVER — {ident.sched} "
                      f"fenced out expired holder {takeover_from} "
                      f"(token {token})")
        # the acquire row is durable; a sched_crash@between=
        # acquire,dispatch fault kills the new holder RIGHT HERE —
        # before any orphan requeue or dispatch — leaving a held
        # lease with zero progress, the tenure the next peer's
        # deadline math must expire in turn
        _faults.on_lease_boundary("acquire")
        return token

    def renew_lease(self, ident: SchedIdentity, token: int,
                    now: float, ttl_s: Optional[float] = None) -> None:
        """Refresh the lease deadline (one row per scheduling cycle,
        the scheduler-heartbeat cadence made durable)."""
        self._emit("lease_renew", **_telemetry.lease_fields(
            ident.sched, ident.pid, ident.host, ident.start,
            int(token), float(now),
            float(lease_ttl_s() if ttl_s is None else ttl_s)))
        _faults.on_lease_boundary("renew")

    def release_lease(self, ident: SchedIdentity, token: int,
                      now: float,
                      reason: Optional[str] = None) -> None:
        """Voluntarily end tenure (release rows carry ttl_s 0.0 —
        there is no deadline left to compute)."""
        self._emit("lease_release", **_telemetry.lease_fields(
            ident.sched, ident.pid, ident.host, ident.start,
            int(token), float(now), 0.0, reason=reason))

    def requeue_orphans(self, reason: str,
                        fence: Optional[int] = None,
                        sched: Optional[str] = None) -> int:
        """Requeue every job the fold reads as running/preempted —
        the takeover/restart recovery shared by
        Scheduler.recover_interrupted and ``fleet_watch --evict``.
        The requeue rows carry the CALLER's fence/identity (it holds
        the lease now), stamp a fresh ``unix`` (the wait-clock reset)
        and keep the job's trace."""
        n = 0
        for job in self.jobs().values():
            if job.get("status") not in ("running", "preempted"):
                continue
            fields = {"unix": float(time.time()),
                      "reason": str(reason)}
            if fence is not None:
                fields["fence"] = int(fence)
            if sched is not None:
                fields["sched"] = str(sched)
            if job.get("trace_id"):
                fields["trace_id"] = str(job["trace_id"])
            self._emit("job_state", job_id=job["job_id"],
                       tenant=str(job.get("tenant", "default")),
                       status="queued", **fields)
            n += 1
        return n

    # -- compaction ---------------------------------------------------------

    def compact(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Fold the journal into a snapshot row-set and publish it
        atomically as a NEW generation file (same path, new inode —
        tail.py consumers observe a NAMED rotation and re-fold from
        zero; nobody ever sees silent truncation).

        The snapshot layout is [submit rows][current-state rows]
        [lease lineage], in that order on purpose:

        * submit rows first (state overlay needs them), in original
          submit order, each re-based with ``age_base`` = its folded
          age minus the snapshot's terminal-row count — the fold's
          positional recount adds exactly that count back, so ages
          survive byte-for-byte and post-compaction terminals keep
          ticking every older job's clock;
        * ONE fully-overlaid job_state row per transitioned job (its
          entire history folded; historical fence values ride along
          untouched — they are validated BEFORE the lease lineage
          re-raises max_token, exactly like the original order);
        * the lease lineage LAST (the folded acquire + a release row
          when released), so ``max_token`` is re-established before
          any tail row lands and a zombie writing after compaction is
          still rejected;
        * spans of NON-terminal jobs survive (their trace continues
          across the rotation); terminal jobs' spans and all
          heartbeat/liveness sensor rows are the compaction win —
          export timelines (tools/trace_export.py) before compacting
          if you want finished jobs' full span history.

        ``fold(compacted) == fold(original)`` (jobs incl. ages,
        lease, max_token — modulo row indexes) is asserted before
        publish; a mismatch aborts with the journal untouched.
        Refuses (:class:`LeaseHeld`) while a live unexpired lease
        exists — the holder's O_APPEND rows would race the rename."""
        records = self.read()
        before = fold(records)
        lease = before["lease"]
        if lease is not None and not lease["released"] \
                and float(time.time() if now is None else now) \
                < lease_deadline(lease):
            raise LeaseHeld(
                f"journal {self.journal} is leased to "
                f"{lease['sched']} (token {lease['token']}, expires "
                f"unix {lease_deadline(lease):g}) — compact from the "
                f"holder between cycles, or after expiry/release")
        jobs = sorted(before["jobs"].values(),
                      key=lambda r: r.get("submit_idx", 0))
        live_ids = {r["job_id"] for r in jobs
                    if r.get("status") not in TERMINAL_STATES}
        submits = {rec["job_id"]: rec for rec in records
                   if rec.get("type") == "job_submit"}
        # the snapshot carries exactly one terminal state row per
        # terminal job; in the [submits][states] layout every one of
        # them recounts into every job's age, so each age_base
        # pre-subtracts the full count (see the docstring)
        n_terminal = sum(1 for r in jobs
                         if r["job_id"] in submits
                         and r.get("status") in TERMINAL_STATES)
        out: List[Dict[str, Any]] = []
        for row in jobs:
            sub = submits.get(row["job_id"])
            if sub is None:
                # a state-only job (no submit row survived) cannot be
                # re-based — refuse rather than silently dropping it
                raise RuntimeError(
                    f"jobqueue: cannot compact {self.journal}: job "
                    f"{row['job_id']} has state rows but no submit "
                    f"row (truncated journal?)")
            sub = dict(sub)
            sub["age_base"] = int(row["age"]) - n_terminal
            out.append(sub)
        # ONE fully-overlaid current-state row per job — emitting it
        # even for never-transitioned jobs is fold-identical (the
        # overlay reproduces the submit row's own fields) and keeps
        # this loop free of accepted-vs-rejected re-derivation
        state_keys = set(_telemetry.RECORD_SCHEMA["job_state"]) \
            | set(_telemetry.RECORD_OPTIONAL["job_state"])
        for row in jobs:
            state = {"v": _telemetry.SCHEMA_VERSION,
                     "type": "job_state",
                     "job_id": row["job_id"],
                     "tenant": str(row.get("tenant", "default")),
                     "status": row["status"]}
            for k in state_keys - {"job_id", "tenant", "status"}:
                if k in row:
                    state[k] = row[k]
            out.append(state)
        for rec in records:
            if rec.get("type") == "span" \
                    and rec.get("job_id") in live_ids:
                out.append(rec)
        if lease is not None:
            out.append({"v": _telemetry.SCHEMA_VERSION,
                        "type": "lease_acquire",
                        **_telemetry.lease_fields(
                            lease["sched"], lease["pid"],
                            lease["host"], lease["start"],
                            lease["token"], lease["unix"],
                            lease["ttl_s"],
                            takeover_from=lease.get("takeover_from"))})
            if lease["released"]:
                out.append({"v": _telemetry.SCHEMA_VERSION,
                            "type": "lease_release",
                            **_telemetry.lease_fields(
                                lease["sched"], lease["pid"],
                                lease["host"], lease["start"],
                                lease["token"], lease["unix"], 0.0,
                                reason="compacted")})
        for rec in out:
            _telemetry.validate_record(rec)
        after = fold(out)
        if self._fold_fingerprint(after) \
                != self._fold_fingerprint(before):
            raise RuntimeError(
                f"jobqueue: compaction would CHANGE the fold of "
                f"{self.journal} — aborted, journal untouched "
                f"(this is a bug in compact(), not your journal)")
        from fdtd3d_tpu import io as _io
        bytes_before = os.path.getsize(self.journal) \
            if os.path.exists(self.journal) else 0
        with _io.atomic_open(self.journal) as fh:
            for rec in out:
                fh.write(json.dumps(rec) + "\n")
        bytes_after = os.path.getsize(self.journal)
        _log.log(f"jobqueue: compacted {self.journal}: "
                 f"{len(records)} -> {len(out)} rows, "
                 f"{bytes_before} -> {bytes_after} bytes "
                 f"({len(jobs)} jobs, lease "
                 f"{'kept' if lease is not None else 'none'})")
        return {"rows_before": len(records), "rows_after": len(out),
                "bytes_before": bytes_before,
                "bytes_after": bytes_after, "jobs": len(jobs),
                "lease": lease, "max_token": before["max_token"]}

    @staticmethod
    def _fold_fingerprint(folded: Dict[str, Any]) -> Dict[str, Any]:
        """The fold-identity surface compaction must preserve: every
        job's full row (ages included; row indexes and the age_base
        re-basing mechanics excluded), the lease, the max token."""
        jobs = {}
        for jid, row in folded["jobs"].items():
            jobs[jid] = {k: v for k, v in row.items()
                        if k not in ("submit_idx", "age_base")}
        return {"jobs": jobs, "lease": folded["lease"],
                "max_token": folded["max_token"]}

    # -- admission ----------------------------------------------------------

    def submit(self, spec_path: str, tenant: Optional[str] = None,
               priority: int = 0, resume: str = "auto",
               policy: Optional[QuotaPolicy] = None) -> str:
        """Admit one job (or raise :class:`QuotaError` /
        ``ValueError``, named). The spec is parsed NOW — a job the
        dispatcher could never run must be refused at the door."""
        policy = policy or QuotaPolicy()
        tenant = tenant or default_tenant()
        t_admit0 = float(time.time())
        cfg = load_spec(spec_path)
        cells = job_cells(cfg)
        jobs = self.jobs()
        n_queued = sum(1 for j in jobs.values()
                       if j.get("tenant") == tenant
                       and j.get("status") == "queued")
        if n_queued >= policy.max_queued:
            raise QuotaError(
                f"tenant {tenant!r} already has {n_queued} queued "
                f"job(s) — the max_queued quota is "
                f"{policy.max_queued}; drain, cancel, or raise the "
                f"quota before submitting more")
        n_submits = sum(1 for j in jobs.values() if "spec" in j)
        job_id = f"j-{n_submits:05d}-{os.urandom(2).hex()}"
        # the job's causal-trace identity (schema v9): minted exactly
        # once, here — every later journal row inherits it through the
        # jobs() fold, so a preempted job's re-dispatch continues the
        # SAME trace across process restarts
        trace_id = _telemetry.new_trace_id()
        self._emit("job_submit", job_id=job_id, tenant=tenant,
                   status="queued", priority=int(priority),
                   wall_time=time.strftime("%Y-%m-%dT%H:%M:%S"),
                   spec=os.path.abspath(spec_path), cells=cells,
                   unix=float(time.time()), resume=str(resume),
                   time_steps=int(cfg.time_steps), trace_id=trace_id)
        # admission/quota span: spec parse + quota check wall
        self._emit("span", **_telemetry.span_fields(
            "admission", trace_id, _telemetry.new_span_id(),
            t_admit0, float(time.time()), job_id=job_id,
            tenant=tenant))
        return job_id

    def cancel(self, job_id: str) -> None:
        """Cancel a non-terminal job (a terminal one is a named
        error — the journal must never un-finish a job)."""
        jobs = self.jobs()
        row = jobs.get(job_id)
        if row is None:
            raise ValueError(f"no such job {job_id!r}")
        if row.get("status") in TERMINAL_STATES:
            raise ValueError(
                f"job {job_id} is already terminal "
                f"({row['status']}); cancel applies to queued/"
                f"running jobs only")
        self._emit("job_state", job_id=job_id,
                   tenant=str(row.get("tenant", "default")),
                   status="cancelled")

    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.dirpath, "jobs", job_id)


# --------------------------------------------------------------------------
# the scheduler
# --------------------------------------------------------------------------


class Scheduler:
    """Drives every queued job to a terminal state.

    In-process and single-threaded on purpose: the concurrency that
    matters (many tenants sharing hardware) lives in the vmap batch
    executor and the sharded mesh, not in host threads — and a
    single-writer journal keeps the crash-safety argument auditable.
    ``batch_chunk`` is the coalesced groups' per-dispatch step count
    (0 = whole horizon in one chunk); ``coalesce=False`` pins every
    job solo (the A/B lever for the shared-executable win)."""

    def __init__(self, queue: JobQueue,
                 policy: Optional[QuotaPolicy] = None,
                 retry_policy=None, batch_chunk: int = 0,
                 coalesce: bool = True,
                 straggler_threshold: int = 3,
                 registry_path: Optional[str] = None,
                 clock: Optional[Callable[[], float]] = None,
                 lease_ttl: Optional[float] = None):
        from fdtd3d_tpu import registry as _registry
        self.queue = queue
        self.policy = policy or QuotaPolicy()
        self.retry_policy = retry_policy
        self.batch_chunk = int(batch_chunk)
        self.coalesce = bool(coalesce)
        self.straggler_threshold = int(straggler_threshold)
        self.registry_path = (registry_path
                              or _registry.registry_path())
        self._dispatches = 0       # sched_crash@job=N ordinal clock
        self._cfgs: Dict[str, Any] = {}   # spec path -> SimConfig
        self._pool = None          # (devices, excluded_ids) cache
        # Live-health heartbeats (schema v10): the scheduler beats
        # onto its own journal at every cycle and dispatch boundary —
        # None (strict no-op) unless FDTD3D_HEARTBEAT_S is set, so a
        # heartbeat-off journal stays byte-identical to v9 emission.
        self._heartbeat = _telemetry.Heartbeater.maybe(
            queue.journal, "scheduler")
        # Fenced lease plane (schema v11): ``clock`` is the injectable
        # deadline clock (tier-1 hands in a fake and never sleeps;
        # default wall clock), ``lease_ttl`` the tenure TTL
        # (FDTD3D_LEASE_TTL_S when None). serve() acquires the lease
        # before touching any job and releases it on the way out;
        # cycle() renews once per pass. A bare cycle() without serve()
        # runs unleased (token None): its rows carry no fence and the
        # fold accepts them — the single-scheduler library mode.
        self.clock: Callable[[], float] = clock or time.time
        self.lease_ttl = float(lease_ttl_s() if lease_ttl is None
                               else lease_ttl)
        self.identity = SchedIdentity.mine(now=self.clock())
        self._lease_token: Optional[int] = None
        # lease_expire@job=N flips this: a zombie stops renewing and
        # stops checking its own expiry, but KEEPS dispatching with
        # its stale token — the fold's rejection is then what keeps
        # the journal consistent, which is the property under test
        self._zombie = False

    # -- config loading -----------------------------------------------------

    def _load(self, spec_path: str):
        cfg = self._cfgs.get(spec_path)
        if cfg is None:
            cfg = load_spec(spec_path)
            self._cfgs[spec_path] = cfg
        return cfg

    def _job_cfg(self, cfg, job_id: str, observed: bool = True):
        """Per-job output overrides: an isolated save_dir (the durable
        resume root), a per-job telemetry stream when ``observed``,
        and the in-graph tripwire on (the supervisor consumes it)."""
        jdir = self.queue.job_dir(job_id)
        out = dataclasses.replace(
            cfg.output, save_dir=jdir,
            telemetry_path=(os.path.join(jdir, "telemetry.jsonl")
                            if observed else cfg.output.telemetry_path),
            metrics_path=None, profile_dir=None, check_finite=True)
        return dataclasses.replace(cfg, output=out)

    # -- placement ----------------------------------------------------------

    def placement_pool(self) -> Tuple[List[Any], List[int]]:
        """``(devices, excluded_ids)``: the device objects auto jobs
        may be placed on, with registry-convicted straggler chips
        REMOVED. Cached for the scheduler's lifetime — this process is
        the only dispatcher, so the conviction rollup cannot change
        under it, and one registry read serves every dispatch. An
        exclusion set that would empty the pool is dropped (warned):
        running on convicted chips beats not running at all."""
        if self._pool is None:
            import jax
            devs = list(jax.devices())
            convicted = set(straggler_chips(self.registry_path,
                                            self.straggler_threshold))
            excluded = sorted(d.id for d in devs
                              if d.id in convicted)
            pool = [d for d in devs if d.id not in convicted]
            if not pool:
                _log.warn(
                    "jobqueue: straggler exclusion would empty the "
                    f"device pool (convicted: {excluded}); placing "
                    "on the full pool instead")
                pool, excluded = devs, []
            self._pool = (pool, excluded)
        return self._pool

    def place(self, cfg) -> Tuple[Any, Optional[Dict[str, Any]],
                                  Optional[List[Any]]]:
        """Apply the placement decision: ``--topology auto`` jobs get
        the scored topology over the straggler-filtered device pool;
        ``none`` stays unsharded and an explicit ``manual``
        decomposition is honored as pinned — the queue never reshapes
        a job behind its tenant's back. Returns ``(cfg, record,
        devices)`` — ``devices`` is the pool the dispatch must build
        its mesh from (threaded into Supervisor/BatchSimulation so an
        excluded chip really hosts no shard), None for non-auto jobs
        (their device set is the tenant's own business)."""
        if cfg.parallel.topology != "auto":
            return cfg, None, None
        pool, excluded = self.placement_pool()
        topo, rec = score_topology(cfg, len(pool) + len(excluded),
                                   exclude_chips=tuple(excluded))
        return _cfg_with_topology(cfg, topo), rec, pool

    # -- the wait clock -----------------------------------------------------

    @staticmethod
    def _wait_s(job: Dict[str, Any]) -> Optional[float]:
        """Seconds this job has waited IN THE QUEUE: since submit, or
        since its latest requeue (`queued` transitions stamp a fresh
        ``unix`` that the journal fold overlays onto the submit row's
        — a preempted job's 10-minute first run must not read as 10
        minutes of queue wait and fire the queue-wait SLO)."""
        unix = job.get("unix")
        if not isinstance(unix, (int, float)):
            return None
        return max(0.0, float(time.time()) - float(unix))

    # -- one scheduling cycle ----------------------------------------------

    def _effective_priority(self, job: Dict[str, Any]) -> float:
        return float(job.get("priority", 0)) \
            + self.policy.aging * float(job.get("age", 0))

    def _tenant_cap_ok(self, tenant_cells: Dict[str, float],
                       job: Dict[str, Any]) -> bool:
        cap = self.policy.max_concurrent_cells
        if cap is None:
            return True
        used = tenant_cells.get(str(job.get("tenant")), 0.0)
        return used + float(job.get("cells", 0.0)) <= float(cap)

    def _lease_tick(self) -> None:
        """One per-cycle lease maintenance pass (no-op unleased).

        Honest holders renew; one whose own deadline lapsed (a long
        GC pause, a laptop lid) re-acquires FIRST — the token bump
        re-fences it forward, and if a peer took over in the gap the
        acquire raises :class:`LeaseHeld` and this scheduler stops
        instead of double-dispatching. A ``lease_expire@job=N``-made
        zombie skips all of it: it keeps its stale token and keeps
        writing, and the fold's rejection carries the proof."""
        if self._lease_token is None:
            return
        if not self._zombie \
                and _faults.lease_zombie(self._dispatches + 1):
            self._zombie = True
            _log.warn(f"jobqueue: scheduler {self.identity.sched} "
                      f"went ZOMBIE (lease_expire fault): no more "
                      f"renewals or expiry checks, stale token "
                      f"{self._lease_token} rides every row")
        if self._zombie:
            return
        now = self.clock()
        st = self.queue.lease_state()
        if st is None or st["token"] != self._lease_token \
                or st["released"] or now >= lease_deadline(st):
            # fenced out, or our own tenure lapsed: re-acquire (or
            # find a live peer and stop — LeaseHeld propagates)
            self._lease_token = self.queue.acquire_lease(
                self.identity, now, self.lease_ttl)
        else:
            self.queue.renew_lease(self.identity, self._lease_token,
                                   now, self.lease_ttl)

    def cycle(self) -> int:
        """One scheduling pass: order the queued jobs by effective
        priority, build dispatch units (coalesced groups or solos),
        run each. Returns the number of journal transitions written —
        0 means the cycle could make no progress at all."""
        self._lease_tick()
        jobs = self.queue.jobs()
        queued = [j for j in jobs.values()
                  if j.get("status") == "queued"]
        queued.sort(key=lambda j: (-self._effective_priority(j),
                                   j.get("submit_idx", 0)))
        transitions = 0
        used: set = set()
        if self._heartbeat is not None:
            self._heartbeat.beat()
        for job in queued:
            if job["job_id"] in used:
                continue
            used.add(job["job_id"])
            if self._heartbeat is not None:
                self._heartbeat.beat(job_id=str(job["job_id"]),
                                     trace_id=job.get("trace_id"))
            try:
                cfg = self._load(job["spec"])
            except (ValueError, OSError) as exc:
                self._state(job, "failed",
                             reason=f"spec unloadable: {exc}")
                transitions += 1
                continue
            cap = self.policy.max_concurrent_cells
            if cap is not None and float(job.get("cells", 0)) > cap:
                self._state(
                    job, "failed",
                    reason=f"job needs {job.get('cells'):.0f} device-"
                           f"cells but tenant {job.get('tenant')!r}'s "
                           f"max_concurrent_cells quota is {cap:.0f} "
                           f"— it can never be scheduled")
                transitions += 1
                continue
            unit = [job]
            if self.coalesce:
                unit = self._coalesce_unit(job, cfg, queued, used)
            if len(unit) >= 2:
                transitions += self._dispatch_batch(unit)
            else:
                transitions += self._dispatch_solo(job)
        return transitions

    def _coalesce_unit(self, leader, leader_cfg, queued,
                       used: set) -> List[Dict[str, Any]]:
        """Grow a coalesce group around ``leader``: queued jobs with
        the same batch fingerprint, within the batch-width bound and
        each tenant's concurrent-cell quota."""
        from fdtd3d_tpu.batch import batch_max
        key = coalesce_key(leader_cfg)
        if key is None:
            return [leader]
        tenant_cells: Dict[str, float] = {}
        unit = []

        def _admit(job) -> bool:
            if not self._tenant_cap_ok(tenant_cells, job):
                return False
            t = str(job.get("tenant"))
            tenant_cells[t] = tenant_cells.get(t, 0.0) \
                + float(job.get("cells", 0.0))
            unit.append(job)
            return True

        _admit(leader)
        limit = batch_max()
        for job in queued:
            if len(unit) >= limit:
                break
            if job["job_id"] in used:
                continue
            try:
                cfg = self._load(job["spec"])
            except (ValueError, OSError):
                continue    # its own dispatch turn will name this
            if coalesce_key(cfg) == key and _admit(job):
                used.add(job["job_id"])
        return unit

    # -- journal transitions ------------------------------------------------

    def _state(self, job: Dict[str, Any], status: str,
               run_id: Optional[str] = None,
               reason: Optional[str] = None,
               wait_s: Optional[float] = None,
               topology: Optional[List[int]] = None,
               group: Optional[str] = None,
               lane: Optional[int] = None,
               t: Optional[int] = None,
               excluded_chips: Optional[List[int]] = None,
               resumed_from: Optional[int] = None) -> None:
        """One journal transition; None-valued optionals are omitted
        (the schema's optional-key table, telemetry.RECORD_OPTIONAL,
        names every parameter here). ``queued`` transitions stamp a
        fresh ``unix`` — the wait-clock reset the fold overlays."""
        fields = {}
        if status == "queued":
            fields["unix"] = float(time.time())
        if run_id:
            fields["run_id"] = str(run_id)
        if reason is not None:
            fields["reason"] = str(reason)
        if wait_s is not None:
            fields["wait_s"] = round(float(wait_s), 3)
        if topology is not None:
            fields["topology"] = [int(p) for p in topology]
        if group is not None:
            fields["group"] = str(group)
        if lane is not None:
            fields["lane"] = int(lane)
        if t is not None:
            fields["t"] = int(t)
        if excluded_chips is not None:
            fields["excluded_chips"] = [int(c)
                                        for c in excluded_chips]
        if resumed_from is not None:
            fields["resumed_from"] = int(resumed_from)
        if job.get("trace_id"):
            # the causal-trace stamp (v9): the fold overlays the
            # submit row's trace_id onto the job dict, so every
            # transition — including post-preemption re-dispatches —
            # journals under the job's one trace
            fields["trace_id"] = str(job["trace_id"])
        if self._lease_token is not None:
            # the fencing stamps (v11): every row a leased scheduler
            # writes carries its token + identity, so the fold can
            # reject this row the moment a newer acquire precedes it
            fields["fence"] = int(self._lease_token)
            fields["sched"] = self.identity.sched
        self.queue._emit("job_state", job_id=job["job_id"],
                         tenant=str(job.get("tenant", "default")),
                         status=status, **fields)

    def _span(self, job: Dict[str, Any], name: str, t0: float,
              t1: float, span_id: Optional[str] = None,
              parent: Optional[str] = None,
              attrs: Optional[Dict[str, Any]] = None,
              group: Optional[str] = None,
              lane: Optional[int] = None,
              run_id: Optional[str] = None) -> Optional[str]:
        """Journal one lifecycle span for ``job`` (no-op for pre-v9
        jobs without a trace_id). Returns the span id (for
        parent-linking) or None."""
        tid = job.get("trace_id")
        if not tid:
            return None
        sid = span_id or _telemetry.new_span_id()
        self.queue._emit("span", **_telemetry.span_fields(
            name, str(tid), sid, float(t0), float(t1),
            parent_span_id=parent, attrs=attrs,
            job_id=str(job["job_id"]),
            tenant=str(job.get("tenant", "default")),
            group=group, lane=lane, run_id=run_id))
        return sid

    # -- dispatch: solo (supervised, durable) -------------------------------

    def _peek_supervisor_state(self, cfg) -> Optional[Dict]:
        """The recovery state a previous (preempted) dispatch of this
        job persisted into its snapshots — the CLI supervised-resume
        peek, scoped to the job's own save_dir."""
        from fdtd3d_tpu import io as _io
        from fdtd3d_tpu.sim import ckpt_meta_mismatch
        for t_ck, cand in _io.find_checkpoints(cfg.output.save_dir):
            if t_ck > cfg.time_steps:
                continue
            try:
                meta = _io.read_checkpoint_meta(cand)
            except (OSError, ValueError, KeyError) as exc:
                _log.warn(f"jobqueue: cannot peek {cand} ({exc}); "
                          f"trying the next snapshot")
                continue
            if ckpt_meta_mismatch(cfg, meta):
                continue
            return meta.get("supervisor")
        return None

    def _restore_latest(self, sim, cfg) -> Optional[str]:
        """--resume auto, scoped to the job dir: newest usable
        committed snapshot at or before the horizon."""
        from fdtd3d_tpu import io as _io
        for t_ck, cand in _io.find_checkpoints(cfg.output.save_dir):
            if t_ck > cfg.time_steps:
                continue
            try:
                sim.restore(cand)
                return cand
            except (_io.CheckpointCorrupt, ValueError) as exc:
                _log.warn(f"jobqueue: skipping unusable checkpoint "
                          f"{cand}: {exc}")
        return None

    def _dispatch_solo(self, job: Dict[str, Any]) -> int:
        from fdtd3d_tpu import registry as _registry
        from fdtd3d_tpu.supervisor import Supervisor
        self._dispatches += 1
        ordinal = self._dispatches
        wait = self._wait_s(job)
        t_disp0 = float(time.time())
        # the dispatch span id is minted UP FRONT so the run's own
        # spans (registry/telemetry side) can parent on it; the span
        # record itself lands at the terminal transition below
        dsid = _telemetry.new_span_id()
        sup = None
        try:
            cfg = self._job_cfg(self._load(job["spec"]),
                                job["job_id"])
            cfg, placement, pool = self.place(cfg)
            resume_state = self._peek_supervisor_state(cfg) \
                if os.path.isdir(cfg.output.save_dir) else None
            with _registry.job_context(job["job_id"],
                                       str(job.get("tenant")),
                                       trace_id=job.get("trace_id"),
                                       parent_span_id=dsid):
                sup = Supervisor(cfg=cfg, policy=self.retry_policy,
                                 resume_state=resume_state,
                                 devices=pool)
                sim = sup.ensure_sim()
        except (ValueError, RuntimeError, OSError) as exc:
            if sup is not None:
                # the ctor may have pinned kernel escape hatches from
                # the persisted resume state; a failed build must not
                # leak them into the scheduler's later dispatches
                sup._restore_env()
            # a failed construction is still the Nth dispatch: offer
            # the ordinal to sched_crash@job=N before its journal
            # write, so fault targeting cannot silently shift
            _faults.on_sched_journal(ordinal)
            self._state(job, "failed",
                         reason=f"construction failed: "
                                f"{type(exc).__name__}: "
                                f"{str(exc)[:200]}")
            self._span(job, "dispatch", t_disp0, float(time.time()),
                       span_id=dsid,
                       attrs={"status": "failed"})
            return 1
        cfg = sup.cfg
        self._state(job, "running", run_id=sim.run_id, wait_s=wait,
                    topology=list(sim.topology),
                    excluded_chips=(placement["excluded_chips"]
                                    if placement is not None
                                    else None))
        if isinstance(job.get("unix"), (int, float)):
            # queue-wait span: from the wait clock (submit, or the
            # latest requeue) to this dispatch
            self._span(job, "queue_wait", float(job["unix"]), t_disp0,
                       attrs={"wait_s": round(float(wait or 0.0), 3)},
                       run_id=str(sim.run_id or "") or None)
        t_res0 = float(time.time())
        restored = self._restore_latest(sim, cfg)
        if restored:
            _log.log(f"jobqueue: job {job['job_id']} resumes from "
                     f"{restored} at t={sim.t}")
            self._span(job, "resume", t_res0, float(time.time()),
                       parent=dsid,
                       attrs={"checkpoint": os.path.basename(restored),
                              "t": int(sim.t)},
                       run_id=str(sim.run_id or "") or None)
        interval = cfg.output.checkpoint_every or 0
        try:
            sup.run(time_steps=cfg.time_steps, interval=interval)
        except _faults.SimulatedPreemption as exc:
            # the JOB's slice died (see the module docstring's note on
            # why observing that death is not swallowing a kill): its
            # stream ends run_end-less exactly like a killed process,
            # and the job re-queues for a durable resume
            sink = sup.sim.telemetry if sup.sim is not None else None
            if sink is not None:
                sink.abandon()
            _faults.on_sched_journal(ordinal)
            self._state(job, "preempted",
                        reason=f"{type(exc).__name__}: "
                               f"{str(exc)[:200]}",
                        run_id=str(sim.run_id or ""),
                        t=int(sup.sim._t_host))
            self._state(job, "queued",
                        reason="requeued for durable resume")
            self._span(job, "dispatch", t_disp0, float(time.time()),
                       span_id=dsid,
                       attrs={"status": "preempted",
                              "t": int(sup.sim._t_host)},
                       run_id=str(sim.run_id or "") or None)
            return 3
        except FloatingPointError as exc:
            sup.sim.close()
            _faults.on_sched_journal(ordinal)
            self._state(job, "failed",
                         reason=f"health trip unrecovered: "
                                f"{str(exc)[:200]}",
                         run_id=str(sim.run_id or ""),
                         t=int(sup.sim._t_host))
            self._span(job, "dispatch", t_disp0, float(time.time()),
                       span_id=dsid, attrs={"status": "failed"},
                       run_id=str(sim.run_id or "") or None)
            return 2
        except (RuntimeError, OSError) as exc:
            sup.sim.close()
            _faults.on_sched_journal(ordinal)
            self._state(job, "failed",
                         reason=f"retry budget exhausted: "
                                f"{type(exc).__name__}: "
                                f"{str(exc)[:200]}",
                         run_id=str(sim.run_id or ""),
                         t=int(sup.sim._t_host))
            self._span(job, "dispatch", t_disp0, float(time.time()),
                       span_id=dsid, attrs={"status": "failed"},
                       run_id=str(sim.run_id or "") or None)
            return 2
        sim = sup.sim
        if cfg.output.checkpoint_every:
            # commit the final state so operators (and the
            # bit-identical acceptance test) read the finished job
            # from a snapshot, not a live process
            sim.checkpoint_now()
        sim.close()
        _faults.on_sched_journal(ordinal)
        self._state(job, "completed", run_id=str(sim.run_id or ""),
                     t=int(sim._t_host))
        self._span(job, "dispatch", t_disp0, float(time.time()),
                   span_id=dsid, attrs={"status": "completed"},
                   run_id=str(sim.run_id or "") or None)
        return 2

    # -- dispatch: coalesced group (one vmap executable) --------------------

    def _group_snapshots(self, gdir: str) -> List[str]:
        """The group's committed snapshots, newest first (an .npz
        under its final name IS committed — io.save_checkpoint writes
        through the atomic renamer)."""
        import re as _re
        try:
            names = [f for f in os.listdir(gdir)
                     if _re.fullmatch(r"ckpt_t\d+\.npz", f)]
        except OSError:
            return []
        return [os.path.join(gdir, f)
                for f in sorted(names, reverse=True)]

    def _restore_group(self, bsim, gdir: str) -> int:
        """-> the committed t every lane resumed from (0 = from
        scratch). Newest snapshot passing its integrity + membership
        guards wins; a corrupt or mismatched one falls back OLDER
        (the solo _restore_latest discipline) — never a crash, never
        a silent wrong-state adoption."""
        from fdtd3d_tpu import io as _io
        for path in self._group_snapshots(gdir):
            try:
                bsim.restore(path)
                return int(bsim.t)
            except (_io.CheckpointCorrupt, ValueError, OSError) as exc:
                _log.warn(f"jobqueue: group snapshot {path} unusable "
                          f"({type(exc).__name__}: {str(exc)[:120]}); "
                          f"trying an older one")
        return 0

    def _prune_group_snapshots(self, gdir: str, keep: int = 2):
        """Keep the newest ``keep`` snapshots (>= 2: the corrupt-
        fallback needs an older committed one to land on)."""
        for path in self._group_snapshots(gdir)[keep:]:
            try:
                os.remove(path)
            except OSError:
                pass

    def _dispatch_batch(self, unit: List[Dict[str, Any]]) -> int:
        from fdtd3d_tpu import registry as _registry
        from fdtd3d_tpu.batch import BatchSimulation
        self._dispatches += 1
        ordinal = self._dispatches
        gid = "g-" + hashlib.sha256(
            "/".join(j["job_id"] for j in unit).encode()
        ).hexdigest()[:10]
        gdir = os.path.join(self.queue.dirpath, "groups", gid)
        waits = [self._wait_s(j) for j in unit]
        t_disp0 = float(time.time())
        # per-member dispatch span ids (minted up front: the group's
        # run spans parent on the LEADER's; each lane's batch_lane
        # rows parent on its own member's)
        dsids = [_telemetry.new_span_id() for _ in unit]
        try:
            cfgs = [self._job_cfg(self._load(j["spec"]),
                                  j["job_id"], observed=False)
                    for j in unit]
            # lane 0's output config drives the SHARED sink: one
            # stream per group, beside the group's artifacts
            out0 = dataclasses.replace(
                cfgs[0].output,
                telemetry_path=os.path.join(gdir, "telemetry.jsonl"))
            cfgs[0] = dataclasses.replace(cfgs[0], output=out0)
            was_auto = cfgs[0].parallel.topology == "auto"
            cfgs[0], placement, pool = self.place(cfgs[0])
            if was_auto:
                # topology is graph-shaping: the whole group moves to
                # lane 0's placed decomposition — INCLUDING the
                # degenerate one-chip "none" (a lane left on "auto"
                # would split the batch fingerprint and lose the
                # shared executable to the solo fallback)
                topo = cfgs[0].parallel.manual_topology or (1, 1, 1)
                cfgs[1:] = [_cfg_with_topology(c, topo)
                            for c in cfgs[1:]]
            tenants = ",".join(sorted({str(j.get("tenant"))
                                       for j in unit}))
            # the group's shared run registers under the LEADER's
            # trace (the group IS one dispatch of lane 0's trace);
            # every other member's trace joins through its own
            # journal spans + the per-lane batch_lane stamps below
            with _registry.job_context(
                    gid, tenants,
                    trace_id=unit[0].get("trace_id"),
                    parent_span_id=dsids[0]):
                bsim = BatchSimulation(cfgs, devices=pool)
        except (ValueError, RuntimeError, OSError) as exc:
            # the fingerprint said coalescible but the constructor
            # disagreed (structure divergence shapes cannot see) or
            # the build failed: fall back to solo dispatches. The
            # group consumed dispatch ordinal N — offer it to
            # sched_crash@job=N first (the grammar counts a coalesced
            # group as ONE dispatch; a skipped ordinal would shift
            # every later fault target)
            _faults.on_sched_journal(ordinal)
            _log.warn(f"jobqueue: group {gid} fell back to solo "
                      f"dispatches ({type(exc).__name__}: "
                      f"{str(exc)[:160]})")
            n = 0
            for j in unit:
                n += self._dispatch_solo(j)
            return n
        # durable group resume: adopt the newest committed snapshot in
        # the group's directory (written at every chunk boundary
        # below) so a preempted group's re-dispatch continues every
        # lane bit-identical from the committed t, not from t=0 — the
        # recovery-matrix row docs/SERVICE.md used to mark open
        t_built = float(time.time())
        # per-lane causal-trace stamps (v9): BatchSimulation.advance
        # puts them on each lane's batch_lane + imbalance rows, so a
        # lane's health stream joins its OWN tenant's trace even
        # though the group shares one telemetry sink
        bsim.lane_traces = [
            {"trace_id": j.get("trace_id"),
             "span_id": _telemetry.new_span_id(),
             "parent_span_id": dsids[i]}
            if j.get("trace_id") else None
            for i, j in enumerate(unit)]
        bsim.group_id = gid
        os.makedirs(gdir, exist_ok=True)
        t_res0 = float(time.time())
        resumed = self._restore_group(bsim, gdir)
        t_res1 = float(time.time())
        if resumed:
            _log.log(f"jobqueue: group {gid} resumes from its "
                     f"committed snapshot at t={resumed}")
        for i, (j, wait) in enumerate(zip(unit, waits)):
            self._state(j, "running", run_id=bsim.run_id, group=gid,
                        lane=i, wait_s=wait,
                        topology=list(bsim.topology),
                        excluded_chips=(placement["excluded_chips"]
                                        if placement is not None
                                        else None),
                        resumed_from=int(resumed))
            if isinstance(j.get("unix"), (int, float)):
                self._span(j, "queue_wait", float(j["unix"]), t_disp0,
                           attrs={"wait_s": round(float(wait or 0.0),
                                                  3)},
                           run_id=str(bsim.run_id or "") or None)
            # the coalesce decision + group build wall, one span per
            # member so every tenant's trace shows the shared phase
            self._span(j, "coalesce", t_disp0, t_built,
                       parent=dsids[i], group=gid, lane=i,
                       attrs={"lanes": len(unit)},
                       run_id=str(bsim.run_id or "") or None)
            if resumed:
                prev_t = j.get("t")
                if isinstance(prev_t, int):
                    # the preempted dispatch's in-flight work past
                    # the committed snapshot is discarded: the
                    # re-dispatch rolls back to t_restored
                    self._span(j, "rollback", t_res0, t_res1,
                               parent=dsids[i], group=gid, lane=i,
                               attrs={"t_failed": int(prev_t),
                                      "t_restored": int(resumed)},
                               run_id=str(bsim.run_id or "") or None)
                self._span(j, "resume", t_res0, t_res1,
                           parent=dsids[i], group=gid, lane=i,
                           attrs={"t": int(resumed)},
                           run_id=str(bsim.run_id or "") or None)
        try:
            total = int(bsim.cfg.time_steps)
            chunk = self.batch_chunk \
                if self.batch_chunk and self.batch_chunk > 0 else total
            while bsim.t < total:
                bsim.advance(min(chunk, total - bsim.t))
                # one committed snapshot per chunk boundary: the
                # atomic .npz write is the durability point a later
                # re-dispatch resumes from (preemption fires on the
                # chunk boundary BEFORE its snapshot, so the resume
                # lands on the previous committed one)
                bsim.checkpoint(os.path.join(
                    gdir, f"ckpt_t{bsim.t:06d}.npz"))
                self._prune_group_snapshots(gdir)
            bsim.verify_final_lanes()
        except _faults.SimulatedPreemption as exc:
            if bsim.telemetry is not None:
                bsim.telemetry.abandon()
            _faults.on_sched_journal(ordinal)
            snaps = self._group_snapshots(gdir)
            ct = int(os.path.basename(snaps[0])[6:-4]) if snaps else 0
            reason = (f"{type(exc).__name__}: {str(exc)[:160]} "
                      f"(group re-dispatch resumes every lane from "
                      f"the committed snapshot t={ct})")
            for i, j in enumerate(unit):
                self._state(j, "preempted", reason=reason,
                            group=gid, t=int(bsim.t))
                self._state(j, "queued",
                            reason="requeued after group preemption")
                self._span(j, "dispatch", t_disp0, float(time.time()),
                           span_id=dsids[i], group=gid, lane=i,
                           attrs={"status": "preempted",
                                  "t": int(bsim.t)},
                           run_id=str(bsim.run_id or "") or None)
            return 2 * len(unit)
        except (RuntimeError, OSError) as exc:
            bsim.close()
            _faults.on_sched_journal(ordinal)
            for i, j in enumerate(unit):
                self._state(j, "failed", group=gid,
                             reason=f"group dispatch failed: "
                                    f"{type(exc).__name__}: "
                                    f"{str(exc)[:160]}")
                self._span(j, "dispatch", t_disp0, float(time.time()),
                           span_id=dsids[i], group=gid, lane=i,
                           attrs={"status": "failed"},
                           run_id=str(bsim.run_id or "") or None)
            return len(unit)
        bsim.close()
        _faults.on_sched_journal(ordinal)
        for i, j in enumerate(unit):
            if bsim.lane_finite[i] is False:
                self._state(
                    j, "failed", group=gid,
                    run_id=str(bsim.run_id or ""),
                    reason=f"lane {i} non-finite (first bad step <= "
                           f"{bsim.lane_first_unhealthy_t[i]})",
                    t=int(bsim.t))
                self._span(j, "dispatch", t_disp0, float(time.time()),
                           span_id=dsids[i], group=gid, lane=i,
                           attrs={"status": "failed"},
                           run_id=str(bsim.run_id or "") or None)
            else:
                self._state(j, "completed", group=gid,
                             run_id=str(bsim.run_id or ""),
                             t=int(bsim.t))
                self._span(j, "dispatch", t_disp0, float(time.time()),
                           span_id=dsids[i], group=gid, lane=i,
                           attrs={"status": "completed"},
                           run_id=str(bsim.run_id or "") or None)
        return len(unit)

    # -- the serve loop -----------------------------------------------------

    def recover_interrupted(self) -> int:
        """Re-queue every job the journal reads as ``running`` or
        ``preempted``: whoever held the lease behind those rows is
        gone (this scheduler just acquired it — a live holder would
        have made serve() stop with :class:`LeaseHeld`), so they are
        the crash window made visible and replay is the recovery.
        The requeue rows carry THIS scheduler's fence."""
        return self.queue.requeue_orphans(
            "requeued on scheduler restart (journal read a "
            "running/preempted job with no live dispatcher)",
            fence=self._lease_token,
            sched=(self.identity.sched
                   if self._lease_token is not None else None))

    def serve(self, max_cycles: Optional[int] = None
              ) -> Dict[str, Any]:
        """Drive the queue until no job is queued (or ``max_cycles``).
        Returns the terminal summary ``{"cycles", "jobs": folded
        rows}``. A cycle that makes NO progress while jobs remain
        queued stops the loop loudly (an in-process scheduler cannot
        wait for capacity nothing will free).

        serve() is the LEASED entry point: it acquires the journal's
        fenced dispatch lease before touching any job (raising
        :class:`LeaseHeld`, named, when a live peer owns it — never a
        second dispatcher), requeues the previous holder's orphans,
        and releases on the way out — except as a zombie, whose stale
        token must stay visible in the journal for the fold to
        reject (a zombie's "release" would be one more stale row the
        lease fold already ignores, so it skips the write)."""
        from fdtd3d_tpu import registry as _registry
        # runs this scheduler builds register under kind "queue" (the
        # batch executor still stamps its own "batch"); restored on
        # exit so a library caller's later runs keep their own kind
        old_kind = _registry._DEFAULT_KIND
        _registry.set_default_kind("queue")
        try:
            self._lease_token = self.queue.acquire_lease(
                self.identity, self.clock(), self.lease_ttl)
            self.recover_interrupted()
            cycles = 0
            while max_cycles is None or cycles < max_cycles:
                cycles += 1
                moved = self.cycle()
                if self.metrics is not None:
                    self.metrics.maybe_write()
                remaining = [j for j in self.queue.jobs().values()
                             if j.get("status") == "queued"]
                if not remaining:
                    break
                if moved == 0:
                    _log.warn(
                        f"jobqueue: cycle {cycles} made no progress "
                        f"with {len(remaining)} job(s) still queued "
                        f"(deferred by quota); stopping — re-serve "
                        f"when capacity frees")
                    break
            if self.metrics is not None:
                self.metrics.maybe_write()
            # release on ORDERLY exit only: an exception leaving this
            # loop is the scheduler dying (sched_crash's
            # SimulatedPreemption, a real signal, a LeaseHeld from a
            # fenced-out re-acquire) — a dead process releases
            # nothing, its lease must be left to EXPIRE so the
            # takeover path recovers it. A zombie never releases
            # either: its stale token stays visible for the fold.
            if self._lease_token is not None and not self._zombie:
                self.queue.release_lease(
                    self.identity, self._lease_token, self.clock(),
                    reason="serve loop exited")
                self._lease_token = None
            return {"cycles": cycles, "jobs": self.queue.jobs()}
        finally:
            _registry.set_default_kind(old_kind)

    @property
    def metrics(self):
        return self.queue.metrics
