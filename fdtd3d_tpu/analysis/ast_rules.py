"""Engine 1 — AST rules over the fdtd3d_tpu/ + tools/ source surface.

Each rule is a small, self-contained :class:`~fdtd3d_tpu.analysis.Rule`
subclass; ``tests/fixtures/lint/`` keeps one known-bad snippet per rule
(tests/test_analysis.py proves every rule fires on its fixture, so no
rule can go vacuously green). The two oldest rules — ``no-bare-print``
and ``atomic-write`` — are the round-3/round-9 hand-rolled lints ported
onto the framework; ``tests/test_lint_no_print.py`` and
``tests/test_lint_atomic_write.py`` are now thin wrappers over them.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, List, Optional, Set, Tuple

from fdtd3d_tpu.analysis import (LEGACY_FILES, Context, Finding, Rule,
                                 SourceFile, walk_shallow)


def _dotted(func: ast.AST) -> Optional[str]:
    """'os.environ.get' for an Attribute chain rooted at a Name."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# no-bare-print (ported from tests/test_lint_no_print.py, rounds 3+7)
# ---------------------------------------------------------------------------

_PRINT_CALL = re.compile(r"(?<![\w.])print\(")

# log.py IS the print wrapper — the single allowed call site.
_PRINT_ALLOWED = frozenset(("log.py",))


class NoBarePrintRule(Rule):
    name = "no-bare-print"
    engine = "ast"
    doc = ("no bare print() outside fdtd3d_tpu/log.py — route through "
           "log.log()/log.warn()/log.report() (one-switch logging)")

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        findings: List[Finding] = []
        for sf in ctx.files():
            if sf.basename in _PRINT_ALLOWED \
                    or sf.basename in LEGACY_FILES:
                continue
            for lineno, tok in sf.code_lines():
                if _PRINT_CALL.search(tok):
                    findings.append(Finding(
                        self.name, sf.relpath, lineno,
                        f"bare print() — use log.log()/log.warn()/"
                        f"log.report(): {tok.strip()[:80]}"))
        return findings, {"files_scanned": len(ctx.files())}


# ---------------------------------------------------------------------------
# atomic-write (ported from tests/test_lint_atomic_write.py, round 9)
# ---------------------------------------------------------------------------

# io.py hosts the primitives; inside it, w-mode opens may appear only
# within these function names ("_write" = the atomic_publish writer-
# closure convention).
_IO_ALLOWED_FUNCS = frozenset(("atomic_open", "_write"))
_BANNED_WRITE_ATTRS = frozenset(("tofile", "savez", "savez_compressed"))


def _is_write_mode(mode: str) -> bool:
    return "w" in mode or "x" in mode


class _AtomicWriteVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        import os
        self.is_io = os.path.basename(relpath) == "io.py"
        self.func_stack: List[str] = []
        self.offenders: List[Tuple[int, str]] = []

    def _flag(self, node: ast.AST, what: str):
        self.offenders.append((node.lineno, what))

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _allowed_here(self) -> bool:
        if not self.is_io:
            return False
        return bool(set(self.func_stack) & _IO_ALLOWED_FUNCS)

    def visit_Call(self, node):
        func = node.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
            if name in _BANNED_WRITE_ATTRS and not self.is_io:
                self._flag(node, f".{name}() writes files directly — "
                                 f"route through fdtd3d_tpu.io's "
                                 f"atomic writer")
            if name == "open" and not (
                    isinstance(func.value, ast.Name)
                    and func.value.id in ("io", "builtins")):
                name = None  # os.open / gzip.open etc: not builtin open
        if name == "open":
            mode = "r"
            if len(node.args) >= 2 and isinstance(node.args[1],
                                                  ast.Constant):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value,
                                                   ast.Constant):
                    mode = str(kw.value.value)
            literal = (len(node.args) < 2
                       or isinstance(node.args[1], ast.Constant))
            if (_is_write_mode(mode) or not literal) \
                    and not self._allowed_here():
                self._flag(node, f"open(..., {mode!r}) outside the "
                                 f"atomic writer — use io.atomic_open/"
                                 f"io.atomic_publish (append-mode JSONL "
                                 f"sinks are the one exception)")
        self.generic_visit(node)


class AtomicWriteRule(Rule):
    name = "atomic-write"
    engine = "ast"
    doc = ("every file write in fdtd3d_tpu/ routes through io's atomic "
           "writer (docs/ROBUSTNESS.md durability contract)")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        v = _AtomicWriteVisitor(sf.relpath)
        v.visit(sf.tree)
        return [Finding(self.name, sf.relpath, line, what)
                for line, what in v.offenders]

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        findings: List[Finding] = []
        n = 0
        for sf in ctx.files():
            # the durability contract covers the package, not tools/
            # (tools write reports the atomic guarantee adds nothing
            # to; checkpoints and solver artifacts all live in-package)
            if not sf.relpath.replace("\\", "/").startswith(
                    "fdtd3d_tpu"):
                continue
            n += 1
            findings += self.check_file(sf)
        return findings, {"files_scanned": n}


# ---------------------------------------------------------------------------
# env-registry
# ---------------------------------------------------------------------------

_ENV_NAME = re.compile(r"^FDTD3D_[A-Z0-9_]+$")

# The read surface beyond the default fdtd3d_tpu/ + tools/ scan:
# bench.py and the graft entry read bench knobs, tests/ reads
# FDTD3D_TEST_TPU (conftest CPU pin) — a registry entry read only
# there must still count as read.
_ENV_EXTRA_SURFACE = ("bench.py", "__graft_entry__.py", "tests")


def _env_reads(tree: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, name) for every literal FDTD3D_* environment READ:
    os.environ.get/os.getenv/environ[...] loads. Writes (environ[k]=v,
    .pop cleanup) are not reads."""
    out: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            if d.endswith("environ.get") or d in ("os.getenv", "getenv"):
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and _ENV_NAME.match(node.args[0].value):
                    out.append((node.lineno, node.args[0].value))
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            d = _dotted(node.value) or ""
            if d.endswith("environ"):
                sl = node.slice
                if isinstance(sl, ast.Constant) \
                        and isinstance(sl.value, str) \
                        and _ENV_NAME.match(sl.value):
                    out.append((node.lineno, sl.value))
    return out


def _env_mentions(tree: ast.AST) -> Set[str]:
    """Every FDTD3D_* string constant in the file (the lenient side of
    the registered-but-unread check: setenv/monkeypatch/docs-in-code
    references all count as 'this knob is alive')."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if _ENV_NAME.match(node.value):
                out.add(node.value)
    return out


class EnvRegistryRule(Rule):
    name = "env-registry"
    engine = "ast"
    doc = ("every literal FDTD3D_* env read appears in config.ENV_KNOBS "
           "with type/default/doc; registered-but-unread entries fail "
           "too")

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        from fdtd3d_tpu.config import ENV_KNOBS
        findings: List[Finding] = []
        surface = list(ctx.files()) \
            + ctx.extra_files(*_ENV_EXTRA_SURFACE)
        mentions: Set[str] = set()
        n_reads = 0
        for sf in surface:
            parts = sf.relpath.replace("\\", "/").split("/")
            # fixtures are deliberate known-bad snippets, not code
            if sf.basename in LEGACY_FILES or "fixtures" in parts:
                continue
            mentions |= _env_mentions(sf.tree)
            for lineno, envname in _env_reads(sf.tree):
                n_reads += 1
                if envname not in ENV_KNOBS:
                    findings.append(Finding(
                        self.name, sf.relpath, lineno,
                        f"unregistered env knob {envname!r} — declare "
                        f"it in fdtd3d_tpu.config.ENV_KNOBS with "
                        f"type/default/doc"))
        from fdtd3d_tpu.analysis import ROOT as _REPO_ROOT
        for envname, knob in sorted(ENV_KNOBS.items()):
            # registered-but-unread is a property of THIS repo's
            # surface; on a foreign tree (--path) only reads are
            # checkable
            if ctx.root != _REPO_ROOT:
                break
            if envname not in mentions:
                findings.append(Finding(
                    self.name, "fdtd3d_tpu/config.py", None,
                    f"registered env knob {envname!r} is never read "
                    f"anywhere — dead registry entry (delete it or "
                    f"wire the knob)"))
            if not knob.doc.strip():
                findings.append(Finding(
                    self.name, "fdtd3d_tpu/config.py", None,
                    f"registered env knob {envname!r} has an empty "
                    f"doc"))
        return findings, {"registered": len(ENV_KNOBS),
                          "literal_reads": n_reads}


# ---------------------------------------------------------------------------
# tracer-hostility
# ---------------------------------------------------------------------------

# The marker the rule understands: a module-level
#   GRAPH_SAFE_FNS = ("fn_a", "fn_b", ...)
# declares that every function of that name in the module (at any
# nesting depth — the step/health closures are nested builders) is
# GRAPH CODE: it runs under jit/scan/shard_map tracing, where a host
# call either crashes (``.item()`` on a tracer) or silently pins a
# trace-time constant (``time.time()``). The rule checks the marked
# functions AND every same-module function they call by simple name,
# transitively.
GRAPH_SAFE_MARKER = "GRAPH_SAFE_FNS"

_HOSTILE_NAME_CALLS = frozenset(("float", "open", "input", "breakpoint"))
_HOSTILE_ATTR_CALLS = frozenset(("item", "tolist", "block_until_ready"))
_HOSTILE_DOTTED = (
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get", "jax.device_put",
)
_HOSTILE_ROOTS = ("time.", "os.")


def _marker_names(tree: ast.AST) -> Optional[Set[str]]:
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == GRAPH_SAFE_MARKER:
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return {e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
    return None


def _all_funcdefs(tree: ast.AST) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


class TracerHostilityRule(Rule):
    name = "tracer-hostility"
    engine = "ast"
    doc = ("no host calls (float()/.item()/np.asarray/time.time()/"
           "open/os.*) inside functions a module marks GRAPH_SAFE_FNS, "
           "nor in same-module functions they call")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        marked = _marker_names(sf.tree)
        if marked is None:
            return []
        findings: List[Finding] = []
        by_name: Dict[str, List[ast.AST]] = {}
        for fn in _all_funcdefs(sf.tree):
            by_name.setdefault(fn.name, []).append(fn)
        missing = sorted(marked - set(by_name))
        for name in missing:
            findings.append(Finding(
                self.name, sf.relpath, None,
                f"{GRAPH_SAFE_MARKER} names {name!r} but no such "
                f"function exists in the module (marker rot)"))
        # reachability: marked defs + same-module Name-calls, transitive
        visited: List[ast.AST] = []
        seen: Set[int] = set()
        frontier = [fn for name in sorted(marked & set(by_name))
                    for fn in by_name[name]]
        while frontier:
            fn = frontier.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            visited.append(fn)
            for node in walk_shallow(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id in by_name:
                    frontier.extend(by_name[node.func.id])
        for fn in visited:
            for node in walk_shallow(fn):
                if not isinstance(node, ast.Call):
                    continue
                hit = None
                func = node.func
                if isinstance(func, ast.Name) \
                        and func.id in _HOSTILE_NAME_CALLS:
                    hit = f"{func.id}()"
                elif isinstance(func, ast.Attribute):
                    d = _dotted(func)
                    if d is not None and (
                            d in _HOSTILE_DOTTED
                            or any(d.startswith(r)
                                   for r in _HOSTILE_ROOTS)):
                        hit = f"{d}()"
                    elif func.attr in _HOSTILE_ATTR_CALLS:
                        hit = f".{func.attr}()"
                if hit:
                    findings.append(Finding(
                        self.name, sf.relpath, node.lineno,
                        f"host call {hit} inside graph-safe function "
                        f"{fn.name!r} (reachable from "
                        f"{GRAPH_SAFE_MARKER}) — it would pin a "
                        f"trace-time constant or crash on a tracer"))
        return findings

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        findings: List[Finding] = []
        n_marked = 0
        for sf in ctx.files():
            if _marker_names(sf.tree) is not None:
                n_marked += 1
            findings += self.check_file(sf)
        return findings, {"modules_with_markers": n_marked}


# ---------------------------------------------------------------------------
# exception-hygiene
# ---------------------------------------------------------------------------

# Recovery-path files: code here sits between faults and their
# handlers, so a too-broad catch can swallow the SimulatedPreemption-
# family BaseExceptions the fault harness uses to model kills
# (fdtd3d_tpu/faults.py docstring).
_RECOVERY_FILES = frozenset(("fdtd3d_tpu/supervisor.py",
                             "fdtd3d_tpu/faults.py"))
_PREEMPT_NAMES = frozenset(("SimulatedPreemption", "SimulatedHostLoss"))


def _handler_type_names(h: ast.ExceptHandler) -> List[str]:
    t = h.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        if isinstance(e, ast.Name):
            out.append(e.id)
        elif isinstance(e, ast.Attribute):
            out.append(e.attr)
    return out


def _reraises(h: ast.ExceptHandler) -> bool:
    """Does the handler body contain a raise? Nested function/lambda
    subtrees are EXCLUDED (a raise inside a callback the handler merely
    defines is not a re-raise) without aborting the rest of the scan —
    walk_shallow skips exactly those subtrees."""
    for stmt in h.body:
        if isinstance(stmt, ast.Raise):
            return True
        for node in walk_shallow(stmt):
            if isinstance(node, ast.Raise):
                return True
    return False


class ExceptionHygieneRule(Rule):
    name = "exception-hygiene"
    engine = "ast"
    doc = ("no bare except anywhere; except BaseException must "
           "re-raise; supervisor.py/faults.py recovery paths may not "
           "catch Exception/SimulatedPreemption broadly")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        rel = sf.relpath.replace("\\", "/")
        recovery = rel in _RECOVERY_FILES
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            names = _handler_type_names(node)
            if node.type is None:
                findings.append(Finding(
                    self.name, sf.relpath, node.lineno,
                    "bare 'except:' swallows BaseExceptions "
                    "(SimulatedPreemption kills, KeyboardInterrupt) — "
                    "name the exception types"))
                continue
            if "BaseException" in names and not _reraises(node):
                findings.append(Finding(
                    self.name, sf.relpath, node.lineno,
                    "'except BaseException' without a re-raise would "
                    "swallow kills — re-raise, or name narrower types"))
            if recovery:
                if "Exception" in names:
                    findings.append(Finding(
                        self.name, sf.relpath, node.lineno,
                        "'except Exception' in a recovery path — name "
                        "the concrete transient types "
                        "(supervisor.TRANSIENT_ERRORS) so a future "
                        "broadening to BaseException can never swallow "
                        "a SimulatedPreemption"))
                hit = sorted(set(names) & _PREEMPT_NAMES)
                if hit and not _reraises(node):
                    findings.append(Finding(
                        self.name, sf.relpath, node.lineno,
                        f"handler catches {hit[0]} (a simulated kill) "
                        f"without re-raising — a kill is a kill "
                        f"(docs/ROBUSTNESS.md fault model)"))
        return findings

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        findings: List[Finding] = []
        for sf in ctx.files():
            if sf.basename in LEGACY_FILES:
                continue
            findings += self.check_file(sf)
        return findings, {"files_scanned": len(ctx.files())}
