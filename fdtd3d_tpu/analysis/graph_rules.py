"""Engine 2 — jaxpr/structural rules on the PRODUCTION chunk runner.

These rules need jax (imported lazily inside ``run``) but no chip:
everything runs on the CPU backend, with the sharded checks tracing
inside ``shard_map`` over the 8-device virtual host mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``, the same
environment tier-1 and ``tools/fdtd_lint.py`` set up).

* ``donation-safety`` generalizes the per-kernel structural tests
  (``test_h_inputs_never_donated``, ``test_tb_donation_fetch_before_
  write``) into ONE parameterized rule over EVERY Pallas kernel: each
  aliased (donated) operand's in-map must be monotone and fetch every
  HBM block no later than the aliased out-map's first visit of it —
  otherwise a backward read can observe a block its own output already
  flushed (a hazard interpreter mode can never surface at runtime).
* ``scope-coverage`` promotes the comm lane's >=95% statistical
  attribution to an ENUMERATED zero: every collective
  (ppermute/psum/pmax/pmin/all_gather/...) in every sharded step
  kind's traced jaxpr must carry a named scope from
  ``telemetry.GRAPH_SPANS`` (the docs/OBSERVABILITY.md table). The
  report counts unscoped collectives per kind; the bar is 0.
* ``readback-discipline`` drives a real (tiny, CPU) Simulation chunk
  and asserts the flight recorder's contract: <=1 ``jax.device_get``
  per chunk and never a full-field transfer (every leaf scalar-sized).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

from fdtd3d_tpu.analysis import Context, Finding, Rule

# -------------------------------------------------------------------------
# donation-safety
# -------------------------------------------------------------------------

# Every Pallas kernel builder in the repo, with a canonical CPU-
# buildable config that engages it (CPML + mid-grid point source where
# the kernel supports them, so the full operand set — psi stacks,
# source masks, walls — is present in the capture). Adding a kernel
# module without registering it here fails the rule (see run()).
_KERNEL_TARGETS: Tuple[Tuple[str, str, str], ...] = (
    ("pallas",           "fdtd3d_tpu.ops.pallas3d",        "make_pallas_step"),
    ("pallas_fused",     "fdtd3d_tpu.ops.pallas_fused",    "make_fused_eh_step"),
    ("pallas_packed",    "fdtd3d_tpu.ops.pallas_packed",   "make_packed_eh_step"),
    ("pallas_packed_tb", "fdtd3d_tpu.ops.pallas_packed_tb", "make_packed_tb_step"),
    # the round-14 widened sharded build: TFSF value-plane + tfofs +
    # coefficient-grid + Drude-J operands all present alongside the
    # depth-k ghost operands, so their donation structure is gated too
    ("pallas_packed_tb_widened",
     "fdtd3d_tpu.ops.pallas_packed_tb", "make_packed_tb_step"),
    ("pallas_packed_ds", "fdtd3d_tpu.ops.pallas_packed_ds", "make_packed_ds_step"),
    # the round-16 lane-capable BATCHED build (batch=3): the packed
    # pallas_call under the batch_lane-surcharged tile pick — the
    # executable batch.BatchSimulation vmaps; its donation structure
    # is gated like every solo build
    ("pallas_packed_batch",
     "fdtd3d_tpu.ops.pallas_packed", "make_packed_eh_step_batched"),
)


def _target_config(label: str):
    """-> (cfg, topology or None): the canonical config that engages
    the labeled kernel build; a topology makes the capture a SHARDED
    build (mesh axis NAMES only — constructing the pallas_call needs
    no live mesh)."""
    from fdtd3d_tpu import costs
    from fdtd3d_tpu.config import (PmlConfig, PointSourceConfig,
                                   SimConfig)
    if label == "pallas_packed_tb":
        # the temporal-blocked kernel needs x-extent >= a few tiles and
        # an interior source with >=1-tile margin (its eligibility gate)
        return SimConfig(
            scheme="3D", size=(48, 16, 16), time_steps=8, dx=1e-3,
            courant_factor=0.4, wavelength=8e-3, use_pallas=True,
            pml=PmlConfig(size=(3, 3, 3)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(24, 8, 8))), None
    if label == "pallas_packed_tb_widened":
        return costs.config_tb_widened(), (1, 2, 2)
    if label == "pallas_packed_batch":
        import dataclasses
        return dataclasses.replace(
            costs.config_for_kind("pallas_packed"),
            use_pallas=True), None
    kind = label if label in costs.STEP_KINDS else "pallas"
    cfg = costs.config_for_kind(kind)
    import dataclasses
    return dataclasses.replace(cfg, use_pallas=True), None


def _index_tuple(index_map, idx: Tuple[int, ...]) -> Tuple[int, ...]:
    res = index_map(*idx)
    if not isinstance(res, tuple):
        res = (res,)
    return tuple(int(v) for v in res)


def check_pallas_capture(label: str, kw: Dict[str, Any]) -> List[str]:
    """Donation-safety check of one captured ``pl.pallas_call``'s
    keyword arguments -> list of problem strings (empty = safe).

    For every aliased (donated) operand pair: the in-map's block
    sequence over the grid iteration order must be monotone
    (1-D grids; per-block contiguous for multi-D), and each block must
    be fetched no later than the aliased out-map's FIRST visit of it —
    the generalized form of the tb kernel's fetch-before-write test.
    Non-aliased operands are unconstrained (never flushed under the
    call). An aliased pair whose block shapes differ is unverifiable
    and reported as such.
    """
    problems: List[str] = []
    aliases = dict(kw.get("input_output_aliases") or {})
    if not aliases:
        return problems
    grid = kw.get("grid") or ()
    if isinstance(grid, int):
        grid = (grid,)
    grid = tuple(int(g) for g in grid)
    in_specs = list(kw.get("in_specs") or ())
    out_specs = list(kw.get("out_specs") or ())
    if not grid or not in_specs or not out_specs:
        # an aliased call we cannot introspect must FAIL the gate, not
        # silently pass it (e.g. a kernel migrated to pl.GridSpec /
        # grid_spec= — teach this checker the new shape, don't skip)
        problems.append(
            f"{label}: pallas_call donates operands "
            f"({sorted(aliases)}) but its grid/in_specs/out_specs "
            f"kwargs are not retrievable — donation-safety "
            f"unverifiable; update check_pallas_capture for this "
            f"call form")
        return problems
    iters = list(itertools.product(*(range(g) for g in grid)))
    for j_in, k_out in sorted(aliases.items()):
        try:
            in_spec = in_specs[j_in]
            out_spec = out_specs[k_out]
        except IndexError:
            problems.append(f"{label}: alias {j_in}->{k_out} out of "
                            f"range ({len(in_specs)} inputs, "
                            f"{len(out_specs)} outputs)")
            continue
        if getattr(in_spec, "block_shape", None) != \
                getattr(out_spec, "block_shape", None):
            problems.append(
                f"{label}: aliased operand {j_in} and output {k_out} "
                f"have different block shapes — donation unverifiable")
            continue
        fetches = [_index_tuple(in_spec.index_map, idx)
                   for idx in iters]
        visits = [_index_tuple(out_spec.index_map, idx)
                  for idx in iters]
        if len(grid) == 1 and fetches != sorted(fetches):
            problems.append(
                f"{label}: donated operand {j_in} has a NON-MONOTONE "
                f"in-map {fetches} — a later iteration re-fetches an "
                f"earlier HBM block the aliased output may already "
                f"have flushed")
        else:
            # multi-dim grids: each block's fetches must at least be
            # one contiguous run (no leave-and-return re-fetch)
            runs: Dict[Tuple[int, ...], List[int]] = {}
            for i, b in enumerate(fetches):
                runs.setdefault(b, []).append(i)
            for b, ii in runs.items():
                if ii[-1] - ii[0] + 1 != len(ii):
                    problems.append(
                        f"{label}: donated operand {j_in} re-fetches "
                        f"block {b} non-contiguously at iterations "
                        f"{ii}")
        first_fetch: Dict[Tuple[int, ...], int] = {}
        for i, b in enumerate(fetches):
            first_fetch.setdefault(b, i)
        first_visit: Dict[Tuple[int, ...], int] = {}
        for i, b in enumerate(visits):
            first_visit.setdefault(b, i)
        for b, fi in sorted(first_fetch.items()):
            vi = first_visit.get(b)
            if vi is not None and fi > vi:
                problems.append(
                    f"{label}: donated operand {j_in} fetches block "
                    f"{b} at iteration {fi}, AFTER the aliased "
                    f"output {k_out} first visits it at iteration "
                    f"{vi} — the read can observe flushed output "
                    f"(donation hazard)")
    return problems


def capture_kernel_calls(module, builder_name: str, static,
                         mesh_axes=None, mesh_shape=None
                         ) -> List[Dict[str, Any]]:
    """Build the kernel with ``pl.pallas_call`` spied, returning every
    captured call's kwargs (a builder may issue several calls — the
    two-pass kernels build one per family). ``mesh_axes``/
    ``mesh_shape`` make it a SHARDED build (the widened-wedge
    target)."""
    captured: List[Dict[str, Any]] = []
    pl = module.pl
    real_call = pl.pallas_call

    def spy(kernel, **kw):
        captured.append(dict(kw))
        return real_call(kernel, **kw)

    pl.pallas_call = spy
    try:
        if mesh_axes is not None:
            step = getattr(module, builder_name)(static, mesh_axes,
                                                 mesh_shape)
        else:
            step = getattr(module, builder_name)(static)
    finally:
        pl.pallas_call = real_call
    if step is None:
        raise RuntimeError(
            f"{builder_name} returned None for its canonical config — "
            f"the kernel is ineligible and its donation structure "
            f"cannot be verified (update _target_config)")
    if not captured:
        raise RuntimeError(
            f"{builder_name} built no pallas_call — nothing captured")
    return captured


class DonationSafetyRule(Rule):
    name = "donation-safety"
    engine = "structural"
    doc = ("every Pallas kernel's aliased (donated) operands have "
           "monotone in-maps and fetch each block before the aliased "
           "output's first visit — one parameterized rule, all kernels")

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        import importlib
        import os

        from fdtd3d_tpu.solver import build_static
        findings: List[Finding] = []
        stats: Dict[str, Any] = {}
        registered = {mod for _l, mod, _b in _KERNEL_TARGETS}
        ops_dir = os.path.join(ctx.root, "fdtd3d_tpu", "ops")
        if os.path.isdir(ops_dir):
            for fn in sorted(os.listdir(ops_dir)):
                if fn.startswith("pallas") and fn.endswith(".py"):
                    mod = f"fdtd3d_tpu.ops.{fn[:-3]}"
                    if mod not in registered:
                        findings.append(Finding(
                            self.name, f"fdtd3d_tpu/ops/{fn}", None,
                            f"Pallas kernel module {mod} is not "
                            f"registered in the donation-safety "
                            f"targets — add it to _KERNEL_TARGETS "
                            f"with a canonical config"))
        for label, modname, builder in _KERNEL_TARGETS:
            module = importlib.import_module(modname)
            cfg, topo = _target_config(label)
            static = build_static(cfg)
            mesh_axes = mesh_shape = None
            if topo is not None:
                import dataclasses

                from fdtd3d_tpu.parallel.mesh import (mesh_axis_map,
                                                      mesh_shape_map)
                static = dataclasses.replace(static, topology=topo)
                mesh_axes = mesh_axis_map(topo)
                mesh_shape = mesh_shape_map(topo)
            try:
                calls = capture_kernel_calls(module, builder, static,
                                             mesh_axes, mesh_shape)
            except RuntimeError as exc:
                findings.append(Finding(
                    self.name, modname.replace(".", "/") + ".py", None,
                    str(exc)))
                continue
            n_aliased = 0
            for kw in calls:
                n_aliased += len(kw.get("input_output_aliases") or {})
                for problem in check_pallas_capture(label, kw):
                    findings.append(Finding(
                        self.name, modname.replace(".", "/") + ".py",
                        None, problem))
            stats[label] = {"pallas_calls": len(calls),
                            "aliased_operands": n_aliased}
        return findings, stats


# -------------------------------------------------------------------------
# scope-coverage: ZERO unscoped collectives
# -------------------------------------------------------------------------

_COLLECTIVES = frozenset(("ppermute", "psum", "pmax", "pmin",
                          "all_gather", "all_to_all", "reduce_scatter"))

_SCOPE_TOPOLOGY = (2, 2, 2)


def collect_collectives(jaxpr, prefix: str = ""
                        ) -> List[Tuple[str, str, str]]:
    """-> [(primitive, section, name_stack)] for every collective eqn,
    walking ALL control-flow branches (coverage must not skip the
    branch a cond rarely takes)."""
    from fdtd3d_tpu.costs import _INNER_JAXPR_PARAMS, _section_of
    out: List[Tuple[str, str, str]] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        stack = f"{prefix}/{eqn.source_info.name_stack}"
        if name in _COLLECTIVES:
            out.append((name, _section_of(stack), stack))
        if name == "cond":
            for br in eqn.params.get("branches", ()):
                out += collect_collectives(br.jaxpr, stack)
            continue
        if name == "while":
            for p in ("cond_jaxpr", "body_jaxpr"):
                if p in eqn.params:
                    out += collect_collectives(eqn.params[p].jaxpr,
                                               stack)
            continue
        if name == "pallas_call":
            out += collect_collectives(
                getattr(eqn.params["jaxpr"], "jaxpr",
                        eqn.params["jaxpr"]), stack)
            continue
        for p in _INNER_JAXPR_PARAMS:
            if p in eqn.params:
                inner = eqn.params[p]
                out += collect_collectives(getattr(inner, "jaxpr",
                                                   inner), stack)
                break
    return out


def unscoped_collectives(colls):
    """The scope bar, per collective kind: ppermute IS the halo
    exchange — the docs/OBSERVABILITY.md table assigns EVERY
    neighbor-plane ppermute to the ``halo-exchange`` scope, and the
    comm lane's attribution rides exactly that — so a ppermute merely
    inheriting an outer E-update/H-update scope is a MIS-ATTRIBUTED
    exchange, not a scoped one. Other collectives (health psums/pmax,
    the per-chip all_gather) need any GRAPH_SPANS scope."""
    return [(prim, sec, stack) for prim, sec, stack in colls
            if (sec != "halo-exchange" if prim == "ppermute"
                else sec == "unattributed")]


class ScopeCoverageRule(Rule):
    name = "scope-coverage"
    engine = "structural"
    doc = ("every collective (ppermute/psum/all_gather/...) in every "
           "sharded step kind's traced chunk names a GRAPH_SPANS scope "
           "— zero unscoped collectives, enumerated (not a percentage)")

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        import jax

        from fdtd3d_tpu import costs
        n_need = 1
        for p in _SCOPE_TOPOLOGY:
            n_need *= p
        if jax.device_count() < n_need:
            raise RuntimeError(
                f"scope-coverage needs {n_need} devices for the "
                f"{_SCOPE_TOPOLOGY} trace; set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={n_need} "
                f"before jax initializes (tools/fdtd_lint.py does)")
        findings: List[Finding] = []
        stats: Dict[str, Any] = {}
        # the round-14 widened sharded tb path (TFSF wedge incident-
        # line port + Drude-J ring + material-grid sub-blocks) traces
        # as its own lane: new exchange/psum sites in the widened
        # wedge must be mesh-scoped like every other collective
        lanes = [(kind, costs.config_for_kind(kind, n=16, pml=2),
                  kind, 0) for kind in costs.SHARDED_STEP_KINDS]
        lanes.append(("pallas_packed_tb_widened",
                      costs.config_tb_widened(),
                      "pallas_packed_tb", 0))
        # the round-16 SHARDED BATCHED lane: the vmapped packed runner
        # inside shard_map — the batch's ONE shared halo exchange per
        # step must be mesh-scoped like every solo collective
        lanes.append(("pallas_packed_batch",
                      costs.config_for_kind("pallas_packed",
                                            n=16, pml=2),
                      "pallas_packed", 3))
        for label, cfg, kind, batch in lanes:
            # pml=2 keeps the CPML slabs inside the 8-cell shards of a
            # 16^3 grid on (2,2,2) (solver.slab_axes needs
            # local_n > 2*(pml+1)) — the tests/test_comm_costs.py probe
            _runner, closed, _static, _topo, _spc = costs.trace_chunk(
                cfg, n_steps=8, kind=kind, topology=_SCOPE_TOPOLOGY,
                batch=batch)
            colls = collect_collectives(closed.jaxpr)
            unscoped = unscoped_collectives(colls)
            stats[label] = {"collectives": len(colls),
                            "unscoped_collectives": len(unscoped)}
            for prim, sec, stack in unscoped:
                want = ("the halo-exchange scope"
                        if prim == "ppermute"
                        else "a telemetry.GRAPH_SPANS scope")
                findings.append(Finding(
                    self.name, "", None,
                    f"step kind {label!r} on {_SCOPE_TOPOLOGY}: "
                    f"{prim} does not carry {want} (attributed: "
                    f"{sec}; stack: "
                    f"{stack.strip('/')[:110] or '<empty>'}) — wrap "
                    f"it in telemetry.named(...) per the "
                    f"docs/OBSERVABILITY.md scope table"))
        return findings, stats


# -------------------------------------------------------------------------
# readback-discipline
# -------------------------------------------------------------------------

# Any device->host transfer bigger than this is a field, not a health
# scalar (the per-chip lane's all_gathered vectors stay <= n_chips).
_SCALAR_ELEMS = 64


def check_transfer_log(calls: Sequence[Sequence[int]],
                       n_chunks: int) -> List[str]:
    """Validate a per-advance log of device_get leaf sizes against the
    flight-recorder budget: <=1 device_get per chunk, every leaf
    scalar-class (never a field array)."""
    problems: List[str] = []
    if len(calls) > n_chunks:
        problems.append(
            f"{len(calls)} device_get calls across {n_chunks} "
            f"chunk(s) — the budget is <=1 scalar-tuple readback per "
            f"chunk (telemetry.readback)")
    for i, sizes in enumerate(calls):
        big = [s for s in sizes if s > _SCALAR_ELEMS]
        if big:
            problems.append(
                f"device_get #{i} transfers leaves of {big} elements "
                f"— a full-field host transfer; health counters must "
                f"reduce in-graph")
    return problems


class ReadbackDisciplineRule(Rule):
    name = "readback-discipline"
    engine = "structural"
    doc = ("a telemetering advance() performs <=1 device_get per chunk "
           "and never transfers a field array (in-graph health "
           "reduction contract)")

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        import tempfile

        import jax
        import numpy as np

        from fdtd3d_tpu.config import (OutputConfig, PmlConfig,
                                       PointSourceConfig, SimConfig)
        from fdtd3d_tpu.sim import Simulation
        findings: List[Finding] = []
        with tempfile.TemporaryDirectory() as td:
            cfg = SimConfig(
                scheme="3D", size=(16, 16, 16), time_steps=6, dx=1e-3,
                courant_factor=0.4, wavelength=8e-3,
                pml=PmlConfig(size=(2, 2, 2)),
                point_source=PointSourceConfig(
                    enabled=True, component="Ez", position=(8, 8, 8)),
                output=OutputConfig(
                    telemetry_path=f"{td}/telemetry.jsonl"))
            sim = Simulation(cfg)
            try:
                sim.advance(3)  # compile outside the counting window
                calls: List[List[int]] = []
                real_get = jax.device_get

                def counting_get(tree):
                    calls.append([int(np.size(x))
                                  for x in jax.tree.leaves(tree)])
                    return real_get(tree)

                jax.device_get = counting_get
                try:
                    sim.advance(3)
                finally:
                    jax.device_get = real_get
            finally:
                sim.close()
            for problem in check_transfer_log(calls, n_chunks=1):
                findings.append(Finding(self.name, "fdtd3d_tpu/sim.py",
                                        None, problem))
            stats = {"device_gets_per_chunk": len(calls),
                     "max_leaf_elems": max(
                         (s for c in calls for s in c), default=0)}
        return findings, stats
