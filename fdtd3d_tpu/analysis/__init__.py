"""Unified static-analysis framework: one analyzer, every invariant.

Eight PRs in, the repo's correctness contracts were enforced by
scattered one-off mechanisms: two hand-rolled AST lints (bare print,
atomic writes), a kernel-specific donation test, a statistical >=95%
scope-coverage assertion, and ~10 undocumented ``FDTD3D_*`` env knobs.
This package makes those invariants *enumerable and zero-tolerance* —
the single-source-per-invariant discipline the PIConGPU/WarpX
multi-backend codebases use to keep kernels honest (PAPERS.md) — via
two engines behind one CLI (``tools/fdtd_lint.py``):

* **Engine 1 — AST** (:mod:`fdtd3d_tpu.analysis.ast_rules`): walks
  every ``.py`` file in ``fdtd3d_tpu/`` + ``tools/`` (env-registry
  additionally scans ``bench.py``/``__graft_entry__.py``/``tests/``)
  and hosts pluggable rule classes: ``no-bare-print``,
  ``atomic-write``, ``env-registry``, ``tracer-hostility``,
  ``exception-hygiene``.
* **Engine 2 — jaxpr/structural**
  (:mod:`fdtd3d_tpu.analysis.graph_rules`,
  :mod:`fdtd3d_tpu.analysis.schema_rules`): reuses the cost ledger's
  production-runner tracing (``costs.trace_chunk``) to verify, per
  step kind and topology on the CPU virtual mesh: ``donation-safety``
  (aliased in/out block maps monotone for EVERY Pallas kernel),
  ``scope-coverage`` (ZERO unscoped collectives — enumerated, not a
  percentage), ``readback-discipline`` (<=1 device_get per chunk, no
  full-field transfer) and ``schema-drift`` (every key each writer
  emits exists in the matching validator's key table).

Rules return :class:`Finding` lists; a checked-in suppression baseline
(``tools/lint_baseline.json``) may waive specific findings with a
per-entry reason (docs/STATIC_ANALYSIS.md documents the policy: the
baseline ships EMPTY and every addition needs a justification).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import tokenize
from typing import Any, Dict, List, Optional, Sequence, Tuple

REPORT_SCHEMA = "fdtd3d-lint-report"
REPORT_VERSION = 1
BASELINE_SCHEMA = "fdtd3d-lint-baseline"

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Engine-1 default scan surface (repo-relative directories).
SCAN_DIRS = ("fdtd3d_tpu", "tools")

# Quarantined LEGACY tools (round 10): frozen historical reproduction
# scripts gated behind --i-know-this-is-legacy; not part of the
# maintained surface any AST rule guards.
LEGACY_FILES = frozenset(("measure_r3.py", "measure_r4.py"))


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, pointing at a file/line when known."""

    rule: str
    file: str                 # repo-relative path ("" = repo-wide)
    line: Optional[int]
    message: str

    def format(self) -> str:
        loc = self.file or "<repo>"
        if self.line is not None:
            loc += f":{self.line}"
        return f"{loc}: [{self.rule}] {self.message}"

    def to_json(self) -> Dict[str, Any]:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message}


class SourceFile:
    """One parsed source file, shared across AST rules (parse once)."""

    def __init__(self, relpath: str, abspath: str):
        self.relpath = relpath
        self.abspath = abspath
        with open(abspath, "rb") as f:
            self.source_bytes = f.read()
        self.source = self.source_bytes.decode("utf-8")
        self.tree = ast.parse(self.source, filename=relpath)
        self._code_lines: Optional[List[Tuple[int, str]]] = None

    @property
    def basename(self) -> str:
        return os.path.basename(self.relpath)

    def code_lines(self) -> List[Tuple[int, str]]:
        """-> [(lineno, code)] with strings and comments stripped via
        the tokenizer, so docstring prose never trips token rules."""
        if self._code_lines is None:
            import io as _io
            from collections import defaultdict
            lines: Dict[int, str] = defaultdict(str)
            reader = _io.BytesIO(self.source_bytes).readline
            for tok in tokenize.tokenize(reader):
                if tok.type in (tokenize.STRING, tokenize.COMMENT):
                    continue
                lines[tok.start[0]] += tok.string
            self._code_lines = sorted(lines.items())
        return self._code_lines


class Context:
    """Shared state for one analysis run: the parsed file surface.

    ``paths``: explicit list of (relpath, abspath) pairs; default is
    every ``.py`` under SCAN_DIRS. ``extra`` surfaces (env-registry's
    bench.py/tests/ read scan) are loaded lazily and cached too.
    """

    def __init__(self, root: str = ROOT,
                 paths: Optional[Sequence[Tuple[str, str]]] = None,
                 scan_all: bool = False):
        self.root = root
        self._files: Optional[List[SourceFile]] = None
        self._cache: Dict[str, SourceFile] = {}
        self._paths = list(paths) if paths is not None else None
        # scan_all: walk every .py under root instead of SCAN_DIRS —
        # the CLI's --path mode for linting an arbitrary tree
        self._scan_all = scan_all

    def _walk(self, reldir: str) -> List[Tuple[str, str]]:
        out = []
        base = os.path.join(self.root, reldir)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    ap = os.path.join(dirpath, fn)
                    out.append((os.path.relpath(ap, self.root), ap))
        return sorted(out)

    def load(self, relpath: str, abspath: str) -> SourceFile:
        sf = self._cache.get(relpath)
        if sf is None:
            sf = SourceFile(relpath, abspath)
            self._cache[relpath] = sf
        return sf

    def files(self) -> List[SourceFile]:
        """The default engine-1 surface (fdtd3d_tpu/ + tools/)."""
        if self._files is None:
            pairs = self._paths
            if pairs is None:
                if self._scan_all:
                    pairs = self._walk(".")
                else:
                    pairs = []
                    for d in SCAN_DIRS:
                        if os.path.isdir(os.path.join(self.root, d)):
                            pairs += self._walk(d)
            self._files = [self.load(rp, ap) for rp, ap in pairs]
        return self._files

    def extra_files(self, *patterns: str) -> List[SourceFile]:
        """Additional read-surface files: repo-relative file names or
        directory names (walked recursively). Missing entries are
        skipped (a fixture tree has no bench.py)."""
        out: List[SourceFile] = []
        for pat in patterns:
            ap = os.path.join(self.root, pat)
            if os.path.isfile(ap):
                out.append(self.load(pat, ap))
            elif os.path.isdir(ap):
                out += [self.load(rp, p) for rp, p in self._walk(pat)]
        return out


def walk_shallow(node: ast.AST):
    """Walk an AST subtree WITHOUT descending into nested function
    defs / lambdas — those are separate analysis units (shared by the
    tracer-hostility reachability walk, the exception-hygiene re-raise
    scan and the schema-drift resolver, so the traversal cannot
    drift between engines). Yields every other descendant; the root
    itself is not yielded."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class Rule:
    """Base class: one named invariant with a ``run(ctx)`` check.

    ``name``: the CLI/--rule identifier. ``engine``: "ast" (pure
    stdlib, runs anywhere) or "structural" (imports jax / traces the
    production runner; chip-free but heavier). ``run`` returns
    (findings, stats) — stats is a small JSON-able dict surfaced in
    the --json report (e.g. scope-coverage's per-kind unscoped
    collective counts).
    """

    name: str = ""
    engine: str = "ast"
    doc: str = ""

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        raise NotImplementedError


def all_rules() -> List[Rule]:
    """Every registered rule, AST engine first (cheap before heavy)."""
    from fdtd3d_tpu.analysis import ast_rules, graph_rules, schema_rules
    return [cls() for cls in (
        ast_rules.NoBarePrintRule,
        ast_rules.AtomicWriteRule,
        ast_rules.EnvRegistryRule,
        ast_rules.TracerHostilityRule,
        ast_rules.ExceptionHygieneRule,
        schema_rules.SchemaDriftRule,
        graph_rules.DonationSafetyRule,
        graph_rules.ScopeCoverageRule,
        graph_rules.ReadbackDisciplineRule,
    )]


def rules_by_name() -> Dict[str, Rule]:
    return {r.name: r for r in all_rules()}


# ---------------------------------------------------------------------------
# suppression baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> List[Dict[str, Any]]:
    """Parse + validate the suppression baseline; [] when absent.

    Shape: {"schema": "fdtd3d-lint-baseline", "version": 1,
    "suppressions": [{"rule", "file", "contains", "reason"}, ...]} —
    every entry MUST carry a non-empty reason (the per-entry comment
    the acceptance bar requires; JSON has no comments, so the reason
    field is the comment)."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    if data.get("schema") != BASELINE_SCHEMA:
        raise ValueError(f"{path}: schema {data.get('schema')!r} != "
                         f"{BASELINE_SCHEMA!r}")
    sups = data.get("suppressions")
    if not isinstance(sups, list):
        raise ValueError(f"{path}: suppressions missing or not a list")
    for i, s in enumerate(sups):
        for key in ("rule", "file", "contains", "reason"):
            if not isinstance(s.get(key), str):
                raise ValueError(
                    f"{path}: suppression #{i} missing {key!r}")
        if not s["reason"].strip():
            raise ValueError(
                f"{path}: suppression #{i} has an empty reason — every "
                f"baseline entry must justify itself "
                f"(docs/STATIC_ANALYSIS.md)")
    return sups


def apply_baseline(findings: List[Finding],
                   suppressions: List[Dict[str, Any]]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """-> (live findings, suppressed findings)."""
    live, suppressed = [], []
    for f in findings:
        hit = False
        for s in suppressions:
            if s["rule"] == f.rule and s["file"] == f.file \
                    and s["contains"] in f.message:
                hit = True
                break
        (suppressed if hit else live).append(f)
    return live, suppressed


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------


def run_rules(rule_names: Optional[Sequence[str]] = None,
              ctx: Optional[Context] = None,
              baseline_path: Optional[str] = None) -> Dict[str, Any]:
    """Run the selected rules (default: all) -> JSON-able report.

    Report: {"schema", "version", "rules": {name: {"engine", "doc",
    "findings", "suppressed", "stats"}}, "findings": [...],
    "suppressed": [...], "clean": bool}. A rule that crashes is itself
    reported as a finding (rule="analysis-error") — a broken analyzer
    must fail the gate, not silently pass it.
    """
    ctx = ctx or Context()
    registry = rules_by_name()
    names = list(rule_names) if rule_names else list(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown rule(s) {unknown}; available: "
                         f"{sorted(registry)}")
    suppressions = load_baseline(baseline_path) \
        if baseline_path else []

    report: Dict[str, Any] = {
        "schema": REPORT_SCHEMA, "version": REPORT_VERSION,
        "rules": {}, "findings": [], "suppressed": [],
    }
    for name in names:
        rule = registry[name]
        try:
            findings, stats = rule.run(ctx)
        except Exception as exc:  # a broken rule must fail the gate
            findings = [Finding("analysis-error", "", None,
                                f"rule {name!r} crashed: "
                                f"{type(exc).__name__}: {exc}")]
            stats = {}
        live, suppressed = apply_baseline(findings, suppressions)
        report["rules"][name] = {
            "engine": rule.engine, "doc": rule.doc,
            "findings": len(live), "suppressed": len(suppressed),
            "stats": stats,
        }
        report["findings"] += [f.to_json() for f in live]
        report["suppressed"] += [f.to_json() for f in suppressed]
    report["clean"] = not report["findings"]
    return report
