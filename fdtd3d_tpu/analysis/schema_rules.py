"""schema-drift: every key a writer emits exists in its validator.

The telemetry JSONL validators deliberately ALLOW extra keys at read
time (old readers must not choke on new files), which means a writer
can silently start emitting keys no validator version knows about —
the reader side then has no contract for them and the two drift apart.
This rule closes the loop statically, with no runtime scenario needed
(triggering a ``retry`` record takes a fault-injection run; reading
the emit call takes an AST walk):

* every ``*.emit("<type>", key=...)`` / supervisor ``_emit`` call and
  every dict-literal record (``{"v": ..., "type": "<type>", ...}``,
  the tools/trace_attribution.py pattern) may only use keys from
  ``telemetry.RECORD_SCHEMA[type]`` ∪ ``telemetry.RECORD_OPTIONAL
  [type]``; ``**expansions`` are resolved through the producing
  function's returned-dict keys (``provenance``,
  ``imbalance_summary``, call-site keywords for parameters) and an
  UNRESOLVABLE expansion is itself a finding — explicit beats silent;
* the cost-ledger writers (``costs.chunk_ledger`` / ``costs._comm_
  lane``) must emit exactly ``costs.LEDGER_KEYS`` / ``costs.COMM_
  KEYS`` (declared beside the validators);
* the overlap-artifact writer (``tools/aot_overlap.py analyze()``)
  must emit exactly the ``costs._OVERLAP_KEYS`` the ledger embed and
  the perf sentinel read.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from fdtd3d_tpu.analysis import (Context, Finding, Rule, SourceFile,
                                 walk_shallow)

_EMIT_NAMES = frozenset(("emit", "_emit"))


def _func_defs(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _const_keys(d: ast.Dict) -> Set[str]:
    return {k.value for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)}


def dict_keys_produced(fn: ast.AST,
                       varname: Optional[str] = None) -> Set[str]:
    """Union of string keys a function's returned dict(s) can carry:
    dict literals returned (directly or via a variable), subscript
    stores ``var["k"] = ...`` and ``var.update(k=...)`` keyword names.
    ``varname`` restricts the harvest to one variable (the ledger's
    ``ledger``/``comm`` accumulators)."""
    names: Set[str] = set()
    if varname is None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Name):
                names.add(node.value.id)
    else:
        names.add(varname)
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and varname is None \
                and isinstance(node.value, ast.Dict):
            keys |= _const_keys(node.value)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id in names \
                        and isinstance(node.value, ast.Dict):
                    keys |= _const_keys(node.value)
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in names \
                        and isinstance(t.slice, ast.Constant) \
                        and isinstance(t.slice.value, str):
                    keys.add(t.slice.value)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "update" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in names:
            keys |= {kw.arg for kw in node.keywords
                     if kw.arg is not None}
    return keys


def _popped_keys(fn: ast.AST, param: str) -> Set[str]:
    """Keys ``param.pop("k", ...)``-consumed inside ``fn`` — they never
    reach a ``**param`` re-expansion."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "pop" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == param \
                and node.args \
                and isinstance(node.args[0], ast.Constant):
            out.add(str(node.args[0].value))
    return out


def _declared_params(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in a.args + a.kwonlyargs + a.posonlyargs}
    return names


class _Surface:
    """Cross-file resolution tables for the **expansion resolver."""

    def __init__(self, files: List[SourceFile]):
        self.files = files
        # last-name -> union of producible dict keys, for every
        # function in the surface (used to resolve `**f(...)`)
        self.producers: Dict[str, Set[str]] = {}
        # enclosing-callable last-name -> [(file, Call node)] call sites
        self.calls: Dict[str, List[Tuple[SourceFile, ast.Call]]] = {}
        for sf in files:
            for fn in _func_defs(sf.tree):
                keys = dict_keys_produced(fn)
                if keys:
                    self.producers.setdefault(fn.name, set()).update(
                        keys)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    last = None
                    if isinstance(node.func, ast.Name):
                        last = node.func.id
                    elif isinstance(node.func, ast.Attribute):
                        last = node.func.attr
                    if last:
                        self.calls.setdefault(last, []).append(
                            (sf, node))


def _resolve_expr_keys(expr: ast.AST, fn: ast.AST, owner: str,
                       surface: _Surface) -> Optional[Set[str]]:
    """Keys a ``**expr`` expansion can contribute; None = unresolvable.

    Handles: dict literals; calls to a known producer function;
    variables assigned either of those in the enclosing function; and
    function PARAMETERS, resolved through the surface's call sites of
    the enclosing callable (``owner``: the function name, or the class
    name for ``__init__``) minus ``.pop()``-consumed keys.
    """
    if isinstance(expr, ast.Dict):
        if any(k is None or not isinstance(k, ast.Constant)
               for k in expr.keys):
            return None
        return _const_keys(expr)
    if isinstance(expr, ast.Call):
        last = None
        if isinstance(expr.func, ast.Name):
            last = expr.func.id
        elif isinstance(expr.func, ast.Attribute):
            last = expr.func.attr
        return surface.producers.get(last)
    if isinstance(expr, ast.Name):
        # locally assigned?
        for node in walk_shallow(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == expr.id:
                        return _resolve_expr_keys(node.value, fn,
                                                  owner, surface)
        # a parameter (declared or the **kwargs catch-all): gather the
        # keyword names call sites pass beyond the declared params
        is_param = expr.id in _declared_params(fn) or (
            fn.args.kwarg is not None and fn.args.kwarg.arg == expr.id)
        if is_param:
            declared = _declared_params(fn)
            popped = _popped_keys(fn, expr.id)
            keys: Set[str] = set()
            for _sf, call in surface.calls.get(owner, ()):
                for kw in call.keywords:
                    if kw.arg is None:
                        # a **forward at the call site: opaque
                        return None
                    if kw.arg == expr.id:
                        sub = _resolve_expr_keys(kw.value, fn, owner,
                                                 surface)
                        if sub is None:
                            return None
                        keys |= sub
                    elif kw.arg not in declared:
                        keys.add(kw.arg)
            return keys - popped
    return None


class SchemaDriftRule(Rule):
    name = "schema-drift"
    engine = "structural"
    doc = ("every key each telemetry/ledger/overlap writer emits "
           "exists in the matching validator's key table — writer and "
           "reader provably cannot drift")

    # -- telemetry ---------------------------------------------------------

    def _check_telemetry(self, files: List[SourceFile],
                         surface: _Surface) -> Tuple[List[Finding], int]:
        from fdtd3d_tpu.telemetry import RECORD_OPTIONAL, RECORD_SCHEMA
        findings: List[Finding] = []
        n_sites = 0

        def allowed_for(rtype: str) -> Set[str]:
            return (set(RECORD_SCHEMA[rtype])
                    | set(RECORD_OPTIONAL.get(rtype, ()))
                    | {"v", "type"})

        for sf in files:
            # the schema tables themselves live in telemetry.py as
            # dict literals; only CALL/record construction sites count
            for fn in _func_defs(sf.tree):
                owner = fn.name
                if fn.name == "__init__":
                    # resolve call sites by the class name
                    for cls in ast.walk(sf.tree):
                        if isinstance(cls, ast.ClassDef) \
                                and fn in cls.body:
                            owner = cls.name
                            break
                for node in walk_shallow(fn):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and node.func.attr in _EMIT_NAMES \
                            and node.args \
                            and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        rtype = node.args[0].value
                        n_sites += 1
                        if rtype not in RECORD_SCHEMA:
                            findings.append(Finding(
                                self.name, sf.relpath, node.lineno,
                                f"emit of unknown record type "
                                f"{rtype!r} — add it to "
                                f"telemetry.RECORD_SCHEMA"))
                            continue
                        ok = allowed_for(rtype)
                        for kw in node.keywords:
                            if kw.arg is not None:
                                if kw.arg not in ok:
                                    findings.append(Finding(
                                        self.name, sf.relpath,
                                        node.lineno,
                                        f"{rtype} writer emits key "
                                        f"{kw.arg!r} that no validator "
                                        f"version knows — declare it "
                                        f"in RECORD_SCHEMA or "
                                        f"RECORD_OPTIONAL"))
                                continue
                            keys = _resolve_expr_keys(kw.value, fn,
                                                      owner, surface)
                            if keys is None:
                                findings.append(Finding(
                                    self.name, sf.relpath, node.lineno,
                                    f"{rtype} writer expands "
                                    f"**{ast.unparse(kw.value)[:40]} "
                                    f"that static analysis cannot "
                                    f"resolve — emit literal keys or "
                                    f"route through a dict-returning "
                                    f"function"))
                                continue
                            for k in sorted(keys - ok):
                                findings.append(Finding(
                                    self.name, sf.relpath, node.lineno,
                                    f"{rtype} writer emits key {k!r} "
                                    f"(via **expansion) that no "
                                    f"validator version knows — "
                                    f"declare it in RECORD_SCHEMA or "
                                    f"RECORD_OPTIONAL"))
                    # dict-literal record construction (the
                    # trace_attribution pattern): {"v":..., "type": T}
                    if isinstance(node, ast.Assign) \
                            and isinstance(node.value, ast.Dict) \
                            and len(node.targets) == 1 \
                            and isinstance(node.targets[0], ast.Name):
                        d = node.value
                        ks = _const_keys(d)
                        if not {"v", "type"} <= ks:
                            continue
                        rtype = None
                        for k, v in zip(d.keys, d.values):
                            if isinstance(k, ast.Constant) \
                                    and k.value == "type" \
                                    and isinstance(v, ast.Constant):
                                rtype = v.value
                        if not isinstance(rtype, str) \
                                or rtype not in RECORD_SCHEMA:
                            continue
                        n_sites += 1
                        var = node.targets[0].id
                        emitted = ks | dict_keys_produced(fn, var)
                        for k in sorted(emitted - allowed_for(rtype)):
                            findings.append(Finding(
                                self.name, sf.relpath, node.lineno,
                                f"{rtype} record literal emits key "
                                f"{k!r} that no validator version "
                                f"knows — declare it in RECORD_SCHEMA "
                                f"or RECORD_OPTIONAL"))
        return findings, n_sites

    # -- ledger + overlap --------------------------------------------------

    def _check_keyset(self, sf: SourceFile, fn_name: str, var: str,
                      declared: Set[str], declared_name: str
                      ) -> List[Finding]:
        findings: List[Finding] = []
        for fn in _func_defs(sf.tree):
            if fn.name != fn_name:
                continue
            produced = dict_keys_produced(fn, var) if var else \
                dict_keys_produced(fn)
            if not produced:
                findings.append(Finding(
                    self.name, sf.relpath, fn.lineno,
                    f"{fn_name}: no emitted keys found for {var or 'the returned dict'} "
                    f"— the schema-drift extraction rotted"))
                return findings
            for k in sorted(produced - declared):
                findings.append(Finding(
                    self.name, sf.relpath, fn.lineno,
                    f"{fn_name} emits key {k!r} missing from "
                    f"{declared_name}"))
            for k in sorted(declared - produced):
                findings.append(Finding(
                    self.name, sf.relpath, fn.lineno,
                    f"{declared_name} declares key {k!r} that "
                    f"{fn_name} never emits (dead schema entry)"))
            return findings
        findings.append(Finding(
            self.name, sf.relpath, None,
            f"writer function {fn_name} not found — the schema-drift "
            f"rule's target table rotted"))
        return findings

    def run(self, ctx: Context) -> Tuple[List[Finding], Dict[str, Any]]:
        from fdtd3d_tpu import costs
        files = list(ctx.files()) + ctx.extra_files("bench.py")
        surface = _Surface(files)
        findings, n_sites = self._check_telemetry(files, surface)
        by_rel = {sf.relpath.replace("\\", "/"): sf for sf in files}
        costs_sf = by_rel.get("fdtd3d_tpu/costs.py")
        if costs_sf is not None:
            findings += self._check_keyset(
                costs_sf, "chunk_ledger", "ledger",
                set(costs.LEDGER_KEYS), "costs.LEDGER_KEYS")
            findings += self._check_keyset(
                costs_sf, "_comm_lane", "comm",
                set(costs.COMM_KEYS), "costs.COMM_KEYS")
        overlap_sf = by_rel.get("tools/aot_overlap.py")
        if overlap_sf is not None:
            findings += self._check_keyset(
                overlap_sf, "analyze", None,
                set(costs._OVERLAP_KEYS), "costs._OVERLAP_KEYS")
        return findings, {"emit_sites_checked": n_sites}
