"""``fdtd3d`` console entry point.

Reference parity: ``Source/main.cpp`` + the ``Source/Settings`` flag surface
(SURVEY.md §2 main/Settings rows): reference-style long flags, ``.txt``
command files replayed via ``--cmd-from-file`` (one flag, or flag+value, per
line; ``#`` comments allowed), and ``--save-cmd-to-file`` re-emission. The
parsed flags populate one runtime ``SimConfig`` (config.py) — the rebuild's
replacement for the reference's compile-time CMake matrix + runtime
``solverSettings`` singleton.
"""

from __future__ import annotations

import argparse
import json
import shlex
import sys
import time
from typing import List, Optional

from fdtd3d_tpu import diag
from fdtd3d_tpu.config import (MaterialsConfig, NtffConfig, OutputConfig,
                               ParallelConfig, PmlConfig, PointSourceConfig,
                               SimConfig, SphereConfig, TfsfConfig)
from fdtd3d_tpu.layout import SCHEME_MODES


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fdtd3d",
        description="TPU-native 1D/2D/3D FDTD Maxwell solver "
                    "(JAX/XLA rebuild of fdtd3d)")
    g = p.add_argument_group("scheme / grid")
    g.add_argument("--scheme", choices=sorted(SCHEME_MODES), default=None,
                   help="solver mode (reference SchemeType)")
    g.add_argument("--1d", dest="dim1", metavar="PAIR",
                   help="1D mode shorthand, e.g. --1d EzHy")
    g.add_argument("--2d", dest="dim2", metavar="POL",
                   help="2D mode shorthand, e.g. --2d TMz")
    g.add_argument("--3d", dest="dim3", action=argparse.BooleanOptionalAction, default=False,
                   help="3D mode shorthand")
    g.add_argument("--sizex", type=int, default=32)
    g.add_argument("--sizey", type=int, default=32)
    g.add_argument("--sizez", type=int, default=32)
    g.add_argument("--same-size", type=int, metavar="N",
                   help="set sizex=sizey=sizez=N")
    g.add_argument("--time-steps", type=int, default=100)
    g.add_argument("--dx", type=float, default=1e-3, help="cell size, m")
    g.add_argument("--courant-factor", type=float, default=0.5)
    g.add_argument("--wavelength", type=float, default=20e-3,
                   help="source wavelength, m")
    g.add_argument("--dtype", choices=["float32", "float64", "bfloat16",
                                       "float32x2"],
                   default="float32")
    g.add_argument("--compensated", action=argparse.BooleanOptionalAction, default=False,
                   help="Kahan-compensated f32 updates: f64-class "
                        "long-horizon accuracy at ~1.25x the f32 "
                        "traffic (float32 only)")
    g.add_argument("--complex-field-values", action=argparse.BooleanOptionalAction, default=False)

    g = p.add_argument_group("boundaries (CPML)")
    g.add_argument("--use-pml", action=argparse.BooleanOptionalAction, default=False)
    g.add_argument("--pml-size", type=int, default=8,
                   help="thickness on every active axis")
    g.add_argument("--pml-sizex", type=int, default=None)
    g.add_argument("--pml-sizey", type=int, default=None)
    g.add_argument("--pml-sizez", type=int, default=None)

    g = p.add_argument_group("TFSF plane-wave source")
    g.add_argument("--use-tfsf", action=argparse.BooleanOptionalAction, default=False)
    g.add_argument("--tfsf-margin", type=int, default=8)
    g.add_argument("--angle-teta", type=float, default=0.0)
    g.add_argument("--angle-phi", type=float, default=0.0)
    g.add_argument("--angle-psi", type=float, default=0.0)
    g.add_argument("--tfsf-amplitude", type=float, default=1.0)
    g.add_argument("--tfsf-waveform", default="sin",
                   choices=["sin", "gauss_pulse"])

    g = p.add_argument_group("point source")
    g.add_argument("--point-source", metavar="COMP",
                   help="enable soft point source on component, e.g. Ez")
    g.add_argument("--point-source-x", type=int, default=None)
    g.add_argument("--point-source-y", type=int, default=None)
    g.add_argument("--point-source-z", type=int, default=None)
    g.add_argument("--point-source-amplitude", type=float, default=1.0)
    g.add_argument("--point-source-waveform", default="sin",
                   choices=["sin", "gauss_pulse", "ricker"])

    g = p.add_argument_group("materials")
    g.add_argument("--eps", type=float, default=1.0)
    g.add_argument("--mu", type=float, default=1.0)
    g.add_argument("--sigma-e", type=float, default=0.0)
    g.add_argument("--sigma-m", type=float, default=0.0)
    g.add_argument("--eps-sphere", type=float, default=None,
                   metavar="EPSVAL", help="spherical inclusion permittivity")
    g.add_argument("--eps-sphere-center-x", type=float, default=0.0)
    g.add_argument("--eps-sphere-center-y", type=float, default=0.0)
    g.add_argument("--eps-sphere-center-z", type=float, default=0.0)
    g.add_argument("--eps-sphere-radius", type=float, default=0.0)
    g.add_argument("--load-eps-from-file", metavar="PATH", default=None)
    g.add_argument("--load-mu-from-file", metavar="PATH", default=None)
    g.add_argument("--use-drude", action=argparse.BooleanOptionalAction, default=False)
    g.add_argument("--eps-inf", type=float, default=1.0)
    g.add_argument("--omega-p", type=float, default=0.0, help="rad/s")
    g.add_argument("--gamma-d", type=float, default=0.0, help="rad/s")
    g.add_argument("--drude-sphere-center-x", type=float, default=0.0)
    g.add_argument("--drude-sphere-center-y", type=float, default=0.0)
    g.add_argument("--drude-sphere-center-z", type=float, default=0.0)
    g.add_argument("--drude-sphere-radius", type=float, default=0.0)
    # magnetic Drude (reference metamaterial mode: OmegaPM/GammaM)
    g.add_argument("--use-drude-m", action=argparse.BooleanOptionalAction, default=False,
                   help="dispersive mu(w) via an ADE magnetic current")
    g.add_argument("--mu-inf", type=float, default=1.0)
    g.add_argument("--omega-pm", type=float, default=0.0, help="rad/s")
    g.add_argument("--gamma-m", type=float, default=0.0, help="rad/s")
    g.add_argument("--drude-m-sphere-center-x", type=float, default=0.0)
    g.add_argument("--drude-m-sphere-center-y", type=float, default=0.0)
    g.add_argument("--drude-m-sphere-center-z", type=float, default=0.0)
    g.add_argument("--drude-m-sphere-radius", type=float, default=0.0)

    g = p.add_argument_group("near-to-far-field (NTFF)")
    g.add_argument("--ntff", action=argparse.BooleanOptionalAction, default=False,
                   help="accumulate the NTFF running DFT during the run "
                        "and write the far-field pattern at the end")
    g.add_argument("--ntff-frequency", type=float, default=None,
                   help="DFT frequency, Hz (default: source frequency)")
    g.add_argument("--ntff-every", type=int, default=None,
                   help="sample every N steps (default ~16/period)")
    g.add_argument("--ntff-start", type=int, default=None,
                   help="first sampling step (default: half the run)")
    g.add_argument("--ntff-margin", type=int, default=2,
                   help="box margin inward from the PML inner face, cells")
    g.add_argument("--ntff-box-lo", metavar="X,Y,Z", default=None,
                   help="explicit box lower corner (overrides margin)")
    g.add_argument("--ntff-box-hi", metavar="X,Y,Z", default=None,
                   help="explicit box upper corner (overrides margin)")
    g.add_argument("--ntff-theta-steps", type=int, default=19)
    g.add_argument("--ntff-phi-steps", type=int, default=24)

    g = p.add_argument_group("parallel decomposition")
    g.add_argument("--topology", choices=["none", "auto", "manual"],
                   default="none")
    g.add_argument("--manual-topology", metavar="PXxPYxPZ", default=None,
                   help="e.g. 2x2x2 (reference --manual-topology)")
    g.add_argument("--num-devices", type=int, default=None)
    # multi-process runtime (the reference's mpirun surface): one process
    # per host; the device mesh then spans every process's chips.
    g.add_argument("--coordinator-address", default=None,
                   metavar="HOST:PORT")
    g.add_argument("--num-processes", type=int, default=None)
    g.add_argument("--process-id", type=int, default=None)

    g = p.add_argument_group("kernels")
    g.add_argument("--use-pallas", choices=["auto", "on", "off"],
                   default="auto",
                   help="fused Pallas TPU kernels for the 3D hot path: "
                        "auto engages them on TPU when eligible; on "
                        "forces them (interpreter mode off-TPU, slow); "
                        "off always runs the jnp path")
    g.add_argument("--require-pallas", action=argparse.BooleanOptionalAction, default=False,
                   help="error out if the fused kernels do not engage "
                        "instead of silently running the jnp fallback")

    g = p.add_argument_group("output")
    g.add_argument("--save-res", type=int, default=0,
                   help="dump fields every N steps")
    g.add_argument("--save-dir", default="out")
    g.add_argument("--save-formats", default="dat",
                   help="comma list of dat,txt,bmp")
    g.add_argument("--save-materials", action=argparse.BooleanOptionalAction, default=False)
    g.add_argument("--checkpoint-every", type=int, default=0)
    g.add_argument("--checkpoint-backend", choices=["npz", "orbax"],
                   default="npz",
                   help="npz: rank-0 single file; orbax: sharding-aware "
                        "per-host shard writes (large/multi-host runs)")
    g.add_argument("--checkpoint-keep", type=int, default=3,
                   help="keep-K rotation for --checkpoint-every: only "
                        "the newest K committed snapshots stay on disk "
                        "(0 = keep all)")
    g.add_argument("--load-checkpoint", metavar="PATH", default=None)
    g.add_argument("--resume", metavar="auto|PATH", default=None,
                   help="resume a killed/preempted run from a COMMITTED "
                        "checkpoint and finish the remaining steps: "
                        "'auto' picks the newest committed snapshot in "
                        "--save-dir (snapshots failing their integrity "
                        "checks are skipped with a warning), or give an "
                        "explicit path (docs/ROBUSTNESS.md runbook)")
    g.add_argument("--norms-every", type=int, default=0,
                   help="print field norms every N steps")
    g.add_argument("--metrics-every", type=int, default=0,
                   help="append a structured metrics record (energy, "
                        "norms, divergence residual) to "
                        "save_dir/metrics.jsonl every N steps")
    g.add_argument("--log-level", type=int, default=1)
    g.add_argument("--profile", nargs="?", const=True, default=False,
                   metavar="DIR",
                   help="time every compute chunk (StepClock) and print "
                        "a throughput summary at the end; with DIR, also "
                        "capture a jax.profiler device trace there "
                        "(crash-safe, finalized on every exit; attribute "
                        "it with tools/trace_attribution.py; degrades to "
                        "a clean skip when no profiler is available)")
    # compat: --profile was a BooleanOptionalAction before round 7, so
    # command files saved by earlier builds may contain --no-profile;
    # replay must keep working (hidden from --help and from
    # save_cmd_file, which skips SUPPRESS'd actions)
    g.add_argument("--no-profile", dest="profile", action="store_const",
                   const=False, help=argparse.SUPPRESS)
    g.add_argument("--check-finite", action=argparse.BooleanOptionalAction, default=False,
                   help="NaN/Inf tripwire over the state after each chunk")
    g.add_argument("--trace", metavar="DIR", default=None,
                   help="legacy alias for --profile DIR (kept for saved "
                        "command files)")
    g.add_argument("--telemetry", metavar="PATH", default=None,
                   help="flight recorder: append schema-versioned JSONL "
                        "records (per-chunk in-graph health counters, "
                        "wall time, run provenance, VMEM-ladder events) "
                        "to PATH; summarize with "
                        "tools/telemetry_report.py")
    g.add_argument("--metrics", metavar="PATH", default=None,
                   help="write an OpenMetrics/Prometheus text "
                        "exposition of this run's counters (chunk "
                        "throughput, wall-time histogram, recovery "
                        "events, unhealthy lanes, cache hits) to PATH "
                        "at exit, fed host-side from the same events "
                        "the telemetry sink records — any scraper "
                        "can ingest a run without parsing our JSONL; "
                        "works with or without --telemetry")
    g.add_argument("--per-chip-telemetry",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="with --telemetry: also record the UN-psummed "
                        "per-chip health counters (schema-v4 per_chip "
                        "records, tiny all_gathered scalars on the "
                        "same readback) plus a per-chunk imbalance "
                        "summary (max/mean ratio, straggler chip). "
                        "With --batch: per-LANE per_chip/imbalance "
                        "rows naming each tenant's straggler chip")

    g = p.add_argument_group("durability (docs/ROBUSTNESS.md)")
    g.add_argument("--supervise", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="run under the durable-run supervisor: bounded "
                        "retry with exponential backoff for transient "
                        "device errors; on a NaN/Inf health trip, roll "
                        "back to the last committed checkpoint and "
                        "resume down the kernel degradation ladder "
                        "(implies --check-finite)")

    g = p.add_argument_group("planning")
    g.add_argument("--dry-run", action=argparse.BooleanOptionalAction, default=False,
                   help="print the per-chip memory/communication plan "
                        "(no device needed) and exit — size pod-scale "
                        "configs on a laptop")

    g = p.add_argument_group("batched execution (docs/SERVICE.md)")
    g.add_argument("--batch", metavar="SPEC.txt", nargs="+",
                   default=None,
                   help="run B same-shape scenarios as ONE vmap-"
                        "batched execution: each SPEC.txt is a "
                        "command file (--cmd-from-file format) "
                        "describing one lane. Lanes must share the "
                        "graph-shaping config (grid/scheme/dtype/"
                        "steps/sources geometry) and may differ in "
                        "material values and point-source amplitude; "
                        "one compiled executable, one dispatch per "
                        "chunk for the whole batch. Per-lane health "
                        "flags — one lane's NaN never fails the "
                        "others. Top-level --telemetry/--metrics/"
                        "--check-finite apply to the batch; "
                        "FDTD3D_BATCH_MAX bounds the lane count.")
    g.add_argument("--batch-chunk", type=int, default=0, metavar="N",
                   help="advance the batch in N-step compiled chunks "
                        "(per-chunk telemetry cadence + per-lane "
                        "health granularity: a mid-run NaN is "
                        "attributed to its chunk, not just the final "
                        "state sweep); 0 = the whole horizon as one "
                        "chunk (fastest)")

    g = p.add_argument_group("command files")
    g.add_argument("--cmd-from-file", metavar="FILE", default=None,
                   help="read flags from a .txt command file (reference "
                        "format: one flag [value] per line)")
    g.add_argument("--save-cmd-to-file", metavar="FILE", default=None,
                   help="re-emit the effective flags to a command file")
    return p


def read_cmd_file(path: str) -> List[str]:
    """Reference-style .txt command file -> argv list."""
    argv: List[str] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                argv.extend(shlex.split(line))
    return argv


def _parse_xyz(val):
    """'X,Y,Z' -> (int, int, int), or None passthrough."""
    if val is None:
        return None
    parts = [p for p in str(val).replace("x", ",").split(",") if p]
    try:
        triple = tuple(int(p) for p in parts)
    except ValueError:
        triple = ()
    if len(triple) != 3:
        raise SystemExit(f"expected X,Y,Z integer triple, got {val!r}")
    return triple


def _resolve_scheme(args) -> str:
    if args.dim3:
        return "3D"
    if args.dim2:
        return f"2D_{args.dim2}"
    if args.dim1:
        return f"1D_{args.dim1}"
    return args.scheme or "3D"


def args_to_config(args) -> SimConfig:
    if args.same_size:
        args.sizex = args.sizey = args.sizez = args.same_size
    pml_size = (0, 0, 0)
    if args.use_pml:
        pml_size = tuple(
            args.pml_sizex if (a == 0 and args.pml_sizex is not None) else
            args.pml_sizey if (a == 1 and args.pml_sizey is not None) else
            args.pml_sizez if (a == 2 and args.pml_sizez is not None) else
            args.pml_size for a in range(3))
    manual = None
    if args.manual_topology:
        parts = args.manual_topology.lower().split("x")
        if len(parts) != 3:
            raise SystemExit("--manual-topology must look like 2x2x1")
        manual = tuple(int(v) for v in parts)
    ps_default = {0: args.sizex // 2, 1: args.sizey // 2,
                  2: args.sizez // 2}
    cfg = SimConfig(
        scheme=_resolve_scheme(args),
        size=(args.sizex, args.sizey, args.sizez),
        time_steps=args.time_steps,
        dx=args.dx,
        courant_factor=args.courant_factor,
        wavelength=args.wavelength,
        dtype=args.dtype,
        compensated=args.compensated,
        complex_fields=args.complex_field_values,
        pml=PmlConfig(size=pml_size),
        tfsf=TfsfConfig(
            enabled=args.use_tfsf,
            margin=(args.tfsf_margin,) * 3,
            angle_teta=args.angle_teta, angle_phi=args.angle_phi,
            angle_psi=args.angle_psi, amplitude=args.tfsf_amplitude,
            waveform=args.tfsf_waveform),
        point_source=PointSourceConfig(
            enabled=args.point_source is not None,
            component=args.point_source or "Ez",
            position=(
                args.point_source_x if args.point_source_x is not None
                else ps_default[0],
                args.point_source_y if args.point_source_y is not None
                else ps_default[1],
                args.point_source_z if args.point_source_z is not None
                else ps_default[2]),
            amplitude=args.point_source_amplitude,
            waveform=args.point_source_waveform),
        materials=MaterialsConfig(
            eps=args.eps, mu=args.mu,
            sigma_e=args.sigma_e, sigma_m=args.sigma_m,
            eps_sphere=SphereConfig(
                enabled=args.eps_sphere is not None,
                center=(args.eps_sphere_center_x, args.eps_sphere_center_y,
                        args.eps_sphere_center_z),
                radius=args.eps_sphere_radius,
                value=args.eps_sphere or 1.0),
            use_drude=args.use_drude,
            eps_inf=args.eps_inf, omega_p=args.omega_p, gamma=args.gamma_d,
            drude_sphere=SphereConfig(
                enabled=args.drude_sphere_radius > 0,
                center=(args.drude_sphere_center_x,
                        args.drude_sphere_center_y,
                        args.drude_sphere_center_z),
                radius=args.drude_sphere_radius),
            use_drude_m=args.use_drude_m,
            mu_inf=args.mu_inf, omega_pm=args.omega_pm,
            gamma_m=args.gamma_m,
            drude_m_sphere=SphereConfig(
                enabled=args.drude_m_sphere_radius > 0,
                center=(args.drude_m_sphere_center_x,
                        args.drude_m_sphere_center_y,
                        args.drude_m_sphere_center_z),
                radius=args.drude_m_sphere_radius),
            eps_file=args.load_eps_from_file,
            mu_file=args.load_mu_from_file),
        parallel=ParallelConfig(
            topology="manual" if manual else args.topology,
            manual_topology=manual, n_devices=args.num_devices),
        output=OutputConfig(
            save_res=args.save_res, save_dir=args.save_dir,
            formats=tuple(args.save_formats.split(",")),
            save_materials=args.save_materials,
            checkpoint_every=args.checkpoint_every,
            checkpoint_backend=args.checkpoint_backend,
            checkpoint_keep=args.checkpoint_keep,
            norms_every=args.norms_every, metrics_every=args.metrics_every,
            log_level=args.log_level,
            profile=bool(args.profile), check_finite=args.check_finite,
            telemetry_path=args.telemetry,
            metrics_path=args.metrics,
            per_chip_telemetry=args.per_chip_telemetry,
            # --profile DIR routes the device-trace lane; --trace is
            # the legacy alias (saved command files)
            profile_dir=(args.profile
                         if isinstance(args.profile, str) else None)
            or args.trace),
        ntff=NtffConfig(
            enabled=args.ntff, frequency=args.ntff_frequency,
            every=args.ntff_every, start=args.ntff_start,
            margin=args.ntff_margin,
            box_lo=_parse_xyz(args.ntff_box_lo),
            box_hi=_parse_xyz(args.ntff_box_hi),
            theta_steps=args.ntff_theta_steps,
            phi_steps=args.ntff_phi_steps),
        use_pallas={"auto": None, "on": True, "off": False}[args.use_pallas],
        require_pallas=args.require_pallas,
    )
    return cfg


def resolve_ntff_cadence(cfg):
    """(frequency_hz, every, start) with derived defaults filled in.

    Shared by main() and save_cmd_file so a saved command file pins the
    DERIVED cadence too — the default formulas below may change between
    versions, and replay must not drift with them.
    """
    from fdtd3d_tpu import physics
    freq = cfg.ntff.frequency or physics.C0 / cfg.wavelength
    period_steps = 1.0 / (freq * cfg.dt)
    every = cfg.ntff.every or max(1, round(period_steps / 16.0))
    start = (cfg.ntff.start if cfg.ntff.start is not None
             else cfg.time_steps // 2)
    # align up to the sampling grid: the loop only lands on multiples
    # of `every`, so an unaligned start would never sample
    start = -(-start // every) * every
    return freq, every, start


def save_cmd_file(args, path: str):
    """Re-emit effective flags (reference --save-cmd-to-file).

    EVERY effective value is written, including ones that currently equal
    the parser default — and values whose defaults are DERIVED later
    (NTFF cadence) are resolved first: a file saved under today's
    defaults must replay identically even if a default or formula
    changes in a later version (the reference re-emits the full
    effective settings the same way). Written crash-safely
    (io.atomic_open): a kill mid-save must not leave a half command
    file that would replay as a different run.
    """
    if args.ntff:
        freq, every, start = resolve_ntff_cadence(args_to_config(args))
        args = argparse.Namespace(**{**vars(args), "ntff_frequency": freq,
                                     "ntff_every": every,
                                     "ntff_start": start})
    parser = build_parser()
    lines = []
    for action in parser._actions:
        if not action.option_strings or action.dest in (
                "help", "cmd_from_file", "save_cmd_to_file",
                "batch") or \
                action.help == argparse.SUPPRESS:
            # SUPPRESS'd actions are compat aliases (--no-profile):
            # re-emitting them would mis-serialize the shared dest
            continue
        val = getattr(args, action.dest, None)
        if val is None:
            continue
        opt = action.option_strings[0]
        if isinstance(val, bool):
            # boolean flags use BooleanOptionalAction, so BOTH states
            # are representable (--flag / --no-flag): a saved file
            # replays identically even if a flag's default ever flips
            # to True (ADVICE r3).
            if val:
                lines.append(opt)
            else:
                neg = next((o for o in action.option_strings
                            if o.startswith("--no-")), None)
                if neg is not None:
                    lines.append(neg)
        else:
            lines.append(f"{opt} {val}")
    from fdtd3d_tpu.io import atomic_open
    with atomic_open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def write_ntff_pattern(col, cfg) -> str:
    """Write the far-field |E|^2 pattern over the angle grid to save_dir.

    Format: '# theta_deg phi_deg directivity' rows (TXT, reference-dump
    style); directivity is normalized to the pattern peak.
    """
    import os
    import numpy as np
    thetas = np.linspace(0.0, 180.0, cfg.ntff.theta_steps)
    phis = np.arange(cfg.ntff.phi_steps) * (360.0 / cfg.ntff.phi_steps)
    pattern = col.directivity_pattern(thetas, phis)
    peak = pattern.max()
    if peak > 0:
        pattern = pattern / peak
    os.makedirs(cfg.output.save_dir, exist_ok=True)
    path = os.path.join(cfg.output.save_dir, "ntff_pattern.txt")
    from fdtd3d_tpu.io import atomic_open
    with atomic_open(path, "w") as f:
        f.write("# theta_deg phi_deg directivity(normalized)\n")
        for i, th in enumerate(thetas):
            for j, ph in enumerate(phis):
                f.write(f"{th:.3f} {ph:.3f} {pattern[i, j]:.9e}\n")
    return path


def _peek_supervisor_state(cfg, resume: str):
    """-> (supervisor recovery state or None, snapshot path or None).

    The recovery state a previous SUPERVISED run persisted into the
    snapshot ``--resume`` will pick (io.read_checkpoint_meta — metadata
    only, no state bytes). Lets a supervised resume re-apply ladder
    pins and the degraded topology BEFORE the Simulation is built, so
    a preemption mid-degrade resumes degraded. Applies the same cheap
    metadata guards the restore loop does (scheme/size/dtype), so a
    FOREIGN run's leftover snapshot in the same save_dir cannot donate
    its recovery state; the restore loop warns if it ends up restoring
    a different snapshot than the one peeked (payload corruption is
    only discovered at load time)."""
    from fdtd3d_tpu import io
    from fdtd3d_tpu.log import warn
    if resume == "auto":
        cands = [p for t, p in io.find_checkpoints(cfg.output.save_dir)
                 if t <= cfg.time_steps]
    else:
        cands = [resume]
    for cand in cands:
        try:
            meta = io.read_checkpoint_meta(cand)
        except Exception as exc:
            warn(f"supervised resume: cannot peek {cand} ({exc}); "
                 f"trying the next snapshot")
            continue
        # the SAME metadata guards sim._check_ckpt_meta enforces at
        # restore time (one shared predicate — they cannot drift): a
        # snapshot the restore loop would skip must not decide how
        # this run resumes
        from fdtd3d_tpu.sim import ckpt_meta_mismatch
        reason = ckpt_meta_mismatch(cfg, meta)
        if reason:
            warn(f"supervised resume: not adopting recovery state "
                 f"from {cand} ({reason})")
            continue
        # the newest usable snapshot decides — matching what the
        # resume below will restore from. The path is only reported
        # when state was actually adopted (the mismatch warning below
        # must never claim an adoption that did not happen).
        state = meta.get("supervisor")
        return state, (cand if state else None)
    return None, None


def _check_topology_fits(cfg, resuming: bool = False):
    """Friendly SystemExit when the requested decomposition cannot map
    onto the available device count — never a raw mesh/shard_map
    traceback (the named-error satellite of docs/ROBUSTNESS.md)."""
    import jax

    from fdtd3d_tpu.parallel.mesh import resolve_topology
    try:
        topo = resolve_topology(cfg.parallel, cfg.grid_shape,
                                cfg.mode.active_axes,
                                n_devices=jax.device_count())
    except ValueError as exc:
        raise SystemExit(f"invalid decomposition topology: {exc}")
    n = topo[0] * topo[1] * topo[2]
    if n > jax.device_count():
        hint = ""
        if resuming:
            hint = (" — snapshots are topology-portable: pass a "
                    "smaller --manual-topology (or --topology none) "
                    "and --resume reshards the checkpoint onto it "
                    "(docs/ROBUSTNESS.md)")
        raise SystemExit(
            f"topology {topo} needs {n} devices but only "
            f"{jax.device_count()} are available{hint}")


def _run_batch_cli(parser, args) -> int:
    """``--batch spec1.txt spec2.txt ...``: the multi-tenant lane of
    docs/SERVICE.md — parse each command file into one scenario, run
    them as one vmap batch, report per-lane health. A tripped lane is
    a WARNED per-lane verdict, never a batch failure (exit stays 0:
    the other tenants' runs completed)."""
    import dataclasses as _dc
    import time as _time

    from fdtd3d_tpu.log import log, set_level, warn
    if args.supervise:
        # supervised batch: the vmap executor's recovery IS per-lane
        # isolation (one tenant's NaN flips only its lane; the batch
        # never dies for it) — --supervise therefore forces the
        # in-graph tripwire on, and the run-registry row of a batch
        # that isolated a lane folds to status "recovered"
        args.check_finite = True
    cfgs = []
    for path in args.batch:
        largs = parser.parse_args(read_cmd_file(path))
        if largs.batch:
            raise SystemExit(
                f"--batch: {path} itself contains --batch (nested "
                f"batches are not a thing)")
        cfgs.append(args_to_config(largs))
    if args.telemetry or args.metrics or args.check_finite \
            or args.per_chip_telemetry:
        # top-level observability flags apply to the batch (lane 0's
        # output config drives the shared sink / tripwire / per-chip
        # lane — the batched runner honors per_chip_telemetry since
        # the trace plane, emitting per-LANE per_chip/imbalance rows)
        out0 = _dc.replace(
            cfgs[0].output,
            telemetry_path=args.telemetry
            or cfgs[0].output.telemetry_path,
            metrics_path=args.metrics
            or cfgs[0].output.metrics_path,
            check_finite=args.check_finite
            or cfgs[0].output.check_finite,
            per_chip_telemetry=args.per_chip_telemetry
            or cfgs[0].output.per_chip_telemetry)
        cfgs[0] = _dc.replace(cfgs[0], output=out0)
    set_level(cfgs[0].output.log_level)
    from fdtd3d_tpu.sim import Simulation
    t0 = _time.time()
    try:
        bsim = Simulation.run_batch(cfgs, chunk=args.batch_chunk)
    except ValueError as exc:
        raise SystemExit(f"--batch: {exc}")
    wall = _time.time() - t0
    # the batch dispatch verdict, mirroring the solo step-kind line:
    # the engaged kind, and when the batch could NOT ride the
    # lane-capable packed kernels the named batch_unsupported:<token>
    # (solver.batch_fallback_reason) — the ~6x-HBM downgrade is never
    # silent
    kind_line = f"step_kind={bsim.step_kind}"
    tile = ((bsim.step_diag or {}).get("tile") or {}).get("EH")
    if tile is not None:
        kind_line += f" tile={tile}"
    if bsim.batch_fallback:
        kind_line += f" {bsim.batch_fallback}"
    log(f"batch: {bsim.batch_size} lanes {kind_line}")
    # (run_batch has already run the verify_final_lanes end-of-run
    # sweep, so the verdicts below reflect damage landing after the
    # last chunk's in-graph measurement too)
    cells = 1.0
    for a in bsim.static.mode.active_axes:
        cells *= bsim.cfg.grid_shape[a]
    mcps = cells * bsim.batch_size * bsim.cfg.time_steps \
        / max(wall, 1e-9) / 1e6
    for lane in range(bsim.batch_size):
        verdict = {True: "healthy", False: "NON-FINITE",
                   None: "unmeasured"}[bsim.lane_finite[lane]]
        extra = ""
        if bsim.lane_first_unhealthy_t[lane] is not None:
            extra = (f" (first bad step <= "
                     f"{bsim.lane_first_unhealthy_t[lane]})")
        log(f"batch lane {lane}: {verdict}{extra}")
    bad = [i for i, f in enumerate(bsim.lane_finite) if f is False]
    if bad:
        warn(f"batch: lane(s) {bad} tripped non-finite; the other "
             f"{bsim.batch_size - len(bad)} completed healthy "
             f"(per-lane rows in the telemetry batch_lane records)")
    log(f"done: {bsim.batch_size} lanes x {bsim.cfg.time_steps} steps "
        f"in {wall:.2f}s ({mcps:.1f} Mcells/s aggregate, one "
        f"dispatch per chunk)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.cmd_from_file:
        file_argv = read_cmd_file(args.cmd_from_file)
        # CLI flags override the command file (parse file first, then argv).
        args = parser.parse_args(file_argv + argv)
    if args.save_cmd_to_file:
        save_cmd_file(args, args.save_cmd_to_file)
    # run-registry kind (fdtd3d_tpu/registry.py): which entry built
    # this run — the batch executor stamps "batch" itself
    from fdtd3d_tpu import registry as _run_registry
    _run_registry.set_default_kind(
        "supervised" if args.supervise else "cli")
    if args.batch:
        return _run_batch_cli(parser, args)

    if args.dry_run:
        from fdtd3d_tpu import plan as plan_mod
        cfg = args_to_config(args)
        if cfg.parallel.topology == "auto" and not args.num_devices:
            # a pod-sizing flag that silently plans for 1 chip misleads
            # (ADVICE r2) — auto needs the intended device count
            raise SystemExit(
                "--dry-run with --topology auto needs --num-devices N "
                "(the plan depends on the chip count you are sizing for)")
        p_ = plan_mod.plan(cfg, n_devices=args.num_devices or 1)
        from fdtd3d_tpu.log import log as _plan_log
        # all_ranks=True skips log()'s jax.process_index() rank gate:
        # --dry-run is a planning-only command that must not initialize
        # the (possibly absent/fragile) backend just to print
        _plan_log(f"dry run: scheme={cfg.scheme} global={cfg.grid_shape} "
                  f"steps={cfg.time_steps} dtype={cfg.dtype}",
                  all_ranks=True)
        _plan_log(p_.report(), all_ranks=True)
        return 0

    if args.coordinator_address or args.num_processes or \
            args.process_id is not None:
        # must happen before any backend-initializing jax call
        from fdtd3d_tpu.parallel import distributed
        distributed.initialize(coordinator=args.coordinator_address,
                               num_processes=args.num_processes,
                               process_id=args.process_id)

    if args.supervise:
        # the supervisor consumes the in-graph tripwire: force it on
        args.check_finite = True
    cfg = args_to_config(args)
    from fdtd3d_tpu import io
    from fdtd3d_tpu.log import log, set_level, warn
    from fdtd3d_tpu.sim import Simulation  # deferred: jax init is slow
    set_level(cfg.output.log_level)
    sup = None  # durable-run supervisor (--supervise); may REPLACE sim
    peeked_ckpt = None  # the snapshot whose supervisor state we adopted
    if args.supervise:
        # built BEFORE the Simulation: a supervised --resume adopts the
        # recovery state (ladder pins, degraded topology) a previous
        # supervised run persisted into its snapshots, so the sim is
        # constructed on the topology the run should CONTINUE on
        from fdtd3d_tpu.supervisor import Supervisor
        resume_state = None
        if args.resume:
            resume_state, peeked_ckpt = _peek_supervisor_state(
                cfg, args.resume)
        sup = Supervisor(cfg=cfg, resume_state=resume_state)
        try:
            cfg = sup.cfg
            _check_topology_fits(cfg, resuming=bool(args.resume))
            sim = sup.ensure_sim()
        except BaseException:
            # the ctor may have pinned kernel escape hatches from the
            # persisted state; a failure before run()'s own finally
            # must not leak them into the calling process
            sup._restore_env()
            raise
    else:
        _check_topology_fits(cfg, resuming=bool(args.resume))
        sim = Simulation(cfg)

    def _current_sim():
        # after a ladder degrade the supervisor's sim replaces the
        # original — every finalizer must resolve the live one
        return sup.sim if (sup is not None and sup.sim is not None) \
            else sim

    def _finalize():
        _current_sim().close()   # idempotent

    # Durability of the observability lanes (docs/ROBUSTNESS.md): the
    # try/finally below covers in-process exits; atexit + SIGTERM/
    # SIGINT -> SystemExit handlers extend the same guarantee to
    # signal-style kills AND an operator Ctrl-C, so the telemetry
    # run_end record and the device-trace finalization survive them
    # too. The previous handlers are restored on every exit (library
    # callers — tests — must not inherit ours).
    import atexit
    import signal
    atexit.register(_finalize)
    _old_handlers = {}
    for _sig, _code in ((signal.SIGTERM, 143), (signal.SIGINT, 130)):
        try:
            _old_handlers[_sig] = signal.signal(
                _sig, lambda _s, _frm, _c=_code: sys.exit(_c))
        except (ValueError, OSError):  # pragma: no cover - non-main thread
            pass
    # ONE try/finally from construction (which opens the telemetry
    # sink and writes run_start) to the end: EVERY exit — config
    # errors before the run, a NaN blow-up's FloatingPointError
    # mid-run, IO failures after it — must end the recording with
    # its run_end record (first_unhealthy_t) and release the fd.
    try:
        if args.resume and args.load_checkpoint:
            raise SystemExit(
                "--resume and --load-checkpoint are mutually exclusive")
        if args.load_checkpoint:
            sim.restore(args.load_checkpoint)
            log(f"restored checkpoint {args.load_checkpoint} at t={sim.t}")
        if args.resume:
            if args.resume == "auto":
                found = io.find_checkpoints(cfg.output.save_dir)
                if not found:
                    raise SystemExit(
                        f"--resume auto: no committed checkpoint in "
                        f"{cfg.output.save_dir!r} (cadence runs write "
                        f"ckpt_tNNNNNN snapshots there; see "
                        f"docs/ROBUSTNESS.md)")
                for _t_ck, cand in found:
                    if _t_ck > cfg.time_steps:
                        # a previous LONGER same-config run's leftover
                        # passes every meta guard (time_steps is not
                        # in the meta) and would "finish" this run
                        # instantly from the old run's state
                        warn(f"skipping {cand}: t={_t_ck} is past "
                             f"this run's horizon ({cfg.time_steps})")
                        continue
                    # ValueError too: a stale snapshot from an earlier
                    # run (other size/topology/dtype/carry family)
                    # fails the _check_ckpt_meta guards — skip it like
                    # a corrupt one, per the --resume help contract
                    try:
                        sim.restore(cand)
                        log(f"resumed from {cand} at t={sim.t}")
                        if peeked_ckpt is not None and \
                                cand != peeked_ckpt:
                            # the supervisor state was adopted from a
                            # snapshot that then failed to load: the
                            # counters/pins may not match this state
                            warn(f"supervisor recovery state was "
                                 f"adopted from {peeked_ckpt} but the "
                                 f"run resumed from {cand}; inspect "
                                 f"both with tools/ckpt_inspect.py")
                        break
                    except (io.CheckpointCorrupt, ValueError) as exc:
                        warn(f"skipping unusable checkpoint: {exc}")
                else:
                    raise SystemExit(
                        "--resume auto: no usable committed checkpoint "
                        "(every candidate was corrupt, incompatible, "
                        "or past this run's horizon)")
            else:
                try:
                    sim.restore(args.resume)
                except (io.CheckpointCorrupt, ValueError) as exc:
                    raise SystemExit(f"--resume: {exc}")
                log(f"resumed from {args.resume} at t={sim.t}")
        if cfg.output.save_materials:
            io.write_materials(sim)
        import jax
        log(f"fdtd3d-tpu: scheme={cfg.scheme} size={cfg.grid_shape} "
            f"steps={cfg.time_steps} dt={cfg.dt:.3e}s "
            f"topology={sim.topology} devices={jax.device_count()}")
        # engaged-path observability (VERDICT r2 item 7): which kernel
        # actually runs, its x-tile size, and the VMEM working set.
        line = f"step_kind={sim.step_kind}"
        # a non-kernel diag (e.g. a jnp step's tb_fallback record) has
        # no tile/VMEM rows — print what is actually there
        if sim.step_diag and sim.step_diag.get("tile"):
            tiles = ",".join(f"{k}:{v}"
                             for k, v in sim.step_diag["tile"].items())
            vmem = ",".join(
                f"{k}:{v / 1048576:.1f}MiB"
                for k, v in sim.step_diag["vmem_block_bytes"].items())
            line += f" tile=[{tiles}] vmem_block=[{vmem}]"
        if (sim.step_diag or {}).get("tb_fallback"):
            line += (f" tb_fallback="
                     f"{sim.step_diag['tb_fallback'].get('reason')}")
        log(line)

        # NTFF: resolve cadence defaults and build the collector (reference
        # --ntff-* surface; running DFT sampled between compute chunks).
        ntff_col = None
        ntff_every = ntff_start = 0
        if cfg.ntff.enabled:
            # Multi-process-capable: sampling accumulates device-side and is
            # collective (every rank runs on_interval); the pattern is
            # evaluated from the allgathered accumulators on rank 0.
            from fdtd3d_tpu.ntff import NtffCollector
            freq, ntff_every, ntff_start = resolve_ntff_cadence(cfg)
            box = None
            if cfg.ntff.box_lo is not None or cfg.ntff.box_hi is not None:
                if cfg.ntff.box_lo is None or cfg.ntff.box_hi is None:
                    raise SystemExit(
                        "--ntff-box-lo and --ntff-box-hi must be given "
                        "together")
                box = (cfg.ntff.box_lo, cfg.ntff.box_hi)
            ntff_col = NtffCollector(sim, frequency=freq, box=box,
                                     margin=cfg.ntff.margin)

        t0 = time.time()
        # gcd, not min: with cadences 10 and 3, chunking by 3 would never land
        # on a multiple of 10 and those dumps would silently be skipped.
        import math
        interval = 0
        for v in (cfg.output.save_res, cfg.output.norms_every,
                  cfg.output.checkpoint_every, cfg.output.metrics_every,
                  ntff_every):
            if v:
                interval = math.gcd(interval, v)

        from fdtd3d_tpu import telemetry as _telemetry

        def on_interval(s):
            if ntff_col is not None and ntff_col.sim is not s:
                # a supervisor ladder degrade replaced the Simulation:
                # the collector must read the LIVE one (same grid, dt
                # and box — the degraded cfg differs only in kernel
                # dispatch), not the stale pre-trip fields
                ntff_col.sim = s
            if ntff_col is not None and s.t >= ntff_start and \
                    s.t % ntff_every == 0:
                with _telemetry.span("ntff-sample"):
                    ntff_col.sample()
            # metrics BEFORE norms: when both cadences land on one step,
            # field_norms reuses the full metrics pass via diag's per-step
            # cache instead of launching its own max reductions.
            if cfg.output.metrics_every and \
                    s.t % cfg.output.metrics_every == 0:
                import jax
                rec = diag.metrics(s)   # collective gathers: ALL ranks
                if jax.process_index() == 0:
                    import os
                    os.makedirs(cfg.output.save_dir, exist_ok=True)
                    with open(os.path.join(cfg.output.save_dir,
                                           "metrics.jsonl"), "a") as f:
                        f.write(json.dumps(rec) + "\n")
            if cfg.output.norms_every and s.t % cfg.output.norms_every == 0:
                norms = diag.field_norms(s)   # collective: ALL ranks
                txt = " ".join(f"{k}={v:.4e}"
                               for k, v in sorted(norms.items()))
                log(f"[t={s.t}] {txt}")  # rank-0-only inside log()
            if cfg.output.save_res and s.t % cfg.output.save_res == 0:
                with _telemetry.span("io-dump"):
                    io.write_outputs(s, s.t)
            # (checkpoint cadence moved INTO Simulation.advance —
            # crash-safe keep-K rotation aligned to chunk boundaries;
            # the gcd interval above still includes checkpoint_every so
            # chunks land exactly on the cadence multiples)

        # After a checkpoint restore (--load-checkpoint / --resume),
        # run only the REMAINING steps so the resumed run ends at the
        # same t as the uninterrupted one.
        # (The device-trace lane — --profile DIR / --trace — is wired
        # through Simulation: capture starts at the first advance and
        # the finally below finalizes it on EVERY exit.)
        remaining = max(0, cfg.time_steps - sim.t) \
            if (args.load_checkpoint or args.resume) else cfg.time_steps
        if sup is not None:
            # Supervisor.run takes the ABSOLUTE horizon (it tracks its
            # own progress across rollbacks); max() keeps an
            # already-finished resume a no-op.
            sim = sup.run(time_steps=max(cfg.time_steps, sim.t),
                          on_interval=on_interval if interval else None,
                          interval=interval)
        else:
            sim.run(time_steps=remaining,
                    on_interval=on_interval if interval else None,
                    interval=interval)
        sim.block_until_ready()
        if ntff_col is not None:
            if ntff_col.n_samples > 0:
                import jax
                _ = ntff_col.acc  # collective gather: ALL ranks participate
                if jax.process_index() == 0:
                    path = write_ntff_pattern(ntff_col, cfg)
                    log(f"ntff: {ntff_col.n_samples} samples -> {path}")
            else:
                from fdtd3d_tpu.log import warn
                warn(f"ntff: no samples collected (first sample at "
                     f"step {ntff_start}, every {ntff_every}, run ends at "
                     f"{cfg.time_steps}) — no pattern written")
        dt_wall = time.time() - t0
        cells = 1.0
        for a in sim.static.mode.active_axes:
            cells *= cfg.grid_shape[a]
        mcps = cells * cfg.time_steps / dt_wall / 1e6
        if sim.clock is not None:
            log(f"profile: {sim.clock.report()}")
        if sup is not None and (sup.retries or sup.rollbacks
                                or sup.degrades or sup.topology_rung):
            log(f"supervisor: {sup.retries} retries, "
                f"{sup.rollbacks} rollbacks, {sup.degrades} ladder "
                f"degrades, {sup.topology_rung} topology rungs "
                f"(now {sim.step_kind} on {sim.topology})")
        log(f"done: {cfg.time_steps} steps in {dt_wall:.2f}s "
            f"({mcps:.1f} Mcells/s)")
        return 0
    finally:
        # finalizes BOTH observability lanes on every exit: the
        # device-trace capture (a crash mid-capture must still leave a
        # parseable trace directory, never a partial artifact) and the
        # telemetry sink's run_end record. The current sim may be a
        # supervisor ladder replacement of the one built above.
        cur = _current_sim()
        n_rec = cur.telemetry.n_records if cur.telemetry is not None \
            else 0
        cur.close()
        atexit.unregister(_finalize)
        for _sig, _old in _old_handlers.items():
            try:
                signal.signal(_sig, _old)
            except (ValueError, OSError):  # pragma: no cover
                pass
        if sup is not None:
            sup._restore_env()  # idempotent; run()'s finally usually did
        if cur.telemetry is not None and cfg.output.telemetry_path:
            log(f"telemetry: {n_rec + 1} records -> "
                f"{cfg.output.telemetry_path}")
        if cfg.output.metrics_path:
            log(f"metrics: OpenMetrics exposition -> "
                f"{cfg.output.metrics_path} (gate with "
                f"tools/slo_gate.py; fleet view: "
                f"tools/fleet_report.py)")


if __name__ == "__main__":
    sys.exit(main())
