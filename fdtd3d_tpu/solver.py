"""Functional solver core: state pytree, coefficients, leapfrog step.

TPU-native replacement for the reference's ``InternalScheme`` (hot stencils)
+ ``Scheme`` (orchestration) pair (SURVEY.md §2, §3.1). Design stance per
SURVEY.md §7: the solver state is a pytree
``{E, H, psi_E, psi_H, J, inc, t}``; materials/profiles are a coeffs pytree;
one pure ``step(state, coeffs) -> state``; ``lax.scan`` over steps; ``jit``
around the whole loop; the SAME step runs single-chip or inside
``shard_map`` (halo exchange is inside the difference ops, stencil.py).

Update equations (SI units; leapfrog; acc is the curl accumulator):

  E_c^{n+1} = ca_c E_c^n + cb_c (acc_E - J_c^{n+1/2})
      acc_E = sum_terms s * (ik_a * dH_d/da + psi_{c,a}) + TFSF corrections
      psi_{c,a}^{n+1} = b_a psi + c_a dH_d/da            (CPML, "e" profiles)
  H_c^{n+3/2} = da_c H_c^{n+1/2} - db_c acc_H            ("h" profiles)
  J_c^{n+1/2} = kj J_c^{n-1/2} + bj E_c^n                (Drude ADE)

with ca = (1 - se)/(1 + se), cb = dt/(eps0 eps_r)/(1 + se),
se = sigma_e dt/(2 eps0 eps_r) (dually da/db with mu, sigma_m), and
kj = (1 - g dt/2)/(1 + g dt/2), bj = eps0 wp^2 dt/(1 + g dt/2).

The 13 scheme modes share this one kernel: inactive axes are singleton dims
(zero derivative), inactive components are absent from the pytree
(layout.py). PEC walls are 1D multiplicative masks on tangential E.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fdtd3d_tpu import materials, physics
from fdtd3d_tpu.telemetry import named as _named
from fdtd3d_tpu.config import SimConfig
from fdtd3d_tpu.layout import CURL_TERMS, component_axis
from fdtd3d_tpu.ops import cpml, tfsf
from fdtd3d_tpu.ops.sources import point_mask, waveform
from fdtd3d_tpu.ops.stencil import make_diff_ops

AXES = "xyz"

# Graph-safe region marker (tracer-hostility lint rule, fdtd3d_tpu/
# analysis/ast_rules.py): every function of these names — the traced
# step closures and their helpers, at any nesting depth — is GRAPH
# code; the rule bans host calls (float()/.item()/np.asarray/
# time.time()/os.*) inside them and in every same-module function
# they call by name. The paired-complex pack/unpack are deliberately
# NOT listed: they route through host numpy by design.
GRAPH_SAFE_FNS = ("step", "_half_update", "_slab_delta", "_pad_slab",
                  "_bcast1d", "_slab_delta_ds", "ds_diff")


@dataclasses.dataclass(frozen=True)
class StaticSetup:
    """Everything trace-static: closed over by the step function."""

    cfg: SimConfig
    mode: Any
    grid_shape: Tuple[int, int, int]
    dt: float
    dx: float
    omega: float
    pml_axes: Tuple[int, ...]        # active axes with a PML slab
    tfsf_setup: Optional[tfsf.TfsfSetup]
    use_drude: bool
    field_dtype: Any
    real_dtype: Any
    use_drude_m: bool = False        # magnetic Drude (metamaterial mode)
    # Complex fields on a backend without complex arithmetic (the TPU
    # 'axon' platform): run PAIRED REAL legs instead (see
    # _make_paired_complex_step) — the solver is linear with real
    # coefficients and real sources, so complex == re-leg (sourced)
    # + 1j * im-leg (source-free), each leg on the full real kernel
    # stack (packed Pallas included).
    paired_complex: bool = False

    @property
    def aux_dtype(self):
        """dtype of the recursion state (psi, J, inc): f32 when fields
        are bf16 storage, else the field dtype."""
        return np.float32 if self.field_dtype == jnp.bfloat16 \
            else self.field_dtype

    @property
    def compute_dtype(self):
        """dtype the update arithmetic runs in (the recursion state must
        be stored in the same precision the arithmetic uses)."""
        return self.aux_dtype
    # Decomposition topology (px, py, pz). Simulation rewrites this after
    # resolving the mesh; it controls the psi slab layout below.
    topology: Tuple[int, int, int] = (1, 1, 1)


def slab_axes(static: StaticSetup) -> Dict[int, int]:
    """axis -> npml for PML axes using compact slab psi storage.

    CPML psi memory is identically zero outside the two npml-thick absorbing
    slabs of its own axis (ops/cpml.py forces c=0 there), so storing the
    full-domain array — as v0 did, mirroring the reference's full-size sigma
    grids — wastes ~(1 - 2*npml/n) of its HBM traffic every step. Instead
    psi keeps only the 2*npml boundary planes per shard (lo slab ++ hi
    slab); interior shards hold all-zero slabs so the SAME shard_map step
    works for every rank. Falls back to full storage when a shard is too
    thin to hold two disjoint slabs.
    """
    out: Dict[int, int] = {}
    for a in static.pml_axes:
        npml = static.cfg.pml.size[a]
        # One extra plane: the h-staggered (offset 0.5) hi-side profile is
        # nonzero at index n-1-npml, one plane inside of the npml-thick
        # slab (ops/cpml.py d_hi), so exact parity with full storage needs
        # npml+1 planes per side.
        m = npml + 1
        local_n = static.grid_shape[a] // static.topology[a]
        if npml > 0 and local_n > 2 * m:
            out[a] = m
    return out


# True once probed OK; the probe's Exception when the backend failed it.
_complex_backend_ok: Any = None


def _complex_backend_supported() -> bool:
    """Probe whether the active backend can do complex arithmetic.

    Complex-field mode runs natively on CPU; some experimental TPU
    backends (the tunneled 'axon' platform here) create complex arrays
    but raise UNIMPLEMENTED on the first complex op. A failed probe
    routes the run to the paired-real step instead (VERDICT r3 item 4 —
    previously a fail-fast config error).
    """
    global _complex_backend_ok
    import os
    if os.environ.get("FDTD3D_FORCE_PAIRED_COMPLEX"):
        return False  # test hook: exercise the paired path on CPU
    if jax.default_backend() in ("tpu", "axon"):
        # TPU backends take the paired-real route unconditionally:
        # (a) it is faster even where native complex works — complex
        # arrays are ineligible for every Pallas kernel, so a native
        # run would fall to the jnp path while the paired legs ride
        # the packed kernel; (b) the tunneled axon platform (which
        # registers as "tpu") lacks complex ops entirely, and merely
        # RUNNING the probe leaves the backend unable to execute ANY
        # later transfer in the process (measured: every device_put
        # returns UNIMPLEMENTED afterwards). Decide by name, never
        # probe on TPU.
        return False
    if _complex_backend_ok is None:
        try:
            # Mirror the real workload: a jitted complex scan plus a
            # device->host transfer (some backends only fail lazily there).
            x = jnp.ones((4, 4), jnp.complex64)

            def body(c, _):
                return c * (0.99 + 0.01j) + c.conj() * 0.001j, None

            y, _ = jax.jit(
                lambda v: jax.lax.scan(body, v, None, length=3))(x)
            np.asarray(y)
            _complex_backend_ok = True
        except Exception as exc:
            _complex_backend_ok = exc
    return _complex_backend_ok is True


def build_static(cfg: SimConfig) -> StaticSetup:
    cfg.validate()
    paired = cfg.complex_fields and not _complex_backend_supported()
    if cfg.dtype == "float64" and not jax.config.jax_enable_x64:
        # The reference computes in C++ double; honor float64 requests
        # instead of letting jax silently truncate to f32.
        jax.config.update("jax_enable_x64", True)
    mode = cfg.mode
    # bfloat16 is a STORAGE dtype only (fields in HBM): coefficients,
    # CPML psi, Drude J, the incident line, and all arithmetic stay f32
    # (mixed precision) — bf16 accumulation of the leapfrog recursions
    # loses the wave within tens of steps, while bf16 storage alone
    # halves the HBM traffic that bounds FDTD throughput.
    real = {"float32": np.float32, "float64": np.float64,
            "bfloat16": np.float32, "float32x2": np.float32}[cfg.dtype]
    field = cfg.np_dtype()
    pml_axes = tuple(a for a in mode.active_axes if cfg.pml.size[a] > 0)
    st = StaticSetup(
        cfg=cfg, mode=mode, grid_shape=cfg.grid_shape, dt=cfg.dt,
        dx=cfg.dx, omega=cfg.omega, pml_axes=pml_axes, tfsf_setup=None,
        use_drude=cfg.materials.use_drude, field_dtype=field,
        real_dtype=real, use_drude_m=cfg.materials.use_drude_m,
        paired_complex=paired)
    if cfg.tfsf.enabled:
        st = dataclasses.replace(st, tfsf_setup=tfsf.build_setup(cfg, st))
    return st


# --------------------------------------------------------------------------
# coefficients (host-built numpy; device_put + sharding happens in parallel/)
# --------------------------------------------------------------------------

def build_coeffs(static: StaticSetup) -> Dict[str, Any]:
    cfg, mode = static.cfg, static.mode
    shape = static.grid_shape
    dt, rd = static.dt, static.real_dtype
    mat = cfg.materials
    out: Dict[str, Any] = {}

    for a in range(3):
        out[f"g{AXES[a]}"] = np.arange(shape[a], dtype=np.int32)
        wall = np.ones(shape[a], dtype=rd)
        if a in mode.active_axes:
            wall[0] = 0.0
            wall[-1] = 0.0
        out[f"wall_{AXES[a]}"] = wall

    def _cast(v):
        return rd(v) if np.isscalar(v) else v.astype(rd)

    def _cast_ds(key, v):
        """Store coefficient `key`; in compensated and float32x2 modes
        also store its double-single low word
        ``key_lo`` = f32(v64 - f32(v64)).

        Why: rounding ca/cb/da/db to f32 perturbs the DISCRETE SYSTEM
        itself (an effective material/impedance shift of ~eps32), which
        diverges from the f64 reference linearly in t — measured 5e-6
        by 1600 steps, dwarfing the accumulation error the Kahan
        residuals fix. Applying hi+lo restores ~2^-48 coefficient
        accuracy for two extra FMAs per term (free: the step is
        HBM-bound)."""
        out[key] = _cast(v)
        if cfg.compensated or cfg.ds_fields:
            v64 = np.asarray(v, np.float64)
            out[f"{key}_lo"] = _cast(v64 - np.asarray(out[key],
                                                      np.float64))

    for c in mode.e_components:
        eps = materials.scalar_or_grid(c, shape, mode.active_axes, mat.eps,
                                       mat.eps_sphere, mat.eps_file)
        if static.use_drude:
            wp, gamma, _ = materials.drude_params(c, shape,
                                                  mode.active_axes, mat)
            eps = materials.merge_drude_eps(eps, wp, mat.eps_inf)
            out[f"kj_{c}"] = _cast((1.0 - gamma * dt / 2.0)
                                   / (1.0 + gamma * dt / 2.0))
            out[f"bj_{c}"] = _cast(physics.EPS0 * np.square(wp) * dt
                                   / (1.0 + gamma * dt / 2.0))
        se = mat.sigma_e * dt / (2.0 * physics.EPS0 * np.asarray(eps))
        _cast_ds(f"ca_{c}", (1.0 - se) / (1.0 + se))
        _cast_ds(f"cb_{c}", dt / (physics.EPS0 * np.asarray(eps))
                 / (1.0 + se))

    for c in mode.h_components:
        mu = materials.scalar_or_grid(c, shape, mode.active_axes, mat.mu,
                                      mat.mu_sphere, mat.mu_file)
        if static.use_drude_m:
            wpm, gm, _ = materials.drude_params(c, shape,
                                                mode.active_axes, mat,
                                                magnetic=True)
            mu = materials.merge_drude_eps(mu, wpm, mat.mu_inf)
            out[f"km_{c}"] = _cast((1.0 - gm * dt / 2.0)
                                   / (1.0 + gm * dt / 2.0))
            out[f"bm_{c}"] = _cast(physics.MU0 * np.square(wpm) * dt
                                   / (1.0 + gm * dt / 2.0))
        sm = mat.sigma_m * dt / (2.0 * physics.MU0 * np.asarray(mu))
        _cast_ds(f"da_{c}", (1.0 - sm) / (1.0 + sm))
        _cast_ds(f"db_{c}", dt / (physics.MU0 * np.asarray(mu))
                 / (1.0 + sm))

    if static.pml_axes:
        if cfg.ds_fields:
            # double-single CPML profiles: the slab algebra runs in ds
            # (f32 slab deltas were the measured ~1e-6 residual — the
            # eps32 noise injected at the absorbing interface reflects
            # back into the interior coherently). Naming keeps the
            # _x/_y/_z suffix LAST: parallel/mesh.coeff_specs keys its
            # sharding inference on it.
            from fdtd3d_tpu.ops import ds as _ds_mod
            full64 = cpml.build_cpml_coeffs(cfg, static, np.float64)
            slab64 = cpml.build_slab_coeffs(full64, static,
                                            slab_axes(static))
            for src64 in (full64, slab64):
                for k, v in src64.items():
                    hi, lo = _ds_mod.from_f64(v)
                    out[k] = hi
                    base, ax = k.rsplit("_", 1)
                    out[f"{base}lo_{ax}"] = lo
        else:
            full = cpml.build_cpml_coeffs(cfg, static, rd)
            out.update(full)
            out.update(cpml.build_slab_coeffs(full, static,
                                              slab_axes(static)))

    if cfg.point_source.enabled:
        # Traced source amplitude (round 15): the jnp step reads the
        # drive strength from the coeffs pytree instead of baking the
        # python float into the graph, so the vmap-batched executor
        # (fdtd3d_tpu/batch.py) can give every lane its own amplitude
        # under ONE compiled executable. Same value bit-for-bit for a
        # single run (the float was rounded to rd at trace time
        # anyway). The packed/tb kernels read it too — their
        # post-kernel point_source_patch (ops/pallas3d.py) threads the
        # traced value, which is what makes them lane-capable; only
        # the ds step keeps its host-side hi+lo split (float32x2 does
        # not batch — fdtd3d_tpu/batch.py names the limit).
        out["ps_amp"] = rd(cfg.point_source.amplitude)

    if static.tfsf_setup is not None:
        if cfg.ds_fields:
            # double-single line coefficients: the incident line's own
            # f32 coefficient rounding would otherwise re-introduce the
            # linear-in-t operator drift the mode exists to remove
            from fdtd3d_tpu.ops import ds as _ds
            prof64 = tfsf.line_loss_profiles(
                static.tfsf_setup.n_inc, dt, static.dx, np.float64)
            for k, v in zip(("inc_ae", "inc_be", "inc_ah", "inc_bh"),
                            prof64):
                out[k], out[f"{k}_lo"] = _ds.from_f64(v)
        else:
            ae, be, ah, bh = tfsf.line_loss_profiles(
                static.tfsf_setup.n_inc, dt, static.dx, rd)
            out.update(inc_ae=ae, inc_be=be, inc_ah=ah, inc_bh=bh)

    return out


def init_state(static: StaticSetup) -> Dict[str, Any]:
    shape, fd = static.grid_shape, static.field_dtype
    aux = static.aux_dtype
    mode = static.mode
    slabs = slab_axes(static)
    # paired-complex mode keeps the complex OUTER state host-side
    # (numpy): even creating or transferring a complex device array
    # raises UNIMPLEMENTED on backends without complex support; the
    # real legs live on device (pack/unpack convert at the boundary).
    xp = np if static.paired_complex else jnp
    zeros = lambda: xp.zeros(shape, dtype=fd)  # noqa: E731

    def psi_zeros(a: int):
        """psi_{c,a} storage: slab-compacted along its own axis a."""
        s = list(shape)
        if a in slabs:
            s[a] = 2 * slabs[a] * static.topology[a]
        return xp.zeros(tuple(s), dtype=aux)

    state: Dict[str, Any] = {
        "E": {c: zeros() for c in mode.e_components},
        "H": {c: zeros() for c in mode.h_components},
        "t": jnp.zeros((), dtype=jnp.int32),
    }
    psi_e, psi_h = {}, {}
    for c in mode.e_components:
        for (a, d_axis, s) in CURL_TERMS[component_axis(c)]:
            if a in static.pml_axes:
                psi_e[f"{c}_{AXES[a]}"] = psi_zeros(a)
    for c in mode.h_components:
        for (a, d_axis, s) in CURL_TERMS[component_axis(c)]:
            if a in static.pml_axes:
                psi_h[f"{c}_{AXES[a]}"] = psi_zeros(a)
    if psi_e:
        state["psi_E"] = psi_e
        state["psi_H"] = psi_h
        if static.cfg.ds_fields:
            # psi recursions run in ds too (see build_coeffs)
            state["lopsi_E"] = {k: xp.zeros(v.shape, np.float32)
                                for k, v in psi_e.items()}
            state["lopsi_H"] = {k: xp.zeros(v.shape, np.float32)
                                for k, v in psi_h.items()}
    if static.use_drude:
        state["J"] = {c: xp.zeros(shape, dtype=aux)
                      for c in mode.e_components}
    if static.use_drude_m:
        state["K"] = {c: xp.zeros(shape, dtype=aux)
                      for c in mode.h_components}
    if static.cfg.compensated:
        # Kahan residuals: the low-order bits the f32 accumulation
        # E += u drops each step. bf16 storage keeps ~8 of them —
        # enough to push the effective accumulation error ~2^-8 below
        # plain f32 (validated in tests/test_compensated.py) at a
        # quarter of the residual's f32 traffic.
        state["rE"] = {c: jnp.zeros(shape, dtype=jnp.bfloat16)
                       for c in mode.e_components}
        state["rH"] = {c: jnp.zeros(shape, dtype=jnp.bfloat16)
                       for c in mode.h_components}
    if static.cfg.ds_fields:
        # double-single low words: E/H are carried as hi+lo f32 pairs
        # end-to-end (ops/ds.py; _make_ds_step) — ~f64-class
        # accumulation at 2x f32 field traffic.
        state["loE"] = {c: xp.zeros(shape, dtype=np.float32)
                        for c in mode.e_components}
        state["loH"] = {c: xp.zeros(shape, dtype=np.float32)
                        for c in mode.h_components}
    if static.tfsf_setup is not None:
        n = static.tfsf_setup.n_inc
        state["inc"] = {"Einc": xp.zeros(n, dtype=aux),
                        "Hinc": xp.zeros(n, dtype=aux)}
        if static.cfg.ds_fields:
            state["inc"]["Einc_lo"] = xp.zeros(n, dtype=np.float32)
            state["inc"]["Hinc_lo"] = xp.zeros(n, dtype=np.float32)
    return state


# --------------------------------------------------------------------------
# the step
# --------------------------------------------------------------------------

def _bcast1d(arr: jnp.ndarray, axis: int) -> jnp.ndarray:
    shape = [1, 1, 1]
    shape[axis] = arr.shape[0]
    return arr.reshape(shape)


def _slab_delta(a, tag, s, dfa, psi, coeffs, m):
    """Slab-psi CPML correction: -> (new compact psi, lo delta, hi delta).

    The full-domain family update runs the PURE interior curl (term =
    dfa, no PML logic at all — one fused memory-bound pass); the exact
    CPML term differs from it only inside the two npml slabs of axis a,
    by  s * ((ik - 1) * dfa + psi).  Those deltas are added back onto
    the thin slab regions with in-place slice-adds. Deltas of different
    axes commute, so overlap corners compose correctly.

    Local shapes are trace-time static, so this is shard_map-safe; on
    interior shards the slab profiles are identically (b=0, c=0, ik=1)
    and both deltas are exactly zero. Shared by the f32 jnp step and
    the float32x2 step (whose dfa is the collapsed hi+lo — exact
    outside the slabs, where the delta vanishes identically).
    """
    ax = AXES[a]
    nloc = dfa.shape[a]
    cut = lambda f, lo, hi: jax.lax.slice_in_dim(f, lo, hi, axis=a)  # noqa: E731
    b = _bcast1d(coeffs[f"pml_slab_b{tag}_{ax}"], a)
    cc = _bcast1d(coeffs[f"pml_slab_c{tag}_{ax}"], a)
    ik = _bcast1d(coeffs[f"pml_slab_ik{tag}_{ax}"], a)
    d_lo, d_hi = cut(dfa, 0, m), cut(dfa, nloc - m, nloc)
    p_lo = cut(b, 0, m) * cut(psi, 0, m) + cut(cc, 0, m) * d_lo
    p_hi = cut(b, m, 2 * m) * cut(psi, m, 2 * m) + cut(cc, m, 2 * m) * d_hi
    dl = s * ((cut(ik, 0, m) - 1.0) * d_lo + p_lo)
    dh = s * ((cut(ik, m, 2 * m) - 1.0) * d_hi + p_hi)
    return jnp.concatenate([p_lo, p_hi], axis=a), dl, dh


def _pad_slab(dl, dh, a, nloc, m):
    """Zero-pad the two slab deltas back to the full local extent.

    jnp.pad (constant 0) fuses into its elementwise consumer under XLA,
    so adding the padded deltas onto the accumulator costs no extra
    full-array materialization — unlike dynamic-update-slice patches,
    which compile to full copies here.
    """
    pad_lo = [(0, 0)] * 3
    pad_hi = [(0, 0)] * 3
    pad_lo[a] = (0, nloc - m)
    pad_hi[a] = (nloc - m, 0)
    return jnp.pad(dl, pad_lo) + jnp.pad(dh, pad_hi)


def _want_pallas(static: StaticSetup, mesh_axes) -> bool:
    flag = static.cfg.use_pallas
    if flag is False:
        return False
    if flag is None:
        # auto: only on real TPU (interpret mode on CPU is test-only slow);
        # "axon" is the tunneled-TPU platform in this environment.
        import jax as _jax
        if _jax.default_backend() not in ("tpu", "axon"):
            return False
    from fdtd3d_tpu.ops import pallas3d, pallas_packed
    return (pallas3d.eligible(static, mesh_axes)
            or pallas_packed.eligible(static, mesh_axes))


def batch_fallback_reason(static: StaticSetup, mesh_axes=None,
                          lane_coeffs=None, batch: int = 0):
    """Machine-readable reason a coalesced batch of ``batch`` lanes
    over this static canNOT ride the lane-capable packed kernels, or
    None when it can. THE batch dispatch authority: run_batch
    (batch.py), the queue dispatcher's coalesced groups, the cost
    tracer (costs.trace_chunk) and the lint lanes all consult this one
    function, so they can never disagree about whether/why a batch
    fell back to the ~6x-slower vmap-jnp path. Recorded downstream as
    ``batch_unsupported:<token>`` in telemetry run_start and the CLI
    step-kind line — never a silent downgrade.

    Token order mirrors tb_fallback_reason: dispatch-context tokens
    first (pallas off, env escape hatches), then kernel scope/VMEM
    viability at the batched width, and the per-lane scalar sweep
    strictly last (it needs built coefficients; the others are pure
    config analysis).

    ``lane_coeffs``: optional list of per-lane host coefficient dicts
    (solver.build_coeffs output). The packed kernels BAKE scalar
    coefficients as compile-time floats (pallas_packed.
    baked_coeff_keys), so any scalar-valued key differing across lanes
    — or scalar in one lane, material-grid in another — is
    ``scalar_coeff_divergence``: the lane-capable build would run lane
    0's constant in every lane. Grid-valued (ndim >= 3) coefficients
    everywhere are traced operands and batch freely; the traced
    ``ps_amp`` likewise exempts per-lane source amplitudes."""
    import os as _os

    from fdtd3d_tpu.ops import pallas_packed
    if not _want_pallas(static, mesh_axes):
        return "pallas_disabled"
    if _os.environ.get("FDTD3D_NO_PACKED"):
        return "env:FDTD3D_NO_PACKED"
    if _os.environ.get("FDTD3D_FORCE_FUSED"):
        return "env:FDTD3D_FORCE_FUSED"
    if not pallas_packed.eligible(static, mesh_axes) \
            or pallas_packed.packed_tile(static, batch=batch) == 0:
        return "kernel_ineligible"
    if lane_coeffs:
        for key in pallas_packed.baked_coeff_keys(static):
            vals = [lc[key] for lc in lane_coeffs]
            nds = [np.ndim(v) for v in vals]
            if all(nd >= 3 for nd in nds):
                continue      # grids are traced operands: lanes may vary
            if any(nd >= 3 for nd in nds):
                return "scalar_coeff_divergence"
            v0 = np.asarray(vals[0])
            if any(not np.array_equal(np.asarray(v), v0)
                   for v in vals[1:]):
                return "scalar_coeff_divergence"
    return None


def tb_fallback_reason(static: StaticSetup, mesh_axes=None,
                       allow_multistep: bool = True):
    """Machine-readable reason the dispatch did NOT engage the
    temporal-blocked kernel, or None when it would. Config-level scope
    and viability tokens come from the single decision authority
    (ops/pallas_packed_tb.plan_tb); this layer only adds the
    dispatch-context tokens a pure config analysis cannot see (env
    escape hatches, the one-step contract, pallas disabled). Recorded
    as ``tb_fallback{reason}`` in telemetry run_start and the cost
    ledger so fleets can see which scenarios are paying the 2x-HBM
    tax — the downgrade used to be silent.

    Order matters: scope tokens first (most informative), then the
    dispatch-context tokens, and the DEPTH-VIABILITY scan strictly
    last — when the context declined tb (the escape hatch, pallas
    off, the one-step contract) the dispatch never consulted the
    depth picker, so neither may this stamp: an unviable
    ``FDTD3D_TB_DEPTH`` pin must not raise from a run that was never
    going to temporal-block (the pin error itself recommends
    FDTD3D_NO_TEMPORAL=1 as the remedy)."""
    import os as _os

    from fdtd3d_tpu.ops import pallas_packed_tb
    reason = pallas_packed_tb._reject_reason(static, mesh_axes)
    if reason is not None:
        return reason
    # config is in tb scope: the dispatch context declined it
    if not allow_multistep:
        return "single_step_contract"
    if _os.environ.get("FDTD3D_NO_TEMPORAL"):
        return "env:FDTD3D_NO_TEMPORAL"
    if not _want_pallas(static, mesh_axes):
        return "pallas_disabled"
    if _os.environ.get("FDTD3D_NO_PACKED"):
        return "env:FDTD3D_NO_PACKED"
    if _os.environ.get("FDTD3D_FORCE_FUSED"):
        return "env:FDTD3D_FORCE_FUSED"
    # in scope, context allowed: the dispatch DID consult plan_tb and
    # declined on geometry/viability (an unviable pin would already
    # have raised there, before any step reached this stamp)
    return pallas_packed_tb.plan_tb(static, mesh_axes).reason


def _stamp_tb_fallback(step, static, mesh_axes, allow_multistep=True):
    """Attach the tb_fallback record to a non-tb step's diag (the
    telemetry/ledger writers read it from there — the reason is
    computed at BUILD time, under the env that shaped the dispatch)."""
    if getattr(step, "kind", None) == "pallas_packed_tb":
        return step
    reason = tb_fallback_reason(static, mesh_axes, allow_multistep)
    diag = getattr(step, "diag", None)
    if diag is None:
        diag = {}
        step.diag = diag
    diag["tb_fallback"] = {
        "reason": reason if reason is not None else "unknown"}
    return step


def make_step(static: StaticSetup, mesh_axes=None, mesh_shape=None,
              allow_multistep: bool = True, batch: int = 0):
    """Build the pure leapfrog step. mesh_axes/mesh_shape: see stencil.py.

    ``batch=B`` (B >= 2) builds the LANE-CAPABLE packed step for a
    coalesced batch: the tile/depth pickers charge the per-lane VMEM
    surcharge (config.VMEM_TEMPS_DEFAULTS["batch_lane"]) and the
    caller vmaps the chunk runner over the lane axis. Callers MUST
    gate with batch_fallback_reason(...) is None first — a batched
    build that cannot land on the packed family raises rather than
    silently dispatching a non-lane-capable kind.

    Dispatches to the fused Pallas kernels (ops/pallas3d.py) when the
    configuration is eligible and use_pallas is not False; otherwise the
    pure-jnp step below (identical semantics) is built.

    ``allow_multistep=False`` skips the temporal-blocked kernel
    (ops/pallas_packed_tb.py), whose step advances k steps per call —
    callers that require the one-step contract (the paired-complex leg
    builder) pass it; make_chunk_runner handles multi-step steps via
    ``step.steps_per_call`` / ``step.tail_step``.

    Every step built by a kind OTHER than ``pallas_packed_tb`` carries
    a ``diag["tb_fallback"]`` record naming WHY temporal blocking did
    not engage (tb_fallback_reason) — surfaced in telemetry run_start,
    the cost ledger and tools/telemetry_report.py.
    """
    if batch and batch > 1 \
            and (static.paired_complex or static.cfg.ds_fields):
        raise RuntimeError(
            "make_step(batch>1): paired-complex and float32x2 steps "
            "are not lane-capable; gate batched builds with "
            "solver.batch_fallback_reason")
    if static.paired_complex:
        return _stamp_tb_fallback(
            _make_paired_complex_step(static, mesh_axes, mesh_shape),
            static, mesh_axes, allow_multistep)
    if static.cfg.ds_fields:
        # float32x2 hot path: the packed double-single Pallas kernel
        # (ops/pallas_packed_ds.py) — same dispatch policy as the f32
        # kernels (use_pallas flag, TPU-or-interpret backend rule,
        # FDTD3D_NO_PACKED escape hatch); sharded topologies included
        # (round 5) — jnp-ds covers what remains (thin-grid psi, or a
        # sharded axis without a mesh axis name)
        import os as _os
        flag = static.cfg.use_pallas
        want = flag is not False and not _os.environ.get(
            "FDTD3D_NO_PACKED")
        if want and flag is None:
            import jax as _jax
            want = _jax.default_backend() in ("tpu", "axon")
        if want:
            from fdtd3d_tpu.ops import pallas_packed_ds
            pk = pallas_packed_ds.make_packed_ds_step(
                static, mesh_axes, mesh_shape)
            if pk is not None:
                pk.kind = "pallas_packed_ds"
                return _stamp_tb_fallback(pk, static, mesh_axes,
                                          allow_multistep)
        step = _make_ds_step(static, mesh_axes, mesh_shape)
        step.kind = "jnp_ds"
        return _stamp_tb_fallback(step, static, mesh_axes,
                                  allow_multistep)
    if _want_pallas(static, mesh_axes):
        import os as _os

        # Packed pipelined single-pass kernel (ops/pallas_packed.py):
        # the round-4 hot path — stacked E/H operands, H update lagging
        # one x-tile on VMEM scratch carry, 12 volumes/step vs the
        # two-pass kernels' 18 — so it engages whenever eligible.
        # FDTD3D_NO_PACKED is the measurement escape hatch
        # (tools/measure_r4.py compares all three in one window);
        # FDTD3D_FORCE_FUSED (below) also skips it, so forcing the
        # fused kernel needs only the one variable.
        if not _os.environ.get("FDTD3D_NO_PACKED") \
                and not _os.environ.get("FDTD3D_FORCE_FUSED"):
            # Temporal-blocked kernel (rounds 8/12): k Yee steps per
            # HBM pass (~48/k B/cell f32, k in {2,3,4} from the VMEM-
            # calibrated auto-depth pick) on its (stricter) scope; its
            # step advances k steps per call (steps_per_call), with a
            # same-tile pallas_packed tail for non-multiple horizons.
            # FDTD3D_NO_TEMPORAL forces the round-6 single-step kernel
            # bit-for-bit (the escape hatch mirroring FDTD3D_NO_PACKED).
            if allow_multistep \
                    and not _os.environ.get("FDTD3D_NO_TEMPORAL"):
                from fdtd3d_tpu.ops import pallas_packed_tb
                tb = pallas_packed_tb.make_packed_tb_step(
                    static, mesh_axes, mesh_shape, batch=batch)
                if tb is not None:
                    tb.kind = "pallas_packed_tb"
                    # tb.tail_step.kind is set by make_packed_tb_step
                    return tb
            from fdtd3d_tpu.ops import pallas_packed
            pk = pallas_packed.make_packed_eh_step(static, mesh_axes,
                                                   mesh_shape,
                                                   batch=batch)
            if pk is not None:
                pk.kind = "pallas_packed"
                return _stamp_tb_fallback(pk, static, mesh_axes,
                                          allow_multistep)
        if batch and batch > 1:
            # the dispatch authority (batch_fallback_reason) approved
            # this batched build, yet no lane-capable kind engaged —
            # an authority/builder disagreement, never a silent
            # downgrade onto fused/pallas3d/jnp
            raise RuntimeError(
                "make_step(batch>1): no lane-capable packed kind "
                "engaged; gate batched builds with "
                "solver.batch_fallback_reason")

        # single-pass E+H kernel where its (stricter) scope allows —
        # ~2/3 the HBM traffic of the two-pass kernels, but ONLY when
        # the VMEM-budgeted x-tile stays large enough: every tile
        # re-reads ~3 extra halo planes per input volume, so at small T
        # the amplification eats the 48-vs-72 B/cell advantage
        # (measured, same window: 256^3 T=8 fused 1.10x faster;
        # 384^3 T=2 fused 0.92x; 512^3 T=1 fused 0.73x).
        # FDTD3D_NO_FUSED is a measurement escape hatch: it forces the
        # two-pass kernels so the fused advantage can be benchmarked on
        # configs where both are eligible (tools/measure_r3.py).
        # FDTD3D_FORCE_FUSED bypasses the tile>=4 dispatch heuristic —
        # the threshold was measured on one throttled tunneled chip and
        # the crossover may sit elsewhere on other TPU generations
        # (ADVICE r3).
        from fdtd3d_tpu.ops import pallas_fused
        eh = None if _os.environ.get("FDTD3D_NO_FUSED") else \
            pallas_fused.make_fused_eh_step(static, mesh_axes, mesh_shape)
        if eh is not None and (eh.diag["tile"]["EH"] >= 4
                               or _os.environ.get("FDTD3D_FORCE_FUSED")):
            eh.kind = "pallas_fused"
            return _stamp_tb_fallback(eh, static, mesh_axes,
                                      allow_multistep)
        from fdtd3d_tpu.ops import pallas3d
        fused = pallas3d.make_pallas_step(static, mesh_axes, mesh_shape)
        if fused is not None:
            fused.kind = "pallas"
            return _stamp_tb_fallback(fused, static, mesh_axes,
                                      allow_multistep)
        # (no eh fallback here: single-pass eligibility is a strict
        # subset of two-pass eligibility, so eh is None whenever
        # make_pallas_step returned None)
    if batch and batch > 1:
        raise RuntimeError(
            "make_step(batch>1): no lane-capable packed kind "
            "engaged; gate batched builds with "
            "solver.batch_fallback_reason")
    mode, cfg = static.mode, static.cfg
    diff_b, diff_f = make_diff_ops(mesh_axes, mesh_shape)
    inv_dx = 1.0 / static.dx
    # compensated mode: double-single 1/dx (its f32 rounding is the
    # same class of systematic discrete-system perturbation as the
    # ca/cb one — see build_coeffs._cast_ds)
    iv_hi = np.float32(inv_dx)
    iv_lo = np.float32(inv_dx - np.float64(iv_hi))
    setup = static.tfsf_setup
    ps = cfg.point_source
    slabs = slab_axes(static)

    def _half_update(field: str, state, coeffs, new_psi):
        """One family update (field='E' or 'H'). Returns new component dict."""
        upd_comps = mode.e_components if field == "E" else mode.h_components
        src = state["H"] if field == "E" else state["E"]
        if static.field_dtype != static.compute_dtype:
            # bf16 storage: difference/psi arithmetic runs in f32 (the
            # convert fuses into the consumers, no extra HBM pass)
            src = {k: v.astype(static.compute_dtype)
                   for k, v in src.items()}
        tag = "e" if field == "E" else "h"
        diff = diff_b if field == "E" else diff_f
        psi_key = "psi_E" if field == "E" else "psi_H"
        out = {}
        for c in upd_comps:
            acc = None
            for (a, d_axis, s) in CURL_TERMS[component_axis(c)]:
                d = ("H" if field == "E" else "E") + AXES[d_axis]
                if d not in src:
                    continue
                if static.cfg.compensated:
                    d0 = diff(src[d], a)
                    dfa = d0 * iv_hi + d0 * iv_lo
                else:
                    dfa = diff(src[d], a) * inv_dx
                if a in slabs:
                    with _named("cpml"):
                        key = f"{c}_{AXES[a]}"
                        psi, dl, dh = _slab_delta(a, tag, s, dfa,
                                                  state[psi_key][key],
                                                  coeffs, slabs[a])
                        new_psi[psi_key][key] = psi
                        # The delta is an acc-level correction (it
                        # carries the curl sign s already): fold it in
                        # before ca/cb.
                        acc_fix = _pad_slab(dl, dh, a, dfa.shape[a],
                                            slabs[a])
                        acc = acc_fix if acc is None else acc + acc_fix
                        term = dfa
                elif a in static.pml_axes:
                    with _named("cpml"):
                        ax = AXES[a]
                        b = _bcast1d(coeffs[f"pml_b{tag}_{ax}"], a)
                        cc = _bcast1d(coeffs[f"pml_c{tag}_{ax}"], a)
                        ik = _bcast1d(coeffs[f"pml_ik{tag}_{ax}"], a)
                        key = f"{c}_{ax}"
                        psi = b * state[psi_key][key] + cc * dfa
                        new_psi[psi_key][key] = psi
                        term = ik * dfa + psi
                else:
                    term = dfa
                acc = s * term if acc is None else acc + s * term
            if acc is None:
                # zeros in the LOCAL shape (shard_map-safe), not grid_shape.
                acc = jnp.zeros(state[field][c].shape,
                                static.compute_dtype)
            if setup is not None:
                corr = tfsf.corrections_for(field, c, setup, coeffs,
                                            state["inc"], mode.active_axes,
                                            static.dx)
                if corr is not None:
                    acc = acc + corr
            out[c] = acc
        return out

    def step(state, coeffs):
        t = state["t"]
        new_state = dict(state)
        new_psi = {"psi_E": dict(state.get("psi_E", {})),
                   "psi_H": dict(state.get("psi_H", {}))}

        # 1. incident line E advance (Einc -> t^{n+1}); see tfsf.py timing.
        if setup is not None:
            with _named("tfsf"):
                new_state["inc"] = tfsf.advance_einc(
                    state["inc"], coeffs, t, static.dt, static.omega,
                    setup)
            state = dict(state, inc=new_state["inc"])

        # 2. E family (the whole family — curl accumulation AND the
        # ca/cb coefficient application — sits inside the E-update
        # scope so the cost ledger (fdtd3d_tpu/costs.py) can attribute
        # the full family cost; cpml/source sub-scopes nest inside and
        # win the attribution for their ops)
        compensated = static.cfg.compensated
        new_E = {}
        new_rE: Dict[str, Any] = {}
        new_J: Dict[str, Any] = {}
        with _named("E-update"):
            acc_e = _half_update("E", state, coeffs, new_psi)
            for c in mode.e_components:
                acc = acc_e[c]
                if static.use_drude:
                    j_new = coeffs[f"kj_{c}"] * state["J"][c] \
                        + coeffs[f"bj_{c}"] * state["E"][c]
                    new_J[c] = j_new
                    acc = acc - j_new
                if ps.enabled and ps.component == c:
                    with _named("source"):
                        mask = point_mask(coeffs["gx"], coeffs["gy"],
                                          coeffs["gz"], ps.position,
                                          mode.active_axes)
                        wf = waveform(ps.waveform, t, 0.5, static.omega,
                                      static.dt, static.real_dtype)
                        # amplitude from coeffs (build_coeffs ps_amp):
                        # traced so the batch executor can vary it per
                        # lane; value-identical to the old static float
                        acc = acc + coeffs["ps_amp"] * wf \
                            * mask.astype(acc.dtype)
                if compensated:
                    # Kahan: E' = E + u with u = (ca-1)E + cb*acc in
                    # double-single coefficients, feeding back the stored
                    # residual of the previous step's add. (XLA does not
                    # reassociate floats, so (t-old)-y is the true
                    # rounding error, not zero.)
                    old = state["E"][c]
                    u = (coeffs[f"ca_{c}"] - 1.0) * old \
                        + coeffs[f"cb_{c}"] * acc \
                        + (coeffs[f"ca_{c}_lo"] * old
                           + coeffs[f"cb_{c}_lo"] * acc)
                    y = u - state["rE"][c].astype(u.dtype)
                    e = old + y
                    r = (e - old) - y
                else:
                    e = coeffs[f"ca_{c}"] * state["E"][c] \
                        + coeffs[f"cb_{c}"] * acc
                # PEC walls: zero tangential E on transverse-axis walls.
                for a in mode.active_axes:
                    if a != component_axis(c):
                        w = _bcast1d(coeffs[f"wall_{AXES[a]}"], a)
                        e = e * w
                        if compensated:
                            r = r * w
                new_E[c] = e.astype(static.field_dtype)
                if compensated:
                    new_rE[c] = r.astype(jnp.bfloat16)
        new_state["E"] = new_E
        if compensated:
            new_state["rE"] = new_rE
        if static.use_drude:
            new_state["J"] = new_J
        state = dict(state, E=new_E)

        # 3. incident line H advance (Hinc -> t^{n+3/2})
        if setup is not None:
            with _named("tfsf"):
                new_state["inc"] = tfsf.advance_hinc(new_state["inc"],
                                                     coeffs, setup)
            state = dict(state, inc=new_state["inc"])

        # 4. H family (dual of step 2: mu0 mu dH/dt = -curl E - K)
        new_H = {}
        new_rH: Dict[str, Any] = {}
        new_K: Dict[str, Any] = {}
        with _named("H-update"):
            acc_h = _half_update("H", state, coeffs, new_psi)
            for c in mode.h_components:
                acc = acc_h[c]
                if static.use_drude_m:
                    k_new = coeffs[f"km_{c}"] * state["K"][c] \
                        + coeffs[f"bm_{c}"] * state["H"][c]
                    new_K[c] = k_new
                    acc = acc + k_new
                if compensated:
                    old = state["H"][c]
                    u = (coeffs[f"da_{c}"] - 1.0) * old \
                        - coeffs[f"db_{c}"] * acc \
                        + (coeffs[f"da_{c}_lo"] * old
                           - coeffs[f"db_{c}_lo"] * acc)
                    y = u - state["rH"][c].astype(u.dtype)
                    h = old + y
                    new_rH[c] = ((h - old) - y).astype(jnp.bfloat16)
                else:
                    h = coeffs[f"da_{c}"] * state["H"][c] \
                        - coeffs[f"db_{c}"] * acc
                new_H[c] = h.astype(static.field_dtype)
        new_state["H"] = new_H
        if compensated:
            new_state["rH"] = new_rH
        if static.use_drude_m:
            new_state["K"] = new_K

        if new_psi["psi_E"]:
            new_state["psi_E"] = new_psi["psi_E"]
            new_state["psi_H"] = new_psi["psi_H"]
        new_state["t"] = t + 1
        return new_state

    step.kind = "jnp"
    return _stamp_tb_fallback(step, static, mesh_axes,
                              allow_multistep)


def _make_ds_step(static: StaticSetup, mesh_axes=None, mesh_shape=None):
    """Double-single (float32x2) leapfrog step: hi+lo f32 field pairs.

    The accuracy rung between f32 and XLA-emulated f64 (BASELINE.md
    "Accuracy"): plain f32's measured floor is the curl arithmetic
    itself — its rounding acts as an eps32-scale systematic
    perturbation of the discrete operator that no accumulation
    compensation can remove (compensated f32 froze at ~6e-6 vs f64 at
    1000 steps, round 4). Carrying E/H and the TFSF incident line as
    double-single pairs, with error-free-transform arithmetic
    (ops/ds.py) in every difference, product, and accumulation,
    restores ~2^-47 effective significand end-to-end while staying on
    the f32 vector units.

    Deliberately plain-f32 sub-parts (argued/measured non-factors at
    the 1e-6 bar): CPML psi recursions and the slab-delta algebra
    (identically zero outside the absorbing slabs, geometrically
    decaying inside them), Drude J/K ADE currents, the source
    waveform's sin (a constant ~eps32 amplitude error on a hard
    source — the 64-bit fixed-point phase already removed the growing
    part), and interpolation weights (fixed geometry). Reference
    parity: the C++ double accuracy class of the reference's
    FieldValue (SURVEY.md §2 FieldValue row).
    """
    mode, cfg = static.mode, static.cfg
    from fdtd3d_tpu.ops import ds as _ds
    diff_b, diff_f = make_diff_ops(mesh_axes, mesh_shape)
    shift_b, shift_f = diff_b.shift, diff_f.shift
    iv_h, iv_l = _ds.from_f64(1.0 / np.float64(static.dx))
    setup = static.tfsf_setup
    ps = cfg.point_source
    slabs = slab_axes(static)

    def _slab_delta_ds(a, tag, s, dfa, psi, coeffs, m):
        """_slab_delta in double-single: -> (psi pair, lo/hi delta pairs).

        The f32 slab algebra was the measured ~1e-6 residual of the
        float32x2 mode: its eps32-scale per-step noise enters at the
        absorbing interface (where fields are O(1)) and reflects back
        into the interior coherently. Profiles are hi+lo pairs
        (build_coeffs), psi carries lo words (lopsi_* state).
        """
        ax = AXES[a]
        dh_, dl_ = dfa
        ph_, pl_ = psi
        nloc = dh_.shape[a]
        cut = lambda f, lo, hi: jax.lax.slice_in_dim(f, lo, hi, axis=a)  # noqa: E731

        def prof(name):
            return (_bcast1d(coeffs[f"pml_slab_{name}{tag}_{ax}"], a),
                    _bcast1d(coeffs[f"pml_slab_{name}{tag}lo_{ax}"], a))

        bh, bl = prof("b")
        ch, cl = prof("c")
        ikh, ikl = prof("ik")

        def side(d0, d1, p0, p1):
            d_pair = (cut(dh_, d0, d1), cut(dl_, d0, d1))
            p_pair = (cut(ph_, p0, p1), cut(pl_, p0, p1))
            p_new = _ds.add_ff(
                *_ds.mul_ff(cut(bh, p0, p1), cut(bl, p0, p1), *p_pair),
                *_ds.mul_ff(cut(ch, p0, p1), cut(cl, p0, p1), *d_pair))
            ikm1 = _ds.add_f(cut(ikh, p0, p1), cut(ikl, p0, p1),
                             np.float32(-1.0))
            delta = _ds.add_ff(*_ds.mul_ff(*ikm1, *d_pair), *p_new)
            if s < 0:
                delta = (-delta[0], -delta[1])
            return p_new, delta

        pn_lo, delta_lo = side(0, m, 0, m)
        pn_hi, delta_hi = side(nloc - m, nloc, m, 2 * m)
        psi_new = (jnp.concatenate([pn_lo[0], pn_hi[0]], axis=a),
                   jnp.concatenate([pn_lo[1], pn_hi[1]], axis=a))
        return psi_new, delta_lo, delta_hi

    def ds_diff(fh, fl, a, backward):
        """Exact-error double-single difference * (1/dx)."""
        if backward:
            sh, sl_ = shift_b(fh, a), shift_b(fl, a)
            if sh is None:
                return None
            dh, de = _ds.two_diff(fh, sh)
            dl = fl - sl_
        else:
            sh, sl_ = shift_f(fh, a), shift_f(fl, a)
            if sh is None:
                return None
            dh, de = _ds.two_diff(sh, fh)
            dl = sl_ - fl
        dh, dl = _ds.two_sum(dh, de + dl)
        return _ds.mul_ff(dh, dl, iv_h, iv_l)

    def _half_update(field, state, coeffs, new_psi):
        upd = mode.e_components if field == "E" else mode.h_components
        srch = state["H"] if field == "E" else state["E"]
        srcl = state["loH"] if field == "E" else state["loE"]
        backward = field == "E"
        tag = "e" if field == "E" else "h"
        psi_key = "psi_E" if field == "E" else "psi_H"
        lopsi_key = "lopsi_E" if field == "E" else "lopsi_H"
        out = {}
        for c in upd:
            acc = None
            for (a, d_axis, s) in CURL_TERMS[component_axis(c)]:
                d = ("H" if field == "E" else "E") + AXES[d_axis]
                if d not in srch:
                    continue
                dfa = ds_diff(srch[d], srcl[d], a, backward)
                if dfa is None:
                    continue
                dh, dl = dfa
                fix = None
                if a in slabs:
                    key = f"{c}_{AXES[a]}"
                    psi_new, delta_lo, delta_hi = _slab_delta_ds(
                        a, tag, s, (dh, dl),
                        (state[psi_key][key], state[lopsi_key][key]),
                        coeffs, slabs[a])
                    new_psi[psi_key][key] = psi_new[0]
                    new_psi[lopsi_key][key] = psi_new[1]
                    nloc = dh.shape[a]
                    fix = (_pad_slab(delta_lo[0], delta_hi[0], a, nloc,
                                     slabs[a]),
                           _pad_slab(delta_lo[1], delta_hi[1], a, nloc,
                                     slabs[a]))
                    th, tl = dh, dl
                elif a in static.pml_axes:
                    ax = AXES[a]
                    key = f"{c}_{ax}"

                    def pr(name, ax=ax):
                        return (_bcast1d(coeffs[f"pml_{name}{tag}_{ax}"],
                                         a),
                                _bcast1d(
                                    coeffs[f"pml_{name}{tag}lo_{ax}"],
                                    a))

                    psi_new = _ds.add_ff(
                        *_ds.mul_ff(*pr("b"), state[psi_key][key],
                                    state[lopsi_key][key]),
                        *_ds.mul_ff(*pr("c"), dh, dl))
                    new_psi[psi_key][key] = psi_new[0]
                    new_psi[lopsi_key][key] = psi_new[1]
                    th, tl = _ds.mul_ff(*pr("ik"), dh, dl)
                    th, tl = _ds.add_ff(th, tl, *psi_new)
                else:
                    th, tl = dh, dl
                if s < 0:
                    th, tl = -th, -tl
                acc = (th, tl) if acc is None \
                    else _ds.add_ff(*acc, th, tl)
                if fix is not None:  # carries s already (_slab_delta_ds)
                    acc = _ds.add_ff(*acc, *fix)
            if acc is None:
                z = jnp.zeros(state[field][c].shape, np.float32)
                acc = (z, z)
            if setup is not None:
                corr = tfsf.corrections_for_ds(
                    field, c, setup, coeffs, state["inc"],
                    mode.active_axes, static.dx)
                if corr is not None:
                    acc = _ds.add_ff(*acc, *corr)
            out[c] = acc
        return out

    def step(state, coeffs):
        t = state["t"]
        new_state = dict(state)
        new_psi = {"psi_E": dict(state.get("psi_E", {})),
                   "psi_H": dict(state.get("psi_H", {})),
                   "lopsi_E": dict(state.get("lopsi_E", {})),
                   "lopsi_H": dict(state.get("lopsi_H", {}))}
        if setup is not None:
            with _named("tfsf"):
                new_state["inc"] = tfsf.advance_einc(
                    state["inc"], coeffs, t, static.dt, static.omega,
                    setup)
            state = dict(state, inc=new_state["inc"])

        new_E, new_lo, new_J = {}, {}, {}
        with _named("E-update"):
            acc_e = _half_update("E", state, coeffs, new_psi)
            for c in mode.e_components:
                ah, al = acc_e[c]
                if static.use_drude:
                    j_new = coeffs[f"kj_{c}"] * state["J"][c] \
                        + coeffs[f"bj_{c}"] * state["E"][c]
                    new_J[c] = j_new
                    ah, al = _ds.add_f(ah, al, -j_new)
                if ps.enabled and ps.component == c:
                    with _named("source"):
                        from fdtd3d_tpu.ops.sources import waveform_ds
                        mask = point_mask(coeffs["gx"], coeffs["gy"],
                                          coeffs["gz"], ps.position,
                                          mode.active_axes)
                        wh, wl = waveform_ds(ps.waveform, t, 0.5,
                                             static.omega, static.dt)
                        amph, ampl = _ds.from_f64(
                            np.float64(ps.amplitude))
                        wh, wl = _ds.mul_ff(wh, wl, jnp.float32(amph),
                                            jnp.float32(ampl))
                        m = mask.astype(ah.dtype)
                        ah, al = _ds.add_ff(ah, al, wh * m, wl * m)
                t1 = _ds.mul_ff(state["E"][c], state["loE"][c],
                                coeffs[f"ca_{c}"], coeffs[f"ca_{c}_lo"])
                t2 = _ds.mul_ff(ah, al,
                                coeffs[f"cb_{c}"], coeffs[f"cb_{c}_lo"])
                eh, el = _ds.add_ff(*t1, *t2)
                for a in mode.active_axes:  # PEC walls: exact 0/1 mask
                    if a != component_axis(c):
                        w = _bcast1d(coeffs[f"wall_{AXES[a]}"], a)
                        eh = eh * w
                        el = el * w
                new_E[c] = eh
                new_lo[c] = el
        new_state["E"] = new_E
        new_state["loE"] = new_lo
        if static.use_drude:
            new_state["J"] = new_J
        state = dict(state, E=new_E, loE=new_lo)

        if setup is not None:
            with _named("tfsf"):
                new_state["inc"] = tfsf.advance_hinc(new_state["inc"],
                                                     coeffs, setup)
            state = dict(state, inc=new_state["inc"])

        new_H, new_loH, new_K = {}, {}, {}
        with _named("H-update"):
            acc_h = _half_update("H", state, coeffs, new_psi)
            for c in mode.h_components:
                ah, al = acc_h[c]
                if static.use_drude_m:
                    k_new = coeffs[f"km_{c}"] * state["K"][c] \
                        + coeffs[f"bm_{c}"] * state["H"][c]
                    new_K[c] = k_new
                    ah, al = _ds.add_f(ah, al, k_new)
                t1 = _ds.mul_ff(state["H"][c], state["loH"][c],
                                coeffs[f"da_{c}"], coeffs[f"da_{c}_lo"])
                t2 = _ds.mul_ff(ah, al,
                                coeffs[f"db_{c}"], coeffs[f"db_{c}_lo"])
                hh, hl = _ds.sub_ff(*t1, *t2)
                new_H[c] = hh
                new_loH[c] = hl
        new_state["H"] = new_H
        new_state["loH"] = new_loH
        if static.use_drude_m:
            new_state["K"] = new_K
        if new_psi["psi_E"]:
            new_state["psi_E"] = new_psi["psi_E"]
            new_state["psi_H"] = new_psi["psi_H"]
            new_state["lopsi_E"] = new_psi["lopsi_E"]
            new_state["lopsi_H"] = new_psi["lopsi_H"]
        new_state["t"] = t + 1
        return new_state

    return step


def _make_paired_complex_step(static: StaticSetup, mesh_axes=None,
                              mesh_shape=None):
    """Complex fields as two real legs (COMPLEX_FIELD_VALUES on TPU).

    The update is linear with REAL coefficients and REAL sources
    (tests/test_complex.py's superposition identity), so a complex run
    decomposes exactly: the re leg carries the sources, the im leg runs
    the identical step with source amplitudes zeroed (its TFSF incident
    line stays identically zero, so the machinery is structurally
    present but inert). Each leg dispatches through the normal kernel
    chain — on TPU that is the packed Pallas kernel, making complex
    mode run at 2x the real-mode cost instead of not at all (VERDICT
    r3 item 4: previously a fail-fast probe error).

    The carry is {"re": leg, "im": leg, "t": ...} with each leg in its
    step's own representation (packed when the leg step is packed).
    pack/unpack convert to/from the complex dict state THROUGH HOST
    NUMPY: re/im extraction and re + 1j*im are themselves complex ops
    the backend lacks.
    """
    if mesh_axes and any(v is not None for v in mesh_axes.values()):
        raise ValueError(
            "complex fields on a backend without native complex "
            "arithmetic (the paired-real path) cannot run on a sharded "
            "topology: the complex<->paired conversion routes through "
            "host numpy (complex device arrays are unsupported on this "
            "backend), which cannot execute inside shard_map. Run "
            "complex sharded on a backend with native complex (CPU), "
            "or run real-dtype sharded; see solver._make_paired_"
            "complex_step.")
    cfg = static.cfg
    cfg_re = dataclasses.replace(cfg, complex_fields=False)
    cfg_im = dataclasses.replace(
        cfg_re,
        point_source=dataclasses.replace(cfg.point_source, amplitude=0.0),
        tfsf=dataclasses.replace(cfg.tfsf, amplitude=0.0))
    st_re = dataclasses.replace(build_static(cfg_re),
                                topology=static.topology)
    st_im = dataclasses.replace(build_static(cfg_im),
                                topology=static.topology)
    # allow_multistep=False: the paired wrapper calls each leg once per
    # step, so a two-steps-per-call leg would silently double-advance
    step_re = make_step(st_re, mesh_axes, mesh_shape,
                        allow_multistep=False)
    step_im = make_step(st_im, mesh_axes, mesh_shape,
                        allow_multistep=False)
    leg_pack = getattr(step_re, "pack", None)
    leg_unpack = getattr(step_re, "unpack", None)

    def step(s, coeffs):
        re = step_re(s["re"], coeffs)
        # ps_amp is a TRACED coefficient (build_coeffs): the im leg's
        # zeroed-amplitude config no longer zeroes the drive on its
        # own, so zero the traced value for that leg here — the re leg
        # alone carries the sources (the decomposition's contract)
        im_coeffs = coeffs
        if "ps_amp" in coeffs:
            im_coeffs = dict(coeffs)
            im_coeffs["ps_amp"] = jnp.zeros_like(coeffs["ps_amp"])
        im = step_im(s["im"], im_coeffs)
        return {"re": re, "im": im, "t": re["t"]}

    def _leg(state, part):
        # every leaf becomes a FRESH device buffer (via host numpy):
        # the carry is donated, and a leaf shared between the legs (or
        # with the top-level t) would be donated twice
        def cv(x):
            x = np.asarray(x)
            return jnp.asarray(part(x) if np.iscomplexobj(x)
                               else np.array(x))
        out = jax.tree.map(cv, state)
        return leg_pack(out) if leg_pack is not None else out

    def pack(state):
        return {"re": _leg(state, np.real), "im": _leg(state, np.imag),
                "t": jnp.asarray(np.array(state["t"]))}

    def unpack(p):
        re = leg_unpack(p["re"]) if leg_unpack is not None else p["re"]
        im = leg_unpack(p["im"]) if leg_unpack is not None else p["im"]
        cdtype = static.field_dtype

        def join(a, b):
            a, b = np.asarray(a), np.asarray(b)
            if not np.issubdtype(a.dtype, np.floating):
                return a  # t and other integer leaves
            return (a + 1j * b).astype(cdtype)
        return jax.tree.map(join, re, im)

    def health_view(s):
        # in-graph dict-form views for the flight recorder
        # (telemetry.make_health_fn combines the two real legs): the
        # LEG pack/unpack are pure jax even though the top-level
        # complex<->paired conversion routes through host numpy
        if leg_unpack is not None:
            return [leg_unpack(s["re"]), leg_unpack(s["im"])]
        return [s["re"], s["im"]]

    step.pack = pack
    step.unpack = unpack
    step.packed = True
    step.health_view = health_view
    step.kind = "complex2x_" + getattr(step_re, "kind", "jnp")
    step.diag = getattr(step_re, "diag", None)
    return step


def make_chunk_runner(static: StaticSetup, mesh_axes=None, mesh_shape=None,
                      health: bool = False, per_chip: bool = False,
                      batch: int = 0):
    """scan-over-steps runner: run_chunk(state, coeffs, n) with static n.

    ``batch=B`` builds the lane-capable packed runner (make_step's
    batch axis): the runner itself stays single-lane — the caller
    (batch.BatchSimulation / costs.trace_chunk) wraps it in jax.vmap
    over stacked lane-major state+coeffs, which batches the
    pallas_call, the in-step lax.ppermute halo exchanges (ONE
    collective per axis per step, lanes ride the same message) and the
    in-graph health reduction (per-lane counter vectors) in one
    compiled executable. Gate with batch_fallback_reason first.

    When the packed kernel is engaged (``run_chunk.packed``), the scan
    carry is the PACKED state pytree (stacked E/H/psi arrays); callers
    convert once per run with ``run_chunk.pack`` / ``run_chunk.unpack``
    (Simulation keeps the packed carry across chunks so the conversion
    cost is paid once, not per chunk).

    Steps exposing ``prepare`` (the packed kernels) get it called ONCE
    per chunk, outside the scan: the per-step profile stacks / wall
    reshapes are loop-invariant, and hoisting them off the scan body
    shaves the fixed per-step dispatch floor instead of trusting XLA's
    loop-invariant code motion with them (round 6).

    ``health=True`` (the flight recorder, fdtd3d_tpu/telemetry.py):
    run_chunk returns ``(state, health_dict)`` with the health counters
    computed IN-GRAPH from the chunk's final state — one fused
    reduction appended to the scan, no separate dispatch and no host
    pass. Packed carries are unpacked in-graph (pack/unpack are pure
    jax); steps exposing ``health_view`` (the paired-complex path,
    whose top-level unpack routes through host numpy) instead supply
    their own in-graph list of dict-form views. ``run_chunk.health``
    reports whether the counters are actually wired.

    ``per_chip=True`` additionally all_gathers the un-psummed local
    counters into the health dict's ``per_chip`` vectors (telemetry
    schema v4's per-chip lane; ``run_chunk.per_chip`` reports it).
    """
    step = make_step(static, mesh_axes, mesh_shape, batch=batch)
    prep = getattr(step, "prepare", None)
    # Temporal-blocked steps advance steps_per_call (= the pipeline
    # depth k in {2, 3, 4}) steps per call: the scan runs n // k
    # blocked passes and the n mod k remainder runs as single steps on
    # tail_step — a pallas_packed step built at the SAME tile, so both
    # share one packed-carry layout and one prepared coeffs dict
    # (ops/pallas_packed_tb.py) INSIDE one compiled chunk.
    spc = int(getattr(step, "steps_per_call", 1))
    tail_step = getattr(step, "tail_step", None)
    if spc > 1 and tail_step is None:
        raise ValueError(f"step advances {spc} steps/call but exposes "
                         f"no tail_step for remainder handling")

    health_fn = None
    if health:
        from fdtd3d_tpu import telemetry
        view = getattr(step, "health_view", None)
        if view is None:
            if getattr(step, "packed", False):
                view = lambda s: [step.unpack(s)]  # noqa: E731
            else:
                view = lambda s: [s]  # noqa: E731
        hfn = telemetry.make_health_fn(static, mesh_axes,
                                       per_chip=per_chip)
        health_fn = lambda s: hfn(view(s))  # noqa: E731

    def run_chunk(state, coeffs, n: int):
        if prep is not None:
            # "prepare" scope: per-chunk loop-invariant packing, so the
            # cost ledger can split it from the per-step scan body
            with _named("prepare"):
                cc = prep(coeffs)
        else:
            cc = coeffs

        def body(s, _):
            return step(s, cc), None
        if spc > 1:
            nb, rem = divmod(n, spc)
            out, _ = jax.lax.scan(body, state, None, length=nb)
            for _ in range(rem):
                # n mod k trailing single steps (up to k-1 of them) on
                # the identical packed-carry layout
                out = tail_step(out, cc)
        else:
            out, _ = jax.lax.scan(body, state, None, length=n)
        if health_fn is not None:
            # the scope covers the in-graph unpack of packed carries
            # too (view(s) runs before make_health_fn's own scope)
            with _named("health"):
                return out, health_fn(out)
        return out

    run_chunk.health = health_fn is not None
    run_chunk.per_chip = health_fn is not None and per_chip
    run_chunk.kind = getattr(step, "kind", "jnp")
    run_chunk.diag = getattr(step, "diag", None)
    run_chunk.steps_per_call = spc
    if getattr(step, "packed", False):
        run_chunk.packed = True
        run_chunk.pack = step.pack
        run_chunk.unpack = step.unpack
    return run_chunk
