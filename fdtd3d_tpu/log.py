"""Leveled logging for the whole package (SURVEY.md §2 assert/logging).

One process-global level, set once from ``OutputConfig.log_level``
(``--log-level``): 0 = silent (errors still raise), 1 = normal progress
lines, 2+ = verbose. Replaces the scattered ``if log_level >= 1:
print(...)`` gates (VERDICT r3 item 8) so library users and the CLI
share one switch; multi-process runs log on rank 0 only unless
``all_ranks`` is passed.
"""

from __future__ import annotations

import sys

_level = 1


def set_level(level: int) -> None:
    global _level
    _level = int(level)


def get_level() -> int:
    return _level


def log(msg: str, level: int = 1, all_ranks: bool = False) -> None:
    """Print ``msg`` when the configured level is >= ``level``."""
    if _level < level:
        return
    if not all_ranks:
        try:
            import jax
            if jax.process_index() != 0:
                return
        except Exception:
            pass
    print(msg, flush=True)


def warn(msg: str) -> None:
    """Warnings always print (to stderr), at any level."""
    print(f"WARNING: {msg}", file=sys.stderr, flush=True)


def report(msg: str = "") -> None:
    """A tool's PRIMARY stdout product (summaries, JSON lines).

    Unlike :func:`log` this never consults the level or the rank gate:
    a report is the output the caller asked for, not progress chatter.
    Exists so the no-bare-print lint (tests/test_lint_no_print.py,
    which covers tools/ too) can keep ``print`` call sites structural:
    log() for progress, warn() for stderr, report() for product.
    """
    print(msg, flush=True)
