"""Runtime configuration.

TPU-first replacement for the reference's dual compile-time (CMake matrix) +
runtime (``Source/Settings`` macro flag table, ~100 flags, global
``solverSettings`` singleton — SURVEY.md §2, §3.5) configuration: ONE runtime
dataclass. Compile-time axes of the reference (value type, complex fields,
parallel mode, dim modes) become plain fields (``dtype``, ``complex_fields``,
``parallel.topology``, ``scheme``).

The reference-compatible command-line surface (including ``--cmd-from-file
x.txt`` replay and ``--save-cmd-to-file``) lives in ``fdtd3d_tpu.cli``,
which parses flags into this dataclass.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

from fdtd3d_tpu import physics
from fdtd3d_tpu.layout import get_mode


# ---------------------------------------------------------------------------
# environment-knob registry (the single source of truth for FDTD3D_* vars)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EnvKnob:
    """One declared ``FDTD3D_*`` environment knob.

    ``kind``: "flag" (presence/any non-empty value = on), "int"
    (numeric value), "str" (free-form value), "path" (filesystem path).
    ``default`` is the effective behavior when the variable is unset.
    The ``env-registry`` static-analysis rule (fdtd3d_tpu/analysis/)
    enforces that every literal ``os.environ``/``os.getenv`` read of a
    ``FDTD3D_*`` name in the repo appears here, and that every entry
    here is actually read somewhere — so this table can neither rot nor
    under-document (docs/STATIC_ANALYSIS.md renders it).
    """

    name: str
    kind: str
    default: Any
    doc: str


def _knob(name: str, kind: str, default: Any, doc: str) -> EnvKnob:
    if kind not in ("flag", "int", "str", "path"):
        raise ValueError(f"bad env-knob kind {kind!r} for {name}")
    return EnvKnob(name=name, kind=kind, default=default, doc=doc)


ENV_KNOBS: Dict[str, EnvKnob] = {k.name: k for k in (
    _knob("FDTD3D_NO_PACKED", "flag", False,
          "Escape hatch: skip the packed pipelined Pallas kernels "
          "(ops/pallas_packed*.py); dispatch falls to fused/two-pass/"
          "jnp. Supervisor degrade rung; measurement A/B lever."),
    _knob("FDTD3D_NO_TEMPORAL", "flag", False,
          "Escape hatch: skip the temporal-blocked kernel "
          "(ops/pallas_packed_tb.py), forcing the round-6 single-step "
          "packed kernel bit-for-bit. Supervisor tb->packed rung."),
    _knob("FDTD3D_NO_FUSED", "flag", False,
          "Escape hatch: skip the recompute-fused single-pass kernel "
          "(ops/pallas_fused.py), forcing the two-pass family kernels "
          "where both are eligible (measurement A/B lever)."),
    _knob("FDTD3D_FORCE_FUSED", "flag", False,
          "Bypass the tile>=4 fused-kernel dispatch heuristic AND skip "
          "the packed kernel, forcing ops/pallas_fused.py (the "
          "crossover was measured on one throttled chip; other TPU "
          "generations may sit elsewhere)."),
    _knob("FDTD3D_FORCE_PAIRED_COMPLEX", "flag", False,
          "Test hook: route complex_fields through the paired-real "
          "two-leg step even on backends with native complex (CPU), "
          "so the TPU complex path is exercisable in tier-1."),
    _knob("FDTD3D_VMEM_BUDGET_MB", "int", None,
          "Override the per-kernel VMEM budget (MiB) the Pallas tile "
          "pickers model against (ops/pallas3d.py, ops/pallas_packed"
          ".py). Default: the kernel's physical-VMEM model; the "
          "runtime ladder (sim._vmem_fallback) shrinks on compile "
          "failure."),
    _knob("FDTD3D_VMEM_TEMPS_TABLE", "str", None,
          "Override entries of the central Mosaic-temporaries "
          "calibration table (config.VMEM_TEMPS_DEFAULTS, f32 per cell "
          "per tile plane) the Pallas tile pickers model against: "
          "comma-separated key=int pairs, e.g. 'tb3=44,tb4=58'. Keys: "
          "packed, packed_ds, tb2/tb3/tb4 (temporal-blocked per "
          "pipeline depth). The first chip window recalibrates ONE "
          "table instead of scattered per-module constants."),
    _knob("FDTD3D_TB_DEPTH", "int", None,
          "Pin the temporal-blocked kernel's pipeline depth k (2, 3 or "
          "4 Yee steps per HBM pass) instead of the VMEM-calibrated "
          "auto-depth pick (ops/pallas_packed_tb.py); bench's k-sweep "
          "and the per-depth ledger fixtures use it. A pin the VMEM "
          "model or sharded wedge extents cannot honor is a NAMED "
          "config error, never a silent single-step fallback. Unset: "
          "deepest depth whose budgeted tile stays viable."),
    _knob("FDTD3D_COMM_STRATEGY", "str", None,
          "Override the planner's communication-strategy choice "
          "(plan.comm_strategy): comma-separated tokens from "
          "fused/per-plane (message split) and async/sync "
          "(scheduling), e.g. 'per-plane,sync'. Default: the "
          "deterministic cost-model choice, recorded in the ledger "
          "comm lane and telemetry run_start."),
    _knob("FDTD3D_FAULT_PLAN", "str", None,
          "Deterministic fault-injection plan spec (fdtd3d_tpu/faults"
          ".py), e.g. 'nan@t=8,field=Ez; preempt@t=16'. Adopted once "
          "per process by Simulation.__init__; docs/ROBUSTNESS.md "
          "documents the grammar."),
    _knob("FDTD3D_TEST_TPU", "flag", False,
          "Run the test suite against the real TPU backend instead of "
          "the 8-device virtual CPU mesh (tests/conftest.py skips the "
          "CPU pin; opens the chip-lane-only skips)."),
    _knob("FDTD3D_BENCH_TELEMETRY", "path", None,
          "bench.py: append flight-recorder JSONL for every bench "
          "stage to this path (telemetry schema; summarize with "
          "tools/telemetry_report.py)."),
    _knob("FDTD3D_BENCH_PER_CHIP", "flag", False,
          "bench.py: enable the per-chip telemetry lane (schema v4 "
          "per_chip/imbalance records) for multi-chip bench windows; "
          "needs FDTD3D_BENCH_TELEMETRY."),
    _knob("FDTD3D_BENCH_PROFILE", "path", None,
          "bench.py: capture a device trace per stage into "
          "DIR/<path>_<dtype>_<n>/ subdirectories (attribute with "
          "tools/trace_attribution.py)."),
    _knob("FDTD3D_AOT_CACHE_DIR", "path", None,
          "On-disk layer of the AOT executable cache (fdtd3d_tpu/"
          "exec_cache.py): chunk executables serialized via "
          "jax.experimental.serialize_executable land here "
          "(atomic-published, provenance-checked on load) so a repeat "
          "scenario skips compile ACROSS process boundaries. Unset: "
          "in-process layer only. Point only at trusted directories "
          "(the payload is a pickle, like jax's own persistent "
          "compilation cache)."),
    _knob("FDTD3D_AOT_CACHE", "str", "on",
          "Off-switch for the AOT executable cache: 0/off/no disables "
          "BOTH layers (every chunk compile then traces+compiles "
          "exactly as the pre-cache build; stats still count). Any "
          "other value (or unset) leaves the cache on."),
    _knob("FDTD3D_BATCH_MAX", "int", 16,
          "Lane-count bound for vmap-batched execution "
          "(fdtd3d_tpu/batch.py run_batch / CLI --batch): vmap is "
          "linear in lanes for HBM and compile time, so an unbounded "
          "batch is an OOM with extra steps."),
    _knob("FDTD3D_JOB_QUEUE_DIR", "path", None,
          "Default queue directory for the durable multi-tenant job "
          "queue (fdtd3d_tpu/jobqueue.py; operator CLI tools/"
          "fdtd_queue.py submit/serve/status/cancel). The append-"
          "only journal.jsonl plus per-job/group artifact dirs live "
          "under it. Unset: --queue-dir must be passed explicitly."),
    _knob("FDTD3D_QUEUE_TENANT", "str", "default",
          "Default tenant name for job-queue submissions "
          "(tools/fdtd_queue.py submit without --tenant): per-tenant "
          "quotas, the jobs_total{tenant} metrics and the fleet "
          "rollups key on it."),
    _knob("FDTD3D_RUN_REGISTRY", "path", None,
          "Append-only fleet run index (fdtd3d_tpu/registry.py): "
          "every Simulation/BatchSimulation run appends one "
          "run_begin row at construction and one run_final row "
          "(status completed/failed/recovered, recovery rollup) at "
          "close to this runs.jsonl, each a single atomic O_APPEND "
          "write; the run_id is stamped into telemetry run_start and "
          "checkpoint metadata so streams and snapshots are "
          "joinable. Monitor with tools/fleet_report.py. Unset: no "
          "registry writes."),
    _knob("FDTD3D_HEARTBEAT_S", "str", None,
          "Live-health heartbeat cadence, seconds (fdtd3d_tpu/"
          "telemetry.Heartbeater, schema v10): runs beat at chunk "
          "boundaries, the job-queue scheduler at dispatch-loop "
          "iterations and the supervisor at recovery boundaries — "
          "one atomic O_APPEND row per beat onto the stream each "
          "emitter already owns. 0 = beat at EVERY boundary (the "
          "deterministic tier-1 mode). Unset: no heartbeats, "
          "streams stay byte-identical to v9 emission."),
    _knob("FDTD3D_WATCH_INTERVAL_S", "str", None,
          "Fleet-watcher poll cadence, seconds (fdtd3d_tpu/watch.py; "
          "tools/fleet_watch.py --interval overrides). Also the "
          "presumed heartbeat spacing for liveness-deadline math "
          "when a beat declares no cadence (or the 0 every-boundary "
          "mode). Unset: 10."),
    _knob("FDTD3D_LEASE_TTL_S", "str", None,
          "Scheduler lease time-to-live, seconds (fdtd3d_tpu/"
          "jobqueue.py, schema v11): a scheduler's fenced dispatch "
          "lease on its queue journal expires this long after its "
          "last acquire/renew row, measured on the scheduler's "
          "injectable clock — an expired lease is what a peer (or "
          "fleet_watch --evict) may take over with a higher fencing "
          "token. Renewed every scheduling cycle. Unset: 30."),
)}


# ---------------------------------------------------------------------------
# Mosaic-temporaries calibration table (VMEM tile-picker model)
# ---------------------------------------------------------------------------

# f32 temporaries per (cell x tile plane) that Mosaic's kernel body
# holds beyond the modeled operand blocks + scratch, per kernel kind —
# THE central calibration surface the Pallas tile pickers consume
# (ops/pallas_packed.py `_pick_tile_packed`). One table, one chip-window
# recalibration (`FDTD3D_VMEM_TEMPS_TABLE`), instead of the scattered
# per-module constants PR 4 flagged.
#
#   packed    — MEASURED on the v5e tunnel (128^3 T=32 fail / 512^3
#               T=2 pass boundary; ops/pallas_packed.py comment).
#   packed_ds — double-single kernel (ops/pallas_packed_ds.py's own
#               pass/fail probe).
#   tb2/3/4   — temporal-blocked kernel per pipeline depth k:
#               UNCALIBRATED scale-ups of the measured 25 (the 2k-phase
#               body holds ~k generations of live values); re-run the
#               128^3/512^3 probe per depth on the first chip window.
VMEM_TEMPS_DEFAULTS: Dict[str, int] = {
    "packed": 25,
    "packed_ds": 80,
    "tb2": 40,
    "tb3": 52,
    "tb4": 64,
    # batch_lane — PER-EXTRA-LANE surcharge the tile picker charges on
    # a lane-capable batched build (ops/pallas_packed._pick_tile_packed
    # with batch=B adds (B-1) x this row). The vmap batching rule runs
    # ONE lane's blocks per outer-grid iteration, so the true
    # per-iteration footprint is unchanged; this row is conservative
    # headroom for Mosaic's cross-iteration prefetch of the lane-major
    # grid dimension. UNCALIBRATED (no chip window yet) — re-run the
    # 128^3/512^3 probe with a 3-lane batch on the first window.
    "batch_lane": 6,
}


def vmem_temps(kind: str, depth: Optional[int] = None) -> int:
    """Calibrated Mosaic-temporaries constant for one kernel kind
    (``depth`` selects the temporal-blocked per-k row, e.g.
    ``vmem_temps("tb", 3)`` -> the ``tb3`` entry). Env override:
    ``FDTD3D_VMEM_TEMPS_TABLE=key=int,key=int`` — unknown keys or
    non-integer values are a config error, never a silent default."""
    import os
    key = f"{kind}{depth}" if depth is not None else kind
    table = dict(VMEM_TEMPS_DEFAULTS)
    env = os.environ.get("FDTD3D_VMEM_TEMPS_TABLE")
    if env:
        for tok in env.split(","):
            tok = tok.strip()
            if not tok:
                continue
            name, sep, val = tok.partition("=")
            if not sep or name.strip() not in VMEM_TEMPS_DEFAULTS:
                raise ValueError(
                    f"FDTD3D_VMEM_TEMPS_TABLE token {tok!r}: expected "
                    f"key=int with key one of "
                    f"{sorted(VMEM_TEMPS_DEFAULTS)}")
            try:
                table[name.strip()] = int(val)
            except ValueError:
                raise ValueError(
                    f"FDTD3D_VMEM_TEMPS_TABLE value for {name!r} is "
                    f"not an integer: {val!r}") from None
    if key not in table:
        raise KeyError(f"no VMEM temps calibration row {key!r} "
                       f"(known: {sorted(table)})")
    return table[key]


# Supported temporal-blocked pipeline depths (Yee steps per HBM pass)
# — THE single domain authority: ops/pallas_packed_tb.DEPTHS aliases
# it, plan.halo_bytes_per_step_tb_at validates against it, and
# bench.py derives the per-depth byte roofs (48/k) from it.
TB_DEPTHS: Tuple[int, ...] = (2, 3, 4)


def tb_depth_env() -> Optional[int]:
    """The pinned temporal-blocked pipeline depth, or None (auto).
    Out-of-domain or non-numeric values are a NAMED config error at
    dispatch time (the registered-knob convention)."""
    import os
    v = os.environ.get("FDTD3D_TB_DEPTH")
    if not v:
        return None
    try:
        k = int(v)
    except ValueError:
        raise ValueError(
            f"FDTD3D_TB_DEPTH={v!r}: pipeline depth must be an "
            f"integer, one of {'/'.join(map(str, TB_DEPTHS))}") \
            from None
    if k not in TB_DEPTHS:
        raise ValueError(f"FDTD3D_TB_DEPTH={v!r}: pipeline depth must "
                         f"be one of {'/'.join(map(str, TB_DEPTHS))}")
    return k


@dataclasses.dataclass
class PmlConfig:
    """CPML absorbing boundary (reference PML/CPML flags, SURVEY.md §0/§2).

    ``size``: thickness in cells per axis (0 disables on that axis). Applied
    on both ends of each active axis, backed by the PEC wall.
    Grading follows Roden & Gedney recursive-convolution CPML:
    sigma ~ sigma_max * d^m, kappa = 1+(kappa_max-1) d^m, alpha linear in
    (1-d), with sigma_max = -(m+1) ln(R0) / (2 eta0 dx * size).
    """

    size: Tuple[int, int, int] = (0, 0, 0)
    m: float = 3.0                 # polynomial grading order
    r0: float = 1e-8               # target normal-incidence reflection
    # kappa_max > 1 trades normal-incidence absorption for evanescent/
    # grazing handling (measured: 10-cell slab reflects 4e-4 at kappa=1 but
    # 1.4e-2 at kappa=5, identical numbers from an independent textbook
    # implementation). Default favors the common propagating-wave case.
    kappa_max: float = 1.0
    alpha_max: float = 0.05
    sigma_scale: float = 1.0       # multiplier on the optimal sigma_max

    @property
    def enabled(self) -> bool:
        return any(s > 0 for s in self.size)


@dataclasses.dataclass
class TfsfConfig:
    """Total-field/scattered-field plane-wave injection.

    Reference: TFSF source with 1D auxiliary incident grids EInc/HInc and
    ``--angle-teta/phi/psi`` oblique incidence (SURVEY.md §3.4).
    ``margin``: distance in cells from the domain wall (or from the PML inner
    face if PML is on) to the TFSF box face, per axis.
    Angles in degrees: teta = polar from +z, phi = azimuth from +x,
    psi = polarization rotation about the propagation direction
    (psi=0 -> E along the unit theta vector).
    """

    enabled: bool = False
    margin: Tuple[int, int, int] = (8, 8, 8)
    angle_teta: float = 0.0
    angle_phi: float = 0.0
    angle_psi: float = 0.0
    amplitude: float = 1.0
    # Incident waveform: "sin" (CW ramp-up) | "gauss_pulse" (modulated)
    waveform: str = "sin"


@dataclasses.dataclass
class PointSourceConfig:
    """Soft point (current) source on one field component.

    Reference analog: point-source excitation used by BASELINE config #2
    ("2D TMz point source"). Position in global cells.
    """

    enabled: bool = False
    component: str = "Ez"
    position: Tuple[int, int, int] = (0, 0, 0)
    amplitude: float = 1.0
    waveform: str = "sin"          # "sin" | "gauss_pulse" | "ricker"


@dataclasses.dataclass
class SphereConfig:
    """Spherical inclusion (reference ``--eps-sphere*`` style material init)."""

    enabled: bool = False
    center: Tuple[float, float, float] = (0.0, 0.0, 0.0)  # cells
    radius: float = 0.0                                   # cells
    value: float = 1.0


@dataclasses.dataclass
class MaterialsConfig:
    """Material definition (reference ``Scheme::initGrids`` fills, SURVEY §2).

    Uniform background + optional sphere inclusions + optional load-from-file
    (array path, .npy/.dat). Drude media: eps(w) = eps_inf -
    wp^2 / (w^2 + i gamma w), active where omega_p > 0.
    """

    eps: float = 1.0               # background relative permittivity
    mu: float = 1.0                # background relative permeability
    sigma_e: float = 0.0           # electric conductivity S/m
    sigma_m: float = 0.0           # magnetic loss
    eps_sphere: SphereConfig = dataclasses.field(default_factory=SphereConfig)
    mu_sphere: SphereConfig = dataclasses.field(default_factory=SphereConfig)
    # Drude (electric)
    use_drude: bool = False
    eps_inf: float = 1.0
    omega_p: float = 0.0           # rad/s (0 -> no plasma response)
    gamma: float = 0.0             # collision rate, rad/s
    drude_sphere: SphereConfig = dataclasses.field(default_factory=SphereConfig)
    # Drude (magnetic) — the reference's metamaterial mode pairs the
    # OmegaPE/GammaE grids with OmegaPM/GammaM ones so both eps(w) and
    # mu(w) disperse (double-negative media): mu(w) = mu_inf -
    # wpm^2/(w^2 + i gm w), realized as an ADE magnetic current K.
    use_drude_m: bool = False
    mu_inf: float = 1.0
    omega_pm: float = 0.0
    gamma_m: float = 0.0
    drude_m_sphere: SphereConfig = dataclasses.field(
        default_factory=SphereConfig)
    # load-from-file (path to .npy with shape (Nx,Ny,Nz) or broadcastable)
    eps_file: Optional[str] = None
    mu_file: Optional[str] = None


@dataclasses.dataclass
class ParallelConfig:
    """Spatial domain decomposition (reference ParallelGrid modes, SURVEY §2.9).

    topology: "none" | "auto" | explicit (px,py,pz) via manual_topology.
    Auto picks the factorization of n_devices over the ACTIVE axes minimizing
    total halo surface (the reference's optimal-node-grid heuristic).

    Deliberate non-feature: the reference's configurable ghost width
    (``--buffer-size``: exchange k planes, then step k times without
    communicating, recomputing the overlap) is an MPI-latency lever. On
    the TPU torus the one-plane ``ppermute`` per axis per half-step rides
    ICI at ~us latency and XLA overlaps it with the interior compute, so
    redundant-compute halos would pay FLOPs + memory for a latency that
    is not the bottleneck; the knob is omitted rather than accepted and
    ignored.
    """

    topology: str = "none"
    manual_topology: Optional[Tuple[int, int, int]] = None
    n_devices: Optional[int] = None  # default: all visible devices


@dataclasses.dataclass
class NtffConfig:
    """Near-to-far-field transform (reference --ntff-* flags, SURVEY §2).

    A running DFT of the tangential fields on a closed virtual box
    accumulates during the run (fdtd3d_tpu.ntff.NtffCollector); the
    far-field directivity pattern is written at the end.

    frequency: DFT frequency in Hz; None = the source frequency
    (C0/wavelength). every: sampling cadence in steps; None = auto
    (~16 samples per period). start: first sampling step; None = auto
    (after half the run, once the CW state is established). margin:
    box distance in cells inward from the PML inner face.
    """

    enabled: bool = False
    frequency: Optional[float] = None
    every: Optional[int] = None
    start: Optional[int] = None
    margin: int = 2
    # Explicit box override (global cell coords, inclusive): when set,
    # wins over `margin` (the collector's `box=` argument).
    box_lo: Optional[Tuple[int, int, int]] = None
    box_hi: Optional[Tuple[int, int, int]] = None
    theta_steps: int = 19          # pattern grid: theta in [0, 180]
    phi_steps: int = 24            # phi in [0, 360)


@dataclasses.dataclass
class OutputConfig:
    """Dump/diagnostics cadence (reference --save-res/dumpers, SURVEY §2)."""

    save_res: int = 0              # every N steps dump fields (0 = never)
    save_dir: str = "out"
    formats: Tuple[str, ...] = ("dat",)   # subset of {"dat","txt","bmp"}
    save_materials: bool = False
    checkpoint_every: int = 0      # full-state checkpoint cadence
    # "npz": rank-0 gathers and writes one file; "orbax": sharding-aware,
    # every host writes its own shards (large/multi-host runs)
    checkpoint_backend: str = "npz"
    # keep-K rotation for the checkpoint_every cadence: after each
    # cadence snapshot commits, only the newest K stay on disk
    # (0 = keep all). Snapshots are written crash-safely (io.atomic_open)
    # and named ckpt_tNNNNNN[.npz] in save_dir; resume with the CLI's
    # --resume auto (io.find_latest_checkpoint).
    checkpoint_keep: int = 3
    norms_every: int = 0           # print L2/Linf norms every N steps
    # structured per-interval metrics (energy, norms, divergence
    # residual — diag.metrics) appended to save_dir/metrics.jsonl
    # (SURVEY §5.5 observability)
    metrics_every: int = 0
    log_level: int = 1
    # Attach a profiling.StepClock to the Simulation: every advance()
    # chunk is timed (with a device sync, so honest but intrusive) and
    # aggregated in sim.clock (reference Clock compute-share timing,
    # SURVEY.md §5.1).
    profile: bool = False
    # NaN/Inf tripwire after every advance() chunk. Implemented by the
    # IN-GRAPH health counters (fdtd3d_tpu/telemetry.py): one fused
    # reduction inside the compiled chunk + one scalar readback, never
    # a host-side pass over the full pytree (the paired-complex path's
    # legs are reduced in-graph too). Independent of log_level so it
    # can guard production runs.
    check_finite: bool = False
    # Flight-recorder JSONL (fdtd3d_tpu/telemetry.py): when set, every
    # advance() chunk appends a schema-versioned record (in-graph
    # health counters, wall time, throughput) to this path, after a
    # run_start provenance record; VMEM-ladder downgrades are recorded
    # as ladder_downgrade events. CLI flag: --telemetry PATH.
    # Summarize with tools/telemetry_report.py.
    telemetry_path: Optional[str] = None
    # OpenMetrics exposition (fdtd3d_tpu/metrics.py): when set, a
    # MetricsRegistry observes every telemetry record host-side
    # (counters/gauges/histograms: throughput, chunk wall, recovery
    # events, unhealthy lanes, cache hits) and the Prometheus text
    # exposition is written to this path at close — any scraper can
    # ingest a run without parsing our JSONL. Works with or without
    # telemetry_path (a file-less sink feeds it). CLI: --metrics PATH.
    metrics_path: Optional[str] = None
    # Per-chip lane (telemetry schema v4, round 10): with a sink
    # attached, each chunk additionally records the UN-psummed per-chip
    # health counters (tiny all_gathered scalars on the same single
    # readback) as a "per_chip" record plus an "imbalance" summary
    # (max/mean ratio + argmax straggler chip). CLI flag:
    # --per-chip-telemetry. No-op without telemetry_path.
    per_chip_telemetry: bool = False
    # Device-trace lane (round 7): when set, Simulation starts a
    # jax.profiler capture into this directory at the first advance()
    # and finalizes it in Simulation.close() — crash-safe via the
    # callers' try/finally, degrade-to-skip when no profiler/chip is
    # available (profiling.TraceCapture). CLI flag: --profile DIR;
    # bench: FDTD3D_BENCH_PROFILE. Attribute the capture back onto the
    # named solver sections with tools/trace_attribution.py.
    profile_dir: Optional[str] = None


@dataclasses.dataclass
class SimConfig:
    """Top-level solver configuration (reference Settings + CMake matrix)."""

    scheme: str = "3D"
    size: Tuple[int, int, int] = (32, 32, 32)   # cells per axis (global)
    time_steps: int = 100
    dx: float = 1e-3               # uniform spatial step, meters
    courant_factor: float = 0.5
    wavelength: float = 20e-3      # source wavelength, meters
    # "float32" | "float64" | "bfloat16" | "float32x2" (double-single:
    # hi+lo f32 pairs, ~f64-class accumulation at 2x f32 traffic)
    dtype: str = "float32"
    complex_fields: bool = False   # reference COMPLEX_FIELD_VALUES mode
    # Kahan-compensated f32 updates: each field family carries a bf16
    # residual of the lost low-order bits of its leapfrog accumulation,
    # recovering ~1e-7-class long-horizon accuracy (the reference is
    # f64 C++; plain f32 drifts past 1e-6 by ~1000 steps — BASELINE.md
    # frontier table) at ~1.25x the f32 HBM traffic instead of f64's
    # ~10x slowdown. float32 only.
    compensated: bool = False

    pml: PmlConfig = dataclasses.field(default_factory=PmlConfig)
    tfsf: TfsfConfig = dataclasses.field(default_factory=TfsfConfig)
    point_source: PointSourceConfig = dataclasses.field(
        default_factory=PointSourceConfig)
    materials: MaterialsConfig = dataclasses.field(
        default_factory=MaterialsConfig)
    parallel: ParallelConfig = dataclasses.field(
        default_factory=ParallelConfig)
    output: OutputConfig = dataclasses.field(default_factory=OutputConfig)
    ntff: NtffConfig = dataclasses.field(default_factory=NtffConfig)

    # Fused Pallas kernels for the 3D hot path (ops/pallas3d.py):
    # None = auto (use on TPU when the config is eligible), True = force
    # (interpreter mode off-TPU — slow, test-only), False = always jnp.
    use_pallas: Optional[bool] = None
    # Error out at construction if the fused kernels do NOT engage
    # (instead of silently falling back to the ~3x slower jnp path) —
    # the guard against topology/feature drift re-disabling the fast
    # path unnoticed (VERDICT r2 weak item 1).
    require_pallas: bool = False

    # ---- derived ----
    @property
    def mode(self):
        return get_mode(self.scheme)

    @property
    def grid_shape(self) -> Tuple[int, int, int]:
        return self.mode.grid_shape(self.size)

    @property
    def dt(self) -> float:
        return physics.courant_dt(self.dx, self.courant_factor,
                                  self.mode.ndim)

    @property
    def omega(self) -> float:
        return 2.0 * math.pi * physics.C0 / self.wavelength

    def np_dtype(self):
        import numpy as np
        base = {"float32": np.float32, "float64": np.float64,
                "bfloat16": None, "float32x2": np.float32}[self.dtype]
        if self.dtype == "bfloat16":
            import jax.numpy as jnp
            base = jnp.bfloat16
        if self.complex_fields:
            return {"float32": np.complex64, "float32x2": np.complex64,
                    "float64": np.complex128}[self.dtype]
        return base

    @property
    def ds_fields(self) -> bool:
        """Double-single (hi+lo f32 pair) field storage — ~f64-class
        accumulation on the f32 vector units (ops/ds.py) at 2x field
        traffic; the ``--dtype float32x2`` accuracy rung."""
        return self.dtype == "float32x2"

    def validate(self) -> "SimConfig":
        mode = self.mode  # raises on bad scheme
        if not (0.0 < self.courant_factor <= 1.0):
            raise ValueError("courant_factor must be in (0, 1]")
        for a in range(3):
            if a in mode.active_axes and self.size[a] < 4:
                raise ValueError(f"active axis {a} needs >= 4 cells")
        if self.pml.enabled:
            for a in mode.active_axes:
                if self.pml.size[a] * 2 + 4 > self.size[a] and \
                        self.pml.size[a] > 0:
                    raise ValueError(f"PML too thick on axis {a}")
        if self.dtype not in ("float32", "float64", "bfloat16",
                              "float32x2"):
            raise ValueError(f"bad dtype {self.dtype}")
        if self.output.checkpoint_backend not in ("npz", "orbax"):
            raise ValueError(
                f"bad checkpoint backend "
                f"{self.output.checkpoint_backend!r} (npz | orbax)")
        for use, wp, base, tag in (
                (self.materials.use_drude, self.materials.omega_p,
                 self.materials.eps_inf, "eps_inf"),
                (self.materials.use_drude_m, self.materials.omega_pm,
                 self.materials.mu_inf, "mu_inf")):
            if use and wp > 0:
                # Drude dispersion w^2 = (wp^2 + c^2 k^2)/base tightens
                # the leapfrog stability limit:
                # ((wp dt/2)^2 + cf^2)/base <= 1 (cf is the fraction of
                # the vacuum Courant limit). Violations blow up to NaN.
                margin = ((wp * self.dt / 2.0) ** 2
                          + self.courant_factor ** 2) / base
                if margin > 1.0:
                    raise ValueError(
                        f"unstable Drude configuration: ((wp*dt/2)^2 + "
                        f"courant_factor^2)/{tag} = {margin:.3f} > 1; "
                        f"reduce courant_factor or the plasma frequency")
        if self.point_source.enabled and \
                self.point_source.component not in mode.e_components:
            raise ValueError(
                f"point source component {self.point_source.component!r} "
                f"is not an active E component of scheme {self.scheme} "
                f"(active: {mode.e_components})")
        if self.complex_fields and self.dtype == "bfloat16":
            raise ValueError("complex_fields requires float32/float64")
        if self.compensated and (self.dtype != "float32"
                                 or self.complex_fields):
            raise ValueError(
                "compensated updates require real float32 fields "
                "(float64 needs no compensation; bfloat16 storage is "
                "already below the residual's resolution; float32x2 "
                "supersedes compensation — its lo words ARE the "
                "residuals, carried through the curls too)")
        if self.ntff.enabled:
            if mode.name != "3D":
                raise ValueError("NTFF requires the 3D scheme")
            if self.ntff.theta_steps < 2 or self.ntff.phi_steps < 1:
                raise ValueError(
                    "NTFF needs theta_steps >= 2 and phi_steps >= 1")
            if self.ntff.every is not None and self.ntff.every < 1:
                raise ValueError("ntff.every must be >= 1")
        return self
