"""Metrics facade + OpenMetrics exposition (service observability).

The scraper-facing half of the fleet-observability layer
(docs/OBSERVABILITY.md "Service observability"): a tiny host-side
counters/gauges/histograms registry fed from the events the telemetry
layer ALREADY emits — no new device traffic, no extra readbacks — and
rendered as Prometheus/OpenMetrics text, so any standard scraper can
ingest a run without parsing our JSONL.

Wiring: :class:`fdtd3d_tpu.telemetry.TelemetrySink` calls
:meth:`MetricsRegistry.observe_record` on every record AFTER schema
validation (``Simulation``/``BatchSimulation`` attach one when
``OutputConfig.metrics_path`` / CLI ``--metrics PATH`` is set, and
write the exposition atomically at close); :meth:`from_jsonl` builds
the same registry from an existing telemetry or registry JSONL
(tools/fleet_report.py's fleet rollups).

Metric name table (all prefixed ``fdtd3d_``; docs/OBSERVABILITY.md
carries the rendered version):

==================================  =========  =========================
name                                type       fed from
==================================  =========  =========================
runs_started_total                  counter    run_start
runs_finished_total                 counter    run_end
runs_total{status}                  counter    registry run_final rows
chunks_total                        counter    chunk
steps_total                         counter    chunk.steps
unhealthy_chunks_total              counter    chunk.finite == false
chunk_wall_seconds                  histogram  chunk.wall_s
throughput_mcells_per_s             gauge      chunk.mcells_per_s (last)
run_mcells_per_s                    gauge      run_end.mcells_per_s
run_compile_ms                      gauge      run_end.compile_ms
recovery_events_total{kind}         counter    retry/rollback/degrade/
                                               topology_change
vmem_ladder_downgrades_total        counter    ladder_downgrade
lane_unhealthy_total{lane}          counter    batch_lane.finite==false
straggler_ratio                     gauge      imbalance.ratio (worst)
straggler_chip                      gauge      imbalance.argmax (worst)
alerts_total{rule}                  counter    alert (fdtd3d_tpu/slo.py)
aot_cache_hits / _misses /
  _disk_hits / _traces              gauge      run_end.aot_cache
jobs_submitted_total{tenant}        counter    job_submit (queue journal)
jobs_total{status,tenant}           counter    job_state terminal rows
queue_depth                         gauge      journal fold (last-status
                                               == queued job count)
queue_wait_seconds                  histogram  queue_wait spans (v9);
                                               job_state running.wait_s
                                               on pre-v9 journals
compile_ms                          histogram  compile spans (v9)
snapshot_commit_seconds             histogram  snapshot_commit spans
recovery_seconds                    histogram  retry/rollback/degrade/
                                               topology_change spans
==================================  =========  =========================

The four phase histograms are the causal-trace plane's scraper view
(docs/OBSERVABILITY.md "Trace plane"): each observes the wall duration
(t1 - t0) of one lifecycle span class, so a dashboard reads the same
latency decomposition ``tools/fleet_report.py`` tabulates per tenant.
``runs_total`` folds registry run_final rows BY TRACE when the row
carries a ``trace_id``: a preempted-and-resumed job contributes one
logical sample (the latest dispatch's status), not two.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

PREFIX = "fdtd3d_"

# chunk-wall histogram buckets, seconds (log-ish ladder: sub-ms CPU
# test chunks through minute-class tunnel dispatches)
WALL_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                30.0, 60.0)

# queue-wait histogram buckets, seconds: queue waits live on a longer
# clock than chunk walls (an aged job can sit behind quota for
# minutes), so the ladder runs out to an hour
QUEUE_WAIT_BUCKETS = (0.1, 1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 3600.0)

# compile-span buckets, milliseconds: sub-ms cache hits through
# minute-class cold tunnel compiles
COMPILE_MS_BUCKETS = (1.0, 10.0, 50.0, 100.0, 500.0, 1000.0, 5000.0,
                      15000.0, 60000.0)

# span names folded into the recovery_seconds phase histogram (the
# supervisor's v9 spans beside its v5 recovery records)
_RECOVERY_SPANS = ("retry", "rollback", "degrade", "topology_change")

# the queue-journal statuses that end a job (fdtd3d_tpu/jobqueue.py
# owns the lifecycle; this module only needs to know which rows close
# the jobs_total{status,tenant} counter)
_JOB_TERMINAL = ("completed", "failed", "cancelled")


def _esc(v: Any) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"") \
        .replace("\n", r"\n")


def _labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class _Metric:
    def __init__(self, name: str, mtype: str, help_: str):
        self.name = name
        self.mtype = mtype          # "counter" | "gauge" | "histogram"
        self.help = help_
        # label-tuple -> value (counter/gauge) or
        # label-tuple -> {"sum", "count", "buckets": [n per le]}
        self.samples: Dict[Tuple[Tuple[str, str], ...], Any] = {}

    def _key(self, labels: Dict[str, Any]
             ) -> Tuple[Tuple[str, str], ...]:
        return tuple(sorted((k, str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Host-side metric store with OpenMetrics text rendering.

    ``path`` remembers where the exposition belongs (the sim's
    ``OutputConfig.metrics_path``); it travels WITH the registry when
    the supervisor swaps sims, so a ladder-degraded run still writes
    its exposition at close (:meth:`maybe_write`)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._metrics: Dict[str, _Metric] = {}
        # queue-journal fold: job_id -> last status, so queue_depth is
        # a true gauge (a requeued job re-enters the depth) instead of
        # an ever-growing counter difference
        self._job_status: Dict[str, str] = {}
        # trace fold (v9): trace_id -> latest run_final status, so
        # runs_total counts a resumed job as ONE logical run
        self._trace_final: Dict[str, str] = {}

    # -- primitives ----------------------------------------------------

    def _get(self, name: str, mtype: str, help_: str) -> _Metric:
        m = self._metrics.get(name)
        if m is None:
            m = _Metric(name, mtype, help_)
            self._metrics[name] = m
        elif m.mtype != mtype:
            raise ValueError(f"metric {name!r} is a {m.mtype}, not a "
                             f"{mtype}")
        return m

    def inc(self, name: str, amount: float = 1.0, help_: str = "",
            **labels) -> None:
        m = self._get(name, "counter", help_)
        k = m._key(labels)
        m.samples[k] = m.samples.get(k, 0.0) + float(amount)

    def set_gauge(self, name: str, value: float, help_: str = "",
                  **labels) -> None:
        m = self._get(name, "gauge", help_)
        m.samples[m._key(labels)] = float(value)

    def observe(self, name: str, value: float, help_: str = "",
                buckets: Tuple[float, ...] = WALL_BUCKETS,
                **labels) -> None:
        m = self._get(name, "histogram", help_)
        k = m._key(labels)
        s = m.samples.get(k)
        if s is None:
            s = {"sum": 0.0, "count": 0, "buckets": buckets,
                 "counts": [0] * (len(buckets) + 1)}
            m.samples[k] = s
        v = float(value)
        s["sum"] += v
        s["count"] += 1
        for i, le in enumerate(s["buckets"]):
            if v <= le:
                s["counts"][i] += 1
        s["counts"][-1] += 1        # +Inf

    def value(self, name: str, **labels) -> Optional[float]:
        """Counter/gauge readback (tests + fleet rollups)."""
        m = self._metrics.get(name)
        if m is None:
            return None
        return m.samples.get(m._key(labels))

    # -- the telemetry feed --------------------------------------------

    def observe_record(self, rec: Dict[str, Any]) -> None:
        """One validated telemetry/registry record -> metric updates
        (the mapping in the module docstring's name table)."""
        rtype = rec.get("type")
        if rtype == "run_start":
            self.inc("runs_started_total",
                     help_="telemetry run_start records seen")
        elif rtype == "chunk":
            self.inc("chunks_total", help_="compiled chunks dispatched")
            self.inc("steps_total", amount=rec["steps"],
                     help_="solver steps advanced")
            self.observe("chunk_wall_seconds", rec["wall_s"],
                         help_="per-chunk wall time, seconds")
            self.set_gauge("throughput_mcells_per_s",
                           rec["mcells_per_s"],
                           help_="latest chunk throughput, Mcells/s")
            if not rec.get("finite", True):
                self.inc("unhealthy_chunks_total",
                         help_="chunks whose non-finite flag tripped")
        elif rtype == "batch_lane":
            if not rec.get("finite", True):
                self.inc("lane_unhealthy_total", lane=rec["lane"],
                         help_="non-finite batch-lane chunk records "
                               "per lane (tenant)")
        elif rtype in ("retry", "rollback", "degrade",
                       "topology_change"):
            self.inc("recovery_events_total", kind=rtype,
                     help_="supervisor recovery events by kind")
        elif rtype == "ladder_downgrade":
            self.inc("vmem_ladder_downgrades_total",
                     help_="VMEM-ladder tile/depth downgrades")
        elif rtype == "imbalance":
            if rec.get("ratio") is not None:
                self.set_gauge("straggler_ratio", rec["ratio"],
                               help_="per-chip max/mean imbalance "
                                     "ratio (latest)")
            self.set_gauge("straggler_chip", rec["argmax"],
                           help_="straggler-candidate chip id "
                                 "(latest)")
        elif rtype == "alert":
            self.inc("alerts_total", rule=rec["rule"],
                     help_="SLO alerts fired by rule id")
        elif rtype == "run_end":
            self.inc("runs_finished_total",
                     help_="telemetry run_end records seen")
            self.set_gauge("run_mcells_per_s", rec["mcells_per_s"],
                           help_="whole-run mean throughput, Mcells/s")
            if rec.get("compile_ms") is not None:
                self.set_gauge("run_compile_ms", rec["compile_ms"],
                               help_="wall spent in lower+compile "
                                     "this run, ms")
            cache = rec.get("aot_cache")
            if isinstance(cache, dict):
                for k in ("hits", "misses", "disk_hits", "traces"):
                    if isinstance(cache.get(k), (int, float)):
                        self.set_gauge(f"aot_cache_{k}", cache[k],
                                       help_="AOT executable cache "
                                             "counter snapshot")
        elif rtype == "run_final":
            # registry rows (runs.jsonl): the fleet-status counter.
            # Trace-joined (v9): a re-dispatched job's second final
            # row REPLACES its first sample — one logical run per
            # trace, latest status wins.
            trace = rec.get("trace_id")
            if trace:
                prev = self._trace_final.get(trace)
                if prev is not None:
                    m = self._get("runs_total", "counter",
                                  "registry run_final rows by status "
                                  "(one logical run per trace)")
                    k = m._key({"status": prev})
                    if m.samples.get(k):
                        m.samples[k] -= 1.0
                self._trace_final[trace] = rec["status"]
            self.inc("runs_total", status=rec["status"],
                     help_="registry run_final rows by status "
                           "(one logical run per trace)")
        elif rtype == "span":
            self._observe_span(rec)
        elif rtype == "job_submit":
            # queue-journal rows (fdtd3d_tpu/jobqueue.py): admission
            self.inc("jobs_submitted_total", tenant=rec["tenant"],
                     help_="queue jobs admitted, by tenant")
            self._observe_job(rec)
        elif rtype == "job_state":
            if rec["status"] in _JOB_TERMINAL:
                self.inc("jobs_total", status=rec["status"],
                         tenant=rec["tenant"],
                         help_="queue jobs reaching a terminal "
                               "state, by status and tenant")
            if rec["status"] == "running" \
                    and isinstance(rec.get("wait_s"), (int, float)) \
                    and not rec.get("trace_id"):
                # pre-v9 journals only: a traced job's queue wait
                # arrives as its queue_wait span (observing both
                # would double-count the same dispatch)
                self.observe("queue_wait_seconds", rec["wait_s"],
                             buckets=QUEUE_WAIT_BUCKETS,
                             help_="queue wait between submit and "
                                   "dispatch, seconds")
            self._observe_job(rec)
        elif rtype == "heartbeat":
            # v10 live-health rows: the scraper sees emitter
            # freshness without parsing the stream itself
            self.inc("heartbeats_total", emitter=rec["emitter"],
                     help_="heartbeat rows observed, by emitter")
            self.set_gauge("heartbeat_last_unix", rec["unix"],
                           emitter=rec["emitter"],
                           help_="wall clock of the latest "
                                 "heartbeat, by emitter")
        elif rtype == "liveness":
            self.inc("liveness_flags_total", emitter=rec["emitter"],
                     status=rec["status"],
                     help_="watcher liveness verdicts, by emitter "
                           "and status")

    def _observe_span(self, rec: Dict[str, Any]) -> None:
        """One v9 ``span`` record -> the phase histograms (the
        causal-trace plane's scraper view)."""
        name = rec.get("name")
        dur = float(rec["t1"]) - float(rec["t0"])
        if name == "queue_wait":
            self.observe("queue_wait_seconds", dur,
                         buckets=QUEUE_WAIT_BUCKETS,
                         help_="queue wait between submit and "
                               "dispatch, seconds")
        elif name == "compile":
            attrs = rec.get("attrs") or {}
            ms = attrs.get("compile_ms")
            self.observe("compile_ms",
                         float(ms) if isinstance(ms, (int, float))
                         and not isinstance(ms, bool) else dur * 1e3,
                         buckets=COMPILE_MS_BUCKETS,
                         help_="AOT-compile phase wall per span, ms "
                               "(~0 on exec-cache hits)")
        elif name == "snapshot_commit":
            self.observe("snapshot_commit_seconds", dur,
                         help_="snapshot-commit phase wall per span, "
                               "seconds")
        elif name in _RECOVERY_SPANS:
            self.observe("recovery_seconds", dur,
                         help_="recovery phase wall per span (retry/"
                               "rollback/degrade/topology_change), "
                               "seconds")

    def _observe_job(self, rec: Dict[str, Any]) -> None:
        """Update the journal fold + the queue_depth gauge from one
        job row (shared by the submit/state branches)."""
        self._job_status[rec["job_id"]] = rec["status"]
        depth = sum(1 for s in self._job_status.values()
                    if s == "queued")
        self.set_gauge("queue_depth", depth,
                       help_="jobs whose latest journal status is "
                             "queued")

    # -- exposition ----------------------------------------------------

    def render(self) -> str:
        """OpenMetrics/Prometheus text exposition (deterministic
        ordering; ``# EOF`` terminated)."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            full = PREFIX + name
            lines.append(f"# HELP {full} {m.help or name}")
            lines.append(f"# TYPE {full} {m.mtype}")
            for key in sorted(m.samples):
                labels = dict(key)
                if m.mtype == "histogram":
                    s = m.samples[key]
                    for le, n in zip(
                            [*s["buckets"], float("inf")],
                            s["counts"]):
                        le_s = "+Inf" if le == float("inf") \
                            else _fmt(le)
                        lines.append(
                            f"{full}_bucket"
                            f"{_labels(dict(labels, le=le_s))} {n}")
                    lines.append(f"{full}_sum{_labels(labels)} "
                                 f"{_fmt(s['sum'])}")
                    lines.append(f"{full}_count{_labels(labels)} "
                                 f"{s['count']}")
                else:
                    lines.append(f"{full}{_labels(labels)} "
                                 f"{_fmt(m.samples[key])}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def write(self, path: str) -> None:
        """Atomically publish the exposition (a scraper must never
        read a half-written file)."""
        import os

        from fdtd3d_tpu.io import atomic_open
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with atomic_open(path, "w") as f:
            f.write(self.render())

    def maybe_write(self) -> None:
        """Publish to the remembered ``path`` (no-op without one) —
        the close()-time hook shared by Simulation/BatchSimulation.
        Rank 0 only (the telemetry sink / run registry convention):
        per-rank host timings differ, and N ranks racing one atomic
        replace would leave whichever landed last."""
        if not self.path:
            return
        try:
            import jax
            if jax.process_index() != 0:
                return
        except Exception:
            pass
        self.write(self.path)

    @classmethod
    def from_jsonl(cls, path: str) -> "MetricsRegistry":
        """Build a registry by replaying an existing telemetry or
        registry JSONL (validated) — the offline flavor the fleet
        monitor uses."""
        from fdtd3d_tpu import telemetry as _telemetry
        reg = cls()
        for rec in _telemetry.read_jsonl(path):
            reg.observe_record(rec)
        return reg

    def observe_tail(self, tailer, path: str) -> int:
        """Incremental replay: observe only the records appended to
        ``path`` since ``tailer``'s cursor (fdtd3d_tpu/tail.Tailer) —
        the streaming flavor the fleet watcher polls with. Invalid
        rows become named tailer events instead of killing the
        caller's poll loop. Returns the number of records observed."""
        from fdtd3d_tpu import telemetry as _telemetry
        n = 0
        for rec in tailer.poll_records(path):
            try:
                _telemetry.validate_record(rec)
            except ValueError as exc:
                tailer.events.append(
                    f"invalid record in {path}: {exc}")
                continue
            self.observe_record(rec)
            n += 1
        return n
