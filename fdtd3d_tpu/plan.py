"""Memory / communication planner: size a run before touching a device.

Reference analog: the reference's users size MPI+CUDA runs by hand from
grid counts; here ``plan(cfg)`` computes, per chip, the HBM bytes of
every state and coefficient array (fields, slab-compacted CPML psi,
Drude J, incident line, material grids) and the per-step halo-exchange
traffic of the chosen decomposition — exactly the arrays
``solver.init_state``/``build_coeffs`` would allocate, derived from the
same layout logic (slab_axes, scalar-vs-grid materials), without
allocating anything. Drives the CLI ``--dry-run`` flag, so pod-scale
configs (1024^3 on 64 chips) can be validated on a laptop.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple

import numpy as np

from fdtd3d_tpu import solver
from fdtd3d_tpu.layout import CURL_TERMS, component_axis
from fdtd3d_tpu.parallel.mesh import resolve_topology

AXES = "xyz"

# Message-split crossover for the strategy chooser: below this
# per-message stacked-plane size the exchange is latency/message-count
# bound (fuse the component planes into ONE ppermute per generation);
# above it, per-plane messages let the scheduler start the first
# plane's send before the last is sliced. A modeling constant in the
# same spirit as costs.ICI_GBPS_DEFAULT — override the whole choice
# with FDTD3D_COMM_STRATEGY when a measured crossover exists.
SPLIT_FUSE_MAX_BYTES = 4 << 20


@dataclasses.dataclass(frozen=True)
class CommStrategy:
    """One planned halo-exchange strategy for a (grid, topology,
    dtype, step kind) — the communication-strategy selection program
    of PAPERS.md's 2606.06910 adapted to ICI ppermute: the planner
    scores shard-axis assignment, message split and sync-vs-async
    scheduling against the PR-6 cost model (costs.overlap_model /
    halo_topology_table) and records ONE deterministic choice that the
    temporal-blocked step consumes and every observability lane
    (ledger comm table, telemetry run_start) echoes.

    ``split``: "fused" = each ghost generation ships as ONE stacked
    (ncomp, 1, ·, ·) ppermute per axis; "per-plane" = one ppermute per
    component plane (same bytes, more/smaller messages).
    ``schedule``: "async" places no ordering barrier between the
    exchange and the kernel (XLA's latency-hiding scheduler overlaps
    them — tools/aot_overlap.py proves the lowering); "sync" forces
    the exchange to complete first via an optimization barrier (the
    measurement A/B posture).
    ``ghost_depth``: ghost-plane generations exchanged per pass — the
    temporal-blocked kernel's pipeline depth k (H(t)..H(t+k-1) down,
    E(t+1)..E(t+k) up), scored as a FREE VARIABLE by the VMEM-
    calibrated auto-depth picker (ops/pallas_packed_tb.pick_depth:
    deepest k in {2,3,4} whose budgeted tile stays viable;
    ``FDTD3D_TB_DEPTH`` pins); 1 for single-step kinds. Per-STEP ICI
    bytes are depth-invariant (k stacks per pass / k steps), so depth
    trades only VMEM ring scratch against HBM bytes — the halo-depth-
    vs-bytes frontier of PAPERS.md's 2606.06910 with the bytes axis
    flat.
    """

    step_kind: str
    topology: Tuple[int, int, int]
    shard_axes: Tuple[str, ...]      # axis letters carrying >1 shards
    ghost_depth: int
    split: str                       # "fused" | "per-plane"
    schedule: str                    # "async" | "sync"
    source: str                      # "model" | "env:FDTD3D_COMM_STRATEGY"
    plane_bytes_max: int             # largest stacked message, bytes
    # informational score, set only from an EXPLICIT hbm_gbps argument
    # (never a process-global probe — the record is deterministic);
    # the ledger's quantitative surface is comm.overlap_model
    modeled_async_speedup: Optional[float]

    def as_record(self) -> Dict[str, object]:
        """JSON-ready dict (ledger comm lane / telemetry run_start)."""
        d = dataclasses.asdict(self)
        d["topology"] = list(self.topology)
        d["shard_axes"] = list(self.shard_axes)
        return d


def _parse_strategy_env(value: str) -> Dict[str, str]:
    """FDTD3D_COMM_STRATEGY: comma-separated tokens from
    {fused, per-plane, async, sync}, e.g. "per-plane,sync" or just
    "sync". Unknown tokens are a config error, not a silent default."""
    out: Dict[str, str] = {}
    for tok in value.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok in ("fused", "per-plane"):
            out["split"] = tok
        elif tok in ("async", "sync"):
            out["schedule"] = tok
        else:
            raise ValueError(
                f"FDTD3D_COMM_STRATEGY token {tok!r} not one of "
                f"fused/per-plane/async/sync (comma-separated)")
    return out


@dataclasses.dataclass(frozen=True)
class Plan:
    topology: Tuple[int, int, int]
    local_shape: Tuple[int, int, int]
    fields_bytes: int          # E + H
    psi_bytes: int             # CPML recursion state (slab-compacted)
    drude_bytes: int           # J currents
    inc_bytes: int             # TFSF incident line (Einc + Hinc)
    coeff_bytes: int           # material arrays (3D grids only count
    #                            when spatially varying)
    halo_bytes_per_step: int   # ppermute traffic per chip per full step
    n_chips: int
    # Per-axis halo breakdown (comm-lane observability, round 10): for
    # each SHARDED axis, the curl-term plane count, one plane's bytes,
    # and the per-neighbor / per-step traffic. Keys are axis letters;
    # sum of bytes_per_step over axes == halo_bytes_per_step.
    halo_by_axis: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    # Temporal-blocked (depth-k) halo model (rounds 11/12): the tb
    # kernel exchanges k ghost-plane generations per neighbor per pass
    # — the full H stacks at t..t+k-1 downstream, the full E stacks at
    # t+1..t+k upstream — so per STEP each sharded axis moves one
    # nh-stack + one ne-stack (send+recv), at field dtype, INVARIANT
    # in the pipeline depth k (k stacks per pass / k steps). The
    # ledger's sharded tb trace equals this number to the byte at
    # every k (tests/test_comm_costs.py); invariant under weak scaling
    # like the single-step model. ``halo_bytes_per_step_tb_at(k=)``
    # exposes the per-depth form (and the per-pass bytes).
    halo_bytes_per_step_tb: int = 0
    halo_by_axis_tb: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    # The planned communication strategy for this decomposition
    # (None when unsharded): see CommStrategy.
    comm_strategy: Optional[CommStrategy] = None

    def halo_bytes_per_step_tb_at(self, k: int = 2) -> int:
        """Per-step tb halo bytes at pipeline depth ``k`` — the model
        the traced ppermute bytes must equal for EVERY k. The per-pass
        schedule is k H-stacks down + k E-stacks up (the k-th E stack
        is the post-kernel hi-edge fix), so per step the traffic is
        depth-invariant; the k= form exists so callers (and tests)
        assert that invariance instead of assuming it, and so the
        per-PASS bytes (``k * halo_bytes_per_step_tb_at(k)``) are
        derivable."""
        from fdtd3d_tpu.config import TB_DEPTHS
        if k not in TB_DEPTHS:
            raise ValueError(f"tb pipeline depth {k} not in "
                             f"{TB_DEPTHS}")
        return int(self.halo_bytes_per_step_tb)

    @property
    def hbm_per_chip(self) -> int:
        return (self.fields_bytes + self.psi_bytes + self.drude_bytes
                + self.inc_bytes + self.coeff_bytes)

    def report(self) -> str:
        gib = 1 << 30
        mib = 1 << 20
        lines = [
            f"topology {self.topology} ({self.n_chips} chip"
            f"{'s' if self.n_chips != 1 else ''}), local grid "
            f"{self.local_shape}",
            f"  fields (E+H):        {self.fields_bytes / gib:8.3f} GiB",
            f"  CPML psi (slabs):    {self.psi_bytes / gib:8.3f} GiB",
            f"  Drude J:             {self.drude_bytes / gib:8.3f} GiB",
            f"  TFSF incident line:  {self.inc_bytes / mib:8.3f} MiB",
            f"  material coeffs:     {self.coeff_bytes / gib:8.3f} GiB",
            f"  TOTAL per chip:      {self.hbm_per_chip / gib:8.3f} GiB",
            f"  halo exchange:       {self.halo_bytes_per_step / mib:8.3f}"
            f" MiB/chip/step",
        ]
        if self.n_chips > 1:
            lines.append(
                f"  halo exchange (tb):  "
                f"{self.halo_bytes_per_step_tb / mib:8.3f}"
                f" MiB/chip/step (depth-k invariant: k ghost-plane "
                f"generations/neighbor/pass)")
        if self.comm_strategy is not None:
            s = self.comm_strategy
            lines.append(
                f"  comm strategy:       {s.split} + {s.schedule}, "
                f"ghost depth {s.ghost_depth} ({s.step_kind}; "
                f"source: {s.source})")
        return "\n".join(lines)


def _coeff_grid_counts(static) -> Tuple[int, int]:
    """(grids per E comp, grids per H comp) — mirrors build_coeffs'
    scalar-vs-grid decisions (materials.scalar_or_grid / drude_params /
    merge_drude_eps), asserted equal to the real allocation by
    tests/test_plan.py so the two cannot drift silently."""
    mat = static.cfg.materials

    def sphere_on(s):
        return s is not None and s.enabled and s.radius > 0

    def side(base_grid, use, wp_sphere, wp0):
        drive_grids = 0
        if use:
            if sphere_on(wp_sphere):
                base_grid = True   # merge_drude_eps broadcasts to a grid
                drive_grids = 1    # bj/bm carries wp^2; kj/km is scalar
            elif wp0 > 0:
                base_grid = False  # uniform plasma: collapses to the
                #                    _inf value, discarding any grid
        return 2 * base_grid + drive_grids

    per_e = side(bool(mat.eps_file) or sphere_on(mat.eps_sphere),
                 static.use_drude, mat.drude_sphere, mat.omega_p)
    per_h = side(bool(mat.mu_file) or sphere_on(mat.mu_sphere),
                 static.use_drude_m, mat.drude_m_sphere, mat.omega_pm)
    return per_e, per_h


def _halo_planes(mode, a: int) -> int:
    """Planes exchanged across sharded axis `a` per full step: one per
    curl term whose derivative crosses it (ops/stencil.py ppermutes per
    difference), counted from the mode's actual components."""
    n = 0
    for fam, (upd, srcs) in (("E", (mode.e_components, mode.h_components)),
                             ("H", (mode.h_components, mode.e_components))):
        for c in upd:
            for (ax, d_axis, s) in CURL_TERMS[component_axis(c)]:
                d = ("H" if fam == "E" else "E") + AXES[d_axis]
                if ax == a and d in srcs:
                    n += 1
    return n


def plan(cfg, n_devices: int = 1) -> Plan:
    """Compute the per-chip memory/comm plan WITHOUT any device work."""
    static = solver.build_static(cfg)
    mode = static.mode
    topo = resolve_topology(cfg.parallel, static.grid_shape,
                            mode.active_axes, n_devices=n_devices)
    static = dataclasses.replace(static, topology=topo)
    local = tuple(static.grid_shape[a] // topo[a] for a in range(3))
    cells = int(np.prod(local))
    fb = np.dtype(static.field_dtype).itemsize
    ab = np.dtype(static.aux_dtype).itemsize
    rb = np.dtype(static.real_dtype).itemsize

    fields = len(mode.components) * cells * fb

    slabs = solver.slab_axes(static)
    psi = 0
    for comps in (mode.e_components, mode.h_components):
        for c in comps:
            for (a, d_axis, s) in CURL_TERMS[component_axis(c)]:
                if a in static.pml_axes:
                    shape = list(local)
                    if a in slabs:
                        shape[a] = 2 * slabs[a]
                    psi += int(np.prod(shape)) * ab

    drude = len(mode.e_components) * cells * ab if static.use_drude else 0
    if static.use_drude_m:
        drude += len(mode.h_components) * cells * ab   # K currents
    inc = 2 * static.tfsf_setup.n_inc * ab if static.tfsf_setup else 0

    per_e, per_h = _coeff_grid_counts(static)
    coeff = (len(mode.e_components) * per_e
             + len(mode.h_components) * per_h) * cells * rb

    # halo traffic: ops/stencil.py ppermutes one plane per curl term
    # crossing a sharded axis; each plane is sent AND received.
    halo = 0
    by_axis: Dict[str, Dict[str, int]] = {}
    halo_tb = 0
    by_axis_tb: Dict[str, Dict[str, int]] = {}
    ne = len(mode.e_components)
    nh = len(mode.h_components)
    for a in range(3):
        if topo[a] > 1:
            plane = cells // local[a] * fb
            planes = _halo_planes(mode, a)
            axis_bytes = 2 * planes * plane
            halo += axis_bytes
            by_axis[AXES[a]] = {
                "planes_per_step": planes,
                "plane_bytes": plane,
                # per FULL step each crossing plane goes to ONE
                # neighbor and its counterpart arrives from the other
                # (E-phase down, H-phase up): send+recv totals split
                # evenly across the two neighbors
                "bytes_per_neighbor_per_step": planes * plane,
                "bytes_per_step": axis_bytes,
            }
            # tb (depth-2) model: per PASS (2 steps) each neighbor
            # exchange carries TWO ghost planes — full component
            # stacks of both generations (nh planes down at t and
            # t+1, ne planes up at t+1 and t+2) — so per STEP the
            # axis moves (nh + ne) component planes, each sent AND
            # received (same accounting as the single-step model).
            tb_planes = nh + ne
            tb_axis_bytes = 2 * tb_planes * plane
            halo_tb += tb_axis_bytes
            by_axis_tb[AXES[a]] = {
                "planes_per_step": tb_planes,
                "plane_bytes": plane,
                "bytes_per_neighbor_per_step": tb_planes * plane,
                "bytes_per_step": tb_axis_bytes,
            }
    strat = None
    if any(t > 1 for t in topo):
        strat = _choose_strategy(static, topo, cells, local, fb,
                                 halo, halo_tb)
    return Plan(topology=topo, local_shape=local, fields_bytes=fields,
                psi_bytes=psi, drude_bytes=drude, inc_bytes=inc,
                coeff_bytes=coeff, halo_bytes_per_step=halo,
                n_chips=int(np.prod(topo)), halo_by_axis=by_axis,
                halo_bytes_per_step_tb=halo_tb,
                halo_by_axis_tb=by_axis_tb, comm_strategy=strat)


def plan_for_topology(cfg, topology: Tuple[int, int, int]) -> Plan:
    """plan() with a FORCED (px, py, pz) decomposition — the comm lane
    (fdtd3d_tpu/costs.py) models specific topologies rather than the
    auto heuristic's pick."""
    from fdtd3d_tpu.config import ParallelConfig
    topology = tuple(int(p) for p in topology)
    cfg = dataclasses.replace(
        cfg, parallel=ParallelConfig(topology="manual",
                                     manual_topology=topology))
    return plan(cfg, n_devices=int(np.prod(topology)))


def _infer_step_kind(static, topo) -> str:
    """The best PRODUCTION kernel the config is in scope for — the
    kind the strategy models when the caller does not pin one. Pure
    eligibility checks (host math; no backend dispatch, so a CPU
    planning session models the TPU production path)."""
    from fdtd3d_tpu.parallel.mesh import mesh_axis_map
    mesh_axes = mesh_axis_map(topo)
    if static.cfg.ds_fields:
        return "pallas_packed_ds"
    from fdtd3d_tpu.ops import pallas_packed, pallas_packed_tb
    # plan_tb is the FULL temporal-blocking decision (scope + depth
    # viability + the tile-too-thin bail) — the same authority the
    # dispatch consults, so the planner can never model a tb run the
    # builder would decline (the round-13 disagreement)
    if pallas_packed_tb.plan_tb(static, mesh_axes).eligible:
        return "pallas_packed_tb"
    if pallas_packed.eligible(static, mesh_axes):
        return "pallas_packed"
    return "jnp"


def _choose_strategy(static, topo, cells: int,
                     local: Tuple[int, int, int], fb: int,
                     halo: int, halo_tb: int,
                     forced_kind: Optional[str] = None,
                     hbm_gbps: Optional[float] = None) -> CommStrategy:
    """Score (split, schedule) for one decomposition — DETERMINISTIC
    from its explicit inputs alone (no hidden process state: the same
    (grid, topology, dtype, kind) always yields the same record, so
    ledger / run_start / fixture comparisons hold field-for-field);
    FDTD3D_COMM_STRATEGY overrides. ``forced_kind`` pins the kernel
    the caller actually engaged: depth, halo model and scores are all
    re-scored for it, so the record always describes the exchange it
    claims to."""
    mode = static.mode
    step_kind = forced_kind or _infer_step_kind(static, topo)
    if step_kind == "pallas_packed_tb":
        # ghost_depth is the SCORED pipeline depth (the VMEM-calibrated
        # auto-depth pick, FDTD3D_TB_DEPTH pins) — pure host math, so
        # the record stays deterministic per (grid, topology, dtype,
        # kind) and environment
        from fdtd3d_tpu.ops import pallas_packed_tb
        depth = pallas_packed_tb.planned_depth(static) or 2
    else:
        depth = 1
    halo_bytes = halo_tb if depth >= 2 else halo
    stack = max(len(mode.e_components), len(mode.h_components))
    plane_max = max((cells // local[a] * fb * stack
                     for a in range(3) if topo[a] > 1), default=0)
    split = "fused" if plane_max <= SPLIT_FUSE_MAX_BYTES \
        else "per-plane"
    # schedule: async — overlap costs nothing when comm is negligible
    # and hides the exchange when it is not; "sync" is reachable ONLY
    # via the env override (the measurement A/B posture the
    # sentinel's window gates compare). modeled_async_speedup is an
    # informational score computed only from an EXPLICITLY passed
    # calibration (the ledger's quantitative surface is
    # comm.overlap_model, which carries the full scored window) — a
    # process-global probe here would make the "deterministic" record
    # differ between a probed bench process and an unprobed CLI.
    speedup = None
    if hbm_gbps and hbm_gbps > 0:
        from fdtd3d_tpu import costs
        # fields read+write per step is the dominant HBM term; the tb
        # kernel halves it (12 volumes per TWO steps)
        fields_step = 2 * len(mode.components) * cells * fb / depth
        om = costs.overlap_model(max(0.0, fields_step - halo_bytes),
                                 halo_bytes, hbm_gbps)
        if om is not None:
            speedup = om["modeled_async_speedup"]
    schedule = "async"
    source = "model"
    env = os.environ.get("FDTD3D_COMM_STRATEGY")
    if env:
        forced = _parse_strategy_env(env)
        split = forced.get("split", split)
        schedule = forced.get("schedule", schedule)
        source = "env:FDTD3D_COMM_STRATEGY"
    return CommStrategy(
        step_kind=step_kind, topology=tuple(topo),
        shard_axes=tuple(AXES[a] for a in range(3) if topo[a] > 1),
        ghost_depth=depth, split=split, schedule=schedule,
        source=source, plane_bytes_max=int(plane_max),
        modeled_async_speedup=speedup)


def comm_strategy(cfg, topology: Tuple[int, int, int],
                  step_kind: Optional[str] = None,
                  from_plan: Optional[Plan] = None
                  ) -> Optional[CommStrategy]:
    """THE strategy authority: the deterministic CommStrategy for cfg
    on a forced decomposition (None when unsharded). ``step_kind``
    pins the kernel the caller actually engaged (the ledger comm lane
    and telemetry run_start record the RUNNING kind, which may differ
    from the planner's best-eligible inference — e.g. a ledger forced
    to the single-step kernel, or a supervisor degrade rung); the
    whole choice is then RE-SCORED for that kind — depth, halo model
    and schedule together, never a partially rewritten record.
    ``from_plan`` reuses an already-computed Plan for the same (cfg,
    topology) instead of building a second one."""
    p = from_plan if from_plan is not None \
        else plan_for_topology(cfg, topology)
    strat = p.comm_strategy
    if strat is None or step_kind is None \
            or step_kind == strat.step_kind:
        return strat
    topo = tuple(int(t) for t in p.topology)
    static = dataclasses.replace(solver.build_static(cfg),
                                 topology=topo)
    fb = np.dtype(static.field_dtype).itemsize
    return _choose_strategy(static, topo,
                            int(np.prod(p.local_shape)),
                            p.local_shape, fb,
                            p.halo_bytes_per_step,
                            p.halo_bytes_per_step_tb,
                            forced_kind=step_kind)


# ---------------------------------------------------------------------------
# topology ladder (topology-elastic durable runs, docs/ROBUSTNESS.md)
# ---------------------------------------------------------------------------


def degrade_topology(topology: Tuple[int, int, int]
                     ) -> Optional[Tuple[int, int, int]]:
    """One rung down the topology ladder: the next SMALLER valid
    decomposition, or None at the unsharded bottom.

    The rung shrinks the largest per-axis factor to its largest proper
    divisor (first such axis on ties) — e.g. (2,2,2) -> (1,2,2) ->
    (1,1,2) -> (1,1,1) -> None. Divisibility is preserved by
    construction: any divisor of a factor that divided the grid still
    divides it, so every rung is a valid topology for the same grid.
    The supervisor walks this ladder when recovery on the current
    topology is exhausted (lost chip, shrunken allocation), resuming
    via the reshard-on-resume checkpoint path."""
    t = [int(p) for p in topology]
    mx = max(t)
    if mx <= 1:
        return None
    a = t.index(mx)
    for d in range(mx // 2, 0, -1):
        if mx % d == 0:
            t[a] = d
            break
    return tuple(t)


def fits_devices(topology: Tuple[int, int, int], n_devices: int) -> bool:
    """Whether a decomposition can map onto ``n_devices`` chips."""
    return int(np.prod([int(p) for p in topology])) <= int(n_devices)


def shrink_to_devices(topology: Tuple[int, int, int], n_devices: int
                      ) -> Tuple[int, int, int]:
    """Walk the topology ladder until the decomposition fits the
    available device count (shrunken-allocation resume): returns the
    first rung with at most ``n_devices`` chips — at worst (1, 1, 1),
    which always fits."""
    topo: Optional[Tuple[int, int, int]] = tuple(int(p)
                                                 for p in topology)
    while topo is not None and not fits_devices(topo, n_devices):
        topo = degrade_topology(topo)
    return topo if topo is not None else (1, 1, 1)
