"""Memory / communication planner: size a run before touching a device.

Reference analog: the reference's users size MPI+CUDA runs by hand from
grid counts; here ``plan(cfg)`` computes, per chip, the HBM bytes of
every state and coefficient array (fields, slab-compacted CPML psi,
Drude J, incident line, material grids) and the per-step halo-exchange
traffic of the chosen decomposition — exactly the arrays
``solver.init_state``/``build_coeffs`` would allocate, derived from the
same layout logic (slab_axes, scalar-vs-grid materials), without
allocating anything. Drives the CLI ``--dry-run`` flag, so pod-scale
configs (1024^3 on 64 chips) can be validated on a laptop.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

from fdtd3d_tpu import solver
from fdtd3d_tpu.layout import CURL_TERMS, component_axis
from fdtd3d_tpu.parallel.mesh import resolve_topology

AXES = "xyz"


@dataclasses.dataclass(frozen=True)
class Plan:
    topology: Tuple[int, int, int]
    local_shape: Tuple[int, int, int]
    fields_bytes: int          # E + H
    psi_bytes: int             # CPML recursion state (slab-compacted)
    drude_bytes: int           # J currents
    inc_bytes: int             # TFSF incident line (Einc + Hinc)
    coeff_bytes: int           # material arrays (3D grids only count
    #                            when spatially varying)
    halo_bytes_per_step: int   # ppermute traffic per chip per full step
    n_chips: int
    # Per-axis halo breakdown (comm-lane observability, round 10): for
    # each SHARDED axis, the curl-term plane count, one plane's bytes,
    # and the per-neighbor / per-step traffic. Keys are axis letters;
    # sum of bytes_per_step over axes == halo_bytes_per_step.
    halo_by_axis: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)

    @property
    def hbm_per_chip(self) -> int:
        return (self.fields_bytes + self.psi_bytes + self.drude_bytes
                + self.inc_bytes + self.coeff_bytes)

    def report(self) -> str:
        gib = 1 << 30
        mib = 1 << 20
        lines = [
            f"topology {self.topology} ({self.n_chips} chip"
            f"{'s' if self.n_chips != 1 else ''}), local grid "
            f"{self.local_shape}",
            f"  fields (E+H):        {self.fields_bytes / gib:8.3f} GiB",
            f"  CPML psi (slabs):    {self.psi_bytes / gib:8.3f} GiB",
            f"  Drude J:             {self.drude_bytes / gib:8.3f} GiB",
            f"  TFSF incident line:  {self.inc_bytes / mib:8.3f} MiB",
            f"  material coeffs:     {self.coeff_bytes / gib:8.3f} GiB",
            f"  TOTAL per chip:      {self.hbm_per_chip / gib:8.3f} GiB",
            f"  halo exchange:       {self.halo_bytes_per_step / mib:8.3f}"
            f" MiB/chip/step",
        ]
        return "\n".join(lines)


def _coeff_grid_counts(static) -> Tuple[int, int]:
    """(grids per E comp, grids per H comp) — mirrors build_coeffs'
    scalar-vs-grid decisions (materials.scalar_or_grid / drude_params /
    merge_drude_eps), asserted equal to the real allocation by
    tests/test_plan.py so the two cannot drift silently."""
    mat = static.cfg.materials

    def sphere_on(s):
        return s is not None and s.enabled and s.radius > 0

    def side(base_grid, use, wp_sphere, wp0):
        drive_grids = 0
        if use:
            if sphere_on(wp_sphere):
                base_grid = True   # merge_drude_eps broadcasts to a grid
                drive_grids = 1    # bj/bm carries wp^2; kj/km is scalar
            elif wp0 > 0:
                base_grid = False  # uniform plasma: collapses to the
                #                    _inf value, discarding any grid
        return 2 * base_grid + drive_grids

    per_e = side(bool(mat.eps_file) or sphere_on(mat.eps_sphere),
                 static.use_drude, mat.drude_sphere, mat.omega_p)
    per_h = side(bool(mat.mu_file) or sphere_on(mat.mu_sphere),
                 static.use_drude_m, mat.drude_m_sphere, mat.omega_pm)
    return per_e, per_h


def _halo_planes(mode, a: int) -> int:
    """Planes exchanged across sharded axis `a` per full step: one per
    curl term whose derivative crosses it (ops/stencil.py ppermutes per
    difference), counted from the mode's actual components."""
    n = 0
    for fam, (upd, srcs) in (("E", (mode.e_components, mode.h_components)),
                             ("H", (mode.h_components, mode.e_components))):
        for c in upd:
            for (ax, d_axis, s) in CURL_TERMS[component_axis(c)]:
                d = ("H" if fam == "E" else "E") + AXES[d_axis]
                if ax == a and d in srcs:
                    n += 1
    return n


def plan(cfg, n_devices: int = 1) -> Plan:
    """Compute the per-chip memory/comm plan WITHOUT any device work."""
    static = solver.build_static(cfg)
    mode = static.mode
    topo = resolve_topology(cfg.parallel, static.grid_shape,
                            mode.active_axes, n_devices=n_devices)
    static = dataclasses.replace(static, topology=topo)
    local = tuple(static.grid_shape[a] // topo[a] for a in range(3))
    cells = int(np.prod(local))
    fb = np.dtype(static.field_dtype).itemsize
    ab = np.dtype(static.aux_dtype).itemsize
    rb = np.dtype(static.real_dtype).itemsize

    fields = len(mode.components) * cells * fb

    slabs = solver.slab_axes(static)
    psi = 0
    for comps in (mode.e_components, mode.h_components):
        for c in comps:
            for (a, d_axis, s) in CURL_TERMS[component_axis(c)]:
                if a in static.pml_axes:
                    shape = list(local)
                    if a in slabs:
                        shape[a] = 2 * slabs[a]
                    psi += int(np.prod(shape)) * ab

    drude = len(mode.e_components) * cells * ab if static.use_drude else 0
    if static.use_drude_m:
        drude += len(mode.h_components) * cells * ab   # K currents
    inc = 2 * static.tfsf_setup.n_inc * ab if static.tfsf_setup else 0

    per_e, per_h = _coeff_grid_counts(static)
    coeff = (len(mode.e_components) * per_e
             + len(mode.h_components) * per_h) * cells * rb

    # halo traffic: ops/stencil.py ppermutes one plane per curl term
    # crossing a sharded axis; each plane is sent AND received.
    halo = 0
    by_axis: Dict[str, Dict[str, int]] = {}
    for a in range(3):
        if topo[a] > 1:
            plane = cells // local[a] * fb
            planes = _halo_planes(mode, a)
            axis_bytes = 2 * planes * plane
            halo += axis_bytes
            by_axis[AXES[a]] = {
                "planes_per_step": planes,
                "plane_bytes": plane,
                # per FULL step each crossing plane goes to ONE
                # neighbor and its counterpart arrives from the other
                # (E-phase down, H-phase up): send+recv totals split
                # evenly across the two neighbors
                "bytes_per_neighbor_per_step": planes * plane,
                "bytes_per_step": axis_bytes,
            }
    return Plan(topology=topo, local_shape=local, fields_bytes=fields,
                psi_bytes=psi, drude_bytes=drude, inc_bytes=inc,
                coeff_bytes=coeff, halo_bytes_per_step=halo,
                n_chips=int(np.prod(topo)), halo_by_axis=by_axis)


def plan_for_topology(cfg, topology: Tuple[int, int, int]) -> Plan:
    """plan() with a FORCED (px, py, pz) decomposition — the comm lane
    (fdtd3d_tpu/costs.py) models specific topologies rather than the
    auto heuristic's pick."""
    from fdtd3d_tpu.config import ParallelConfig
    topology = tuple(int(p) for p in topology)
    cfg = dataclasses.replace(
        cfg, parallel=ParallelConfig(topology="manual",
                                     manual_topology=topology))
    return plan(cfg, n_devices=int(np.prod(topology)))


# ---------------------------------------------------------------------------
# topology ladder (topology-elastic durable runs, docs/ROBUSTNESS.md)
# ---------------------------------------------------------------------------


def degrade_topology(topology: Tuple[int, int, int]
                     ) -> Optional[Tuple[int, int, int]]:
    """One rung down the topology ladder: the next SMALLER valid
    decomposition, or None at the unsharded bottom.

    The rung shrinks the largest per-axis factor to its largest proper
    divisor (first such axis on ties) — e.g. (2,2,2) -> (1,2,2) ->
    (1,1,2) -> (1,1,1) -> None. Divisibility is preserved by
    construction: any divisor of a factor that divided the grid still
    divides it, so every rung is a valid topology for the same grid.
    The supervisor walks this ladder when recovery on the current
    topology is exhausted (lost chip, shrunken allocation), resuming
    via the reshard-on-resume checkpoint path."""
    t = [int(p) for p in topology]
    mx = max(t)
    if mx <= 1:
        return None
    a = t.index(mx)
    for d in range(mx // 2, 0, -1):
        if mx % d == 0:
            t[a] = d
            break
    return tuple(t)


def fits_devices(topology: Tuple[int, int, int], n_devices: int) -> bool:
    """Whether a decomposition can map onto ``n_devices`` chips."""
    return int(np.prod([int(p) for p in topology])) <= int(n_devices)


def shrink_to_devices(topology: Tuple[int, int, int], n_devices: int
                      ) -> Tuple[int, int, int]:
    """Walk the topology ladder until the decomposition fits the
    available device count (shrunken-allocation resume): returns the
    first rung with at most ``n_devices`` chips — at worst (1, 1, 1),
    which always fits."""
    topo: Optional[Tuple[int, int, int]] = tuple(int(p)
                                                 for p in topology)
    while topo is not None and not fits_devices(topo, n_devices):
        topo = degrade_topology(topo)
    return topo if topo is not None else (1, 1, 1)
