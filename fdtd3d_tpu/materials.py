"""Material grids evaluated at Yee-staggered component positions.

Reference parity: ``Scheme::initGrids`` material fills (SURVEY.md §2 —
uniform, spherical inclusions like ``--eps-sphere``, or loaded from file)
and the dispersive OmegaPE/GammaE grids of the Drude update.

Memory-conscious design: a uniform material evaluates to a python float
(broadcast by XLA at trace time — zero HBM), only spatially-varying
materials materialize full 3D arrays. Positions are taken at each
component's own staggered location (layout.YEE_OFFSETS), matching the
reference's per-component material sampling.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

from fdtd3d_tpu.layout import YEE_OFFSETS

Material = Union[float, np.ndarray]


def _positions(comp: str, shape, active_axes):
    """Broadcastable (px, py, pz) position arrays, in cell units."""
    off = YEE_OFFSETS[comp]
    out = []
    for a in range(3):
        n = shape[a]
        p = np.arange(n, dtype=np.float64) + (off[a] if n > 1 else 0.0)
        bshape = [1, 1, 1]
        bshape[a] = n
        out.append(p.reshape(bshape))
    return out


def _sphere_mask(comp, shape, active_axes, sphere):
    px, py, pz = _positions(comp, shape, active_axes)
    d2 = 0.0
    for a, p in enumerate((px, py, pz)):
        if a in active_axes:
            d2 = d2 + (p - sphere.center[a]) ** 2
    return d2 <= sphere.radius ** 2


def _load_bmp_grid(path: str, shape, active_axes, base: float) -> np.ndarray:
    """Material grid from a BMP image (reference BMPLoader init path).

    Luminance maps linearly: black -> 1.0 (vacuum), white -> ``base``
    (the configured background value). The image spans the first two
    active axes — columns = first axis, rows = second (the same layout
    dump_bmp writes) — and is broadcast along the third.
    """
    from fdtd3d_tpu import io
    axes = [a for a in range(3) if a in active_axes]
    if len(axes) < 2:
        raise ValueError(
            "BMP material init needs a scheme with >= 2 active axes")
    a, b = axes[0], axes[1]
    lum = io.load_bmp_gray(path)
    if lum.shape != (shape[b], shape[a]):
        raise ValueError(
            f"{path}: image is {lum.shape[1]}x{lum.shape[0]} (WxH) but the "
            f"grid needs {shape[a]}x{shape[b]}")
    vals = 1.0 + (float(base) - 1.0) * lum.T      # (na, nb)
    shp = [1, 1, 1]
    shp[a], shp[b] = shape[a], shape[b]
    grid = np.empty(shape, dtype=np.float64)
    grid[:] = vals.reshape(shp)                   # broadcast along 3rd axis
    return grid


def _load_file(path: str, shape, active_axes=(0, 1, 2),
               base: float = 1.0) -> np.ndarray:
    if path.endswith(".bmp"):
        return _load_bmp_grid(path, shape, active_axes, base)
    arr = np.load(path) if path.endswith(".npy") else np.fromfile(
        path, dtype=np.float64).reshape(shape)
    return np.broadcast_to(arr, shape).astype(np.float64)


def scalar_or_grid(comp: str, shape, active_axes, base: float,
                   sphere, file_path: Optional[str]) -> Material:
    """Evaluate one material channel at ``comp``'s staggered positions."""
    if file_path:
        return _load_file(file_path, shape, active_axes, base)
    if sphere is not None and sphere.enabled and sphere.radius > 0:
        grid = np.full(shape, base, dtype=np.float64)
        grid[_sphere_mask(comp, shape, active_axes, sphere)] = sphere.value
        return grid
    return float(base)


def drude_params(comp: str, shape, active_axes, mat,
                 magnetic: bool = False) -> tuple:
    """(omega_p, gamma, region_is_uniform) at comp positions.

    When the (electric or magnetic) drude sphere is enabled the plasma is
    confined to it (omega_p = 0 outside); otherwise the whole domain is
    dispersive. ``magnetic=True`` selects the OmegaPM/GammaM analog
    (reference metamaterial mode).
    """
    sphere = mat.drude_m_sphere if magnetic else mat.drude_sphere
    wp0 = mat.omega_pm if magnetic else mat.omega_p
    g = mat.gamma_m if magnetic else mat.gamma
    if sphere.enabled and sphere.radius > 0:
        wp = np.zeros(shape, dtype=np.float64)
        wp[_sphere_mask(comp, shape, active_axes, sphere)] = wp0
        return wp, float(g), False
    return float(wp0), float(g), True


def merge_drude_eps(eps: Material, omega_p, eps_inf: float) -> Material:
    """Background eps_r is eps_inf wherever the Drude plasma is active."""
    if np.isscalar(omega_p):
        return float(eps_inf) if omega_p > 0 else eps
    grid = np.asarray(np.broadcast_to(np.asarray(eps, dtype=np.float64),
                                      omega_p.shape)).copy()
    grid[omega_p > 0] = eps_inf
    return grid
