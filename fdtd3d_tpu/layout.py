"""Yee-grid layout: scheme modes, component staggering, wall masks.

This is the staggering authority — the TPU-native replacement for the
reference's ``Source/Layout/YeeGridLayout.h`` (SURVEY.md §2): where each of
Ex/Ey/Ez/Hx/Hy/Hz lives relative to the cell corner (E_CENTERED layout), and
which components/axes are active for each of the 13 scheme modes
(reference ``SchemeType`` explicit template instantiations, SURVEY.md §2
"SchemeType / dim modes").

Design difference vs the reference (deliberate, TPU-first): instead of 13
compile-time template instantiations and stored coordinate objects, every
mode runs through ONE generic 3D kernel. Arrays are always rank-3
``(Nx, Ny, Nz)``; an inactive axis has size 1 and its spatial derivative is
identically zero; inactive field components simply do not exist in the state
pytree. XLA folds the singleton dims away, so a 1D solve compiles to true 1D
code.

Yee staggering (offsets in units of the cell, E_CENTERED):

    Ex at (i+1/2, j,     k    )     Hx at (i,     j+1/2, k+1/2)
    Ey at (i,     j+1/2, k    )     Hy at (i+1/2, j,     k+1/2)
    Ez at (i,     j,     k+1/2)     Hz at (i+1/2, j+1/2, k    )

E components sit at INTEGER positions along their transverse axes (the axes
they are differentiated along), H components at HALF positions — this drives
which of the two staggered CPML coefficient sets each psi update uses.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

E_COMPONENTS = ("Ex", "Ey", "Ez")
H_COMPONENTS = ("Hx", "Hy", "Hz")
ALL_COMPONENTS = E_COMPONENTS + H_COMPONENTS

AXIS_NAMES = ("x", "y", "z")

# Yee offsets of each component, in cell units, E_CENTERED layout.
YEE_OFFSETS: Dict[str, Tuple[float, float, float]] = {
    "Ex": (0.5, 0.0, 0.0),
    "Ey": (0.0, 0.5, 0.0),
    "Ez": (0.0, 0.0, 0.5),
    "Hx": (0.0, 0.5, 0.5),
    "Hy": (0.5, 0.0, 0.5),
    "Hz": (0.5, 0.5, 0.0),
}

# curl structure: component c's update couples the two other axes.
# E-update (Ampere):  dEc/dt ~ +dH[b]/da - dH[a]/db   for (c,a,b) cyclic
# H-update (Faraday): dHc/dt ~ -(+dE[b]/da - dE[a]/db)
# Concretely, with axis indices (0,1,2) and cyclic triples:
#   curl_x(F) = dFz/dy - dFy/dz
#   curl_y(F) = dFx/dz - dFz/dx
#   curl_z(F) = dFy/dx - dFx/dy
# CURL_TERMS[c] = ((axis_of_derivative, source_component, sign), ...)
CURL_TERMS: Dict[int, Tuple[Tuple[int, int, int], ...]] = {
    0: ((1, 2, +1), (2, 1, -1)),  # x: +d(comp z)/dy - d(comp y)/dz
    1: ((2, 0, +1), (0, 2, -1)),  # y: +d(comp x)/dz - d(comp z)/dx
    2: ((0, 1, +1), (1, 0, -1)),  # z: +d(comp y)/dx - d(comp x)/dy
}


@dataclasses.dataclass(frozen=True)
class SchemeMode:
    """One of the 13 solver modes (reference SchemeType)."""

    name: str
    e_components: Tuple[str, ...]
    h_components: Tuple[str, ...]
    active_axes: Tuple[int, ...]  # axes with spatial variation

    @property
    def ndim(self) -> int:
        return len(self.active_axes)

    @property
    def components(self) -> Tuple[str, ...]:
        return self.e_components + self.h_components

    def grid_shape(self, size: Tuple[int, int, int]) -> Tuple[int, int, int]:
        """Always-rank-3 shape; inactive axes collapse to 1."""
        return tuple(
            size[a] if a in self.active_axes else 1 for a in range(3)
        )


def _mode(name, e, h, axes):
    return SchemeMode(name, tuple(e), tuple(h), tuple(axes))


# The 13 modes, matching the reference's SchemeType enumeration
# (SURVEY.md §2: 1D {Ex_Hy, Ex_Hz, Ey_Hx, Ey_Hz, Ez_Hx, Ez_Hy},
#  2D {TEx, TEy, TEz, TMx, TMy, TMz}, 3D).
# 1D propagation axis = the axis completing the E/H right-handed pair.
# 2D TM_a: E along a + the two H transverse; TE_a: H along a + two E.
SCHEME_MODES: Dict[str, SchemeMode] = {
    m.name: m
    for m in [
        # --- 1D (one active axis) ---
        _mode("1D_ExHy", ["Ex"], ["Hy"], [2]),  # varies along z
        _mode("1D_ExHz", ["Ex"], ["Hz"], [1]),  # varies along y
        _mode("1D_EyHx", ["Ey"], ["Hx"], [2]),  # varies along z
        _mode("1D_EyHz", ["Ey"], ["Hz"], [0]),  # varies along x
        _mode("1D_EzHx", ["Ez"], ["Hx"], [1]),  # varies along y
        _mode("1D_EzHy", ["Ez"], ["Hy"], [0]),  # varies along x
        # --- 2D (two active axes) ---
        _mode("2D_TMx", ["Ex"], ["Hy", "Hz"], [1, 2]),
        _mode("2D_TMy", ["Ey"], ["Hx", "Hz"], [0, 2]),
        _mode("2D_TMz", ["Ez"], ["Hx", "Hy"], [0, 1]),
        _mode("2D_TEx", ["Ey", "Ez"], ["Hx"], [1, 2]),
        _mode("2D_TEy", ["Ex", "Ez"], ["Hy"], [0, 2]),
        _mode("2D_TEz", ["Ex", "Ey"], ["Hz"], [0, 1]),
        # --- 3D ---
        _mode("3D", list(E_COMPONENTS), list(H_COMPONENTS), [0, 1, 2]),
    ]
}


def get_mode(name: str) -> SchemeMode:
    try:
        return SCHEME_MODES[name]
    except KeyError:
        raise ValueError(
            f"unknown scheme mode {name!r}; one of {sorted(SCHEME_MODES)}"
        ) from None


def component_axis(comp: str) -> int:
    """0/1/2 for the vector direction of a component name like 'Ex'."""
    return AXIS_NAMES.index(comp[1])


def transverse_axes(comp: str) -> Tuple[int, int]:
    a = component_axis(comp)
    return tuple(x for x in range(3) if x != a)


def stagger_offset(comp: str, axis: int) -> float:
    return YEE_OFFSETS[comp][axis]
