"""Tracing/profiling + runtime guards.

Reference parity (SURVEY.md §5.1, §5.2): the reference instruments
wall-clock compute-vs-share time per rank with its ``Clock`` class (it
exists to feed DYNAMIC_GRID rebalancing, which is a deliberate non-goal
on homogeneous SPMD chips) and leans on ASSERT macros for correctness.
Here:

* ``StepClock`` — per-chunk wall timings + throughput. Wiring:
  ``Simulation.__init__`` attaches one as ``sim.clock`` when
  ``OutputConfig.profile`` is set, and ``Simulation.advance`` then
  brackets every chunk with a device sync to take honest timings
  (tests/test_profiling.py).
* ``trace()`` — context manager around ``jax.profiler.trace`` producing
  a TensorBoard/XProf trace with the compute/collective breakdown (the
  modern equivalent of the reference's compute-vs-share printout).
* ``TraceCapture`` / ``device_trace()`` — the crash-safe device-trace
  lane (round 7): explicit start/stop so ``Simulation.close()`` (held
  in try/finally by the CLI and bench) finalizes the capture on every
  exit, degrading to a warned no-op when no profiler/chip is present.
  Wiring: ``OutputConfig.profile_dir`` / CLI ``--profile DIR`` /
  ``FDTD3D_BENCH_PROFILE``; parse with ``tools/trace_attribution.py``.
* ``assert_finite`` / ``finite_check`` — NaN/Inf tripwires over the
  whole state pytree (the functional stand-in for the reference's
  ASSERT; races are structurally absent in JAX). Wiring:
  ``Simulation.advance`` calls ``assert_finite`` after every chunk when
  ``OutputConfig.check_finite`` is set.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np


# THE shared per-chunk percentile helper (p50/p95/max) lives in
# telemetry.py (numpy-only import surface, so the jsonl tools can use
# it without paying a jax import); StepClock.summary, tools/
# telemetry_report.py, bench's chunk_stats (via StepClock) and the
# fleet rollups (tools/fleet_report.py) all compute through it, so
# fleet-level and per-run percentiles provably cannot drift.
from fdtd3d_tpu.telemetry import pct_summary  # noqa: F401,E402


@dataclasses.dataclass
class ChunkRecord:
    steps: int
    seconds: float
    cells: float

    @property
    def mcells_per_s(self) -> float:
        return self.cells * self.steps / self.seconds / 1e6


class StepClock:
    """Wall-clock per advance() chunk (the reference Clock's successor)."""

    def __init__(self):
        self.records: List[ChunkRecord] = []

    def record(self, steps: int, seconds: float, cells: float):
        self.records.append(ChunkRecord(steps, seconds, cells))

    @property
    def total_steps(self) -> int:
        return sum(r.steps for r in self.records)

    @property
    def total_seconds(self) -> float:
        return sum(r.seconds for r in self.records)

    def summary(self) -> Dict[str, float]:
        """Aggregate + per-chunk Mcells/s percentiles (p50/p95/max).

        The percentiles are the long-run health view a single mean
        hides: a throughput regression confined to a few chunks (tunnel
        throttling, a VMEM-ladder downgrade mid-run) shows up as a
        p95/max gap while the mean barely moves. bench.py embeds this
        dict in the BENCH json; telemetry run_end records derive the
        same numbers from the per-chunk JSONL."""
        if not self.records:
            return {"steps": 0, "seconds": 0.0, "mcells_per_s": 0.0,
                    "best_mcells_per_s": 0.0, "chunks": 0,
                    "p50_mcells_per_s": 0.0, "p95_mcells_per_s": 0.0,
                    "max_mcells_per_s": 0.0}
        pct = pct_summary([r.mcells_per_s for r in self.records])
        return {
            "steps": self.total_steps,
            "seconds": self.total_seconds,
            "chunks": len(self.records),
            "mcells_per_s": (sum(r.cells * r.steps for r in self.records)
                             / self.total_seconds / 1e6),
            "best_mcells_per_s": max(r.mcells_per_s for r in self.records),
            "p50_mcells_per_s": pct["p50"],
            "p95_mcells_per_s": pct["p95"],
            "max_mcells_per_s": pct["max"],
        }

    def report(self) -> str:
        s = self.summary()
        return (f"{s['steps']} steps in {s['seconds']:.3f}s — "
                f"{s['mcells_per_s']:.1f} Mcells/s over {s['chunks']} "
                f"chunks (p50 {s['p50_mcells_per_s']:.1f} / p95 "
                f"{s['p95_mcells_per_s']:.1f} / max "
                f"{s['max_mcells_per_s']:.1f})")


@contextlib.contextmanager
def trace(log_dir: str):
    """jax.profiler trace around a block: XProf shows the per-step HLO
    timeline incl. the ppermute halo collectives vs stencil compute."""
    with jax.profiler.trace(log_dir):
        yield


class TraceCapture:
    """Crash-safe ``jax.profiler`` capture with degrade-to-skip.

    The device-trace lane of the attribution layer (round 7): start()
    begins a jax.profiler trace into ``log_dir``; stop() finalizes it.
    Both are idempotent, and BOTH degrade to a warned no-op when the
    profiler is unavailable or the backend refuses to trace (no chip,
    tunneled backend without profiler support) — a simulation must
    never die because its observability could not attach
    (``ok`` reports whether a capture is actually live). Callers hold
    stop() in a try/finally so a crash mid-capture still finalizes the
    trace directory (the same guarantee the telemetry sink gives its
    run_end record); ``Simulation.close()`` and the CLI/bench wrappers
    do exactly that. Parse the result with
    ``tools/trace_attribution.py``.
    """

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self.ok = False
        self._failed = False

    def start(self) -> bool:
        if self.ok or self._failed:
            return self.ok
        from fdtd3d_tpu import log as _log
        try:
            import os
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self.ok = True
        except Exception as exc:  # degrade: no profiler / no chip
            self._failed = True
            _log.warn(f"device-trace capture unavailable "
                      f"({str(exc)[:120]}); continuing without a trace")
        return self.ok

    def stop(self) -> None:
        if not self.ok:
            return
        self.ok = False
        from fdtd3d_tpu import log as _log
        try:
            jax.profiler.stop_trace()
            _log.log(f"device trace -> {self.log_dir} (attribute with "
                     f"tools/trace_attribution.py)")
        except Exception as exc:  # pragma: no cover - backend hiccup
            self._failed = True
            _log.warn(f"device-trace stop failed ({str(exc)[:120]})")


@contextlib.contextmanager
def device_trace(log_dir: str):
    """try/finally wrapper around TraceCapture: the capture is always
    finalized (or cleanly skipped), even when the block raises."""
    cap = TraceCapture(log_dir)
    cap.start()
    try:
        yield cap
    finally:
        cap.stop()


def finite_check(state) -> Dict[str, bool]:
    """{path: all_finite} over every array leaf of the state pytree."""
    out = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    for path, leaf in flat:
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.inexact):
            name = jax.tree_util.keystr(path)
            out[name] = bool(jnp.isfinite(leaf).all())
    return out


def assert_finite(state, context: str = ""):
    """Raise FloatingPointError naming the offending components."""
    bad = [k for k, ok in finite_check(state).items() if not ok]
    if bad:
        where = f" at {context}" if context else ""
        raise FloatingPointError(
            f"non-finite field values{where}: {', '.join(sorted(bad))} "
            f"(check the Courant factor / Drude stability bound)")
