"""vmap-batched scenario execution: B same-shape runs, ONE dispatch.

ROADMAP item 2b (the millions-of-users direction): many tenants'
same-shape jobs share hardware by stacking their states, coefficient
pytrees and source parameters under a leading batch axis and running
the PRODUCTION chunk runner through ``jax.vmap`` — one compiled
executable (cached by :mod:`fdtd3d_tpu.exec_cache`, keyed with the
batch width), one dispatch per chunk, and on a sharded mesh one halo
exchange per step for the whole batch (the ppermute operands simply
carry the lane axis). Per-lane arithmetic is the unbatched step's,
bit-for-bit (tests/test_batch.py asserts 3-lane == 3 sequential runs
on CPU), and the in-graph health counters reduce per lane, so one
tenant's NaN trips only its own lane's health flag.

Batching eligibility (docs/SERVICE.md has the full table): every lane
must share the graph-shaping config
(:meth:`fdtd3d_tpu.scenario.ScenarioSpec.batch_fingerprint` — grid,
scheme, dtype, steps, PML, TFSF geometry, source position/waveform,
topology...); lanes may differ in material VALUES (coefficients are
traced arguments) and point-source amplitude (threaded through the
traced ``ps_amp`` coefficient).

Lane-capable packed kernels: when the shared config is in packed
scope (``solver.batch_fallback_reason`` returns None — THE batch
dispatch authority), the batch vmaps the PACKED chunk runner
(pallas_packed / pallas_packed_tb): pallas_call's vmap batching rule
prepends a lane-major grid dimension over the same VMEM rings, so B
lanes pay packed-kernel per-lane HBM cost (~12 volumes/step, or
~48/k B/cell temporal-blocked) instead of the ~6x-slower jnp step's.
The carry is then the stacked PACKED pytree; pack/unpack are vmapped
once at init. Ineligible batches fall back to the vmap-jnp path with
a machine-readable ``batch_unsupported:<token>`` recorded in
telemetry run_start and the CLI step-kind line — never silently.
Tokens: ``pallas_disabled``, ``env:FDTD3D_NO_PACKED``,
``env:FDTD3D_FORCE_FUSED``, ``kernel_ineligible``,
``scalar_coeff_divergence`` (the packed kernels BAKE scalar
coefficients: lanes diverging in a scalar — e.g. uniform eps 1.0 vs
1.5 — must ride jnp; material GRIDS and source amplitudes batch on
the packed path freely), ``vmem_exhausted`` (the runtime lanes
ladder ran dry). Structure-level divergence between lanes (a sphere
turning a scalar coefficient into a grid, a Drude flag adding J
state) is caught leaf-by-leaf at stack time with the offending key
named. ``FDTD3D_BATCH_MAX`` bounds the lane count.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from fdtd3d_tpu import faults as _faults
from fdtd3d_tpu import telemetry as _telemetry
from fdtd3d_tpu.scenario import ScenarioSpec, batch_fingerprint_diff

BATCH_MAX_DEFAULT = 16


def batch_max() -> int:
    """Lane-count bound (``FDTD3D_BATCH_MAX``; default 16): vmap is
    linear in lanes for both HBM and compile-time, so an unbounded
    batch is an OOM with extra steps. Non-numeric values are a named
    config error."""
    v = os.environ.get("FDTD3D_BATCH_MAX")
    if not v:
        return BATCH_MAX_DEFAULT
    try:
        return int(v)
    except ValueError:
        raise ValueError(f"FDTD3D_BATCH_MAX={v!r}: must be an "
                         f"integer lane count") from None


def _stack_trees(trees: List[Dict], what: str):
    """np.stack a list of pytrees along a new leading lane axis,
    naming the first structurally-divergent leaf (the batch
    eligibility backstop for everything shapes can catch)."""
    import jax

    t0 = jax.tree.structure(trees[0])
    for i, t in enumerate(trees[1:], start=1):
        ti = jax.tree.structure(t)
        if ti != t0:
            raise ValueError(
                f"batch lanes are not same-shape: lane {i}'s {what} "
                f"tree structure differs from lane 0's ({ti} vs "
                f"{t0}) — material/source STRUCTURE (Drude flags, "
                f"grids vs scalars) must match across the batch")
    leaves0, _ = jax.tree_util.tree_flatten_with_path(trees[0])
    for i, t in enumerate(trees[1:], start=1):
        leaves_i = jax.tree_util.tree_flatten_with_path(t)[0]
        for (path, a), (_p, b) in zip(leaves0, leaves_i):
            if np.shape(a) != np.shape(b) or \
                    np.asarray(a).dtype != np.asarray(b).dtype:
                raise ValueError(
                    f"batch lanes are not same-shape: {what} leaf "
                    f"{jax.tree_util.keystr(path)} is "
                    f"{np.shape(b)}/{np.asarray(b).dtype} in lane "
                    f"{i} vs {np.shape(a)}/{np.asarray(a).dtype} in "
                    f"lane 0 (a sphere/file turning a scalar "
                    f"coefficient into a grid must do so in EVERY "
                    f"lane)")
    return jax.tree.map(lambda *xs: np.stack([np.asarray(x)
                                              for x in xs]), *trees)


class BatchSimulation:
    """B same-shape scenarios advancing under one compiled executable.

    The state pytree carries a leading lane axis on every leaf;
    ``lane_state(i)`` unstacks one tenant's view. Health is per lane:
    ``lane_finite[i]`` / ``lane_first_unhealthy_t[i]`` — a NaN in one
    lane NEVER raises (the other tenants' results must survive), it
    flips that lane's flag and keeps going (docs/SERVICE.md runbook).
    """

    def __init__(self, cfgs, devices: Optional[List] = None):
        from fdtd3d_tpu.parallel import mesh as pmesh
        from fdtd3d_tpu.solver import make_chunk_runner

        specs = [c if isinstance(c, ScenarioSpec) else ScenarioSpec(c)
                 for c in cfgs]
        if not specs:
            raise ValueError("batch needs at least one scenario")
        limit = batch_max()
        if len(specs) > limit:
            raise ValueError(
                f"batch of {len(specs)} lanes exceeds the "
                f"FDTD3D_BATCH_MAX bound ({limit}); split the batch "
                f"or raise the knob")
        fp0 = specs[0].batch_fingerprint()
        for i, sp in enumerate(specs[1:], start=1):
            diff = batch_fingerprint_diff(fp0, sp.batch_fingerprint())
            if diff:
                raise ValueError(
                    f"batch lanes 0 and {i} differ in the "
                    f"graph-shaping config field {diff}; only "
                    f"material values, source amplitude and output "
                    f"settings may vary across a batch "
                    f"(docs/SERVICE.md eligibility table)")
        self.specs = specs
        self.batch_size = len(specs)
        _faults.load_env()
        cfg0 = specs[0].cfg
        self.cfg = cfg0
        if cfg0.ds_fields:
            raise ValueError(
                "float32x2 scenarios do not batch on this jax: the "
                "double-single step's error-free transforms pin "
                "evaluation order with lax.optimization_barrier, "
                "which has no vmap batching rule here — run ds "
                "scenarios solo (docs/SERVICE.md limits)")
        base_static = _build_static(cfg0)
        if base_static.paired_complex:
            raise ValueError(
                "batched execution does not support the paired-"
                "complex path (its complex<->paired conversion routes "
                "through host numpy, which cannot run under vmap); "
                "run complex batches on a backend with native complex")
        topo = pmesh.resolve_topology(
            cfg0.parallel, base_static.grid_shape,
            base_static.mode.active_axes,
            n_devices=len(devices or _devices()))
        self.topology = topo
        self.static = dataclasses.replace(base_static, topology=topo)
        self.mesh = None
        mesh_axes = mesh_shape = None
        if any(p > 1 for p in topo):
            self.mesh = pmesh.build_mesh(topo, devices)
            mesh_axes = pmesh.mesh_axis_map(topo)
            mesh_shape = pmesh.mesh_shape_map(topo)
        self._mesh_axes, self._mesh_shape = mesh_axes, mesh_shape
        out0 = cfg0.output
        self._health_on = bool(out0.telemetry_path) \
            or bool(out0.metrics_path) or out0.check_finite
        self._check_finite = out0.check_finite
        # Per-chip lane INSIDE lanes (ROADMAP item 2 remainder; the
        # batch used to hardwire per_chip=False): the un-psummed
        # per-chip counters ride the same single fused readback, per
        # lane — vmap prepends the lane axis to the all_gathered
        # vectors, so each lane names its own straggler chip.
        self._per_chip_on = self._health_on \
            and bool(out0.per_chip_telemetry) \
            and bool(out0.telemetry_path)

        # Per-lane states + coefficients (stacked along the lane axis
        # below). Each lane's coeffs come from ITS config (material
        # values / ps_amp differ); the static layout is the shared one.
        # Built BEFORE the runner: the dispatch authority's scalar
        # sweep reads the host coefficient dicts.
        lane_statics = [
            dataclasses.replace(_build_static(sp.cfg), topology=topo)
            for sp in specs]
        lane_coeffs = [sp.build_coeffs(st)
                       for sp, st in zip(specs, lane_statics)]
        lane_states = [sp.init_state(st)
                       for sp, st in zip(specs, lane_statics)]

        # THE batch dispatch authority (solver.batch_fallback_reason):
        # None => the lane-capable packed build (vmap over the packed
        # chunk runner — packed-kernel HBM cost per lane); a token =>
        # the vmap-jnp path with use_pallas pinned off for the SHARED
        # build only (the per-lane configs are untouched), recorded as
        # batch_unsupported:<token> in run_start and the CLI line.
        from fdtd3d_tpu import solver as _solver
        token = _solver.batch_fallback_reason(
            self.static, mesh_axes, lane_coeffs=lane_coeffs,
            batch=self.batch_size)
        self.batch_fallback: Optional[str] = \
            None if token is None else f"batch_unsupported:{token}"
        if token is not None:
            self.static = dataclasses.replace(
                _build_static(dataclasses.replace(cfg0,
                                                  use_pallas=False)),
                topology=topo)
        runner = make_chunk_runner(
            self.static, mesh_axes, mesh_shape, health=self._health_on,
            per_chip=self._per_chip_on,
            batch=self.batch_size if token is None else 0)
        self._runner = runner
        self.step_kind = runner.kind
        self.step_diag = getattr(runner, "diag", None)
        self._runner_health = getattr(runner, "health", False)
        self._packed = bool(getattr(runner, "packed", False))

        coeffs_np = _stack_trees(lane_coeffs, "coeffs")
        states_np = _stack_trees(lane_states, "state")
        if self.mesh is not None:
            import jax

            state_sh = jax.eval_shape(
                lambda: specs[0].init_state(self.static))
            self._state_specs = _prepend_specs(
                pmesh.state_specs(state_sh, topo))
            lane0_coeffs = specs[0].build_coeffs(self.static)
            self._coeff_specs = _prepend_specs(
                pmesh.coeff_specs(lane0_coeffs, topo))
            dstate = pmesh.shard_tree(states_np, self._state_specs,
                                      self.mesh)
            self._coeffs = pmesh.shard_tree(coeffs_np,
                                            self._coeff_specs,
                                            self.mesh)
        else:
            import jax.numpy as jnp
            import jax
            self._state_specs = self._coeff_specs = None
            dstate = jax.tree.map(jnp.asarray, states_np)
            self._coeffs = jax.tree.map(jnp.asarray, coeffs_np)
        # the carry: the stacked PACKED pytree on the lane-capable
        # path (pack once at init, unpack lazily for host views), the
        # stacked dict form on jnp
        self._pspecs = None
        self._bind_pack(runner)
        if self._packed:
            self._state = self._pack_fn(dstate)
            self._dstate = None
        else:
            self._state = dstate
            self._dstate = dstate

        self._cells = float(np.prod(
            [self.static.grid_shape[a]
             for a in self.static.mode.active_axes]))
        self._compiled: Dict[int, Any] = {}
        self._compile_ms = 0.0
        self._t_host = 0
        self._chunk_idx = 0
        self._closed = False
        # Causal trace plane (schema v9): the queue dispatcher stamps
        # the coalesce-group id + one {trace_id, span_id,
        # parent_span_id} dict per lane AFTER construction
        # (jobqueue._dispatch_batch); solo run_batch calls leave them
        # None and the batch emits no spans. The GROUP-level
        # trace_id/span_id land on self via registry.attach below
        # (the leader's trace under job_context).
        self.lane_traces: Optional[List[Optional[Dict[str, str]]]] = \
            None
        self.group_id: Optional[str] = None
        # per-lane health ledger: None = never measured, True/False =
        # last chunk's finite flag; first unhealthy t bound per lane
        self.lane_finite: List[Optional[bool]] = \
            [None] * self.batch_size
        self.lane_first_unhealthy_t: List[Optional[int]] = \
            [None] * self.batch_size
        # fleet run registry + OpenMetrics exposition: the same two
        # service-observability lanes Simulation wires (a batch is one
        # run of kind "batch"; its lanes are the tenants)
        from fdtd3d_tpu import registry as _registry
        self.run_id: Optional[str] = None
        self.run_registry = _registry.RunHandle.open_for(
            self, kind="batch")
        self.metrics = None
        if out0.metrics_path:
            from fdtd3d_tpu import metrics as _metrics
            self.metrics = _metrics.MetricsRegistry(
                path=out0.metrics_path)
        self.telemetry: Optional[_telemetry.TelemetrySink] = None
        if out0.telemetry_path or out0.metrics_path:
            self.telemetry = _telemetry.TelemetrySink(
                out0.telemetry_path or None,
                run_meta=_telemetry.provenance(self),
                metrics=self.metrics)
        # Live-health heartbeats (schema v10, Simulation pattern):
        # one "run" emitter for the whole coalesced batch — lane
        # attribution stays on the batch_lane rows.
        import jax as _jax
        self._heartbeat = _telemetry.Heartbeater.maybe(
            out0.telemetry_path
            if _jax.process_index() == 0 else None, "run")

    def _bind_pack(self, runner):
        """(Re)build the vmapped pack/unpack plumbing for a packed
        runner (no-op on jnp). Mirrors Simulation._bind_runner: under
        a mesh, pack/unpack are per-shard functions running inside
        shard_map with lane-prepended packed specs inferred from the
        packed pytree's ranks — the spec TREE depends only on the
        carry structure, so a VMEM-ladder rebuild reuses the one
        computed at init."""
        import jax
        self._pack_fn = self._unpack_fn = None
        if not self._packed:
            return
        pack = jax.vmap(runner.pack)
        unpack = jax.vmap(runner.unpack)
        if self.mesh is not None:
            from fdtd3d_tpu.parallel import mesh as pmesh
            from fdtd3d_tpu.parallel.mesh import shard_map_compat
            if self._pspecs is None:
                state_sh = jax.eval_shape(
                    lambda: self.specs[0].init_state(self.static))
                packed_sh = jax.eval_shape(runner.pack, state_sh)
                self._pspecs = _prepend_specs(
                    pmesh.packed_specs(packed_sh, self.topology))
            pack = shard_map_compat(pack, self.mesh,
                                    in_specs=(self._state_specs,),
                                    out_specs=self._pspecs)
            unpack = shard_map_compat(unpack, self.mesh,
                                      in_specs=(self._pspecs,),
                                      out_specs=self._state_specs)
        self._pack_fn = jax.jit(pack)
        self._unpack_fn = jax.jit(unpack)

    # -- compile (through the AOT executable cache) ------------------------

    def exec_key(self, n: int, donate: Optional[bool] = None):
        """The canonical :class:`fdtd3d_tpu.exec_cache.ExecKey` of
        this batch's ``n``-step chunk executable (batch width in the
        key) — what ``_chunk_fn`` compiles under, and what the run
        registry records at the ``n=0`` sentinel
        (``exec_cache.registry_identity``)."""
        import jax

        from fdtd3d_tpu import exec_cache as _exec_cache
        if donate is None:
            donate = jax.default_backend() in ("tpu", "axon")
        return _exec_cache.make_key(
            self.cfg, step_kind=self.step_kind, topology=self.topology,
            n_steps=n, health=self._runner_health,
            per_chip=bool(getattr(self._runner, "per_chip", False)),
            step_diag=self.step_diag, batch=self.batch_size,
            donate=donate,
            avals_fp=_exec_cache.avals_fingerprint(self._state,
                                                   self._coeffs),
            devices=_exec_cache.mesh_device_ids(self.mesh))

    def _chunk_fn(self, n: int):
        import jax

        from fdtd3d_tpu import exec_cache as _exec_cache
        from fdtd3d_tpu.parallel.mesh import shard_map_compat

        while n not in self._compiled:
            # vmap INSIDE shard_map: the lane axis rides every operand,
            # so each halo ppermute moves ONE message of B stacked
            # planes per step — the whole batch shares the exchange,
            # not B of them. On the lane-capable path the vmapped
            # runner is the PACKED one: pallas_call's vmap batching
            # rule prepends a lane-major grid dimension, and the carry
            # specs are the packed pytree's.
            fn = jax.vmap(functools.partial(self._runner, n=n))
            if self.mesh is not None:
                from jax.sharding import PartitionSpec as P
                carry_specs = self._pspecs if self._packed \
                    else self._state_specs
                out_specs = carry_specs
                if self._runner_health:
                    hspec = {k: P() for k in _telemetry.HEALTH_KEYS}
                    if getattr(self._runner, "per_chip", False):
                        # per-lane per-chip vectors come out of the
                        # vmapped all_gather replicated, lane-leading
                        hspec["per_chip"] = {
                            k: P() for k in _telemetry.PER_CHIP_KEYS}
                    out_specs = (carry_specs, hspec)
                fn = shard_map_compat(fn, self.mesh,
                                      in_specs=(carry_specs,
                                                self._coeff_specs),
                                      out_specs=out_specs)
            donate = jax.default_backend() in ("tpu", "axon")
            key = self.exec_key(n, donate=donate)
            t_sp0 = float(time.time())
            try:
                with _telemetry.span("compile"):
                    compiled, info = _exec_cache.jit_compile(
                        key, fn, lambda: (self._state, self._coeffs),
                        donate)
            except Exception as exc:
                self._vmem_fallback(exc)   # next rung, or re-raise
                continue
            self._compile_ms += float(info.get("compile_ms") or 0.0)
            _telemetry.emit_trace_span(
                self, "compile", t_sp0, float(time.time()),
                attrs={"source": info.get("source"),
                       "compile_ms":
                           float(info.get("compile_ms") or 0.0),
                       "n_steps": int(n)},
                group=self.group_id)
            self._compiled[n] = compiled
        return self._compiled[n]

    def _vmem_fallback(self, exc):
        """The batched lanes ladder, after a COMPILE failure of the
        lane-capable packed executable: rebuild at each smaller VMEM
        budget (Simulation._VMEM_LADDER_MB — smaller x-tile /
        shallower tb depth, the per-lane surcharge still charged), and
        when every packed rung is exhausted rebuild the vmap-jnp
        runner with ``batch_unsupported:vmem_exhausted`` recorded —
        slower, never wrong, and never silent (ladder_downgrade
        telemetry + the warn). Mirrors Simulation._vmem_fallback,
        including routing the live packed carry through the dict form
        (old unpack, new pack — the x-psi stacks are tile-aligned)."""
        from fdtd3d_tpu import log as _log
        from fdtd3d_tpu.ops import pallas_packed
        from fdtd3d_tpu.sim import Simulation
        from fdtd3d_tpu.solver import make_chunk_runner
        if not self._packed:
            raise exc
        kind = self.step_kind
        failed_tile = ((self.step_diag or {}).get("tile")
                       or {}).get("EH")
        ladder = Simulation._VMEM_LADDER_MB
        rung0 = getattr(self, "_vmem_rung", 0)
        old_mb = ladder[rung0 - 1] if rung0 > 0 else None
        old_depth = (self.step_diag or {}).get("temporal_block")
        runner = None
        nxt = 0
        while True:
            rung = getattr(self, "_vmem_rung", 0)
            if rung >= len(ladder):
                break              # dry: the jnp rung below
            self._vmem_rung = rung + 1
            nxt = ladder[rung] << 20
            pallas_packed._RUNTIME_BUDGET = nxt
            try:
                with _telemetry.span("vmem-ladder-rebuild"):
                    runner = make_chunk_runner(
                        self.static, self._mesh_axes, self._mesh_shape,
                        health=self._health_on,
                        per_chip=self._per_chip_on,
                        batch=self.batch_size)
            except RuntimeError:
                # no lane-capable kind fits this budget; smaller rungs
                # cannot fit either — straight to the jnp rung
                runner = None
                break
            finally:
                pallas_packed._RUNTIME_BUDGET = None
            new_kind = getattr(runner, "kind", None)
            new_tile = (runner.diag.get("tile") or {}).get("EH")
            new_depth = (runner.diag or {}).get("temporal_block")
            if new_kind == kind and new_depth == old_depth \
                    and failed_tile is not None \
                    and new_tile is not None \
                    and new_tile >= failed_tile:
                # same-kernel same-depth rebuild at the same/bigger
                # tile would fail again (tb -> packed or a depth
                # downgrade makes tiles incomparable — don't skip)
                runner = None
                continue
            break
        dstate = self._dict_state()   # via the OLD unpack
        if runner is None:
            # every packed rung exhausted: the vmap-jnp fallback, with
            # the token every ineligible batch carries
            self.static = dataclasses.replace(
                _build_static(dataclasses.replace(self.cfg,
                                                  use_pallas=False)),
                topology=self.topology)
            runner = make_chunk_runner(
                self.static, self._mesh_axes, self._mesh_shape,
                health=self._health_on, per_chip=self._per_chip_on)
            self.batch_fallback = "batch_unsupported:vmem_exhausted"
        new_tile = ((getattr(runner, "diag", None) or {}).get("tile")
                    or {}).get("EH")
        new_depth = (getattr(runner, "diag", None)
                     or {}).get("temporal_block")
        _log.warn(
            f"batch: lane-capable packed compile failed at tile "
            f"{failed_tile} ({self.batch_size} lanes); "
            + (f"retrying at tile {new_tile} ({nxt >> 20} MiB VMEM "
               f"budget)" if getattr(runner, "packed", False)
               else "falling back to the vmap-jnp path "
                    "(batch_unsupported:vmem_exhausted)")
            + f". Original error: {str(exc)[:200]}")
        if self.telemetry is not None:
            self.telemetry.emit(
                "ladder_downgrade", t=int(self._t_host),
                old_budget_mb=old_mb,
                new_budget_mb=(nxt >> 20) if getattr(
                    runner, "packed", False) else None,
                old_tile=failed_tile, new_tile=new_tile,
                old_ghost_depth=old_depth, new_ghost_depth=new_depth,
                vmem_rung=int(getattr(self, "_vmem_rung", 0)))
        self._runner = runner
        self.step_kind = runner.kind
        self.step_diag = getattr(runner, "diag", None)
        self._runner_health = getattr(runner, "health", False)
        self._packed = bool(getattr(runner, "packed", False))
        self._bind_pack(runner)
        self._compiled.clear()
        if self._packed:
            self._state = self._pack_fn(dstate)
            self._dstate = None
        else:
            self._state = dstate
            self._dstate = dstate

    # -- stepping ----------------------------------------------------------

    def advance(self, n_steps: int):
        """One compiled chunk for every lane at once. Never raises on
        a lane's NaN — per-lane flags carry the verdict (one tenant
        must not take the batch down); ``check_finite`` turns the trip
        into a loud per-lane warning."""
        import jax

        from fdtd3d_tpu import log as _log
        if n_steps <= 0:
            return self
        fn = self._chunk_fn(n_steps)
        timed = self.telemetry is not None
        wall = 0.0
        t_sp0 = float(time.time())
        if timed:
            jax.block_until_ready(self._state)
            t0 = time.perf_counter()
        with _telemetry.span("chunk"):
            out = fn(self._state, self._coeffs)
        health = None
        if self._runner_health:
            self._state, health = out
        else:
            self._state = out
        self._dstate = None if self._packed else self._state
        if timed:
            jax.block_until_ready(self._state)
            wall = time.perf_counter() - t0
        hv = self._readback(health) if health is not None else None
        t_prev = self._t_host
        self._t_host = t_prev + n_steps
        self._chunk_idx += 1
        _telemetry.emit_trace_span(
            self, "chunk", t_sp0, float(time.time()),
            attrs={"chunk": int(self._chunk_idx),
                   "t": int(self._t_host), "steps": int(n_steps)},
            group=self.group_id)
        if self._heartbeat is not None:
            self._heartbeat.beat(
                t=int(self._t_host), run_id=self.run_id,
                trace_id=getattr(self, "trace_id", None),
                job_id=getattr(self, "job_id", None))
        if hv is not None:
            per = hv.get("per_chip")
            lts = self.lane_traces or []
            tripped = []
            for lane in range(self.batch_size):
                finite = bool(hv["finite"][lane])
                self.lane_finite[lane] = finite
                if not finite and \
                        self.lane_first_unhealthy_t[lane] is None:
                    self.lane_first_unhealthy_t[lane] = self._t_host
                    tripped.append(lane)
                if self.telemetry is not None:
                    tr = lts[lane] if lane < len(lts) else None
                    rec = {
                        "chunk": self._chunk_idx, "t": self._t_host,
                        "lane": lane,
                        "energy": hv["energy"][lane],
                        "div_l2": hv["div_l2"][lane],
                        "div_linf": hv["div_linf"][lane],
                        "max_e": hv["max_e"][lane],
                        "max_h": hv["max_h"][lane], "finite": finite,
                        "trace_id":
                            tr.get("trace_id") if tr else None,
                        "span_id": tr.get("span_id") if tr else None,
                        "parent_span_id":
                            tr.get("parent_span_id") if tr else None,
                    }
                    for key in ("trace_id", "span_id",
                                "parent_span_id"):
                        if rec[key] is None:
                            rec.pop(key)
                    self.telemetry.emit("batch_lane", **rec)
                    if per is not None:
                        # per-lane per-chip lane (ROADMAP item 2
                        # remainder): one per_chip + imbalance row per
                        # LANE per chunk, naming the straggler chip
                        # inside the coalesced group — same single
                        # fused readback, no extra device traffic
                        chips = {k: per[k][lane] for k in per}
                        n_chips = len(chips.get("energy") or ())
                        self.telemetry.emit(
                            "per_chip", chunk=self._chunk_idx,
                            t=self._t_host, lane=lane,
                            group=self.group_id, n_chips=n_chips,
                            counters=chips)
                        imb = _telemetry.imbalance_summary(chips)
                        if imb is not None:
                            self.telemetry.emit(
                                "imbalance", chunk=self._chunk_idx,
                                t=self._t_host, lane=lane,
                                group=self.group_id, **imb)
            if self.telemetry is not None:
                # one aggregate chunk record beside the per-lane rows,
                # so tools/telemetry_report.py's existing summaries
                # (throughput, drift) read batched runs unchanged
                finite_e = [v for v in hv["energy"] if v is not None]
                agg = {
                    "energy": float(sum(finite_e)) if finite_e
                    else None,
                    "div_l2": _agg_max(hv["div_l2"]),
                    "div_linf": _agg_max(hv["div_linf"]),
                    "max_e": _agg_max(hv["max_e"]),
                    "max_h": _agg_max(hv["max_h"]),
                    "finite": all(bool(f) for f in hv["finite"]),
                }
                self.telemetry.emit_chunk(
                    chunk=self._chunk_idx, t=self._t_host,
                    steps=n_steps, wall_s=wall,
                    cells=self._cells * self.batch_size, health=agg)
            if tripped and self._check_finite:
                _log.warn(
                    f"batch: non-finite fields in lane(s) {tripped} "
                    f"(first bad step in ({t_prev}, {self._t_host}]); "
                    f"the other {self.batch_size - len(tripped)} "
                    f"lane(s) continue — per-lane verdicts in "
                    f"lane_finite / batch_lane telemetry")
        if _faults.active() is not None:
            _faults.on_chunk_boundary(self)
        return self

    def _readback(self, health) -> Dict[str, Any]:
        """ONE device->host transfer of the per-lane health vectors
        (the same single-readback budget Simulation.advance holds)."""
        import jax
        with _telemetry.span("telemetry-readback"):
            vals = jax.device_get(health)
        per = vals.pop("per_chip", None)
        out: Dict[str, Any] = {}
        for k, v in vals.items():
            arr = np.asarray(v, dtype=np.float64).ravel()
            if k == "nonfinite":
                out["finite"] = [x == 0.0 for x in arr]
            else:
                out[k] = [float(x) if np.isfinite(x) else None
                          for x in arr]
        if per is not None:
            # the vmapped per-chip vectors are (lanes, n_chips):
            # preserve the per-lane rows (advance() emits one
            # per_chip/imbalance record per lane from them)
            out["per_chip"] = {
                k: [[float(x) if np.isfinite(x) else None
                     for x in np.asarray(row,
                                         dtype=np.float64).ravel()]
                    for row in np.asarray(v).reshape(
                        self.batch_size, -1)]
                for k, v in per.items()}
        return out

    def run(self, time_steps: Optional[int] = None, chunk: int = 0):
        """Advance every lane ``time_steps`` (default: the shared
        cfg.time_steps) in ``chunk``-step dispatches (0 = one chunk)."""
        total = time_steps if time_steps is not None \
            else self.cfg.time_steps
        step = chunk if chunk and chunk > 0 else total
        done = 0
        while done < total:
            n = min(step, total - done)
            self.advance(n)
            done += n
        return self

    # -- access ------------------------------------------------------------

    def _dict_state(self):
        """The stacked DICT-form state (every leaf lane-leading). On
        the lane-capable packed path the carry is the packed pytree;
        this unpacks lazily and caches until the next advance /
        set_field."""
        if not self._packed:
            return self._state
        if self._dstate is None:
            self._dstate = self._unpack_fn(self._state)
        return self._dstate

    @property
    def state(self):
        """The stacked state pytree (every leaf lane-leading; the
        dict-form view when the packed kernel carries the state)."""
        return self._dict_state()

    def lane_state(self, lane: int) -> Dict[str, Any]:
        """One tenant's dict-form state view (host numpy tree) —
        comparable leaf-for-leaf with a sequential Simulation's."""
        import jax
        if not 0 <= lane < self.batch_size:
            raise IndexError(f"lane {lane} out of range "
                             f"(batch of {self.batch_size})")
        return jax.tree.map(lambda x: np.asarray(x)[lane],
                            self._dict_state())

    def lane_field(self, lane: int, comp: str) -> np.ndarray:
        group = "E" if comp[0] == "E" else "H"
        return np.asarray(self._dict_state()[group][comp])[lane]

    def set_field(self, comp: str, value: np.ndarray):
        """Overwrite one component across the WHOLE batch (value must
        carry the leading lane axis) — the faults harness's injection
        surface, mirroring Simulation.set_field."""
        import jax.numpy as jnp

        from fdtd3d_tpu.parallel import mesh as pmesh
        ds = self._dict_state()
        group = "E" if comp[0] == "E" else "H"
        if comp not in ds[group]:
            raise KeyError(f"{comp} not active in scheme "
                           f"{self.cfg.scheme}")
        old = ds[group][comp]
        vnp = np.asarray(value, dtype=np.asarray(old).dtype)
        if vnp.shape != np.shape(old):
            raise ValueError(
                f"set_field on a batch needs the lane-leading shape "
                f"{np.shape(old)}, got {vnp.shape}")
        if self.mesh is not None:
            arr = pmesh.shard_leaf(vnp,
                                   self._state_specs[group][comp],
                                   self.mesh)
        else:
            arr = jnp.asarray(vnp)
        ds[group][comp] = arr
        if self._packed:
            # the packed carry is authoritative: re-pack the edited
            # dict form (pack/unpack are pure layout, bit-exact)
            self._state = self._pack_fn(ds)
            self._dstate = ds
        return self

    def verify_final_lanes(self):
        """Host-side finite sweep of the FINAL state per lane — the
        end-of-run verdict pass. The in-graph counters measure each
        chunk's OUTPUT, so damage landing at the last chunk boundary
        (a fault injected after the final measurement, an operator
        edit) would otherwise read as healthy; the CLI calls this once
        before printing per-lane verdicts (one host pass over the
        final state — off the hot path)."""
        ds = self._dict_state()
        for lane in range(self.batch_size):
            ok = True
            for group in ("E", "H"):
                for v in ds[group].values():
                    arr = np.asarray(v)[lane]
                    if arr.dtype.kind not in "fc":
                        arr = arr.astype(np.float32)
                    if not np.isfinite(arr).all():
                        ok = False
            if not ok:
                self.lane_finite[lane] = False
                if self.lane_first_unhealthy_t[lane] is None:
                    self.lane_first_unhealthy_t[lane] = self._t_host
            elif self.lane_finite[lane] is None:
                # never measured in-graph (health lanes off): the
                # host sweep IS a measurement — record the verdict
                self.lane_finite[lane] = True
        return self

    @property
    def t(self) -> int:
        return int(self._t_host)

    # -- group snapshots (the queue dispatcher's durable resume) -----------

    def _ckpt_meta(self) -> Dict[str, Any]:
        meta = {
            "kind": "batch",
            "t": int(self._t_host),
            "batch": int(self.batch_size),
            "topology": list(self.topology),
            "batch_fp": repr(self.specs[0].batch_fingerprint()),
        }
        # v9: registry + causal-trace joins ride every group snapshot
        # (tools/ckpt_inspect.py prints both) — stamped here because a
        # batch has no extra_ckpt_meta for registry.attach to fill
        if getattr(self, "run_id", None):
            meta["run_id"] = self.run_id
        if getattr(self, "trace_id", None):
            meta["trace_id"] = self.trace_id
        return meta

    def checkpoint(self, path: str):
        """Bit-exact snapshot of the WHOLE batch: the stacked
        dict-form state pytree (lane-leading leaves, per-lane ``t``
        counters included) + group resume metadata. Crash-safe via
        io.save_checkpoint's atomic writer (an .npz under its final
        name is committed by construction). The queue dispatcher
        (jobqueue._dispatch_batch) commits one per coalesced-group
        chunk boundary so a preempted group resumes every lane from
        the last committed t instead of t=0 (docs/SERVICE.md recovery
        matrix)."""
        import jax

        from fdtd3d_tpu import io
        from fdtd3d_tpu.parallel import distributed as pdist
        state_np = jax.tree.map(pdist.gather_to_host,
                                self._dict_state())
        if jax.process_index() != 0:
            return self
        t_sp0 = float(time.time())
        with _telemetry.span("checkpoint"):
            io.save_checkpoint(state_np, path, extra=self._ckpt_meta())
        _telemetry.emit_trace_span(
            self, "snapshot_commit", t_sp0, float(time.time()),
            attrs={"path": os.path.basename(path),
                   "t": int(self._t_host)},
            group=self.group_id)
        _faults.on_checkpoint(path)  # committed: harness hook
        return self

    def restore(self, path: str):
        """Adopt a group snapshot written by :meth:`checkpoint` —
        every lane resumes bit-identical from the committed t. A
        snapshot failing its integrity checks raises
        :class:`fdtd3d_tpu.io.CheckpointCorrupt` (resume paths catch
        it and fall back to an older committed snapshot / t=0); a
        snapshot from a DIFFERENT group shape is a named error."""
        import jax
        import jax.numpy as jnp

        from fdtd3d_tpu import io
        from fdtd3d_tpu.parallel import mesh as pmesh
        loaded, extra = io.load_checkpoint(path)
        if int(extra.get("batch", -1)) != self.batch_size:
            raise ValueError(
                f"group snapshot {path} holds "
                f"{extra.get('batch')} lanes; this batch has "
                f"{self.batch_size} — a coalesced group must resume "
                f"with its own membership")
        fp = repr(self.specs[0].batch_fingerprint())
        if extra.get("batch_fp") not in (None, fp):
            raise ValueError(
                f"group snapshot {path} was written by a batch with a "
                f"different graph-shaping fingerprint; refusing a "
                f"cross-scenario resume")
        cur = self._dict_state()
        loaded = jax.tree.map(
            lambda a, b: np.asarray(a).astype(np.asarray(b).dtype),
            loaded, cur)
        if self.mesh is not None:
            ds = pmesh.shard_tree(loaded, self._state_specs, self.mesh)
        else:
            ds = jax.tree.map(jnp.asarray, loaded)
        if self._packed:
            self._state = self._pack_fn(ds)
            self._dstate = None
        else:
            self._state = ds
            self._dstate = ds
        self._t_host = int(extra.get("t", 0))
        return self

    def close_telemetry(self):
        if self.telemetry is None:
            return self
        from fdtd3d_tpu import exec_cache as _exec_cache
        w = self.telemetry.wall_total
        mcps = (self._cells * self.batch_size
                * self.telemetry.steps_total / w / 1e6) if w > 0 else 0.0
        self.telemetry.close(t=self._t_host, mcells_per_s=mcps,
                             compile_ms=round(self._compile_ms, 3),
                             aot_cache=_exec_cache.stats())
        return self

    def close(self):
        if self._closed:
            return self
        self._closed = True
        self.close_telemetry()
        if self.metrics is not None:
            self.metrics.maybe_write()
        if self.run_registry is not None:
            # a batch with isolated non-finite lanes folds to
            # "recovered" — lane isolation IS this executor's recovery
            self.run_registry.finalize(self)
        return self


def _prepend_specs(spec_tree):
    """Prepend the (replicated) lane axis to every PartitionSpec leaf
    — lanes never shard; the mesh axes keep their spatial meaning."""
    import jax
    from jax.sharding import PartitionSpec as P
    return jax.tree.map(lambda s: P(*((None,) + tuple(s))), spec_tree,
                        is_leaf=lambda x: isinstance(x, P))


def _agg_max(vals) -> Optional[float]:
    xs = [v for v in vals if v is not None]
    return max(xs) if xs else None


def _build_static(cfg):
    from fdtd3d_tpu.solver import build_static
    return build_static(cfg)


def _devices():
    import jax
    return jax.devices()
