"""Physical constants (SI units).

Parity: reference ``Source/Physics/PhysicsConst.h`` (SURVEY.md §2 —
c, eps0, mu0, accuracy constants; Courant dt from dx / courant factor).
"""

import math

# Exact SI values (CODATA 2018).
SPEED_OF_LIGHT = 299_792_458.0  # c0, m/s (exact)
EPS0 = 8.854_187_8128e-12       # vacuum permittivity, F/m
MU0 = 1.256_637_062_12e-6       # vacuum permeability, H/m
ETA0 = math.sqrt(MU0 / EPS0)    # vacuum impedance, ~376.73 Ohm

C0 = SPEED_OF_LIGHT


def courant_dt(dx: float, courant_factor: float, ndim_active: int) -> float:
    """Stable leapfrog timestep.

    dt = cf * dx / (c0 * sqrt(d))  with d = number of active spatial axes.
    The reference derives dt from ``--dx`` / ``--courant-factor`` the same
    way (SURVEY.md §2 Physics row). cf must be <= 1 for stability.
    """
    return courant_factor * dx / (C0 * math.sqrt(float(ndim_active)))
