"""Exact solutions of the DISCRETE Yee scheme, for oracle tests and norms.

Reference parity: the exact-solution callbacks + printed error norms that
back the reference's acceptance tests (SURVEY.md §2 "Exact solutions /
callbacks", §4). Where the reference uses polynomial fields (exact because
central differences reproduce low-order polynomials), we use two families
that are exact eigenfunctions/solutions of the discrete operator itself:

* PEC-cavity eigenmodes — sin-product mode shapes diagonalize the discrete
  curl-curl with PEC walls; their discrete frequency follows the exact
  discrete dispersion relation. Machine-precision oracle in any dimension.
* Discrete-dispersion plane waves — k solved from the Yee dispersion
  relation, matching TFSF-driven steady states far beyond what the
  continuum k would.
"""

from __future__ import annotations

import math
from typing import Dict, Sequence, Tuple

import numpy as np

from fdtd3d_tpu import physics


def discrete_omega(k_cells: Sequence[float], dx: float, dt: float) -> float:
    """Discrete Yee dispersion: frequency of a mode with per-axis wave
    numbers ``k_cells`` (radians per CELL; pass 0 for inactive axes).

    sin^2(w dt/2) = (c dt/dx)^2 * sum_a sin^2(k_a / 2)
    """
    s = sum(math.sin(k / 2.0) ** 2 for k in k_cells)
    arg = (physics.C0 * dt / dx) * math.sqrt(s)
    if arg > 1.0:
        raise ValueError("mode beyond the stability limit")
    return 2.0 / dt * math.asin(arg)


def discrete_k_1d(omega: float, dx: float, dt: float) -> float:
    """Inverse dispersion: wave number (rad/cell) of a CW at ``omega``."""
    s = math.sin(omega * dt / 2.0) / (physics.C0 * dt / dx)
    if s > 1.0:
        raise ValueError("frequency beyond the grid's passband")
    return 2.0 * math.asin(s)


def cavity_mode_tmz(size: Tuple[int, int], m: int, n: int,
                    dx: float, dt: float):
    """2D TMz PEC-cavity eigenmode.

    Returns (Ez0 mode shape on the (Nx, Ny) E-grid, omega_discrete).
    Walls at i=0, i=Nx-1, j=0, j=Ny-1 (where tangential Ez is pinned);
    Ez0 = sin(m pi i/(Nx-1)) sin(n pi j/(Ny-1)).

    Evolution from the solver's init convention (E^0 = mode, H = 0, and the
    step consumes H as H^{n+1/2}): E^t = mode * cos(w(t - 1/2)dt)/cos(w dt/2).
    """
    nx, ny = size
    kx = m * math.pi / (nx - 1)
    ky = n * math.pi / (ny - 1)
    i = np.arange(nx)[:, None]
    j = np.arange(ny)[None, :]
    shape = np.sin(kx * i) * np.sin(ky * j)
    return shape, discrete_omega((kx, ky, 0.0), dx, dt)


def cavity_mode_3d(size: Tuple[int, int, int], mnp: Tuple[int, int, int],
                   dx: float, dt: float):
    """3D PEC-cavity TM-like eigenmode with E = Ez only (p=0 along z).

    With k = (m pi/(Nx-1), n pi/(Ny-1), 0), Ez = sin(kx i) sin(ky j)
    (constant along z) solves the discrete equations with Hz = 0 — the
    z-invariant TMz mode embedded in 3D; exact in the 3D update too.
    """
    nx, ny, nz = size
    m, n, p = mnp
    if p != 0:
        raise NotImplementedError("only z-invariant (p=0) modes")
    shape2d, omega = cavity_mode_tmz((nx, ny), m, n, dx, dt)
    return np.repeat(shape2d[:, :, None], nz, axis=2), omega


def cavity_expectation(mode_shape: np.ndarray, omega: float, dt: float,
                       t: int) -> np.ndarray:
    """Expected E-field of a cavity mode at step ``t`` (solver convention)."""
    return mode_shape * (math.cos(omega * (t - 0.5) * dt)
                         / math.cos(omega * 0.5 * dt))


def plane_wave_1d_steady(x_cells: np.ndarray, t: int, omega: float,
                         dx: float, dt: float, amplitude: float = 1.0,
                         phase0: float = 0.0) -> np.ndarray:
    """Steady-state CW plane wave with the DISCRETE wave number."""
    k = discrete_k_1d(omega, dx, dt)
    return amplitude * np.sin(omega * t * dt - k * x_cells + phase0)
