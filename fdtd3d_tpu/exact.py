"""Exact solutions of the DISCRETE Yee scheme, for oracle tests and norms.

Reference parity: the exact-solution callbacks + printed error norms that
back the reference's acceptance tests (SURVEY.md §2 "Exact solutions /
callbacks", §4). Where the reference uses polynomial fields (exact because
central differences reproduce low-order polynomials), we use two families
that are exact eigenfunctions/solutions of the discrete operator itself:

* PEC-cavity eigenmodes — sin-product mode shapes diagonalize the discrete
  curl-curl with PEC walls; their discrete frequency follows the exact
  discrete dispersion relation. Machine-precision oracle in any dimension.
* Discrete-dispersion plane waves — k solved from the Yee dispersion
  relation, matching TFSF-driven steady states far beyond what the
  continuum k would.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import numpy as np

from fdtd3d_tpu import physics


def discrete_omega(k_cells: Sequence[float], dx: float, dt: float) -> float:
    """Discrete Yee dispersion: frequency of a mode with per-axis wave
    numbers ``k_cells`` (radians per CELL; pass 0 for inactive axes).

    sin^2(w dt/2) = (c dt/dx)^2 * sum_a sin^2(k_a / 2)
    """
    s = sum(math.sin(k / 2.0) ** 2 for k in k_cells)
    arg = (physics.C0 * dt / dx) * math.sqrt(s)
    if arg > 1.0:
        raise ValueError("mode beyond the stability limit")
    return 2.0 / dt * math.asin(arg)


def discrete_k_1d(omega: float, dx: float, dt: float) -> float:
    """Inverse dispersion: wave number (rad/cell) of a CW at ``omega``."""
    s = math.sin(omega * dt / 2.0) / (physics.C0 * dt / dx)
    if s > 1.0:
        raise ValueError("frequency beyond the grid's passband")
    return 2.0 * math.asin(s)


def cavity_mode_tmz(size: Tuple[int, int], m: int, n: int,
                    dx: float, dt: float):
    """2D TMz PEC-cavity eigenmode.

    Returns (Ez0 mode shape on the (Nx, Ny) E-grid, omega_discrete).
    Walls at i=0, i=Nx-1, j=0, j=Ny-1 (where tangential Ez is pinned);
    Ez0 = sin(m pi i/(Nx-1)) sin(n pi j/(Ny-1)).

    Evolution from the solver's init convention (E^0 = mode, H = 0, and the
    step consumes H as H^{n+1/2}): E^t = mode * cos(w(t - 1/2)dt)/cos(w dt/2).
    """
    nx, ny = size
    kx = m * math.pi / (nx - 1)
    ky = n * math.pi / (ny - 1)
    i = np.arange(nx)[:, None]
    j = np.arange(ny)[None, :]
    shape = np.sin(kx * i) * np.sin(ky * j)
    return shape, discrete_omega((kx, ky, 0.0), dx, dt)


def cavity_mode(size: Tuple[int, int, int], mnp: Tuple[int, int, int],
                dx: float, dt: float,
                cvec: Tuple[float, float, float] = (0.37, -0.61, 0.83),
                avec: Tuple[float, float, float] = None):
    """PEC-cavity eigenmode of the DISCRETE Yee operator, any dimension.

    Works for every scheme mode: an inactive axis (size 1, m = 0) simply
    contributes no trig factor. Returns ({comp: staggered E-grid array},
    omega_discrete); identically-zero components are omitted.

    Construction: with k_a = m_a pi/(N_a - 1) (rad/cell) the staggered
    trig product
        Ex(i+1/2, j, k) = Ax cos(kx(i+1/2)) sin(ky j) sin(kz k)   (cyc.)
    turns the discrete curl/div into the continuum ones with the EXACT
    substitution K_a = 2 sin(k_a/2)/dx. An amplitude vector A with
    K . A = 0 (discrete divergence-free) makes E0 a discrete curl-curl
    eigenvector with eigenvalue c^2 |K|^2, so with H = 0 at init it
    evolves as cavity_expectation — machine precision in f64. Tangential
    E vanishes on all PEC walls because sin(k_a g) is zero at g = 0 and
    g = N_a - 1.

    ``avec``: explicit amplitude vector (validated K . A ~ 0) — use it to
    select a scheme's components (e.g. (0,0,1) for TMz, K x e_z for TEz).
    Default: A = K x cvec (generic full-vector mode).
    """
    k = [mnp[a] * math.pi / (size[a] - 1) if size[a] > 1 else 0.0
         for a in range(3)]
    bigk = np.array([2.0 * math.sin(k[a] / 2.0) / dx for a in range(3)])
    if avec is not None:
        amp = np.asarray(avec, dtype=np.float64)
        if abs(float(bigk @ amp)) > 1e-9 * (
                np.linalg.norm(bigk) * np.linalg.norm(amp) + 1e-300):
            raise ValueError("avec is not discrete-divergence-free")
    else:
        amp = np.cross(bigk, np.asarray(cvec, dtype=np.float64))
    scale = np.max(np.abs(amp))
    if scale == 0.0:
        raise ValueError(f"degenerate mode/amplitude combination {mnp}")
    amp = amp / scale

    def axis_fn(a: int, half: bool):
        g = np.arange(size[a], dtype=np.float64) + (0.5 if half else 0.0)
        v = np.cos(k[a] * g) if half else np.sin(k[a] * g)
        sh = [1, 1, 1]
        sh[a] = size[a]
        return v.reshape(sh)

    out = {}
    for a, comp in enumerate(("Ex", "Ey", "Ez")):
        # a sin factor of a k=0 ACTIVE transverse axis zeroes the whole
        # component (inactive axes contribute no factor at all)
        if abs(amp[a]) < 1e-14 or any(
                k[b] == 0.0 and size[b] > 1 for b in range(3) if b != a):
            continue
        f = amp[a]
        for b in range(3):
            if size[b] > 1:
                f = f * axis_fn(b, half=(b == a))
        f = np.broadcast_to(np.asarray(f), size).copy()
        if k[a] != 0.0:
            # The outermost own-axis half-plane (position N_a - 1/2) lies
            # OUTSIDE the PEC box. Zeroed, it stays exactly zero: every
            # term of its update reads other beyond-wall planes that are
            # also zero, so the whole-array evolution is machine-exact.
            sl = [slice(None)] * 3
            sl[a] = size[a] - 1
            f[tuple(sl)] = 0.0
        out[comp] = f
    return out, discrete_omega(tuple(k), dx, dt)


# Backward-compatible name for the 3D case.
cavity_mode_3d = cavity_mode


def cavity_expectation(mode_shape: np.ndarray, omega: float, dt: float,
                       t: int) -> np.ndarray:
    """Expected E-field of a cavity mode at step ``t`` (solver convention)."""
    return mode_shape * (math.cos(omega * (t - 0.5) * dt)
                         / math.cos(omega * 0.5 * dt))


def plane_wave_1d_steady(x_cells: np.ndarray, t: int, omega: float,
                         dx: float, dt: float, amplitude: float = 1.0,
                         phase0: float = 0.0) -> np.ndarray:
    """Steady-state CW plane wave with the DISCRETE wave number."""
    k = discrete_k_1d(omega, dx, dt)
    return amplitude * np.sin(omega * t * dt - k * x_cells + phase0)
