"""Run registry: the append-only fleet index every run reports into.

The fleet-level observability substrate (ROADMAP items 2c/3: a job
queue and a fleet scheduler both need to SEE the fleet before they can
schedule it — the measurement-before-policy posture of PR 6's comm
lane, applied one level up). When ``FDTD3D_RUN_REGISTRY`` names a
path, every run — CLI, bench stage, batched executor, supervised —
appends exactly TWO records to that shared ``runs.jsonl``:

* ``run_begin`` at construction — a stable ``run_id``, the run kind,
  provenance (git sha / platform / jax), the scenario identity
  (config fingerprint + the provenance-free
  :attr:`~fdtd3d_tpu.exec_cache.ExecKey.comparable_digest` at the
  ``n_steps=0`` sentinel), topology / step kind / ghost depth / batch
  width, and the artifact paths (telemetry / metrics / save dir /
  trace dir) a fleet monitor joins against;
* ``run_final`` at close — status ``completed`` / ``failed`` /
  ``recovered``, totals (steps, wall, Mcells/s), the recovery-event
  rollup (retries / rollbacks / degrades / topology changes, tallied
  by the telemetry sink), per-lane unhealthy verdicts, and the
  exec-cache counter snapshot.

Both rows are schema-v7 record types validated by
``telemetry.validate_record`` (the index can never drift from the
telemetry toolchain) and written via :func:`fdtd3d_tpu.io.
atomic_append` — ONE O_APPEND write per run boundary, so concurrent
runs sharing a registry interleave whole lines, never torn ones. The
same ``run_id`` is stamped into the telemetry ``run_start`` (schema
v7 optional key) and into every checkpoint's ``extra_ckpt_meta``, so
a telemetry stream or a snapshot is traceable back to its run
(``tools/ckpt_inspect.py --json`` surfaces it).

Status semantics (``tools/fleet_report.py`` folds the rows by
run_id; the LAST row wins):

* ``running`` — begin row; a fold that never sees a final row is a
  live (or killed-without-close) run.
* ``completed`` — closed with no recovery events and no health trip.
* ``recovered`` — closed after surviving recovery: supervisor
  retries/rollbacks/degrades/topology rungs, or a batch that isolated
  one or more non-finite lanes (lane isolation IS the batch
  executor's recovery — the other tenants' results survived).
* ``failed`` — closed while an exception was propagating (the
  CLI/bench finalizers run inside the raising frame), or completed
  with an unrecovered non-finite health flag.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional

from fdtd3d_tpu import telemetry as _telemetry

REGISTRY_KNOB = "FDTD3D_RUN_REGISTRY"

_SEQ = itertools.count()
_DEFAULT_KIND = "lib"
_SUPPRESS = 0


def registry_path() -> Optional[str]:
    """The shared runs.jsonl path (``FDTD3D_RUN_REGISTRY``), or None
    (registry off — the default; no run-boundary writes happen)."""
    return os.environ.get(REGISTRY_KNOB) or None


def set_default_kind(kind: str) -> None:
    """Process-default run kind for handles opened without an explicit
    one: the CLI sets ``cli``/``supervised``, bench sets ``bench``;
    library constructions read ``lib``. The batched executor passes
    ``kind="batch"`` explicitly (a batch is a batch from any entry)."""
    global _DEFAULT_KIND
    _DEFAULT_KIND = str(kind)


# the queue-job stamp job_context() installs: runs registered inside
# the block carry it on their run_begin row AND on the sim itself
# (sim.job_id -> telemetry run_start), so a registry row, a telemetry
# stream and a queue-journal row are all joinable by job_id/run_id
_JOB_CONTEXT: Optional[Dict[str, str]] = None


@contextlib.contextmanager
def job_context(job_id: str, tenant: Optional[str] = None,
                trace_id: Optional[str] = None,
                parent_span_id: Optional[str] = None):
    """Attribute every run registered inside the block to queue job
    ``job_id`` (fdtd3d_tpu/jobqueue.py dispatches runs under it; a
    coalesced batch passes its GROUP id). The stamp lands on the
    run_begin row and on the telemetry run_start, which is how
    tools/fleet_report.py and tools/telemetry_report.py print
    job-id-joined lines without parsing the journal.

    ``trace_id`` (schema v9) is the job's causal-trace identity
    (minted once at JobQueue.submit — a re-dispatched job passes the
    SAME id, so one trace spans every dispatch); ``parent_span_id``
    is the dispatch span the run's own spans nest under. Both ride
    the same stamp onto run_begin/run_final, telemetry run_start and
    checkpoint metadata."""
    global _JOB_CONTEXT
    old = _JOB_CONTEXT
    ctx = {"job_id": str(job_id)}
    if tenant:
        ctx["tenant"] = str(tenant)
    if trace_id:
        ctx["trace_id"] = str(trace_id)
    if parent_span_id:
        ctx["parent_span_id"] = str(parent_span_id)
    _JOB_CONTEXT = ctx
    try:
        yield
    finally:
        _JOB_CONTEXT = old


@contextlib.contextmanager
def suppress_registration():
    """No new registrations inside the block: the supervisor's ladder
    rebuilds construct REPLACEMENT sims for the same logical run — a
    second begin row would double-count it; :func:`transfer` moves the
    original handle onto the replacement instead."""
    global _SUPPRESS
    _SUPPRESS += 1
    try:
        yield
    finally:
        _SUPPRESS -= 1


def new_run_id() -> str:
    """Stable unique run id: wall time + pid + in-process sequence +
    4 random hex chars (two hosts starting the same second with a
    recycled pid must still not collide in a shared registry)."""
    return (f"r{time.strftime('%Y%m%dT%H%M%S')}-{os.getpid()}"
            f"-{next(_SEQ)}-{os.urandom(2).hex()}")


class RunRegistry:
    """Validating append-only writer for one runs.jsonl path."""

    def __init__(self, path: str):
        self.path = path

    def emit(self, rec_type: str, **fields) -> Dict[str, Any]:
        from fdtd3d_tpu import io as _io
        rec = {"v": _telemetry.SCHEMA_VERSION, "type": rec_type,
               **fields}
        _telemetry.validate_record(rec)
        _io.atomic_append(self.path, json.dumps(rec) + "\n")
        return rec


class RunHandle:
    """One run's registry presence: the begin row is written at
    construction, the final row exactly once at :meth:`finalize`
    (``Simulation.close`` / ``BatchSimulation.close`` call it on
    every exit path)."""

    def __init__(self, path: str, run_id: str, kind: str,
                 writer: bool = True):
        self._reg = RunRegistry(path)
        self.run_id = run_id
        self.kind = kind
        self._writer = writer
        self._finalized = False
        # queue-job attribution, captured at construction (the
        # dispatcher wraps the whole run in one job_context block)
        self._job = dict(_JOB_CONTEXT) if _JOB_CONTEXT else None
        # this run's own span identity within the job trace (v9):
        # run_start carries it; the dispatch span is its parent
        self.span_id = _telemetry.new_span_id()

    @classmethod
    def open_for(cls, sim, kind: Optional[str] = None
                 ) -> Optional["RunHandle"]:
        """Register ``sim`` (a Simulation or BatchSimulation, already
        bound to its runner) in the env-configured registry: returns
        the attached handle, or None when the registry is off,
        registration is suppressed (supervisor rebuilds), or the
        begin write failed (a broken registry must never break the
        run it observes — warned, not raised)."""
        path = registry_path()
        if path is None or _SUPPRESS:
            return None
        writer = True
        try:
            import jax
            writer = jax.process_index() == 0
        except Exception:
            pass
        handle = cls(path, new_run_id(), kind or _DEFAULT_KIND,
                     writer=writer)
        try:
            handle._begin(sim)
        except (OSError, ValueError) as exc:
            # a broken registry (unwritable path, a row failing its
            # own validation) must never break the run it observes
            from fdtd3d_tpu import log as _log
            _log.warn(f"run registry: begin row not written to "
                      f"{path} ({exc}); run continues unregistered")
            return None
        # stamp only AFTER the begin row landed: telemetry/checkpoints
        # must never carry a run_id that exists in no registry row
        handle.attach(sim)
        return handle

    def attach(self, sim) -> None:
        """Stamp the run identity onto the sim: ``sim.run_id`` (the
        telemetry run_start picks it up via ``provenance``), the
        causal-trace identity (``sim.trace_id`` / ``sim.span_id`` /
        ``sim.parent_span_id``, schema v9) and the checkpoint
        metadata (``extra_ckpt_meta`` — every snapshot is then
        traceable to its run AND its job trace,
        tools/ckpt_inspect.py)."""
        sim.run_id = self.run_id
        sim.run_registry = self
        if self._job is not None:
            # telemetry.provenance picks these up into run_start
            sim.job_id = self._job["job_id"]
            if "trace_id" in self._job:
                sim.trace_id = self._job["trace_id"]
                # this run IS a span of the job's trace: one span id
                # per registered run, parented on the dispatch span
                sim.span_id = self.span_id
            if "parent_span_id" in self._job:
                sim.parent_span_id = self._job["parent_span_id"]
        meta = getattr(sim, "extra_ckpt_meta", None)
        if meta is not None:
            meta["run_id"] = self.run_id
            if self._job is not None and "trace_id" in self._job:
                meta["trace_id"] = self._job["trace_id"]

    # -- rows ----------------------------------------------------------

    def _begin_fields(self, sim) -> Dict[str, Any]:
        from fdtd3d_tpu import exec_cache as _exec_cache
        cfg = sim.cfg
        out_cfg = cfg.output
        platform = "unknown"
        jax_version = "unknown"
        try:
            import jax
            platform = jax.default_backend()
            jax_version = jax.__version__
        except Exception:
            pass
        out: Dict[str, Any] = {
            "run_id": self.run_id,
            "status": "running",
            "kind": self.kind,
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "git_sha": _telemetry.git_sha(),
            "platform": platform,
            "jax_version": jax_version,
            "scheme": cfg.scheme,
            "grid": list(cfg.grid_shape),
            "dtype": cfg.dtype,
            "topology": list(sim.topology),
            "batch": int(getattr(sim, "batch_size", 0) or 0),
            "telemetry_path": out_cfg.telemetry_path,
            "metrics_path": out_cfg.metrics_path,
            "save_dir": out_cfg.save_dir,
            "trace_dir": out_cfg.profile_dir,
        }
        if self._job is not None:
            out["job_id"] = self._job["job_id"]
            if "tenant" in self._job:
                out["tenant"] = self._job["tenant"]
            if "trace_id" in self._job:
                out["trace_id"] = self._job["trace_id"]
        # executable identity: the provenance-free comparable digest
        # (exec_cache.registry_identity also carries step_kind and
        # ghost_depth, the engaged step's)
        try:
            out.update(_exec_cache.registry_identity(sim.exec_key(0)))
        except Exception as exc:
            from fdtd3d_tpu import log as _log
            _log.warn(f"run registry: exec-key identity unavailable "
                      f"({str(exc)[:120]}); begin row carries the "
                      f"step kind only")
            out["step_kind"] = getattr(sim, "step_kind", "unknown")
        return out

    def _begin(self, sim) -> None:
        if not self._writer:
            return
        self._reg.emit("run_begin", **self._begin_fields(sim))

    def _final_fields(self, sim, status: Optional[str]
                      ) -> Dict[str, Any]:
        import sys

        from fdtd3d_tpu import exec_cache as _exec_cache
        sink = getattr(sim, "telemetry", None)
        counts: Dict[str, int] = {k: 0 for k in
                                  _telemetry.RECOVERY_TYPES}
        if sink is not None:
            counts.update(sink.recovery_counts)
        if not any(counts.values()):
            # sink-less supervised runs: the supervisor persists its
            # counters into extra_ckpt_meta (state_dict) — use them
            sup = (getattr(sim, "extra_ckpt_meta", None)
                   or {}).get("supervisor") or {}
            counts["retry"] = int(sup.get("retries", 0))
            counts["rollback"] = int(sup.get("rollbacks", 0))
            counts["degrade"] = int(sup.get("degrades", 0))
            counts["topology_change"] = int(
                sup.get("topology_rung", 0))
        n_recoveries = sum(counts.values())
        lanes = list(getattr(sim, "lane_finite", None) or [])
        lane_first = list(getattr(sim, "lane_first_unhealthy_t",
                                  None) or [])
        unhealthy = [[i, lane_first[i] if i < len(lane_first)
                      else None]
                     for i, ok in enumerate(lanes) if ok is False]
        first_bad = sink.first_unhealthy_t if sink is not None \
            else None
        if status is None:
            # the CLI/bench finalizers run inside the raising frame,
            # so a live exception here means the run died mid-flight
            if sys.exc_info()[1] is not None:
                status = "failed"
            elif n_recoveries > 0 or unhealthy:
                status = "recovered"
            elif first_bad is not None:
                status = "failed"
            else:
                status = "completed"
        steps = sink.steps_total if sink is not None \
            else int(getattr(sim, "_t_host", 0))
        wall = sink.wall_total if sink is not None else 0.0
        cells = float(getattr(sim, "_cells", 0.0)) \
            * max(int(getattr(sim, "batch_size", 0) or 1), 1)
        mcps = cells * steps / wall / 1e6 if wall > 0 else 0.0
        out: Dict[str, Any] = {
            "run_id": self.run_id,
            "status": status,
            "t": int(getattr(sim, "_t_host", 0)),
            "steps": int(steps),
            "wall_s": float(wall),
            "mcells_per_s": float(mcps),
            "recovery_events": dict(counts, total=n_recoveries),
            "first_unhealthy_t": first_bad,
            "compile_ms": round(float(getattr(sim, "_compile_ms",
                                              0.0)), 3),
            "aot_cache": _exec_cache.stats(),
        }
        if unhealthy:
            out["unhealthy_lanes"] = unhealthy
        if self._job is not None and "trace_id" in self._job:
            # the causal join key (v9): metrics.runs_total folds
            # run_final rows by it so a resumed job is ONE logical run
            out["trace_id"] = self._job["trace_id"]
        return out

    def finalize(self, sim, status: Optional[str] = None) -> None:
        """Append the final row (idempotent). ``status`` overrides the
        derived verdict; the default derivation is documented in the
        module docstring. Never raises — a broken registry must not
        mask the run's own exit path."""
        if self._finalized or not self._writer:
            self._finalized = True
            return
        self._finalized = True
        try:
            self._reg.emit("run_final",
                           **self._final_fields(sim, status))
        except (OSError, ValueError) as exc:
            from fdtd3d_tpu import log as _log
            _log.warn(f"run registry: final row not written "
                      f"({exc}); the fold will read this run as "
                      f"still running")


def transfer(old_sim, new_sim) -> None:
    """Move a run's registry handle (and run_id stamp) onto a
    replacement sim — the supervisor's ladder rebuilds swap the
    Simulation under one logical run, exactly as they move the
    telemetry sink."""
    handle = getattr(old_sim, "run_registry", None)
    if handle is None:
        return
    old_sim.run_registry = None
    handle.attach(new_sim)


# --------------------------------------------------------------------------
# reading + folding (tools/fleet_report.py)
# --------------------------------------------------------------------------


def fold(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """run_id -> merged row: the begin row's identity/artifact fields
    overlaid by every later row for the same run_id (LAST status
    wins, so an append-only file still reads as current state)."""
    runs: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        if rec.get("type") not in ("run_begin", "run_final"):
            continue
        rid = rec.get("run_id")
        if not isinstance(rid, str):
            continue
        row = runs.setdefault(rid, {})
        row.update({k: v for k, v in rec.items()
                    if k not in ("v", "type")})
    return runs


def read(path: str) -> List[Dict[str, Any]]:
    """Parse + validate a runs.jsonl registry (the telemetry
    validator owns the row schema)."""
    return _telemetry.read_jsonl(path)


def resolve_artifact(registry_path: str,
                     path: Optional[str]) -> Optional[str]:
    """Resolve a registry row's artifact pointer (telemetry_path,
    save_dir, ...) to a readable absolute path, or None.

    Relative paths resolve against the REGISTRY file's directory,
    never the reading tool's CWD: queue jobs run from per-job
    save_dirs and fleet tools run from wherever the operator stands,
    so the registry's own location is the only base both sides agree
    on. THE shared resolver for tools/fleet_report.py and
    tools/slo_gate.py --registry (one rule, so a stream a monitor can
    join is by construction a stream the gate can judge)."""
    if not path:
        return None
    if not os.path.isabs(path):
        base = os.path.dirname(os.path.abspath(registry_path))
        path = os.path.join(base, path)
    return path if os.path.exists(path) else None
