"""Known-bad fixture for the donation-safety rule, DEPTH-k temporal-
blocked surface (round 12): a six-phase (k=3) pipeline capture whose
H-family aliased operand keeps the lag-1 in-map but whose aliased
output lost its 2k-1 lag — the output visits block i while the input
only fetches block i-1 at iteration i+1, i.e. every block is fetched
ONE ITERATION AFTER the aliased output first visited it. This is
exactly the hazard class a depth-k generalization reintroduces if a
generation's output lag is miscounted (lagH = 2k-1, not 2k-2), and
the generalized check must name it a donation hazard.

A second capture drops the drain-iteration min-clamp from a lag-4
(E-family, k=3) in-map: over the ntiles + 2k-1 grid the unclamped map
walks past the last block and back under modular wrap, making the
fetch sequence non-monotone.
"""


def bad_lag_capture():
    from jax.experimental import pallas as pl
    ntiles, k = 4, 3
    grid = ntiles + 2 * k - 1          # the depth-k pipeline grid
    return {
        "grid": (grid,),
        "in_specs": [pl.BlockSpec(
            (8, 8), lambda i: (min(max(i - 1, 0), ntiles - 1), 0))],
        # BROKEN: the H-family output must lag 2k-1 = 5; lag 0 visits
        # block b at iteration b, before the lag-1 fetch at b+1
        "out_specs": [pl.BlockSpec((8, 8), lambda i: (min(i, ntiles - 1),
                                                      0))],
        "input_output_aliases": {0: 0},
    }


def unclamped_drain_capture():
    from jax.experimental import pallas as pl
    ntiles, k = 4, 3
    grid = ntiles + 2 * k - 1
    lag = 2 * (k - 1)

    def imap(i, _n=ntiles, _l=lag):
        # BROKEN: no min-clamp — drain iterations wrap modulo ntiles
        return (max(i - _l, 0) % _n, 0)

    return {
        "grid": (grid,),
        "in_specs": [pl.BlockSpec((8, 8), imap)],
        "out_specs": [pl.BlockSpec((8, 8), imap)],
        "input_output_aliases": {0: 0},
    }
