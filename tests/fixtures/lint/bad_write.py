"""Known-bad fixture for the atomic-write rule: a truncating open()
outside io.py's atomic primitives — torn-file-on-crash behavior."""


def save(path, data):
    with open(path, "w") as fh:
        fh.write(data)


def dump(arr, path):
    arr.tofile(path)
