"""Known-bad fixture for the tracer-hostility rule: a marked
graph-safe function making a host call, plus one reached transitively
(proving the same-module reachability walk, not just the direct
check)."""

import time

GRAPH_SAFE_FNS = ("stepper",)


def stepper(x):
    return helper(x) + time.time()  # host clock pinned at trace time


def helper(x):
    return float(x)  # forces a concrete value — crashes on a tracer
