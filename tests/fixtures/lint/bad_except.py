"""Known-bad fixture for the exception-hygiene rule: a bare except
and an except BaseException that never re-raises — both can swallow
SimulatedPreemption-family kills."""


def swallow_everything(fn):
    try:
        return fn()
    except:  # noqa: E722 — the offense under test
        return None


def swallow_kills(fn):
    try:
        return fn()
    except BaseException:
        return None
