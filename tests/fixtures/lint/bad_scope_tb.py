"""Known-bad fixture for the scope-coverage rule, sharded-tb surface:
a temporal-blocked-style GHOST GATHER — the stacked two-plane ppermute
of the depth-2 halo pipeline — issued under the packed-kernel-tb
family scope but WITHOUT its own halo-exchange scope. The rule's
ppermute bar requires the halo-exchange scope SPECIFICALLY (an
inherited outer scope is a mis-attributed exchange, not a scoped one),
so the traced jaxpr must show one unscoped collective."""


def build_unscoped_tb_gather_jaxpr():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from fdtd3d_tpu.parallel.mesh import shard_map_compat
    from fdtd3d_tpu.telemetry import named

    mesh = Mesh(np.array(jax.devices()[:2]), ("x",))

    def tb_ghost_gather(h):
        # the depth-2 gather: two H-generation boundary planes stacked
        # into one message — but the ppermute inherits the family
        # scope instead of naming halo-exchange
        with named("packed-kernel-tb"):
            planes = jnp.concatenate([h[:, -1:], h[:, -2:-1]], axis=1)
            return jax.lax.ppermute(planes, "x", [(0, 1)])

    f = shard_map_compat(tb_ghost_gather, mesh, in_specs=(P(None, "x"),),
                         out_specs=P(None, "x"))
    return jax.make_jaxpr(f)(jnp.ones((3, 8, 4), jnp.float32))
