"""Known-bad fixture for the donation-safety rule's LANE-CAPABLE
batched target (pallas_packed_batch): captures shaped like the packed
kernel under the batch_lane-surcharged tile pick — more, smaller
blocks along x1 than the solo build — whose donated field operand
breaks the fetch-before-write contract.

``stale_fetch_capture``: the donated packed input re-reads block i-1
(a "neighbor halo" read folded into the donated operand instead of a
separate non-aliased ghost operand) while its aliased output writes
block i — block b is fetched at iteration b+1, AFTER the output's
first visit, so the read can observe flushed output. This is exactly
the hazard a batched build would introduce if the smaller surcharged
tile tempted a fused halo re-read.

``nonmonotone_capture``: the donated in-map walks the surcharged grid
BACKWARD — non-monotone fetch order under donation.
"""


def stale_fetch_capture():
    from jax.experimental import pallas as pl
    return {
        # 8 blocks: the batch=3 surcharge halved the solo tile
        "grid": (8,),
        "in_specs": [pl.BlockSpec((4, 16),
                                  lambda i: (max(i - 1, 0), 0))],
        "out_specs": [pl.BlockSpec((4, 16), lambda i: (i, 0))],
        "input_output_aliases": {0: 0},
    }


def nonmonotone_capture():
    from jax.experimental import pallas as pl
    return {
        "grid": (8,),
        "in_specs": [pl.BlockSpec((4, 16), lambda i: (7 - i, 0))],
        "out_specs": [pl.BlockSpec((4, 16), lambda i: (7 - i, 0))],
        "input_output_aliases": {0: 0},
    }
