"""Known-bad fixture for the env-registry rule: a literal FDTD3D_*
environment read that fdtd3d_tpu.config.ENV_KNOBS does not declare."""

import os

FLAG = os.environ.get("FDTD3D_NOT_IN_REGISTRY")
OTHER = os.getenv("FDTD3D_ALSO_UNDECLARED", "0")
