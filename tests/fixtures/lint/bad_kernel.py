"""Known-bad fixture for the donation-safety rule: a pallas_call
capture whose donated operand reads BACKWARD (block i-1) while its
aliased output writes block i — the fetch of block b happens at
iteration b+1, after the output first visited b, so the read can
observe flushed output. This is exactly the hazard class the fused
kernel's H operands would hit if donated (test_h_inputs_never_donated
history)."""


def bad_capture():
    from jax.experimental import pallas as pl
    return {
        "grid": (4,),
        "in_specs": [pl.BlockSpec((8, 8),
                                  lambda i: (max(i - 1, 0), 0))],
        "out_specs": [pl.BlockSpec((8, 8), lambda i: (i, 0))],
        "input_output_aliases": {0: 0},
    }


def nonmonotone_capture():
    from jax.experimental import pallas as pl
    return {
        "grid": (4,),
        "in_specs": [pl.BlockSpec((8, 8), lambda i: (3 - i, 0))],
        "out_specs": [pl.BlockSpec((8, 8), lambda i: (3 - i, 0))],
        "input_output_aliases": {0: 0},
    }
