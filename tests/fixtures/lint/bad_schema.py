"""Known-bad fixture for the schema-drift rule: writers emitting keys
no telemetry validator version knows — a literal kwarg, a **expansion,
and a dict-literal record (all three detection pathways)."""


def emit_bogus_literal(sink):
    sink.emit("degrade", t=1, old_kind="a", new_kind="b", reason="r",
              chip=None, host=None, extra_mystery=1)


def emit_bogus_expansion(sink):
    sink.emit("run_start", **build_meta())


def build_meta():
    rec = {"wall_time": "now", "git_sha": "x", "jax_version": "0",
           "platform": "cpu"}
    rec["sneaky_extra"] = 1
    return rec


def build_bogus_record():
    rec = {"v": 5, "type": "attribution", "source": "s",
           "sections": {}, "measured_total_ms": None,
           "coverage_bytes": None}
    rec["undeclared_lane"] = {}
    return rec
