"""Known-bad fixture for the no-bare-print rule: a stray print() call
site outside log.py (tests/test_analysis.py proves the rule fires)."""


def shout(msg):
    print(msg)  # the offense: unsilenceable every-rank output
