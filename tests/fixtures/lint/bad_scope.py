"""Known-bad fixture for the scope-coverage rule: a shard_map'd
function whose ppermute carries NO fdtd3d/ named scope — the traced
jaxpr must show one unscoped collective."""


def build_unscoped_jaxpr():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from jax.sharding import Mesh

    from fdtd3d_tpu.parallel.mesh import shard_map_compat

    mesh = Mesh(np.array(jax.devices()[:2]), ("x",))

    def exchange(x):
        return jax.lax.ppermute(x, "x", [(0, 1), (1, 0)])

    f = shard_map_compat(exchange, mesh, in_specs=(P("x"),),
                         out_specs=P("x"))
    return jax.make_jaxpr(f)(jnp.ones((4, 4), jnp.float32))
