"""Fused Pallas kernels INSIDE shard_map vs the unsharded jnp step.

The TPU analog of the reference's hybrid MPI+CUDA mode (SURVEY.md §2.9
item 6: decomposition across nodes, CUDA kernels within): the same fused
kernels must compose with the domain decomposition on ANY topology —
y/z ghost planes ride ppermute outside the kernel
(ops/pallas3d.gather_ghosts) and stream in as thin blocks; a sharded x
(tiling) axis ppermutes its boundary plane into the shard-edge tiles.
Runs in interpreter mode on the 8-device virtual CPU mesh.
"""

import dataclasses
import os

import numpy as np
import pytest


@pytest.fixture(scope="module", autouse=True)
def _pin_two_pass():
    """This module covers the TWO-PASS sharded kernels. Since round 5
    the packed kernel's scope includes sourced + magnetic-Drude sharded
    runs, so without the pin every config here would engage it instead
    (its own coverage lives in tests/test_packed_sourced_sharded.py)."""
    os.environ["FDTD3D_NO_PACKED"] = "1"
    yield
    os.environ.pop("FDTD3D_NO_PACKED", None)

from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation

# x-sharded topologies (incl. the auto-chooser's (2,2,2)) need the x CPML
# slabs to fit each shard: local_n > 2*(pml+1) -> pml=2 at N=16, px=2.
TOPOLOGIES = [(1, 2, 1), (1, 1, 2), (1, 2, 2), (1, 4, 2),
              (2, 1, 1), (2, 2, 1), (2, 1, 2), (2, 2, 2)]

N = 16


def _cfg(parallel=None, use_pallas=None):
    return SimConfig(
        scheme="3D", size=(N, N, N), time_steps=8, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3, use_pallas=use_pallas,
        pml=PmlConfig(size=(2, 2, 2)),
        tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                        angle_teta=30.0, angle_phi=40.0, angle_psi=15.0),
        materials=MaterialsConfig(
            eps=1.0, use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
            drude_sphere=SphereConfig(enabled=True,
                                      center=(8.0, 8.0, 8.0), radius=3.0),
            use_drude_m=True, mu_inf=1.5, omega_pm=1e11, gamma_m=1e10,
            drude_m_sphere=SphereConfig(enabled=True,
                                       center=(8.0, 8.0, 8.0),
                                       radius=3.0)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(5, 9, 7)),
        parallel=parallel or ParallelConfig(),
    )


@pytest.fixture(scope="module")
def reference_fields():
    sim = Simulation(_cfg(use_pallas=False))
    sim.run()
    return sim.fields()


@pytest.mark.parametrize("topo", TOPOLOGIES)
def test_sharded_pallas_matches_unsharded_jnp(topo, reference_fields):
    cfg = _cfg(ParallelConfig(topology="manual", manual_topology=topo),
               use_pallas=True)
    sim = Simulation(cfg)
    assert sim.mesh is not None, "sharded path not engaged"
    # the fused step must actually be in play for this topology (eligible
    # AND the builder did not hit a post-eligibility jnp bailout)
    assert sim.step_kind == "pallas", "pallas path not engaged"
    sim.run()
    got = sim.fields()
    for comp, ref in reference_fields.items():
        scale = np.abs(ref).max() + 1e-30
        err = np.abs(got[comp] - ref).max()
        assert err < 1e-5 * scale, f"{comp}: {err/scale:.2e} on {topo}"


def test_thin_x_shard_uses_jnp_fallback(reference_fields):
    """A shard too thin for the x CPML slabs (local_n <= 2*(pml+1))
    falls back to the jnp path and stays correct."""
    cfg = _cfg(ParallelConfig(topology="manual", manual_topology=(4, 1, 1)),
               use_pallas=True)
    sim = Simulation(cfg)
    assert sim.step_kind == "jnp", "thin x shard should fall back"
    sim.run()
    got = sim.fields()
    for comp, ref in reference_fields.items():
        scale = np.abs(ref).max() + 1e-30
        assert np.abs(got[comp] - ref).max() < 1e-5 * scale


def test_auto_topology_engages_pallas():
    """The auto topology chooser's pick for 8 devices — (2,2,2), which
    shards x — must run the fused kernels (VERDICT r2 weak item 1)."""
    cfg = _cfg(ParallelConfig(topology="auto"), use_pallas=True)
    sim = Simulation(cfg)
    assert sim.topology == (2, 2, 2)
    assert sim.step_kind == "pallas", \
        f"auto topology {sim.topology} fell back to {sim.step_kind}"
    sim.run()
    for comp, v in sim.fields().items():
        assert np.isfinite(v).all()
