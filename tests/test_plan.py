"""Memory/communication planner (plan.py + CLI --dry-run) and the DAT
viewer tool."""

import contextlib
import io as _io
import os
import sys

import numpy as np
import pytest

from fdtd3d_tpu import plan as plan_mod
from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               SimConfig, SphereConfig, TfsfConfig)


def _sphere(r=5):
    return SphereConfig(enabled=True, center=(16, 16, 16), radius=r)


MATERIAL_CASES = {
    "vacuum": MaterialsConfig(),
    "eps-sphere": MaterialsConfig(eps=2.0, eps_sphere=_sphere()),
    "mu-sphere": MaterialsConfig(mu_sphere=_sphere()),
    "drude-sphere": MaterialsConfig(
        use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
        drude_sphere=_sphere()),
    # uniform plasma DISCARDS the eps grid (merge_drude_eps) — the
    # planner must predict zero material grids here
    "uniform-drude-plus-eps-sphere": MaterialsConfig(
        use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
        eps=2.0, eps_sphere=_sphere()),
    # metamaterial mode: K currents + magnetic coefficient grids
    "double-drude-spheres": MaterialsConfig(
        use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
        drude_sphere=_sphere(),
        use_drude_m=True, mu_inf=1.5, omega_pm=1e11, gamma_m=1e10,
        drude_m_sphere=_sphere()),
}


@pytest.mark.parametrize("name", sorted(MATERIAL_CASES))
def test_plan_matches_actual_allocation(name):
    """Planner bytes must EQUAL what init_state/build_coeffs allocate —
    including the coefficient grids, whose scalar-vs-grid rules the
    planner mirrors (it must not drift from build_coeffs)."""
    import jax

    from fdtd3d_tpu import solver
    cfg = SimConfig(scheme="3D", size=(32, 32, 32), time_steps=1,
                    pml=PmlConfig(size=(5, 5, 5)),
                    tfsf=TfsfConfig(enabled=True, margin=(3, 3, 3)),
                    materials=MATERIAL_CASES[name])
    p = plan_mod.plan(cfg)
    static = solver.build_static(cfg)
    shapes = jax.eval_shape(lambda: solver.init_state(static))

    def nbytes(tree):
        return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
                   for l in jax.tree.leaves(tree))

    assert p.fields_bytes == nbytes(shapes["E"]) + nbytes(shapes["H"])
    assert p.psi_bytes == nbytes(shapes["psi_E"]) + nbytes(shapes["psi_H"])
    if static.use_drude or static.use_drude_m:
        want = (nbytes(shapes["J"]) if static.use_drude else 0) + \
            (nbytes(shapes["K"]) if static.use_drude_m else 0)
        assert p.drude_bytes == want
    assert p.inc_bytes == nbytes(shapes["inc"])
    coeffs = solver.build_coeffs(static)
    actual_grids = sum(v.size * v.dtype.itemsize
                       for v in coeffs.values()
                       if getattr(v, "ndim", 0) == 3)
    assert p.coeff_bytes == actual_grids, name


def test_plan_halo_count_per_mode():
    """2D TMz sharded along x exchanges 2 planes/step, 3D exchanges 4."""
    cfg2 = SimConfig(scheme="2D_TMz", size=(32, 32, 1), time_steps=1,
                     parallel=ParallelConfig(topology="manual",
                                             manual_topology=(2, 1, 1)))
    p2 = plan_mod.plan(cfg2)
    plane2 = 32 * 1 * 4              # y*z cells of one x-plane, f32
    assert p2.halo_bytes_per_step == 2 * 2 * plane2

    cfg3 = SimConfig(scheme="3D", size=(16, 16, 16), time_steps=1,
                     parallel=ParallelConfig(topology="manual",
                                             manual_topology=(2, 1, 1)))
    p3 = plan_mod.plan(cfg3)
    plane3 = 16 * 16 * 4
    assert p3.halo_bytes_per_step == 2 * 4 * plane3


def test_plan_rejects_what_simulation_rejects():
    """The dry run must fail exactly where the real run fails."""
    cfg = SimConfig(scheme="3D", size=(30, 30, 30),
                    parallel=ParallelConfig(topology="manual"))
    with pytest.raises(ValueError, match="manual topology requires"):
        plan_mod.plan(cfg)
    cfg2 = SimConfig(scheme="3D", size=(30, 30, 30),
                     parallel=ParallelConfig(topology="manual",
                                             manual_topology=(4, 1, 1)))
    with pytest.raises(ValueError, match="not divisible"):
        plan_mod.plan(cfg2)


def test_plan_1024_cubed_on_64_chips_fits_v5p():
    """The BASELINE config #5 plan: 1024^3 Drude on 64 chips must show a
    per-chip footprint comfortably under v5p's 95 GiB HBM."""
    cfg = SimConfig(scheme="3D", size=(1024, 1024, 1024), time_steps=1,
                    pml=PmlConfig(size=(10, 10, 10)),
                    materials=MaterialsConfig(use_drude=True, eps_inf=4.0,
                                              omega_p=1e12, gamma=5e10,
                                              drude_sphere=SphereConfig(
                                                  enabled=True,
                                                  center=(512,) * 3,
                                                  radius=96)),
                    parallel=ParallelConfig(topology="auto",
                                            n_devices=64))
    p = plan_mod.plan(cfg, n_devices=64)
    assert p.n_chips == 64
    assert np.prod(p.local_shape) * 64 == 1024 ** 3
    gib = p.hbm_per_chip / (1 << 30)
    assert gib < 16.0, f"per-chip plan {gib:.1f} GiB too large"
    assert p.halo_bytes_per_step > 0
    assert "TOTAL per chip" in p.report()


def test_cli_dry_run():
    from fdtd3d_tpu import cli
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(["--3d", "--same-size", "1024", "--use-pml",
                       "--pml-size", "10", "--topology", "auto",
                       "--num-devices", "64", "--dry-run"])
    assert rc == 0
    out = buf.getvalue()
    assert "TOTAL per chip" in out and "halo exchange" in out


def test_view_tool(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import view
    from fdtd3d_tpu import io
    arr = np.linspace(-1, 1, 4 * 5 * 6).reshape(4, 5, 6)
    p = str(tmp_path / "Ez_t000001.dat")
    io.dump_dat(arr, p)
    msg = view.view(p, "z", None)
    assert "shape (4, 5, 6)" in msg
    bmp = str(tmp_path / "Ez_t000001_z3.bmp")
    assert os.path.exists(bmp)
    w, h = io.load_bmp_size(bmp)
    assert (w, h) == (4, 5)
