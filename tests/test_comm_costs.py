"""Ledger v2 ICI comm lane (fdtd3d_tpu/costs.py, ISSUE 7 tentpole).

CPU-deterministic acceptance, asserted in tier-1 on the 8-device
virtual mesh (conftest): for every SHARDED step kind the chunk runner
traces inside shard_map, the comm lane's modeled halo-bytes/chip
matches plan.py exactly per topology (single source of truth), and
>= 95% of the jaxpr's ppermute bytes are attributed to the named
``halo-exchange`` scopes. Plus: schema v2 round-trips, v1 ledgers keep
validating, the per-topology table and modeled overlap window are
deterministic, and the sentinel's comm lane proves both verdicts on
the checked-in fixture pair.
"""

import json
import os

import pytest

from fdtd3d_tpu import costs
from fdtd3d_tpu.plan import plan_for_topology

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(ROOT, "tests", "fixtures")

TOPO = (2, 2, 2)


def _cfg(kind):
    # pml=2 keeps the CPML slabs inside the 8-cell shards of a 16^3
    # grid on (2,2,2) (solver.slab_axes needs local_n > 2*(pml+1))
    return costs.config_for_kind(kind, n=16, pml=2)


@pytest.fixture(scope="module")
def sharded_ledgers():
    """One sharded trace per sharded-capable step kind (module-scoped:
    tracing the packed kernels is the expensive part)."""
    out = {}
    for kind in costs.SHARDED_STEP_KINDS:
        out[kind] = costs.chunk_ledger(_cfg(kind), n_steps=8, kind=kind,
                                       topology=TOPO, hbm_gbps=600.0)
    return out


@pytest.mark.parametrize("kind", costs.SHARDED_STEP_KINDS)
def test_sharded_ledger_validates(sharded_ledgers, kind):
    led = sharded_ledgers[kind]
    costs.validate_ledger(led)
    assert led["ledger_version"] == 2
    assert led["step_kind"] == kind
    assert led["topology"] == list(TOPO)
    # json round-trip clean (the artifact is a file format)
    costs.validate_ledger(json.loads(json.dumps(led)))


@pytest.mark.parametrize("kind", costs.SHARDED_STEP_KINDS)
def test_modeled_halo_matches_plan_exactly(sharded_ledgers, kind):
    """Acceptance: the comm lane's modeled halo-bytes/chip IS plan.py's
    number, per topology — one source of truth, no drift possible.
    The temporal-blocked kind quotes the depth-2 model
    (halo_bytes_per_step_tb: two ghost-plane generations per neighbor
    per pass), every other kind the single-step curl-term model."""
    comm = sharded_ledgers[kind]["comm"]
    p = plan_for_topology(_cfg(kind), TOPO)
    expect = p.halo_bytes_per_step_tb \
        if kind == "pallas_packed_tb" else p.halo_bytes_per_step
    assert comm["plan"]["halo_bytes_per_chip_per_step"] == expect
    # and the helper the tools quote agrees too
    assert costs.halo_bytes_per_chip(_cfg(kind), TOPO,
                                     step_kind=kind) == expect


@pytest.mark.parametrize("kind", costs.SHARDED_STEP_KINDS)
def test_ppermute_attribution_95(sharded_ledgers, kind):
    """Acceptance: >= 95% of traced ppermute bytes land on the named
    halo-exchange scopes (every exchange is observable by name)."""
    ps = sharded_ledgers[kind]["comm"]["per_step"]
    assert ps["halo_attribution"] >= 0.95, \
        f"{kind}: only {ps['halo_attribution']:.1%} of ppermute bytes " \
        f"attributed to halo-exchange"
    assert ps["ppermute_bytes_per_chip"] > 0
    assert ps["ppermute_messages"] > 0


def test_stencil_paths_trace_exactly_plan(sharded_ledgers):
    """The jnp/two-pass stencil paths ppermute exactly the curl-term
    planes plan.py counts — traced == modeled to the byte. The packed
    kernels add thin patch-fix/ghost planes on top (traced >= modeled,
    recorded as traced_minus_modeled_bytes)."""
    for kind in ("jnp", "pallas"):
        comm = sharded_ledgers[kind]["comm"]
        assert comm["per_step"]["ppermute_bytes_per_chip"] == \
            comm["plan"]["halo_bytes_per_chip_per_step"], kind
    for kind in ("pallas_packed", "pallas_packed_ds"):
        comm = sharded_ledgers[kind]["comm"]
        assert comm["per_step"]["ppermute_bytes_per_chip"] >= \
            comm["plan"]["halo_bytes_per_chip_per_step"], kind
        assert comm["plan"]["traced_minus_modeled_bytes"] >= 0
    # the tb path's depth-2 exchange is modeled to the BYTE: the four
    # generation stacks per axis per pass are the whole schedule (no
    # patch-fix planes — sources ride in-kernel)
    comm_tb = sharded_ledgers["pallas_packed_tb"]["comm"]
    assert comm_tb["per_step"]["ppermute_bytes_per_chip"] == \
        comm_tb["plan"]["halo_bytes_per_chip_per_step"]
    assert comm_tb["plan"]["traced_minus_modeled_bytes"] == 0


@pytest.mark.parametrize("kind", costs.SHARDED_STEP_KINDS)
def test_sharded_coverage_holds(sharded_ledgers, kind):
    """The per-chip section tables keep the >=95% attribution bar
    under shard_map too (the sharded fix-up passes are scoped)."""
    ps = sharded_ledgers[kind]["per_step"]
    assert ps["coverage_flops"] >= 0.95
    assert ps["coverage_bytes"] >= 0.95


def test_tb_sharded_roofline_moved(sharded_ledgers):
    """ISSUE-10/12 acceptance, CPU-deterministic: on the SAME sharded
    (2,2,2) config the temporal-blocked kernel's per-step field HBM
    bytes (the packed-kernel section's pallas_call charge) must be
    within the per-depth bound ({2: 0.55, 3: 0.40, 4: 0.32}) of the
    single-step packed kernel's — the depth-k halo pipeline converts
    the repo's best kernel into the default sharded path at 1/k-th the
    per-cell HBM cost. The engaged depth is the auto pick's."""
    from tests.test_costs import TB_RATIO_BOUNDS
    tb = sharded_ledgers["pallas_packed_tb"]
    pk = sharded_ledgers["pallas_packed"]
    depth = tb["steps_per_call"]
    assert depth in TB_RATIO_BOUNDS
    tb_b = tb["sections"]["packed-kernel-tb"]["bytes"] / tb["cells"]
    pk_b = pk["sections"]["packed-kernel"]["bytes"] / pk["cells"]
    bound = TB_RATIO_BOUNDS[depth]
    assert tb_b <= bound * pk_b, \
        f"sharded tb (k={depth}) {tb_b:.1f} B/cell/step vs packed " \
        f"{pk_b:.1f} (bound {bound})"


@pytest.mark.parametrize("depth", (2, 3, 4))
def test_tb_sharded_traced_equals_model_every_k(monkeypatch, depth):
    """Round-12 acceptance: the traced ppermute bytes equal the plan
    model TO THE BYTE for EVERY pipeline depth k on the (2,2,2) mesh —
    the per-pass schedule is k H-stacks down + k-1 E-stacks up + the
    post-fix E stack, so per STEP the bytes are depth-invariant
    (plan.Plan.halo_bytes_per_step_tb_at)."""
    monkeypatch.setenv("FDTD3D_TB_DEPTH", str(depth))
    cfg = _cfg("pallas_packed_tb")
    led = costs.chunk_ledger(cfg, n_steps=12, kind="pallas_packed_tb",
                             topology=TOPO)
    assert led["steps_per_call"] == depth
    comm = led["comm"]
    assert comm["strategy"]["ghost_depth"] == depth
    p = plan_for_topology(cfg, TOPO)
    assert comm["per_step"]["ppermute_bytes_per_chip"] == \
        p.halo_bytes_per_step_tb_at(depth)
    assert p.halo_bytes_per_step_tb_at(depth) == \
        p.halo_bytes_per_step_tb        # the invariance, asserted
    assert comm["plan"]["traced_minus_modeled_bytes"] == 0


@pytest.mark.parametrize("depth", (2, 3))
def test_tb_sharded_widened_traced_equals_model(monkeypatch, depth):
    """ISSUE-14 acceptance: the WIDENED sharded scenario (TFSF +
    electric-Drude sphere incl. its merged eps grids —
    costs.config_tb_widened, all three new wedge ports in one config)
    dispatches pallas_packed_tb and its traced ppermute bytes equal
    the plan model TO THE BYTE at every admitted k. The incident-line
    values are shard-local recomputation and J/coefficients never
    cross shards, so the widened wedge adds ZERO ICI bytes: per-step
    traffic stays depth-invariant."""
    monkeypatch.setenv("FDTD3D_TB_DEPTH", str(depth))
    cfg = costs.config_tb_widened()
    led = costs.chunk_ledger(cfg, n_steps=2 * depth,
                             kind="pallas_packed_tb", topology=TOPO)
    assert led["steps_per_call"] == depth
    assert led["tb_fallback"] is None
    comm = led["comm"]
    assert comm["strategy"]["ghost_depth"] == depth
    p = plan_for_topology(cfg, TOPO)
    assert comm["per_step"]["ppermute_bytes_per_chip"] == \
        p.halo_bytes_per_step_tb_at(depth)
    assert p.halo_bytes_per_step_tb_at(depth) == \
        p.halo_bytes_per_step_tb          # depth-invariance, asserted
    assert comm["plan"]["traced_minus_modeled_bytes"] == 0
    assert comm["per_step"]["halo_attribution"] >= 0.95


def test_tb_sharded_widened_roofline_moved(monkeypatch):
    """ISSUE-14 acceptance, CPU-deterministic: on the widened sharded
    config the per-depth HBM gates hold vs the single-step packed
    kernel — the 2-4x HBM win no longer evaporates when a production
    (TFSF+Drude+grid) workload is sharded.

    Two gates per depth: (1) on the FIELD/STATE traffic — both
    kernels' section bytes minus the modeled per-cell coefficient-grid
    stream (n_grids x 4 B/cell/step on BOTH kernels: each grid is
    read once per STEP at any depth BY DESIGN — ring-buffering
    coefficients would buy VMEM, not bytes), the strict {2: 0.55,
    3: 0.40, 4: 0.32} bounds hold to within the thin widened-operand
    overhead (TFSF value planes + ghost stacks; 2% allowance);
    (2) on the RAW section ratio, the total-traffic bounds
    {2: 0.65, 3: 0.52, 4: 0.46} (measured 0.638/0.510/0.447) guard
    the end-to-end win a fleet actually sees."""
    from tests.test_costs import TB_RATIO_BOUNDS
    from fdtd3d_tpu.plan import _coeff_grid_counts
    from fdtd3d_tpu.solver import build_static
    RAW_BOUNDS = {2: 0.65, 3: 0.52, 4: 0.46}
    cfg = costs.config_tb_widened()
    st = build_static(cfg)
    per_e, per_h = _coeff_grid_counts(st)
    coeff_b = (per_e * len(st.mode.e_components)
               + per_h * len(st.mode.h_components)) * 4
    assert coeff_b > 0     # the probe really streams material grids
    pk = costs.chunk_ledger(cfg, n_steps=12, kind="pallas_packed",
                            topology=TOPO)
    assert pk["tb_fallback"] == {"reason": "env:FDTD3D_NO_TEMPORAL"}
    pk_b = pk["sections"]["packed-kernel"]["bytes"] / pk["cells"]
    for depth in sorted(TB_RATIO_BOUNDS):
        monkeypatch.setenv("FDTD3D_TB_DEPTH", str(depth))
        tb = costs.chunk_ledger(cfg, n_steps=2 * depth,
                                kind="pallas_packed_tb", topology=TOPO)
        assert tb["steps_per_call"] == depth
        tb_b = tb["sections"]["packed-kernel-tb"]["bytes"] / tb["cells"]
        bound = TB_RATIO_BOUNDS[depth]
        assert tb_b - coeff_b <= 1.02 * bound * (pk_b - coeff_b), \
            f"widened k={depth}: field/state {tb_b - coeff_b:.1f} " \
            f"B/cell/step vs packed {pk_b - coeff_b:.1f} " \
            f"(bound {bound})"
        assert tb_b <= RAW_BOUNDS[depth] * pk_b, \
            f"widened k={depth}: raw {tb_b:.1f} vs {pk_b:.1f} " \
            f"(bound {RAW_BOUNDS[depth]})"


def test_ledger_tb_fallback_lane(sharded_ledgers):
    """ISSUE-14 satellite 1: every non-tb ledger names WHY temporal
    blocking did not engage ({"reason": token}); the tb ledger's lane
    is null. The forced-packed trace records the escape hatch the
    forcing used; jnp (pallas off) records pallas_disabled."""
    assert sharded_ledgers["pallas_packed_tb"]["tb_fallback"] is None
    assert sharded_ledgers["pallas_packed"]["tb_fallback"] == \
        {"reason": "env:FDTD3D_NO_TEMPORAL"}
    assert sharded_ledgers["jnp"]["tb_fallback"] == \
        {"reason": "pallas_disabled"}
    assert sharded_ledgers["pallas_packed_ds"]["tb_fallback"] == \
        {"reason": "ds_fields"}
    # round-trips as JSON and stays schema-valid
    led = json.loads(json.dumps(sharded_ledgers["pallas_packed"]))
    costs.validate_ledger(led)
    assert set(led) <= costs.LEDGER_KEYS


def test_strategy_recorded_and_deterministic(sharded_ledgers):
    """ISSUE-10/12 acceptance: the planner's strategy choice is
    deterministic, recorded in the ledger comm lane, and the reference
    (2,2,2) decomposition picks the ASYNC fused exchange for the
    temporal-blocked kind with ghost_depth scored by the VMEM-
    calibrated auto-depth picker (== the engaged steps_per_call)."""
    from fdtd3d_tpu.plan import comm_strategy, plan_for_topology
    led_tb = sharded_ledgers["pallas_packed_tb"]
    strat = led_tb["comm"]["strategy"]
    assert strat is not None
    assert strat["step_kind"] == "pallas_packed_tb"
    # ghost_depth is the SCORED free variable: it equals the depth the
    # step actually engaged (steps_per_call), picked deepest-viable
    assert strat["ghost_depth"] == led_tb["steps_per_call"]
    assert strat["ghost_depth"] in (2, 3, 4)
    assert strat["split"] == "fused"
    assert strat["schedule"] == "async"
    assert strat["source"] == "model"
    assert strat["shard_axes"] == ["x", "y", "z"]
    # plan_for_topology carries the SAME decision (the authority)
    p = plan_for_topology(_cfg("pallas_packed_tb"), TOPO)
    assert p.comm_strategy is not None
    assert p.comm_strategy.as_record() == strat
    # deterministic: a second evaluation is identical
    s2 = comm_strategy(_cfg("pallas_packed_tb"), TOPO,
                       step_kind="pallas_packed_tb")
    assert s2.as_record() == strat
    # single-step kinds record depth 1 on the same topology
    s1 = sharded_ledgers["pallas_packed"]["comm"]["strategy"]
    assert s1["ghost_depth"] == 1 and s1["step_kind"] == "pallas_packed"


def test_strategy_env_override(monkeypatch):
    """FDTD3D_COMM_STRATEGY forces split/schedule (the registered
    knob); unknown tokens are a named config error."""
    from fdtd3d_tpu.plan import comm_strategy
    monkeypatch.setenv("FDTD3D_COMM_STRATEGY", "per-plane,sync")
    s = comm_strategy(_cfg("jnp"), TOPO)
    assert s.split == "per-plane" and s.schedule == "sync"
    assert s.source == "env:FDTD3D_COMM_STRATEGY"
    monkeypatch.setenv("FDTD3D_COMM_STRATEGY", "sync")
    s2 = comm_strategy(_cfg("jnp"), TOPO)
    assert s2.schedule == "sync" and s2.split == "fused"
    monkeypatch.setenv("FDTD3D_COMM_STRATEGY", "bogus")
    with pytest.raises(ValueError, match="FDTD3D_COMM_STRATEGY"):
        comm_strategy(_cfg("jnp"), TOPO)
    monkeypatch.delenv("FDTD3D_COMM_STRATEGY")
    assert comm_strategy(_cfg("jnp"), (1, 1, 1)) is None


def test_comm_lane_deterministic():
    led1 = costs.chunk_ledger(_cfg("jnp"), n_steps=8, kind="jnp",
                              topology=(1, 2, 2))
    led2 = costs.chunk_ledger(_cfg("jnp"), n_steps=8, kind="jnp",
                              topology=(1, 2, 2))
    assert json.dumps(led1, sort_keys=True) == \
        json.dumps(led2, sort_keys=True)


def test_topology_table_covers_factorizations(sharded_ledgers):
    """The per-topology halo-bytes/chip table carries every valid
    factorization of the chip count and each entry equals plan.py."""
    table = sharded_ledgers["jnp"]["comm"]["topology_table"]
    assert "2.2.2" in table and "1.2.4" in table
    for key, val in table.items():
        topo = tuple(int(x) for x in key.split("."))
        assert val == plan_for_topology(_cfg("jnp"),
                                        topo).halo_bytes_per_step, key


def test_plan_halo_by_axis_sums():
    p = plan_for_topology(_cfg("jnp"), TOPO)
    assert set(p.halo_by_axis) == {"x", "y", "z"}
    assert sum(r["bytes_per_step"] for r in p.halo_by_axis.values()) \
        == p.halo_bytes_per_step
    # an unsharded axis never appears
    p12 = plan_for_topology(_cfg("jnp"), (1, 2, 2))
    assert set(p12.halo_by_axis) == {"y", "z"}


def test_overlap_model_math(sharded_ledgers):
    om = sharded_ledgers["jnp"]["comm"]["overlap_model"]
    ps = sharded_ledgers["jnp"]["comm"]["per_step"]
    step_b = sharded_ledgers["jnp"]["per_step"]["bytes"]
    assert om["hbm_gbps"] == 600.0
    # interior-only: the halo planes the byte walk charged move on
    # ICI, not HBM — they must not be double-booked at both rates
    assert om["modeled_compute_ms"] == pytest.approx(
        (step_b - ps["ppermute_bytes_per_chip"])
        / (600.0 * 1e9) * 1e3)
    assert om["modeled_comm_ms"] == pytest.approx(
        ps["ppermute_bytes_per_chip"] / (om["ici_gbps"] * 1e9) * 1e3)
    assert om["modeled_step_ms_sync"] >= om["modeled_step_ms_async"]
    assert om["modeled_async_speedup"] >= 1.0
    # no HBM calibration -> no overlap model, never fabricated
    assert costs.overlap_model(1e6, 1e3, None) is None
    assert costs.overlap_model(1e6, 1e3, -1.0) is None


def test_unsharded_ledger_has_null_comm():
    led = costs.chunk_ledger(costs.config_for_kind("jnp"), n_steps=8,
                             kind="jnp")
    costs.validate_ledger(led)
    assert led["ledger_version"] == 2
    assert led["comm"] is None
    assert led["topology"] is None


def test_v1_ledger_still_validates():
    """Compat: v1 files (no comm key) keep reading — the checked-in
    PR-3 fixtures are the proof corpus."""
    for name in ("ledger_ref.json", "ledger_tb_ref.json"):
        with open(os.path.join(FIX, name)) as f:
            led = json.load(f)
        assert led["ledger_version"] == 1
        costs.validate_ledger(led)
    # but a v2 ledger that DROPS the comm key is malformed
    led2 = costs.chunk_ledger(costs.config_for_kind("jnp"), n_steps=8,
                              kind="jnp")
    bad = json.loads(json.dumps(led2))
    del bad["comm"]
    with pytest.raises(ValueError, match="comm"):
        costs.validate_ledger(bad)
    with pytest.raises(ValueError, match="not in"):
        costs.validate_ledger(dict(led2, ledger_version=3))


def test_validate_comm_rejects_malformed(sharded_ledgers):
    comm = json.loads(json.dumps(sharded_ledgers["jnp"]["comm"]))
    costs.validate_comm(comm)
    costs.validate_comm(None)
    bad = dict(comm)
    bad["per_step"] = dict(comm["per_step"], halo_attribution=1.7)
    with pytest.raises(ValueError, match="halo_attribution"):
        costs.validate_comm(bad)
    bad2 = dict(comm)
    del bad2["topology_table"]
    with pytest.raises(ValueError, match="topology_table"):
        costs.validate_comm(bad2)


def test_overlap_artifact_rides_ledger():
    with open(os.path.join(FIX, "comm_ref.json")) as f:
        ref = json.load(f)
    aw = ref["comm"]["async_windows"]
    assert aw["windows_with_compute"] == 2
    assert aw["sync_collective_permutes"] == 0
    # chunk_ledger(overlap=...) embeds exactly the count keys
    led = costs.chunk_ledger(_cfg("jnp"), n_steps=8, kind="jnp",
                             topology=TOPO,
                             overlap={"schema": "fdtd3d-overlap",
                                      "async_starts": 8, "windows": 8,
                                      "windows_with_compute": 8,
                                      "sync_collective_permutes": 0,
                                      "irrelevant": "dropped"})
    assert led["comm"]["async_windows"]["windows_with_compute"] == 8
    assert "irrelevant" not in led["comm"]["async_windows"]
    # a wrong file fed to overlap= fails at ingest — it must not ship
    # an empty async_windows table that disables the sentinel gates
    with pytest.raises(ValueError, match="fdtd3d-overlap"):
        costs.chunk_ledger(_cfg("jnp"), n_steps=8, kind="jnp",
                           topology=TOPO,
                           overlap={"best_known_mcells": 15000.0})
    with pytest.raises(ValueError, match="windows_with_compute"):
        costs.check_overlap_artifact({"schema": "fdtd3d-overlap",
                                      "sync_collective_permutes": 0,
                                      "async_starts": 2, "windows": 2})


def test_costs_cli_topology(tmp_path, capsys):
    out = tmp_path / "ledger.json"
    rc = costs.main(["--kind", "jnp", "--same-size", "16",
                     "--pml-size", "2", "--topology", "2,2,2",
                     "--hbm-gbps", "600", "--ici-gbps", "45",
                     "--out", str(out)])
    assert rc == 0
    led = json.loads(out.read_text())
    costs.validate_ledger(led)
    assert led["comm"]["overlap_model"]["ici_gbps"] == 45.0
    assert led["comm"]["topology"] == [2, 2, 2]
    capsys.readouterr()
