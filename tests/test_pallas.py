"""Fused Pallas kernels (ops/pallas3d.py) vs the jnp step: exact parity.

The pallas path must be bit-compatible (up to f32 roundoff from operation
reordering) with the reference jnp step across every feature it claims:
vacuum curl, CPML slabs, material arrays, TFSF patches, point sources,
PEC walls. Runs in interpreter mode on the CPU test backend.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from fdtd3d_tpu import solver
from fdtd3d_tpu.config import (MaterialsConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.ops import pallas3d

BASE = dict(scheme="3D", size=(16, 16, 16), time_steps=3, dx=1e-3,
            courant_factor=0.5, wavelength=8e-3, dtype="float32")


def _random_state(static):
    state = solver.init_state(static)
    key = jax.random.PRNGKey(0)
    for grp in ("E", "H"):
        for c in state[grp]:
            key, k2 = jax.random.split(key)
            state[grp][c] = 0.01 * jax.random.normal(
                k2, state[grp][c].shape, jnp.float32)
    return state


def _compare(cfg, steps=3, tol=2e-6):
    static = solver.build_static(cfg)
    coeffs = jax.tree.map(jnp.asarray, solver.build_coeffs(static))
    state = _random_state(static)
    jnp_cfg = dataclasses.replace(cfg, use_pallas=False)
    jstep = solver.make_step(dataclasses.replace(static, cfg=jnp_cfg))
    pstep = pallas3d.make_pallas_step(static)
    assert pstep is not None, "config unexpectedly ineligible"
    s_j = s_p = state
    for _ in range(steps):
        s_j = jstep(s_j, coeffs)
        s_p = pstep(s_p, coeffs)
    for grp in ("E", "H", "psi_E", "psi_H"):
        if grp not in s_j:
            assert grp not in s_p or not s_p[grp]
            continue
        for c in s_j[grp]:
            diff = float(jnp.max(jnp.abs(s_j[grp][c] - s_p[grp][c])))
            ref = max(float(jnp.max(jnp.abs(s_j[grp][c]))), 1e-12)
            assert diff / ref < tol, f"{grp}/{c}: rel {diff / ref:.2e}"


def test_vacuum_parity():
    _compare(SimConfig(**BASE))


def test_cpml_parity():
    _compare(SimConfig(**BASE, pml=PmlConfig(size=(3, 3, 3))))


def test_material_array_parity():
    _compare(SimConfig(**BASE, materials=MaterialsConfig(
        eps=2.0, eps_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                         radius=4, value=6.0))))


def test_tfsf_parity():
    _compare(SimConfig(**BASE, pml=PmlConfig(size=(3, 3, 3)),
                       tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                                       angle_teta=30.0, angle_phi=15.0,
                                       angle_psi=40.0)))


def test_tfsf_driven_parity():
    """Zero initial fields, source-driven: catches corrections the random-
    field 3-step parity masks (round-1 regression: the H-family TFSF
    patches were missing entirely from the fused path)."""
    from fdtd3d_tpu.sim import Simulation
    import numpy as np

    def cfgs(use_pallas):
        return SimConfig(**BASE, use_pallas=use_pallas,
                         pml=PmlConfig(size=(3, 3, 3)),
                         tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                                         angle_teta=30.0, angle_phi=40.0,
                                         angle_psi=15.0))
    ref = Simulation(cfgs(False)); ref.run(30)
    got = Simulation(cfgs(True)); got.run(30)
    for c, r in ref.fields().items():
        scale = np.abs(r).max() + 1e-30
        err = np.abs(got.fields()[c] - r).max() / scale
        assert err < 2e-6, f"{c}: rel {err:.2e}"


def test_point_source_parity():
    _compare(SimConfig(**BASE, point_source=PointSourceConfig(
        enabled=True, component="Ez", position=(8, 8, 8), amplitude=2.0)))


def test_uneven_tile_parity():
    # Nx with a small prime factor exercises non-power-of-two tiling.
    cfg = dict(BASE)
    cfg["size"] = (12, 16, 16)
    _compare(SimConfig(**cfg), steps=2)


def test_drude_uniform_parity():
    # scalar kj/bj embedded as kernel constants
    _compare(SimConfig(**BASE, materials=MaterialsConfig(
        use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10)))


def test_drude_sphere_parity():
    # 3D kj/bj coefficient grids streamed through the kernel, plus CPML
    _compare(SimConfig(**BASE, pml=PmlConfig(size=(3, 3, 3)),
                       materials=MaterialsConfig(
                           use_drude=True, eps_inf=1.5, omega_p=1e11,
                           gamma=1e10,
                           drude_sphere=SphereConfig(
                               enabled=True, center=(8, 8, 8), radius=4))))


@pytest.mark.parametrize("reason,cfg", [
    ("2d-mode", dict(BASE, scheme="2D_TMz")),
    ("f64", dict(BASE, dtype="float64")),
])
def test_ineligible_falls_back(reason, cfg):
    static = solver.build_static(SimConfig(**cfg))
    assert pallas3d.make_pallas_step(static) is None, reason


def test_slab_post_axis_generic_matches_transposed_axis0():
    """slab_post's axis=1 path must equal the axis=0 path applied to
    x<->y transposed data (covers the generic branches, which have no
    production caller while the 2D-tiled fused kernel is shelved)."""
    import numpy as np

    cfg = SimConfig(**BASE, pml=PmlConfig(size=(3, 3, 3)))
    static = solver.build_static(cfg)
    slabs = solver.slab_axes(static)
    coeffs = {k: jnp.asarray(v) for k, v in
              solver.build_coeffs(static).items()}
    rng = np.random.default_rng(7)
    shape = static.grid_shape

    def rnd():
        return jnp.asarray(rng.standard_normal(shape), jnp.float32)

    fields = {c: rnd() for c in static.mode.e_components}
    src = {c: rnd() for c in static.mode.h_components}
    psi_y = {f"{c}_y": jnp.zeros((shape[0], 2 * slabs[1], shape[2]),
                                 jnp.float32)
             for c in static.mode.e_components}
    out_y, psi_out_y = pallas3d.slab_post(
        static, "E", fields, src, psi_y, coeffs, slabs, 1)

    # transpose x<->y: swap axes 0/1 of every array AND the component
    # roles (x-axis derivatives of the swapped components)
    swap = {"Ex": "Ey", "Ey": "Ex", "Ez": "Ez",
            "Hx": "Hy", "Hy": "Hx", "Hz": "Hz"}

    def tr(v):
        return jnp.swapaxes(v, 0, 1)

    fields_t = {swap[c]: tr(v) for c, v in fields.items()}
    src_t = {swap[c]: tr(v) for c, v in src.items()}
    psi_x_t = {f"{swap[k[:2]]}_x": tr(v) for k, v in psi_y.items()}
    # the cubic symmetric config has identical profiles on every axis
    out_x, psi_out_x = pallas3d.slab_post(
        static, "E", fields_t, src_t, psi_x_t, coeffs, slabs, 0)
    for c in fields:
        got = tr(out_x[swap[c]])
        want = out_y[c]
        # the x<->y swap flips the curl-term sign convention: Ey_x's
        # term sign is the negative of Ex_y's, so compare the DELTAS
        # in magnitude against the applied change
        d_y = np.abs(np.asarray(want - fields[c]))
        d_x = np.abs(np.asarray(got - fields[c]))
        np.testing.assert_allclose(d_x, d_y, rtol=1e-5, atol=1e-7,
                                   err_msg=c)
    for k in psi_out_y:
        kx = f"{swap[k[:2]]}_x"
        np.testing.assert_allclose(
            np.abs(np.asarray(tr(psi_out_x[kx]))),
            np.abs(np.asarray(psi_out_y[k])), rtol=1e-5, atol=1e-7)


def test_x_sharded_builds():
    """x-sharded meshes are eligible (VERDICT r2 item 1): the x boundary
    plane ppermutes into the shard-edge tiles. A vacuum 16^3 at px=2 has
    no PML so no slab-fit constraint applies."""
    static = solver.build_static(SimConfig(**BASE))
    static = dataclasses.replace(static, topology=(2, 1, 1))
    assert pallas3d.make_pallas_step(static, {0: "x"}, {"x": 2}) is not None


def test_thin_x_shard_with_pml_falls_back():
    """An x shard too thin for the slab-compacted x psi (local_n <=
    2*(pml+1)) must return None -> jnp fallback."""
    cfg = SimConfig(**BASE, pml=PmlConfig(size=(3, 3, 3)))
    static = solver.build_static(cfg)
    static = dataclasses.replace(static, topology=(2, 1, 1))
    assert pallas3d.make_pallas_step(static, {0: "x"}, {"x": 2}) is None


def test_bfloat16_storage_parity():
    """bf16 STORAGE mode (f32 compute): pallas vs jnp within bf16 rounding,
    and the recursion state (psi, J) must stay f32."""
    import numpy as np
    from fdtd3d_tpu.sim import Simulation

    def run(use_pallas):
        cfg = SimConfig(**{**BASE, "dtype": "bfloat16"},
                        use_pallas=use_pallas,
                        pml=PmlConfig(size=(3, 3, 3)),
                        tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                                        angle_teta=30.0, angle_phi=40.0,
                                        angle_psi=15.0),
                        materials=MaterialsConfig(
                            use_drude=True, eps_inf=1.5, omega_p=1e11,
                            gamma=1e10,
                            drude_sphere=SphereConfig(
                                enabled=True, center=(8, 8, 8), radius=3)))
        sim = Simulation(cfg)
        sim.run(12)
        return sim
    jref = run(False)
    pal = run(True)
    # the widened kernel scopes cover this config (round 12: oblique
    # TFSF + Drude + material grids ride the temporal-blocked kernel
    # in-kernel); any kernel path is the pallas side of the comparison
    assert pal.step_kind in ("pallas", "pallas_fused", "pallas_packed",
                             "pallas_packed_tb")
    assert jref.state["E"]["Ez"].dtype == jnp.bfloat16
    assert jref.state["J"]["Ez"].dtype == jnp.float32
    assert next(iter(jref.state["psi_E"].values())).dtype == jnp.float32
    for comp in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(jref.field(comp), np.float32)
        b = np.asarray(pal.field(comp), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-2, f"{comp}: rel {rel:.2e}"


def test_bfloat16_tracks_f32_within_storage_rounding():
    """Once the TFSF wave fills the box (O(1) amplitudes), bf16 storage
    with f32 compute stays within ~1% of the f32 run. (At leading-edge
    amplitudes the comparison is meaningless: TFSF cancellation in the
    scattered region is floored at the STORAGE epsilon, so bf16 leaks
    ~1e-2 of the incident wave there by construction.)"""
    import numpy as np
    from fdtd3d_tpu.sim import Simulation

    def run(dtype):
        cfg = SimConfig(scheme="3D", size=(24, 24, 24), time_steps=60,
                        dx=1e-3, courant_factor=0.5, wavelength=10e-3,
                        dtype=dtype, use_pallas=False,
                        pml=PmlConfig(size=(4, 4, 4)),
                        tfsf=TfsfConfig(enabled=True, margin=(3, 3, 3),
                                        angle_teta=20.0, angle_phi=30.0,
                                        angle_psi=10.0))
        sim = Simulation(cfg)
        sim.run()
        return sim
    f32 = run("float32")
    b16 = run("bfloat16")
    for comp in ("Ez", "Hy"):
        a = f32.field(comp)
        b = np.asarray(b16.field(comp), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 5e-2, f"{comp}: rel {rel:.2e}"


def test_magnetic_drude_parity():
    # metamaterial mode: K recursion runs in the H-family kernel
    _compare(SimConfig(**BASE, pml=PmlConfig(size=(3, 3, 3)),
                       materials=MaterialsConfig(
                           use_drude=True, eps_inf=1.5, omega_p=1e11,
                           gamma=1e10,
                           drude_sphere=SphereConfig(
                               enabled=True, center=(8, 8, 8), radius=4),
                           use_drude_m=True, mu_inf=1.5, omega_pm=1e11,
                           gamma_m=1e10,
                           drude_m_sphere=SphereConfig(
                               enabled=True, center=(8, 8, 8), radius=4))))
