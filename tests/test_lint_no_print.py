"""Lint guard: no bare ``print(`` in fdtd3d_tpu/ or tools/ outside
log.py.

Round 3 routed every user-facing message through the one-switch leveled
logger (fdtd3d_tpu/log.py: ``--log-level``, rank-0 gating); a stray
print() reintroduces scattered, unsilenceable, every-rank output. This
tier-1 guard makes the decision structural (ISSUE 2 satellite).
Round 7 extends the guard to tools/: a tool's primary stdout product
(reports, JSON lines) goes through the shared ``log.report()`` helper
and progress/warnings through ``log.log()``/``log.warn()`` — argparse
``--help`` output is argparse's own and never a bare print call site.
"""

import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = (os.path.join(ROOT, "fdtd3d_tpu"),
             os.path.join(ROOT, "tools"))

# log.py IS the print wrapper — the single allowed call site.
ALLOWED = {"log.py"}

# Quarantined LEGACY tools (round 10): superseded by the attribution
# layer (PR 3) and gated behind --i-know-this-is-legacy; they are
# frozen historical reproduction scripts, not part of the maintained
# tools surface this lint guards.
LEGACY = {"measure_r3.py", "measure_r4.py"}

# a call site: "print(" not preceded by a word char or dot (so
# pprint(, x.print( and docstring prose mentioning print() with a
# preceding backtick/quote still need the line-level filters below)
import re

_CALL = re.compile(r"(?<![\w.])print\(")


def _code_lines(path):
    """-> [(lineno, code)] with strings and # comments stripped via the
    tokenizer, so docstring prose mentioning print() never trips."""
    import tokenize
    from collections import defaultdict
    lines = defaultdict(str)
    with open(path, "rb") as f:
        for tok in tokenize.tokenize(f.readline):
            if tok.type in (tokenize.STRING, tokenize.COMMENT):
                continue
            lines[tok.start[0]] += tok.string
    return sorted(lines.items())


def test_no_bare_print_outside_log():
    offenders = []
    for scan_root in SCAN_DIRS:
        for root, _dirs, files in os.walk(scan_root):
            for fname in files:
                if not fname.endswith(".py") or fname in ALLOWED \
                        or fname in LEGACY:
                    continue
                path = os.path.join(root, fname)
                for lineno, tok in _code_lines(path):
                    if _CALL.search(tok):
                        rel = os.path.relpath(path, ROOT)
                        offenders.append(f"{rel}:{lineno}: {tok.strip()}")
    assert not offenders, (
        "bare print() outside fdtd3d_tpu/log.py — route through "
        "log.log()/log.warn()/log.report() (one-switch logging, "
        "rounds 3+7):\n" + "\n".join(offenders))
