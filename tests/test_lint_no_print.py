"""Lint guard: no bare ``print(`` in fdtd3d_tpu/ or tools/ outside
log.py.

Round 3 routed every user-facing message through the one-switch leveled
logger (fdtd3d_tpu/log.py: ``--log-level``, rank-0 gating); a stray
print() reintroduces scattered, unsilenceable, every-rank output.
Round 7 extended the guard to tools/ (``log.report()`` for product
output). Round 12 (ISSUE 9): the hand-rolled tokenizer walker moved
into the static-analysis framework — this file is now a thin tier-1
wrapper over the ``no-bare-print`` rule
(fdtd3d_tpu/analysis/ast_rules.py), which ``tools/fdtd_lint.py`` also
runs; the rule's known-bad fixture lives in
tests/fixtures/lint/bad_print.py and tests/test_analysis.py proves it
fires.
"""

from fdtd3d_tpu.analysis import Context
from fdtd3d_tpu.analysis.ast_rules import NoBarePrintRule


def test_no_bare_print_outside_log():
    findings, stats = NoBarePrintRule().run(Context())
    assert stats["files_scanned"] > 20, "scan surface collapsed?"
    assert not findings, (
        "bare print() outside fdtd3d_tpu/log.py — route through "
        "log.log()/log.warn()/log.report() (one-switch logging, "
        "rounds 3+7):\n"
        + "\n".join(f.format() for f in findings))
