"""Fenced multi-scheduler lease plane (ISSUE 20 acceptance).

The load-bearing claims under test:

* FENCING: every ``lease_acquire`` grants a monotonic token (max ever
  granted + 1 — across takeovers, re-acquires and releases), every
  ``job_state`` row a leased scheduler writes carries it as
  ``fence``, and the shared :func:`jobqueue.fold` REJECTS rows whose
  fence is staler than the newest acquire preceding them — so N
  schedulers sharing one append-only journal provably cannot
  double-dispatch. Rows with no fence (pre-v11 journals, bare-cycle
  library mode) are always accepted.
* LEASE LIFECYCLE: ``serve()`` acquires before touching any job,
  renews once per cycle, and releases ONLY on orderly exit; expiry is
  deadline math (``unix + ttl_s``) on an injectable clock — no sleeps
  anywhere in this file.
* TAKEOVER: a crashed/zombified holder's lease expires on the
  survivor's clock; the survivor's acquire names the dead holder in
  ``takeover_from``, requeues its orphans under the fresh token, and
  the orphan completes BIT-IDENTICAL to an uninterrupted run
  (snapshots make re-dispatch deterministic).
* FAULT GRAMMAR: ``sched_crash@between=acquire,dispatch`` /
  ``between=renew,commit`` kill the scheduler at lease boundaries;
  ``lease_expire@job=N`` makes a deterministic zombie — per-kind
  allowed-key validation rejects misapplied plans loudly.
* COMPACTION: ``compact()`` folds the journal into a snapshot
  row-set published atomically as a NEW generation file —
  ``fold(compacted) == fold(original)``, tailing consumers observe a
  NAMED rotation and their re-fold is identical, a live lease refuses
  compaction by name.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from fdtd3d_tpu import faults, io, jobqueue, tail

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")

BASE = ("--3d\n--same-size 12\n--time-steps 8\n"
        "--courant-factor 0.4\n--wavelength 0.008\n")


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    monkeypatch.delenv("FDTD3D_LEASE_TTL_S", raising=False)
    monkeypatch.delenv("FDTD3D_HEARTBEAT_S", raising=False)
    faults.clear()
    yield
    faults.clear()


def _spec(tmp_path, name="a.txt", extra=""):
    p = tmp_path / name
    p.write_text(BASE + extra)
    return str(p)


def _ident(n, start):
    return jobqueue.SchedIdentity(pid=7000 + n, host=f"w{n}",
                                  start=float(start))


def _run_tool(args, extra_env=None, timeout=300):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **(extra_env or {})}
    return subprocess.run([sys.executable] + args,
                          capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=ROOT)


# -------------------------------------------------------------------------
# fault grammar: lease windows + per-kind allowed-key validation
# -------------------------------------------------------------------------

def test_fault_grammar_parses_lease_windows():
    plan = faults.FaultPlan.parse(
        "sched_crash@between=acquire,dispatch")
    assert plan.faults[0].between == "acquire,dispatch"
    assert plan.faults[0].job is None
    plan = faults.FaultPlan.parse(
        "sched_crash@between=renew,commit; lease_expire@job=2")
    assert plan.faults[0].between == "renew,commit"
    assert plan.faults[1].kind == "lease_expire"
    assert plan.faults[1].job == 2


def test_fault_grammar_rejects_bad_lease_plans():
    # an unknown boundary window is named, with the valid set
    with pytest.raises(ValueError, match="between must be one of"):
        faults.FaultPlan.parse("sched_crash@between=lunch,dinner")
    # sched_crash takes EXACTLY one of job= / between=
    with pytest.raises(ValueError, match="exactly one of"):
        faults.FaultPlan.parse(
            "sched_crash@job=1,between=acquire,dispatch")
    with pytest.raises(ValueError, match="exactly one of"):
        faults.FaultPlan.parse("sched_crash")
    # lease_expire needs its dispatch ordinal
    with pytest.raises(ValueError, match="lease_expire needs"):
        faults.FaultPlan.parse("lease_expire")
    # per-kind allowed keys: a key the kind would silently ignore is
    # a plan that "proves" a scenario that never ran
    with pytest.raises(ValueError, match="does not apply"):
        faults.FaultPlan.parse("lease_expire@t=3")
    with pytest.raises(ValueError, match="does not apply"):
        faults.FaultPlan.parse("preempt@between=acquire,dispatch")


# -------------------------------------------------------------------------
# the fold: fencing, lease lineage, deadline math (pure unit tests)
# -------------------------------------------------------------------------

def _acq(ident, token, unix, ttl=30.0, **kw):
    return {"v": 11, "type": "lease_acquire", "sched": ident.sched,
            "pid": ident.pid, "host": ident.host,
            "start": ident.start, "token": token, "unix": unix,
            "ttl_s": ttl, **kw}


def _sub(jid, unix, tenant="acme"):
    return {"v": 11, "type": "job_submit", "job_id": jid,
            "tenant": tenant, "status": "queued", "priority": 0,
            "wall_time": "2026-08-07", "spec": "a.txt",
            "cells": 1728.0, "unix": unix}


def _st(jid, status, tenant="acme", **kw):
    return {"v": 11, "type": "job_state", "job_id": jid,
            "tenant": tenant, "status": status, **kw}


def test_fold_rejects_stale_fenced_rows():
    w0, w1 = _ident(0, 100.0), _ident(1, 200.0)
    recs = [
        _acq(w0, 1, 100.0),
        _sub("j1", 101.0), _sub("j2", 102.0),
        _st("j1", "running", fence=1, sched=w0.sched),
        _acq(w1, 2, 150.0, takeover_from=w0.sched),
        _st("j1", "queued", fence=2, sched=w1.sched, unix=150.5),
        # the zombie's completion lands AFTER the takeover: rejected
        _st("j1", "completed", fence=1, sched=w0.sched, t=8),
        _st("j1", "running", fence=2, sched=w1.sched),
        _st("j1", "completed", fence=2, sched=w1.sched, t=8),
    ]
    out = jobqueue.fold(recs)
    j1 = out["jobs"]["j1"]
    assert j1["status"] == "completed"
    assert j1["fence"] == 2 and j1["sched"] == w1.sched
    assert out["max_token"] == 2
    assert [r["fence"] for r in out["stale_rejected"]] == [1]
    assert out["stale_rejected"][0]["status"] == "completed"
    # the rejected terminal row did NOT tick the aging clock: j2 aged
    # by the ONE accepted completion, not two
    assert out["jobs"]["j2"]["age"] == 1
    # lease view: w1 holds, unreleased, takeover lineage named
    lease = out["lease"]
    assert lease["sched"] == w1.sched and not lease["released"]
    assert lease["takeover_from"] == w0.sched


def test_fold_accepts_unfenced_rows_always():
    """Pre-v11 journals (and bare-cycle library mode) carry no fence:
    the fold accepts their rows even under a high max_token."""
    w1 = _ident(1, 200.0)
    recs = [_acq(w1, 5, 100.0), _sub("j1", 101.0),
            _st("j1", "running"), _st("j1", "completed", t=8)]
    out = jobqueue.fold(recs)
    assert out["jobs"]["j1"]["status"] == "completed"
    assert out["stale_rejected"] == []


def test_fold_renew_and_release_token_rules():
    w0 = _ident(0, 100.0)
    w1 = _ident(1, 200.0)
    recs = [_acq(w0, 1, 100.0, ttl=10.0),
            {**_acq(w0, 1, 105.0, ttl=10.0), "type": "lease_renew"},
            _acq(w1, 2, 120.0, ttl=10.0),
            # a zombie's renew (stale token) is ignored like its rows
            {**_acq(w0, 1, 125.0, ttl=10.0), "type": "lease_renew"},
            # ...and so is a release bearing a stale token
            {**_acq(w0, 1, 126.0, ttl=0.0), "type": "lease_release"}]
    out = jobqueue.fold(recs)
    lease = out["lease"]
    assert lease["token"] == 2 and not lease["released"]
    assert lease["unix"] == 120.0
    assert jobqueue.lease_deadline(lease) == 130.0
    # the current holder's release ends tenure
    recs.append({**_acq(w1, 2, 128.0, ttl=0.0),
                 "type": "lease_release"})
    assert jobqueue.fold(recs)["lease"]["released"] is True
    assert jobqueue.lease_deadline(None) is None


# -------------------------------------------------------------------------
# the lease API: monotonic tokens, named refusal, fenced takeover
# -------------------------------------------------------------------------

def test_acquire_takeover_and_monotonic_tokens(tmp_path):
    q = jobqueue.JobQueue(str(tmp_path / "q"))
    a, b = _ident(0, 100.0), _ident(1, 200.0)
    assert q.lease_state() is None
    t1 = q.acquire_lease(a, now=100.0, ttl_s=10.0)
    assert t1 == 1
    # a live peer's lease refuses by NAME: holder + deadline
    with pytest.raises(jobqueue.LeaseHeld,
                       match=re.escape(a.sched)):
        q.acquire_lease(b, now=105.0, ttl_s=10.0)
    # past the deadline the takeover names the expired holder
    t2 = q.acquire_lease(b, now=111.0, ttl_s=10.0)
    assert t2 == 2
    lease = q.lease_state()
    assert lease["sched"] == b.sched
    assert lease["takeover_from"] == a.sched
    # a live holder re-acquiring bumps the token (re-fences itself
    # forward) — no takeover, no refusal
    t3 = q.acquire_lease(b, now=112.0, ttl_s=10.0)
    assert t3 == 3
    assert q.lease_state()["takeover_from"] is None
    q.release_lease(b, t3, now=113.0, reason="done")
    assert q.lease_state()["released"] is True
    # tokens stay monotonic across a release too
    assert q.acquire_lease(a, now=114.0, ttl_s=10.0) == 4


def test_requeue_orphans_carries_callers_fence(tmp_path):
    q = jobqueue.JobQueue(str(tmp_path / "q"))
    j1 = q.submit(_spec(tmp_path, "a.txt"), tenant="acme")
    j2 = q.submit(_spec(tmp_path, "b.txt", "--eps 2.0\n"),
                  tenant="acme")
    q._emit("job_state", job_id=j1, tenant="acme", status="running")
    q._emit("job_state", job_id=j2, tenant="acme", status="running")
    q._emit("job_state", job_id=j2, tenant="acme",
            status="completed", t=8)
    n = q.requeue_orphans("lost holder", fence=7, sched="w9:1:2")
    assert n == 1   # only the running job; terminal jobs stay put
    job = q.jobs()[j1]
    assert job["status"] == "queued"
    assert job["fence"] == 7 and job["sched"] == "w9:1:2"
    assert job["reason"] == "lost holder"
    assert q.jobs()[j2]["status"] == "completed"


# -------------------------------------------------------------------------
# scheduler lifecycle: leased serve() vs unleased bare cycle()
# -------------------------------------------------------------------------

def test_serve_lease_lifecycle_rows(tmp_path, monkeypatch):
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY",
                       str(tmp_path / "runs.jsonl"))
    q = jobqueue.JobQueue(str(tmp_path / "q"))
    jid = q.submit(_spec(tmp_path))
    now = [1000.0]
    s = jobqueue.Scheduler(q, clock=lambda: now[0], lease_ttl=30.0)
    out = s.serve()
    assert out["jobs"][jid]["status"] == "completed"
    recs = q.read()
    types = [r["type"] for r in recs]
    assert types.count("lease_acquire") == 1
    assert types.count("lease_renew") >= 1
    rel = [r for r in recs if r["type"] == "lease_release"]
    assert len(rel) == 1
    assert rel[0]["reason"] == "serve loop exited"
    assert rel[0]["ttl_s"] == 0.0
    # every job_state row the leased scheduler wrote is fenced with
    # ITS token + identity
    for r in recs:
        if r["type"] == "job_state":
            assert r["fence"] == 1
            assert r["sched"] == s.identity.sched
    assert jobqueue.fold(recs)["stale_rejected"] == []


def test_bare_cycle_runs_unleased(tmp_path, monkeypatch):
    """Library mode: cycle() without serve() writes no lease rows and
    no fence keys — the fold accepts them (single-scheduler mode)."""
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY",
                       str(tmp_path / "runs.jsonl"))
    q = jobqueue.JobQueue(str(tmp_path / "q"))
    jid = q.submit(_spec(tmp_path))
    s = jobqueue.Scheduler(q)
    s.cycle()
    assert q.jobs()[jid]["status"] == "completed"
    recs = q.read()
    assert not [r for r in recs
                if r["type"].startswith("lease_")]
    assert all("fence" not in r for r in recs
               if r["type"] == "job_state")
    assert jobqueue.fold(recs)["lease"] is None


# -------------------------------------------------------------------------
# the zombie: lease_expire@job=N + stale-token rejection, exactly-once
# -------------------------------------------------------------------------

def test_zombie_scheduler_is_fenced_out_exactly_once(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY",
                       str(tmp_path / "runs.jsonl"))
    q = jobqueue.JobQueue(str(tmp_path / "q"))
    j1 = q.submit(_spec(tmp_path, "a.txt"))
    now = [1000.0]
    faults.install("lease_expire@job=1")
    s1 = jobqueue.Scheduler(q, clock=lambda: now[0], lease_ttl=30.0)
    out = s1.serve()
    # the zombie completed j1 (its token was still current) but never
    # renewed and never released: the lease is left to EXPIRE
    assert out["jobs"][j1]["status"] == "completed"
    assert s1._zombie
    lease = q.lease_state()
    assert lease["token"] == 1 and not lease["released"]
    assert [r["type"] for r in q.read()].count("lease_renew") == 0

    # a peer waits out the TTL on ITS clock and fences the zombie out
    j2 = q.submit(_spec(tmp_path, "b.txt", "--eps 2.0\n"))
    now[0] += 31.0
    s2 = jobqueue.Scheduler(q, clock=lambda: now[0], lease_ttl=30.0)
    t2 = q.acquire_lease(s2.identity, now[0], ttl_s=30.0)
    assert t2 == 2
    acq = [r for r in q.read() if r["type"] == "lease_acquire"]
    assert acq[-1]["takeover_from"] == s1.identity.sched

    # the zombie keeps dispatching with its stale token...
    s1.cycle()
    folded = jobqueue.fold(q.read())
    # ...and EVERY row it wrote is rejected: j2 still reads queued
    assert folded["jobs"][j2]["status"] == "queued"
    stale = folded["stale_rejected"]
    assert stale and all(r["fence"] == 1 for r in stale)
    assert {r["job_id"] for r in stale} == {j2}

    # the survivor dispatches j2 under its own fence — the journal
    # folds to exactly ONE accepted completion per job
    out2 = s2.serve()
    jobs = out2["jobs"]
    assert jobs[j1]["status"] == "completed"
    assert jobs[j2]["status"] == "completed"
    assert jobs[j2]["sched"] == s2.identity.sched
    assert jobs[j2]["fence"] > t2  # serve's re-acquire re-fenced
    final = jobqueue.fold(q.read())
    completions = [r for r in q.read()
                   if r["type"] == "job_state"
                   and r["job_id"] == j2
                   and r["status"] == "completed"]
    assert len(completions) == 2          # the zombie's + the real one
    assert sum(1 for r in completions
               if r not in final["stale_rejected"]) == 1
    assert final["lease"]["released"] is True


# -------------------------------------------------------------------------
# lease-boundary crashes: held-but-idle tenure expires, peer takes over
# -------------------------------------------------------------------------

@pytest.mark.parametrize("window", ["acquire,dispatch",
                                    "renew,commit"])
def test_lease_boundary_crash_then_fenced_takeover(tmp_path,
                                                   monkeypatch,
                                                   window):
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY",
                       str(tmp_path / "runs.jsonl"))
    q = jobqueue.JobQueue(str(tmp_path / "q"))
    jid = q.submit(_spec(tmp_path))
    now = [1000.0]
    faults.install(f"sched_crash@between={window}")
    s1 = jobqueue.Scheduler(q, clock=lambda: now[0], lease_ttl=30.0)
    with pytest.raises(faults.SimulatedPreemption,
                       match="crashed between"):
        s1.serve()
    # the lease row is durable, zero progress behind it
    lease = q.lease_state()
    assert lease["sched"] == s1.identity.sched
    assert not lease["released"]
    assert q.jobs()[jid]["status"] == "queued"

    faults.clear()
    # the peer's identity differs by its start stamp (same pid+host
    # in-process — the start clock is what disambiguates restarts)
    now[0] += 1.0
    s2 = jobqueue.Scheduler(q, clock=lambda: now[0], lease_ttl=30.0)
    # the dead holder's lease is still live on this clock: refused
    with pytest.raises(jobqueue.LeaseHeld,
                       match=re.escape(s1.identity.sched)):
        s2.serve()
    # ...until its deadline passes — then the takeover completes it
    now[0] += 30.0
    out = s2.serve()
    assert out["jobs"][jid]["status"] == "completed"
    lease = q.lease_state()
    assert lease["released"] and lease["sched"] == s2.identity.sched
    acq = [r for r in q.read() if r["type"] == "lease_acquire"]
    assert acq[-1]["takeover_from"] == s1.identity.sched


# -------------------------------------------------------------------------
# chaos lane: seeded two-scheduler fault cocktails + compaction after
# -------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chaos_two_scheduler_lease_cocktails(tmp_path, monkeypatch,
                                             seed):
    """Whatever one-fault cocktail kills/zombifies scheduler #1, a
    survivor on an advanced clock drives every job terminal with an
    internally consistent journal, and post-incident compaction
    preserves the fold."""
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY",
                       str(tmp_path / "runs.jsonl"))
    rng = np.random.default_rng(seed)
    cocktails = ["sched_crash@between=acquire,dispatch",
                 "sched_crash@between=renew,commit",
                 "sched_crash@job=1",
                 "lease_expire@job=1"]
    spec = cocktails[int(rng.integers(0, len(cocktails)))]
    q = jobqueue.JobQueue(str(tmp_path / "q"))
    jid = q.submit(_spec(tmp_path))
    now = [1000.0]
    faults.install(spec)
    s1 = jobqueue.Scheduler(q, clock=lambda: now[0], lease_ttl=30.0)
    try:
        s1.serve()
    except faults.SimulatedPreemption:
        pass
    faults.clear()
    now[0] += 31.0
    s2 = jobqueue.Scheduler(q, clock=lambda: now[0], lease_ttl=30.0)
    out = s2.serve()
    assert out["jobs"][jid]["status"] == "completed", spec
    before = jobqueue.fold(q.read())
    assert before["lease"]["released"], spec
    # exactly-once: the accepted completion count is 1 regardless of
    # how many stale rows the incident produced
    accepted = [r for r in q.read()
                if r["type"] == "job_state"
                and r["status"] == "completed"
                and r not in before["stale_rejected"]]
    assert len(accepted) == 1, spec
    # the dust settled: compaction preserves jobs, lease and tokens
    stats = q.compact(now=now[0])
    assert stats["rows_after"] <= stats["rows_before"]
    after = jobqueue.fold(q.read())
    assert after["jobs"][jid]["status"] == "completed"
    assert after["max_token"] == before["max_token"]
    assert after["lease"]["released"]


# -------------------------------------------------------------------------
# compaction under tailing: named rotation, identical re-fold
# -------------------------------------------------------------------------

def test_compact_under_tailing_named_rotation(tmp_path):
    q = jobqueue.JobQueue(str(tmp_path / "q"))
    ident = _ident(0, 100.0)
    j1 = q.submit(_spec(tmp_path, "a.txt"), tenant="acme")
    j2 = q.submit(_spec(tmp_path, "b.txt", "--eps 2.0\n"),
                  tenant="acme")
    j3 = q.submit(_spec(tmp_path, "c.txt", "--eps 3.0\n"),
                  tenant="globex")
    token = q.acquire_lease(ident, now=100.0, ttl_s=10.0)
    for i in range(8):
        q.renew_lease(ident, token, now=101.0 + i, ttl_s=10.0)
    for jid in (j1, j2):
        q._emit("job_state", job_id=jid, tenant="acme",
                status="running", fence=token, sched=ident.sched)
        q._emit("job_state", job_id=jid, tenant="acme",
                status="completed", t=8, fence=token,
                sched=ident.sched)

    # a follow consumer is mid-stream before the compaction
    t = tail.Tailer()
    assert len(t.poll_records(q.journal)) == len(q.read())

    # a LIVE lease refuses compaction, naming the holder
    with pytest.raises(jobqueue.LeaseHeld,
                       match=re.escape(ident.sched)):
        q.compact(now=105.0)

    q.release_lease(ident, token, now=120.0, reason="done")
    before = jobqueue.fold(q.read())
    stats = q.compact(now=121.0)
    assert stats["rows_after"] < stats["rows_before"]
    assert stats["max_token"] == token

    # fold identity survives the rotation (jobs, ages, lease, token)
    after = jobqueue.fold(q.read())
    for jid in (j1, j2, j3):
        assert after["jobs"][jid]["status"] == \
            before["jobs"][jid]["status"]
        assert after["jobs"][jid]["age"] == before["jobs"][jid]["age"]
    assert after["max_token"] == before["max_token"]
    assert after["lease"]["token"] == token
    assert after["lease"]["released"]

    # the tailing consumer sees a NAMED rotation (new inode), replays
    # the new generation from zero, and its re-fold is identical;
    # the replay cost is the compacted size, not the old history
    read0 = t.bytes_read
    replayed = t.poll_records(q.journal)
    assert any(e.startswith("rotated:") for e in t.events)
    refold = jobqueue.fold(replayed)
    for jid in (j1, j2, j3):
        assert refold["jobs"][jid]["status"] == \
            before["jobs"][jid]["status"]
    assert refold["max_token"] == before["max_token"]
    assert t.bytes_read - read0 == os.path.getsize(q.journal)


# -------------------------------------------------------------------------
# the whole incident through real CLIs: crash -> watcher names it ->
# fenced eviction -> survivor completes BIT-IDENTICAL
# -------------------------------------------------------------------------

def test_two_scheduler_cli_takeover_bit_identical(tmp_path,
                                                  monkeypatch):
    reg = str(tmp_path / "runs.jsonl")
    qdir = str(tmp_path / "queue")
    spec = tmp_path / "c.txt"
    spec.write_text("--3d\n--same-size 12\n--time-steps 24\n"
                    "--courant-factor 0.4\n--wavelength 0.008\n"
                    "--point-source Ez\n--checkpoint-every 8\n")
    qtool = os.path.join(TOOLS, "fdtd_queue.py")
    env = {"FDTD3D_RUN_REGISTRY": reg}

    proc = _run_tool([qtool, "submit", str(spec),
                      "--queue-dir", qdir, "--tenant", "acme"],
                     extra_env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    jid = re.search(r"submitted (\S+)", proc.stdout).group(1)

    # scheduler #1: the run is preempted at t=16 (snapshot at t=16
    # committed), then sched_crash kills the scheduler before the
    # journal row lands — a dead dispatcher holding the lease
    proc = _run_tool([qtool, "serve", "--queue-dir", qdir],
                     extra_env={**env, "FDTD3D_HEARTBEAT_S": "1",
                                "FDTD3D_FAULT_PLAN":
                                "preempt@t=16; sched_crash@job=1"})
    assert proc.returncode != 0

    proc = _run_tool([qtool, "status", "--queue-dir", qdir,
                      "--json"], extra_env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    st = json.loads(proc.stdout)
    assert st["jobs"][jid]["status"] == "running"
    lease = st["lease"]
    assert not lease["released"] and st["max_token"] == 1
    dead_sched = lease["sched"]

    # compaction refuses while the (dead but unexpired) lease is live
    proc = _run_tool([qtool, "compact", "--queue-dir", qdir,
                      "--now", str(lease["unix"] + 1.0)],
                     extra_env=env)
    assert proc.returncode == 1
    assert "refused" in (proc.stdout + proc.stderr)

    # the watcher NAMES the lost scheduler at a clock past its lease
    # deadline, and --evict appends the fenced takeover + requeue
    journal = os.path.join(qdir, "journal.jsonl")
    future = lease["unix"] + 1000.0
    proc = _run_tool([os.path.join(TOOLS, "fleet_watch.py"),
                      "--journal", journal, "--once", "--evict",
                      "--now", str(future), "--json"],
                     extra_env=env)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    lost = [r for r in rep["liveness"]
            if r["emitter"] == "scheduler"]
    assert lost and lost[0]["status"] == "lost"
    assert rep["evict"] is not None
    assert rep["evict"]["takeover_from"] == dead_sched
    assert rep["evict"]["requeued"] == 1

    # mid-incident the fold reads the orphan QUEUED under the fresh
    # fence — no double-dispatch can be journaled into existence
    proc = _run_tool([qtool, "status", "--queue-dir", qdir,
                      "--json"], extra_env=env)
    st = json.loads(proc.stdout)
    assert st["jobs"][jid]["status"] == "queued"
    assert st["jobs"][jid]["fence"] == 2
    assert st["lease"]["released"]   # the evictor released its tenure

    # scheduler #2 (fresh identity) resumes from the committed t=16
    # snapshot and completes the orphan
    proc = _run_tool([qtool, "serve", "--queue-dir", qdir],
                     extra_env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_tool([qtool, "status", "--queue-dir", qdir,
                      "--json"], extra_env=env)
    st = json.loads(proc.stdout)
    assert st["jobs"][jid]["status"] == "completed"
    assert st["jobs"][jid]["t"] == 24
    assert st["jobs"][jid]["fence"] == 3
    assert st["jobs"][jid]["sched"] != dead_sched

    # the telemetry report tells the lease story from the journal
    proc = _run_tool([os.path.join(TOOLS, "telemetry_report.py"),
                      journal], extra_env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ACQUIRE" in proc.stdout
    assert "TAKEOVER" in proc.stdout
    assert "jobs by scheduler" in proc.stdout

    # ...and the fleet rollup joins it across the registry
    proc = _run_tool([os.path.join(TOOLS, "fleet_report.py"), reg,
                      "--journal", journal, "--json"],
                     extra_env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rollup = json.loads(proc.stdout)
    leases = rollup["fleet"]["leases"]
    assert leases["takeovers"] == 1
    assert len(leases["job_rows_by_sched"]) >= 2

    # compaction now succeeds and the status fold is unchanged
    proc = _run_tool([qtool, "compact", "--queue-dir", qdir],
                     extra_env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_tool([qtool, "status", "--queue-dir", qdir,
                      "--json"], extra_env=env)
    st = json.loads(proc.stdout)
    assert st["jobs"][jid]["status"] == "completed"
    assert st["max_token"] == 3

    # BIT-IDENTICAL: an uninterrupted run of the same spec ends in
    # the same final snapshot, array for array
    monkeypatch.delenv("FDTD3D_RUN_REGISTRY", raising=False)
    from fdtd3d_tpu import cli
    ref_dir = str(tmp_path / "ref")
    assert cli.main(["--cmd-from-file", str(spec),
                     "--save-dir", ref_dir]) == 0
    q = jobqueue.JobQueue(qdir)
    sref, mref = io.load_checkpoint(io.find_latest_checkpoint(ref_dir))
    sjob, mjob = io.load_checkpoint(
        io.find_latest_checkpoint(q.job_dir(jid)))
    assert mref["t"] == mjob["t"] == 24

    def _leaves(tree, prefix=""):
        for k in sorted(tree):
            v = tree[k]
            if isinstance(v, dict):
                yield from _leaves(v, f"{prefix}{k}/")
            else:
                yield f"{prefix}{k}", v

    ref_leaves = dict(_leaves(sref))
    job_leaves = dict(_leaves(sjob))
    assert set(ref_leaves) == set(job_leaves)
    for key, arr in ref_leaves.items():
        assert np.array_equal(arr, job_leaves[key]), key
