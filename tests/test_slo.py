"""SLO rules engine (fdtd3d_tpu/slo.py): declarative objectives over
telemetry streams, with explicit OK / VIOLATION / INCONCLUSIVE /
SKIPPED verdicts and schema-v7 alert records for every firing rule.
"""

import pytest

from fdtd3d_tpu import slo, telemetry


def _start(**kw):
    rec = {"v": 7, "type": "run_start", "wall_time": "w",
           "git_sha": "s", "jax_version": "j", "platform": "cpu",
           "device_kind": "cpu", "hbm_gbps": None}
    rec.update(kw)
    return rec


def _chunk(chunk, t, steps=4, wall=0.01, mcps=5.0, finite=True):
    return {"v": 7, "type": "chunk", "chunk": chunk, "t": t,
            "steps": steps, "wall_s": wall, "mcells_per_s": mcps,
            "energy": 1.0, "div_l2": 0.1, "div_linf": 0.2,
            "max_e": 0.1, "max_h": 0.1, "finite": finite,
            "vmem_rung": 0}


def _end(t=8, steps=8, mcps=5.0, **kw):
    rec = {"v": 7, "type": "run_end", "t": t, "steps": steps,
           "wall_s": 0.02, "mcells_per_s": mcps,
           "first_unhealthy_t": None}
    rec.update(kw)
    return rec


def _rule(kind, threshold, rid=None):
    return [slo.SloRule(rid or kind.replace("_", "-"), kind,
                       threshold)]


def _one(run, rules, context=None):
    out = slo.evaluate_run(run, rules=rules, context=context)
    assert len(out["results"]) == 1
    return out["results"][0], out["status"]


def test_unknown_rule_kind_is_a_named_error():
    with pytest.raises(ValueError, match="unknown SLO rule kind"):
        slo.SloRule("x", "nope", 1.0)
    with pytest.raises(ValueError, match="missing"):
        slo.rules_from_json([{"id": "x", "kind": "recovery_rate"}])


def test_chunk_wall_p95():
    run = [_start(), _chunk(1, 4, wall=0.01), _chunk(2, 8, wall=5.0),
           _end()]
    res, status = _one(run, _rule("chunk_wall_p95", 1.0))
    assert res["status"] == "VIOLATION" and status == "VIOLATION"
    assert res["value"] > 1.0
    res, status = _one(run, _rule("chunk_wall_p95", 10.0))
    assert res["status"] == "OK" and status == "OK"


def test_unhealthy_lane_fraction_names_lanes():
    def lane(chunk, t, lane, finite):
        return {"v": 7, "type": "batch_lane", "chunk": chunk, "t": t,
                "lane": lane, "energy": None if not finite else 1.0,
                "div_l2": None if not finite else 0.1,
                "div_linf": None if not finite else 0.1,
                "max_e": None if not finite else 0.1,
                "max_h": None if not finite else 0.1,
                "finite": finite}
    run = [_start(batch=3),
           lane(1, 4, 0, True), lane(1, 4, 1, True),
           lane(1, 4, 2, True),
           lane(2, 8, 0, True), lane(2, 8, 1, False),
           lane(2, 8, 2, True), _end()]
    res, status = _one(run, _rule("unhealthy_lane_fraction", 0.0))
    assert res["status"] == "VIOLATION"
    assert "[1]" in res["message"]
    assert res["window"] == [8, 8]
    # threshold above the fraction: OK
    res, _ = _one(run, _rule("unhealthy_lane_fraction", 0.5))
    assert res["status"] == "OK"
    # not a batch: SKIPPED, never a silent pass of nothing
    res, _ = _one([_start(), _chunk(1, 4), _end()],
                  _rule("unhealthy_lane_fraction", 0.0))
    assert res["status"] == "SKIPPED"


def test_recovery_rate():
    retry = {"v": 7, "type": "retry", "t": 4, "attempt": 1,
             "delay_s": 0.0, "error": "x", "chip": None, "host": None}
    run = [_start(), _chunk(1, 4), retry, _chunk(2, 8), _end()]
    res, _ = _one(run, _rule("recovery_rate", 5.0))
    assert res["status"] == "VIOLATION"     # 125/kstep
    res, _ = _one(run, _rule("recovery_rate", 200.0))
    assert res["status"] == "OK"


def test_straggler_ratio_and_diverged_chip():
    imb = {"v": 7, "type": "imbalance", "chunk": 1, "t": 4,
           "metric": "energy", "max": 3.0, "mean": 1.0, "ratio": 3.0,
           "argmax": 5, "n_chips": 8}
    run = [_start(), _chunk(1, 4), imb, _end()]
    res, _ = _one(run, _rule("straggler_ratio", 2.0))
    assert res["status"] == "VIOLATION" and "chip 5" in res["message"]
    res, _ = _one(run, _rule("straggler_ratio", 4.0))
    assert res["status"] == "OK"
    # a diverged chip fires regardless of any ratio threshold
    dead = dict(imb, ratio=None, nonfinite_chips=[2])
    run = [_start(), _chunk(1, 4), dead, _end()]
    res, _ = _one(run, _rule("straggler_ratio", 1e9))
    assert res["status"] == "VIOLATION" and "[2]" in res["message"]


def test_throughput_floor_modes():
    run = [_start(step_kind="jnp"), _chunk(1, 4), _chunk(2, 8),
           _end(mcps=5.0)]
    # absolute floor
    res, _ = _one(run, _rule("throughput_floor", 0.5),
                  context={"min_mcells_per_s": 10.0})
    assert res["status"] == "VIOLATION"
    res, _ = _one(run, _rule("throughput_floor", 0.5),
                  context={"min_mcells_per_s": 1.0})
    assert res["status"] == "OK"
    # BENCH_BEST reference on a CPU run: inconclusive, never a
    # silent pass and never a false regression (the sentinel rule)
    res, status = _one(run, _rule("throughput_floor", 0.5),
                       context={"bench_best": {"jnp_mcells": 100.0}})
    assert res["status"] == "INCONCLUSIVE"
    assert status == "INCONCLUSIVE"
    # on-TPU provenance gates against the matching path key
    tpu = [_start(platform="tpu", step_kind="jnp"), _chunk(1, 4),
           _end(mcps=5.0)]
    res, _ = _one(tpu, _rule("throughput_floor", 0.5),
                  context={"bench_best": {"jnp_mcells": 100.0}})
    assert res["status"] == "VIOLATION"   # 5 < 0.5*100
    assert res["threshold"] == 50.0
    # no floor configured at all: SKIPPED with the reason named
    res, _ = _one(run, _rule("throughput_floor", 0.5))
    assert res["status"] == "SKIPPED" and "floor" in res["message"]


def test_compile_budget_equal_key():
    run = [_start(), _chunk(1, 4), _end(compile_ms=1000.0)]
    # absolute budget
    res, _ = _one(run, _rule("compile_budget", 1.25),
                  context={"compile_budget_ms": 500.0})
    assert res["status"] == "VIOLATION"
    # equal-key reference: 1000 > 1.25 * 700
    ctx = {"compile_refs": {"dig": 700.0},
           "exec_key_comparable": "dig"}
    res, _ = _one(run, _rule("compile_budget", 1.25), context=ctx)
    assert res["status"] == "VIOLATION"
    ctx["compile_refs"] = {"dig": 900.0}
    res, _ = _one(run, _rule("compile_budget", 1.25), context=ctx)
    assert res["status"] == "OK"
    # references exist but none at this key: INCONCLUSIVE (compile
    # cost only compares at equal comparable key)
    ctx = {"compile_refs": {"other": 1.0},
           "exec_key_comparable": "dig"}
    res, _ = _one(run, _rule("compile_budget", 1.25), context=ctx)
    assert res["status"] == "INCONCLUSIVE"


def test_alerts_validate_and_overall_status():
    imb = {"v": 7, "type": "imbalance", "chunk": 1, "t": 4,
           "metric": "energy", "max": 3.0, "mean": 1.0, "ratio": 3.0,
           "argmax": 5, "n_chips": 8}
    run = [_start(), _chunk(1, 4, wall=100.0), imb, _end()]
    summary = slo.evaluate_run(run)   # default rule set
    assert summary["status"] == "VIOLATION"
    alerts = slo.alerts_for(summary["results"])
    assert {a["rule"] for a in alerts} >= {"chunk-wall-p95",
                                           "straggler-ratio"}
    for a in alerts:
        telemetry.validate_record(a)   # schema-v7 alert records
        assert a["t_end"] >= a["t_start"]
    # a stream with nothing gateable is INCONCLUSIVE, not a pass
    empty = [_start()]
    assert slo.evaluate_run(empty)["status"] == "INCONCLUSIVE"


def test_queue_wait_p95():
    """v8 queue journal rows (fdtd3d_tpu/jobqueue.py): the rule
    judges dispatch-time waits; a journal has no run_start, so the
    whole file reads as one truncated-head run."""
    def running(jid, wait):
        return {"v": 8, "type": "job_state", "job_id": jid,
                "tenant": "t", "status": "running", "wait_s": wait}
    run = [running("a", 1.0), running("b", 2.0), running("c", 400.0)]
    res, status = _one(run, _rule("queue_wait_p95", 300.0))
    assert res["status"] == "VIOLATION" and status == "VIOLATION"
    assert res["value"] > 300.0
    res, _ = _one(run, _rule("queue_wait_p95", 1000.0))
    assert res["status"] == "OK"
    # a terminal row without wait_s does not count as a dispatch
    done = {"v": 8, "type": "job_state", "job_id": "a",
            "tenant": "t", "status": "completed", "t": 8}
    res, _ = _one([done], _rule("queue_wait_p95", 300.0))
    assert res["status"] == "SKIPPED"
    # not a queue journal at all: SKIPPED, never a silent pass
    res, _ = _one([_start(), _chunk(1, 4), _end()],
                  _rule("queue_wait_p95", 300.0))
    assert res["status"] == "SKIPPED"


def test_evaluate_stream_splits_runs():
    records = [_start(), _chunk(1, 4), _end(),
               _start(), _chunk(1, 4, wall=100.0), _end()]
    out = slo.evaluate_stream(records,
                              rules=_rule("chunk_wall_p95", 1.0))
    assert [s["status"] for s in out] == ["OK", "VIOLATION"]
