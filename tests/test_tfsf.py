"""TFSF plane-wave injection tests (3D containment, oblique incidence).

The scattered-field region outside the TFSF box must stay clean in vacuum:
machine-precision clean for axis-aligned incidence (the 1D line and the
grid share the same discrete dispersion along an axis), and below the
standard interpolation/dispersion floor (~-45 dB) for oblique incidence.
"""

import numpy as np

from fdtd3d_tpu.config import SimConfig, TfsfConfig
from fdtd3d_tpu.sim import Simulation


def _scattered_max(field, shell):
    return max(
        np.abs(field[:shell]).max(), np.abs(field[-shell:]).max(),
        np.abs(field[:, :shell]).max(), np.abs(field[:, -shell:]).max(),
        np.abs(field[:, :, :shell]).max(), np.abs(field[:, :, -shell:]).max())


def test_3d_normal_incidence_containment():
    cfg = SimConfig(
        scheme="3D", size=(40, 40, 40), time_steps=60, dx=1e-3,
        courant_factor=0.5, wavelength=15e-3,
        tfsf=TfsfConfig(enabled=True, margin=(10, 10, 10),
                        angle_teta=0.0, angle_phi=0.0, angle_psi=0.0))
    sim = Simulation(cfg)
    sim.run()
    ex = sim.field("Ex")
    inside = np.abs(ex[12:28, 12:28, 12:28]).max()
    assert inside > 0.1, "incident wave did not enter the box"
    leak = _scattered_max(ex, 8)
    assert leak < 1e-6 * inside, f"leak {leak} vs inside {inside}"


def test_3d_oblique_incidence_contained_below_dispersion_floor():
    cfg = SimConfig(
        scheme="3D", size=(40, 40, 40), time_steps=80, dx=1e-3,
        courant_factor=0.5, wavelength=15e-3,
        tfsf=TfsfConfig(enabled=True, margin=(10, 10, 10),
                        angle_teta=45.0, angle_phi=30.0, angle_psi=20.0))
    sim = Simulation(cfg)
    sim.run()
    leak, inside = 0.0, 0.0
    for comp in ("Ex", "Ey", "Ez"):
        f = sim.field(comp)
        inside = max(inside, np.abs(f[12:28, 12:28, 12:28]).max())
        leak = max(leak, _scattered_max(f, 8))
    assert inside > 0.05
    assert leak < 2e-2 * inside, f"oblique leak {leak} vs inside {inside}"


def test_2d_tmz_tfsf_containment():
    cfg = SimConfig(
        scheme="2D_TMz", size=(48, 48, 1), time_steps=70, dx=1e-3,
        courant_factor=0.5, wavelength=15e-3,
        tfsf=TfsfConfig(enabled=True, margin=(12, 12, 0),
                        angle_teta=90.0, angle_phi=0.0, angle_psi=180.0))
    sim = Simulation(cfg)
    sim.run()
    ez = sim.field("Ez")[:, :, 0]
    inside = np.abs(ez[14:34, 14:34]).max()
    leak = max(np.abs(ez[:10]).max(), np.abs(ez[-10:]).max(),
               np.abs(ez[:, :10]).max(), np.abs(ez[:, -10:]).max())
    assert inside > 0.1
    assert leak < 1e-5 * inside, f"leak {leak} vs inside {inside}"
