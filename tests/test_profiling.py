"""StepClock + finite-guard wiring (VERDICT round-1 items 22/23/§weak 2).

The reference instruments compute-vs-share wall time with its Clock class
and guards correctness with ASSERT macros (SURVEY.md §5.1, §5.2); here the
equivalents must actually be WIRED: OutputConfig.profile attaches a
StepClock that Simulation.advance feeds, and OutputConfig.check_finite
trips on NaN/Inf state after every chunk.
"""

import numpy as np
import pytest

from fdtd3d_tpu.config import OutputConfig, PmlConfig, PointSourceConfig, \
    SimConfig
from fdtd3d_tpu.sim import Simulation


def _cfg(**out):
    return SimConfig(
        scheme="2D_TMz", size=(32, 32, 1), time_steps=8, dx=1e-3,
        courant_factor=0.5, wavelength=10e-3,
        pml=PmlConfig(size=(4, 4, 0)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(16, 16, 0)),
        output=OutputConfig(**out))


def test_step_clock_records_profiled_chunks():
    sim = Simulation(_cfg(profile=True))
    assert sim.clock is not None
    sim.advance(4)
    sim.advance(4)
    s = sim.clock.summary()
    assert s["steps"] == 8
    assert s["seconds"] > 0.0
    assert s["mcells_per_s"] > 0.0
    assert s["best_mcells_per_s"] >= s["mcells_per_s"] * 0.99
    assert "Mcells/s" in sim.clock.report()
    assert len(sim.clock.records) == 2


def test_clock_absent_without_profile():
    sim = Simulation(_cfg())
    assert sim.clock is None
    sim.advance(2)  # no timing overhead path


def test_check_finite_trips_on_nan():
    sim = Simulation(_cfg(check_finite=True))
    sim.advance(2)  # healthy state passes the guard
    bad = np.full(sim.state["E"]["Ez"].shape, np.nan, np.float32)
    sim.set_field("Ez", bad)
    with pytest.raises(FloatingPointError, match="Ez"):
        sim.advance(1)


def test_cli_profile_flag(capsys, tmp_path):
    from fdtd3d_tpu import cli
    rc = cli.main(["--2d", "TMz", "--sizex", "24", "--sizey", "24",
                   "--time-steps", "4", "--point-source", "Ez",
                   "--profile", "--check-finite"])
    assert rc == 0
    outp = capsys.readouterr().out
    assert "profile:" in outp


def test_cli_trace_writes_profile(tmp_path):
    """--trace produces a jax.profiler (XProf) trace directory."""
    import contextlib
    import io as _io
    import os

    from fdtd3d_tpu import cli

    trace_dir = str(tmp_path / "trace")
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(["--2d", "TMz", "--sizex", "16", "--sizey", "16",
                       "--sizez", "1", "--time-steps", "10",
                       "--point-source", "Ez", "--trace", trace_dir,
                       "--log-level", "0"])
    assert rc == 0
    found = []
    for root, _, files in os.walk(trace_dir):
        found.extend(files)
    assert found, "no trace files written"
