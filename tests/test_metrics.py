"""Structured metrics path: diag.metrics + the CLI --metrics-every JSONL.

Reference parity: SURVEY.md §5.5 observability — per-interval energy,
norms and a divergence-residual health metric, as structured records.
"""

import json

import numpy as np

from fdtd3d_tpu import diag, exact
from fdtd3d_tpu.config import SimConfig
from fdtd3d_tpu.sim import Simulation


def test_divergence_residual_stays_machine_small():
    """Source-free uniform cavity: div E must stay ~0 (the Yee update
    conserves Gauss's law exactly), energy positive and bounded."""
    n, steps = 21, 120
    cfg = SimConfig(scheme="3D", size=(n, n, 13), time_steps=steps,
                    dx=1e-3, courant_factor=0.5, wavelength=10e-3,
                    dtype="float64")
    sim = Simulation(cfg)
    shapes, omega = exact.cavity_mode((n, n, 13), (2, 3, 1), cfg.dx, cfg.dt)
    for comp, shape in shapes.items():
        sim.set_field(comp, shape)
    d0 = diag.divergence_e(sim)
    sim.run()
    rec = diag.metrics(sim)
    assert rec["t"] == steps
    assert rec["energy"] > 0.0
    # the mode is discrete-divergence-free; evolution must keep it so
    k_scale = 2.0 * np.pi / cfg.dx  # ~|K|, the natural div scale
    assert d0["div_linf"] < 1e-9 * k_scale * max(d0["e_scale"], 1.0)
    assert rec["div_linf"] < 1e-9 * k_scale * max(rec["e_scale"], 1.0), \
        f"divergence grew: {rec['div_linf']:.2e}"


def test_cli_metrics_jsonl(tmp_path):
    import contextlib
    import io as _io

    from fdtd3d_tpu import cli

    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(["--2d", "TMz", "--sizex", "32", "--sizey", "32",
                       "--sizez", "1", "--time-steps", "40",
                       "--use-pml", "--pml-size", "5",
                       "--point-source", "Ez",
                       "--metrics-every", "10",
                       "--save-dir", str(tmp_path)])
    assert rc == 0
    lines = (tmp_path / "metrics.jsonl").read_text().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert [r["t"] for r in recs] == [10.0, 20.0, 30.0, 40.0]
    for r in recs:
        assert set(r) >= {"t", "energy", "max_Ez", "div_l2", "div_linf"}
        assert np.isfinite(r["energy"]) and r["energy"] >= 0.0
    assert recs[-1]["max_Ez"] > 0.0
