"""Chip-free fleet-observability e2e chain (ISSUE 13 acceptance).

One supervised faulted batch run through the REAL CLI drives the whole
stack: the fault harness injects a lane NaN mid-run, the batch
isolates the tenant, and then — deterministically on CPU —

* the run-registry row flips to ``recovered`` with the tenant named;
* the OpenMetrics exposition shows the unhealthy-lane counter;
* ``tools/slo_gate.py`` fires the unhealthy-lane rule with exit 1;
* ``tools/fleet_report.py --json`` names the (run, lane) tenant.

A second chain runs the supervised sharded recovery path (chip-scoped
NaN → rollback + topology degrade) and asserts the rollback counter
reaches the metrics exposition and the registry row reads
``recovered`` under kind ``supervised``.
"""

import json
import os
import subprocess
import sys

import pytest

from fdtd3d_tpu import cli, faults, registry, telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")


@pytest.fixture(autouse=True)
def _isolated_plan(monkeypatch):
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    faults.clear()
    yield
    faults.clear()


def _run_tool(args, timeout=120):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, timeout=timeout, env=env,
                          cwd=ROOT)


def test_supervised_batch_lane_nan_fleet_chain(tmp_path, monkeypatch):
    reg = str(tmp_path / "runs.jsonl")
    tele = str(tmp_path / "t.jsonl")
    mets = str(tmp_path / "m.prom")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    specs = []
    for i, eps in enumerate((1.0, 2.0, 4.0)):
        p = tmp_path / f"lane{i}.txt"
        p.write_text(f"--3d\n--same-size 12\n--time-steps 8\n"
                     f"--courant-factor 0.4\n--wavelength 0.008\n"
                     f"--eps {eps}\n")
        specs.append(str(p))
    faults.install("nan@t=4,field=Ez,lane=1")
    rc = cli.main(["--batch", *specs, "--batch-chunk", "4",
                   "--supervise", "--telemetry", tele,
                   "--metrics", mets])
    assert rc == 0   # lane isolation: the other tenants completed

    # (1) registry row: recovered, batch of 3, tenant lane 1 named
    rows = registry.read(reg)
    assert [r["type"] for r in rows] == ["run_begin", "run_final"]
    begin, final = rows
    assert begin["kind"] == "batch" and begin["batch"] == 3
    assert final["status"] == "recovered"
    assert final["unhealthy_lanes"] == [[1, 8]]
    rid = final["run_id"]

    # (2) the telemetry stream joins the registry (run_id) and holds
    # the per-lane verdict rows
    recs = telemetry.read_jsonl(tele)
    start = next(r for r in recs if r["type"] == "run_start")
    assert start["run_id"] == rid and start["batch"] == 3
    bad = [r for r in recs
           if r["type"] == "batch_lane" and not r["finite"]]
    assert bad and all(r["lane"] == 1 for r in bad)

    # (3) metrics exposition: the unhealthy-lane counter, per tenant
    text = open(mets).read()
    assert 'fdtd3d_lane_unhealthy_total{lane="1"} 1' in text
    assert "fdtd3d_chunks_total 2" in text
    assert text.strip().endswith("# EOF")

    # (4) slo_gate fires the unhealthy-lane rule: exit 1, rule named
    proc = _run_tool([os.path.join(TOOLS, "slo_gate.py"), tele,
                      "--emit-alerts"])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "unhealthy-lane-fraction" in proc.stdout
    assert "VIOLATION" in proc.stdout
    # the emitted alert landed in the stream, schema-valid
    alerts = [r for r in telemetry.read_jsonl(tele)
              if r["type"] == "alert"]
    assert any(a["rule"] == "unhealthy-lane-fraction"
               for a in alerts)

    # (5) fleet_report --json names the tenant
    proc = _run_tool([os.path.join(TOOLS, "fleet_report.py"), reg,
                      "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rollup = json.loads(proc.stdout)
    assert rollup["fleet"]["by_status"] == {"recovered": 1}
    assert {"run": rid, "lane": 1, "first_unhealthy_t": 8} in \
        rollup["fleet"]["unhealthy_tenants"]
    assert any(a["rule"] == "unhealthy-lane-fraction"
               for a in rollup["fleet"]["alerts"])


def test_supervised_rollback_reaches_metrics_and_registry(
        tmp_path, monkeypatch):
    """Supervised sharded run, chip-scoped NaN: the kernel ladder has
    no rung below jnp, so the supervisor rolls back and degrades the
    TOPOLOGY; the run completes, the registry row reads recovered
    (kind supervised), and the rollback counter reaches the
    OpenMetrics exposition."""
    reg = str(tmp_path / "runs.jsonl")
    tele = str(tmp_path / "t.jsonl")
    mets = str(tmp_path / "m.prom")
    d = str(tmp_path / "run")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    faults.install("nan@t=8,field=Ez,chip=3")
    rc = cli.main(["--3d", "--same-size", "24", "--time-steps", "24",
                   "--courant-factor", "0.4",
                   "--wavelength", "0.008",
                   "--use-pml", "--pml-size", "3",
                   "--point-source", "Ez",
                   "--topology", "manual",
                   "--manual-topology", "2x2x2",
                   "--checkpoint-every", "8", "--save-dir", d,
                   "--supervise", "--telemetry", tele,
                   "--metrics", mets])
    assert rc == 0

    rows = registry.read(reg)
    assert [r["type"] for r in rows] == ["run_begin", "run_final"]
    begin, final = rows
    assert begin["kind"] == "supervised"
    assert begin["topology"] == [2, 2, 2]
    assert final["status"] == "recovered"
    assert final["recovery_events"]["rollback"] == 1
    assert final["recovery_events"]["topology_change"] == 1
    assert final["t"] == 24

    text = open(mets).read()
    assert 'fdtd3d_recovery_events_total{kind="rollback"} 1' in text
    assert ('fdtd3d_recovery_events_total{kind="topology_change"} 1'
            in text)

    # the cadence snapshots carry the run_id stamp: ckpt_inspect
    # --json traces the newest one back to this run
    from fdtd3d_tpu import io
    newest = io.find_latest_checkpoint(d)
    proc = _run_tool([os.path.join(TOOLS, "ckpt_inspect.py"),
                      newest, "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    meta = json.loads(proc.stdout)["meta"]
    assert meta["run_id"] == final["run_id"]

    # the default recovery-rate SLO fires on this stream (2 events
    # in 24+8 replayed steps is far over 5/kstep): gate exits 1
    proc = _run_tool([os.path.join(TOOLS, "slo_gate.py"), tele])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "recovery-rate" in proc.stdout
