"""CPML absorption tests.

Strategy (mirrors how PML quality is validated in practice and in the
reference's acceptance posture, SURVEY.md §4): run a pulse into the PML and
compare the probe-point time history against a reference run on a much
larger domain whose walls are too far away for reflections to return within
the measurement window. The relative difference IS the PML reflection.
"""

import numpy as np
import pytest

from fdtd3d_tpu.config import PmlConfig, PointSourceConfig, SimConfig
from fdtd3d_tpu.sim import Simulation


def _probe_history(scheme, size, steps, pml, probe, src_pos, interval=2):
    cfg = SimConfig(
        scheme=scheme, size=size, time_steps=0, dx=1e-3,
        courant_factor=0.5, wavelength=10e-3,
        pml=PmlConfig(size=pml) if any(pml) else PmlConfig(),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=src_pos,
                                       waveform="ricker"),
    )
    sim = Simulation(cfg)
    hist = []
    for _ in range(steps // interval):
        sim.advance(interval)
        hist.append(float(sim.field("Ez")[probe]))
    return np.array(hist)


def test_cpml_reflection_below_40db_1d():
    """1D EzHy: pulse into the x PML; probe near the interface."""
    n, npml, steps = 120, 10, 700
    src = (60, 0, 0)
    probe = (20, 0, 0)
    with_pml = _probe_history("1D_EzHy", (n, 1, 1), steps,
                              (npml, 0, 0), probe, src)
    # Reference: walls far enough that nothing reflected reaches the probe.
    big = 120 + 2 * steps  # cf=0.5 -> wave travels steps/2 cells max
    ref = _probe_history("1D_EzHy", (big, 1, 1), steps, (0, 0, 0),
                         (20 + (big - n) // 2, 0, 0),
                         (60 + (big - n) // 2, 0, 0))
    peak = np.max(np.abs(ref))
    assert peak > 0
    err = np.max(np.abs(with_pml - ref))
    # CPML with R0=1e-8, m=3, 10 cells: expect well under 1% reflected.
    assert err < 1e-3 * peak, f"reflection {err/peak:.2e}"


def test_cpml_reflection_below_40db_2d():
    """2D TMz: cylindrical pulse into 4 PML walls."""
    n, npml, steps = 96, 10, 360
    src = (n // 2, n // 2, 0)
    probe = (n // 2 + 18, n // 2, 0)
    with_pml = _probe_history("2D_TMz", (n, n, 1), steps,
                              (npml, npml, 0), probe, src)
    big = n + steps  # generous margin
    off = (big - n) // 2
    ref = _probe_history("2D_TMz", (big, big, 1), steps, (0, 0, 0),
                         (n // 2 + 18 + off, n // 2 + off, 0),
                         (n // 2 + off, n // 2 + off, 0))
    peak = np.max(np.abs(ref))
    assert peak > 0
    err = np.max(np.abs(with_pml - ref))
    assert err < 1e-2 * peak, f"reflection {err/peak:.2e}"


def test_cpml_absorbs_traversing_pulse_3d():
    """3D: a TFSF Gaussian pulse enters, crosses the box, and exits into
    the CPML; afterwards the residual energy must be a tiny fraction of
    the peak (point sources leave quasi-static residue, so a traversing
    pulse is the clean absorption probe)."""
    from fdtd3d_tpu import diag
    from fdtd3d_tpu.config import TfsfConfig
    n = 40
    cfg = SimConfig(
        scheme="3D", size=(n, n, n), time_steps=0, dx=1e-3,
        courant_factor=0.5, wavelength=6e-3,
        pml=PmlConfig(size=(8, 8, 8)),
        tfsf=TfsfConfig(enabled=True, margin=(4, 4, 4),
                        waveform="gauss_pulse"),
    )
    sim = Simulation(cfg)
    peak = 0.0
    for _ in range(12):
        sim.advance(50)
        peak = max(peak, diag.em_energy(sim))
    sim.advance(300)  # pulse fully exited
    e_late = diag.em_energy(sim)
    assert peak > 0
    assert e_late < 2e-4 * peak, f"residual {e_late/peak:.2e}"
