"""Second-order convergence to the CONTINUUM solution under refinement.

The discrete-eigenmode oracles (test_cavity_modes.py) prove the solver
implements its own discretization exactly; this suite proves that
discretization converges to Maxwell at the expected 2nd order — the
reference's sinusoidal convergence-norm tests (SURVEY.md §4).

Probe: a PEC-cavity eigenmode at FIXED physical size and mode numbers,
resolved at 16/32/64 cells per side, evolved ~5 periods. The sin-product
mode shape is exact at every resolution, so the entire error against the
CONTINUUM evolution is the dispersion phase drift (w_d - w_cont) * T =
O(dx^2). The error is measured as its envelope over one full period
(a single snapshot samples an arbitrary phase of the drift) and is
asserted BOTH to fall at 2nd order and to match the analytic envelope
2|sin(drift/2)| — the sim must reproduce the Yee dispersion
quantitatively, not just shrink.
"""

import math

import numpy as np

from fdtd3d_tpu import exact, physics
from fdtd3d_tpu.config import SimConfig
from fdtd3d_tpu.sim import Simulation

L = 16e-3          # physical cavity side
M, N = 2, 3        # mode numbers


def _cavity_drift(res: int):
    """(measured error envelope, analytic envelope prediction)."""
    dx = L / res
    n = res + 1                 # walls at 0 and n-1 -> interior length L
    cfg = SimConfig(scheme="2D_TMz", size=(n, n, 1), time_steps=0,
                    dx=dx, courant_factor=0.5, wavelength=10e-3,
                    dtype="float64")
    sim = Simulation(cfg)
    shape, omega_d = exact.cavity_mode_tmz((n, n), M, N, dx, cfg.dt)
    sim.set_field("Ez", shape[:, :, None])
    omega_c = physics.C0 * math.pi / L * math.hypot(M, N)
    period = 2.0 * math.pi / omega_c
    total = int(round(5.0 * period / cfg.dt))
    p_steps = int(round(period / cfg.dt))
    sim.advance(total - p_steps)
    err = 0.0
    for _ in range(p_steps):
        sim.advance(1)
        t = sim.t
        expected = shape * (math.cos(omega_c * (t - 0.5) * cfg.dt)
                            / math.cos(omega_c * 0.5 * cfg.dt))
        err = max(err, float(np.max(
            np.abs(sim.field("Ez")[:, :, 0] - expected))))
    drift = (omega_d - omega_c) * total * cfg.dt
    return err, abs(2.0 * math.sin(drift / 2.0))


def test_cavity_dispersion_drift_second_order():
    measured, predicted = zip(*[_cavity_drift(r) for r in (16, 32, 64)])
    orders = [math.log2(measured[i] / measured[i + 1]) for i in range(2)]
    for i, o in enumerate(orders):
        assert 1.8 < o < 2.3, f"step {i}: order {o:.2f} ({measured})"
    # and quantitatively the drift the Yee dispersion relation predicts
    for res, m, p in zip((16, 32, 64), measured, predicted):
        assert abs(m - p) < 0.25 * p, (
            f"res {res}: measured {m:.4f} vs predicted {p:.4f}")
