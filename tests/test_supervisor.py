"""Durable-run supervisor tests (ISSUE 5 tentpole piece 2).

Acceptance: a NaN injection at step t triggers rollback to the last
COMMITTED checkpoint plus ONE kernel-ladder degrade, and the run
completes the horizon with the right t and BIT-VALID state (identical
to a clean continuation of the degraded kind from the same snapshot),
with the retry/rollback/degrade records validating against telemetry
schema v3.

CPU-deterministic and sleep-free: the backoff clock is injected
(RetryPolicy.sleep), faults fire on step counters.
"""

import os

import numpy as np
import pytest

from fdtd3d_tpu import faults, io, telemetry
from fdtd3d_tpu.config import (OutputConfig, PmlConfig, PointSourceConfig,
                               SimConfig)
from fdtd3d_tpu.supervisor import (RetryPolicy, Supervisor, degrade_plan,
                                   run_with_retry)


@pytest.fixture(autouse=True)
def _isolated_plan(monkeypatch):
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    faults.clear()
    yield
    faults.clear()


def _cfg2d(save_dir, **out_kw):
    out_kw.setdefault("checkpoint_every", 8)
    return SimConfig(
        scheme="2D_TMz", size=(24, 24, 1), time_steps=24, dx=1e-3,
        courant_factor=0.5, wavelength=10e-3,
        pml=PmlConfig(size=(4, 4, 0)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(12, 12, 0)),
        output=OutputConfig(save_dir=str(save_dir), **out_kw))


# -------------------------------------------------------------------------
# run_with_retry (the stage-shaped flavor bench.py embeds)
# -------------------------------------------------------------------------

def test_run_with_retry_records_attempts():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError(f"transient #{calls['n']}")
        return "done"

    rec = {}
    out = run_with_retry(flaky, policy=RetryPolicy(
        max_retries=3, sleep=sleeps.append), label="stage", record=rec)
    assert out == "done"
    assert rec["ok"] is True and rec["attempts"] == 3
    assert len(rec["errors"]) == 2
    assert sleeps == [1.0, 2.0]  # exponential backoff, injected clock


def test_run_with_retry_exhaustion_keeps_record():
    rec = {}
    with pytest.raises(RuntimeError):
        run_with_retry(lambda: (_ for _ in ()).throw(
            RuntimeError("always")), policy=RetryPolicy(
                max_retries=2, sleep=lambda _s: None), record=rec)
    assert rec["ok"] is False and rec["attempts"] == 3


def test_run_with_retry_nontransient_propagates_immediately():
    rec = {}
    with pytest.raises(KeyError):
        run_with_retry(lambda: (_ for _ in ()).throw(KeyError("nope")),
                       policy=RetryPolicy(sleep=lambda _s: None),
                       record=rec)
    assert rec["attempts"] == 1


# -------------------------------------------------------------------------
# the degradation ladder map
# -------------------------------------------------------------------------

def test_degrade_plan_ladder():
    pins, _fn = degrade_plan("pallas_packed_tb")
    assert pins == {"FDTD3D_NO_TEMPORAL": "1"}
    pins, _fn = degrade_plan("pallas_packed")
    assert pins == {"FDTD3D_NO_PACKED": "1"}
    pins, fn = degrade_plan("pallas")
    assert pins == {} and fn is not None
    assert degrade_plan("jnp") is None          # bottom
    assert degrade_plan("jnp_ds") is None


# -------------------------------------------------------------------------
# transient retry with bounded backoff + rollback
# -------------------------------------------------------------------------

def test_transient_errors_retried_with_rollback(tmp_path):
    faults.install("error@t=8,times=2")
    cfg = _cfg2d(tmp_path,
                 telemetry_path=str(tmp_path / "t.jsonl"))
    sleeps = []
    sup = Supervisor(cfg, policy=RetryPolicy(max_retries=3,
                                             sleep=sleeps.append))
    sim = sup.run(interval=8)
    sim.close()
    assert sim._t_host == 24
    assert sup.retries == 2 and sup.rollbacks == 2
    assert sleeps == [1.0, 2.0]  # no real sleeping in tier-1
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)  # validates
    types = [r["type"] for r in recs]
    assert types.count("retry") == 2 and types.count("rollback") == 2
    assert types[0] == "run_start" and types[-1] == "run_end"
    for comp, v in sim.fields().items():
        assert np.isfinite(v).all(), comp


def test_transient_retry_exhaustion_reraises(tmp_path):
    faults.install("error@t=8,times=5")
    sup = Supervisor(_cfg2d(tmp_path), policy=RetryPolicy(
        max_retries=2, sleep=lambda _s: None))
    with pytest.raises(faults.InjectedTransientError):
        sup.run(interval=8)


def test_preemption_is_never_swallowed(tmp_path):
    faults.install("preempt@t=8")
    sup = Supervisor(_cfg2d(tmp_path), policy=RetryPolicy(
        max_retries=5, sleep=lambda _s: None))
    with pytest.raises(faults.SimulatedPreemption):
        sup.run(interval=8)


# -------------------------------------------------------------------------
# ACCEPTANCE: NaN -> rollback to committed ckpt -> ONE ladder degrade
# -------------------------------------------------------------------------

def test_nan_rollback_degrades_tb_to_packed_bit_valid(tmp_path):
    import dataclasses
    d = tmp_path / "run"
    cfg = SimConfig(
        scheme="3D", size=(16, 16, 16), time_steps=24, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3, use_pallas=True,
        pml=PmlConfig(size=(3, 3, 3)),
        output=OutputConfig(save_dir=str(d), checkpoint_every=8,
                            telemetry_path=str(tmp_path / "t.jsonl")))
    faults.install("nan@t=8,field=Ez")
    sup = Supervisor(cfg, policy=RetryPolicy(sleep=lambda _s: None))
    sim = sup.run(interval=8)
    sim.close()
    faults.clear()

    # the ladder stepped tb -> packed exactly once, finished the horizon
    assert sim.step_kind == "pallas_packed", sim.step_kind
    assert sim._t_host == 24
    assert sup.degrades == 1 and sup.rollbacks == 1
    # the env pin was cleaned up after the supervised run
    assert "FDTD3D_NO_TEMPORAL" not in os.environ
    for comp, v in sim.fields().items():
        assert np.isfinite(np.asarray(v, np.float32)).all(), comp

    # schema v3: rollback + degrade records validate and carry the facts
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    rb = [r for r in recs if r["type"] == "rollback"]
    dg = [r for r in recs if r["type"] == "degrade"]
    assert len(rb) == 1 and len(dg) == 1
    assert rb[0]["t_failed"] == 16 and rb[0]["t_restored"] == 8
    assert rb[0]["source"].endswith("ckpt_t000008.npz")
    assert dg[0]["old_kind"] == "pallas_packed_tb"
    assert dg[0]["new_kind"] == "pallas_packed"
    # ONE run_start/run_end span despite the mid-run sim replacement
    types = [r["type"] for r in recs]
    assert types.count("run_start") == 1 and types.count("run_end") == 1

    # BIT-VALID: identical to a clean continuation of the degraded kind
    # from the same committed snapshot (the NaN never re-fires)
    from fdtd3d_tpu.sim import Simulation
    os.environ["FDTD3D_NO_TEMPORAL"] = "1"
    try:
        ref_cfg = dataclasses.replace(cfg, output=dataclasses.replace(
            cfg.output, telemetry_path=None, checkpoint_every=0))
        ref = Simulation(ref_cfg)
        assert ref.step_kind == "pallas_packed"
        ref.restore(os.path.join(str(d), "ckpt_t000008.npz"))
        ref.advance(8)
        ref.advance(8)
    finally:
        del os.environ["FDTD3D_NO_TEMPORAL"]
    got = sim.fields()
    for comp, v in ref.fields().items():
        assert np.array_equal(np.asarray(v), np.asarray(got[comp])), comp


def test_nan_on_jnp_bottom_of_ladder_reraises(tmp_path):
    """On the reference path the blow-up is physics: no rung below it,
    so the trip propagates instead of looping."""
    faults.install("nan@t=8")
    sup = Supervisor(_cfg2d(tmp_path), policy=RetryPolicy(
        sleep=lambda _s: None))
    with pytest.raises(FloatingPointError):
        sup.run(interval=8)
    assert sup.degrades == 0


def test_rollback_ignores_stale_newer_checkpoint(tmp_path):
    """save_dir still holds a FINISHED previous run's snapshots (same
    config, so every metadata guard passes): a rollback must never
    fast-forward onto the old run's later-t state."""
    from fdtd3d_tpu.sim import Simulation
    Simulation(_cfg2d(tmp_path)).advance(24)  # leaves ckpt_t000024 etc.
    assert io.find_latest_checkpoint(str(tmp_path)).endswith(
        "ckpt_t000024.npz")

    faults.install("error@t=8,times=1")
    cfg = _cfg2d(tmp_path, telemetry_path=str(tmp_path / "t.jsonl"))
    sup = Supervisor(cfg, policy=RetryPolicy(sleep=lambda _s: None))
    sim = sup.run(interval=8)
    sim.close()
    assert sim._t_host == 24 and sup.rollbacks == 1
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    rb = [r for r in recs if r["type"] == "rollback"]
    # restored to THIS run's t=8 snapshot, not the stale t=24 one
    assert rb[0]["t_failed"] == 8 and rb[0]["t_restored"] == 8
    assert rb[0]["source"].endswith("ckpt_t000008.npz")


def test_on_interval_not_refired_after_rollback(tmp_path):
    """A rollback re-advances through boundaries whose interval
    callbacks already ran; re-firing them would double-count the NTFF
    DFT accumulator / duplicate metrics rows."""
    import dataclasses
    cfg = SimConfig(
        scheme="3D", size=(16, 16, 16), time_steps=24, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3, use_pallas=True,
        pml=PmlConfig(size=(3, 3, 3)),
        output=OutputConfig(save_dir=str(tmp_path / "run"),
                            checkpoint_every=8))
    # nan lands at boundary t=12 (first boundary >= 10); the next
    # chunk trips at t=16, rolling back to ckpt_t000008 — boundary 12
    # is then re-advanced through and must NOT re-fire its callback
    faults.install("nan@t=10,field=Ez")
    seen = []
    sup = Supervisor(cfg, policy=RetryPolicy(sleep=lambda _s: None))
    sim = sup.run(interval=4, on_interval=lambda s: seen.append(s.t))
    assert sim._t_host == 24 and sup.degrades == 1
    assert seen == [4, 8, 12, 16, 20, 24], seen


def test_boundary_callbacks_fire_after_same_t_rollback(tmp_path):
    """An error firing AFTER a boundary's cadence checkpoint committed
    (but before its interval callbacks ran) must not permanently skip
    that boundary's callbacks: the rollback restores the boundary
    bit-exact and the callback fires then — metrics/NTFF cadences stay
    identical to an unsupervised run."""
    faults.install("error@t=8,times=1")
    seen = []
    sup = Supervisor(_cfg2d(tmp_path),
                     policy=RetryPolicy(sleep=lambda _s: None))
    sim = sup.run(interval=8, on_interval=lambda s: seen.append(s.t))
    assert sim._t_host == 24
    assert seen == [8, 16, 24], seen


def test_degraded_build_failure_reattaches_sink(tmp_path):
    """If constructing the degraded Simulation itself fails, the
    telemetry sink must land back on the surviving sim so the caller's
    close() still writes the run_end record."""
    from fdtd3d_tpu.sim import Simulation
    cfg = SimConfig(
        scheme="3D", size=(16, 16, 16), time_steps=24, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3, use_pallas=True,
        pml=PmlConfig(size=(3, 3, 3)),
        output=OutputConfig(save_dir=str(tmp_path / "run"),
                            checkpoint_every=8,
                            telemetry_path=str(tmp_path / "t.jsonl")))
    faults.install("nan@t=8,field=Ez")
    calls = {"n": 0}

    def factory(c):
        calls["n"] += 1
        if calls["n"] > 1:
            raise RuntimeError("degraded build failed (injected)")
        return Simulation(c)

    sup = Supervisor(cfg, sim_factory=factory,
                     policy=RetryPolicy(sleep=lambda _s: None))
    with pytest.raises(RuntimeError, match="degraded build failed"):
        sup.run(interval=8)
    assert sup.sim is not None and sup.sim.telemetry is not None
    sup.sim.close()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    assert recs[-1]["type"] == "run_end"


# -------------------------------------------------------------------------
# ACCEPTANCE (ISSUE 8): chip-scoped NaN -> rollback + TOPOLOGY degrade,
# failing chip named in v5 telemetry, run completes
# -------------------------------------------------------------------------

def _cfg3d_sharded(save_dir, topo=(2, 2, 2), **out_kw):
    from fdtd3d_tpu.config import ParallelConfig
    out_kw.setdefault("checkpoint_every", 8)
    return SimConfig(
        scheme="3D", size=(24, 24, 24), time_steps=24, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        pml=PmlConfig(size=(3, 3, 3)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(12, 12, 12)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=topo),
        output=OutputConfig(save_dir=str(save_dir), **out_kw))


def test_chip_nan_topology_degrade_completes_bit_valid(tmp_path):
    """A chip-scoped NaN on the (CPU jnp) reference path: the kernel
    ladder has no rung below, so the supervisor rolls back to the last
    committed checkpoint and degrades the TOPOLOGY — (2,2,2) ->
    (1,2,2) via the reshard-on-resume restore — completing the horizon
    with state bit-identical to an uninterrupted unsharded run, and
    the failing chip named in the v5 records."""
    d = tmp_path / "run"
    cfg = _cfg3d_sharded(d, telemetry_path=str(tmp_path / "t.jsonl"))
    faults.install("nan@t=8,field=Ez,chip=3")
    sup = Supervisor(cfg, policy=RetryPolicy(sleep=lambda _s: None))
    sim = sup.run(interval=8)
    sim.close()
    faults.clear()

    assert sim._t_host == 24
    assert tuple(sim.topology) == (1, 2, 2)
    assert sup.topology_rung == 1 and sup.rollbacks == 1

    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    rb = [r for r in recs if r["type"] == "rollback"]
    tc = [r for r in recs if r["type"] == "topology_change"]
    assert len(rb) == 1 and len(tc) == 1
    assert rb[0]["t_failed"] == 16 and rb[0]["t_restored"] == 8
    assert rb[0]["chip"] == 3            # the failing chip, named
    assert tc[0]["old_topology"] == [2, 2, 2]
    assert tc[0]["new_topology"] == [1, 2, 2]
    assert tc[0]["chip"] == 3
    types = [r["type"] for r in recs]
    assert types.count("run_start") == 1 and types.count("run_end") == 1

    # the cadence snapshots now carry the supervisor's durable state
    newest = io.find_latest_checkpoint(str(d))
    meta = io.read_checkpoint_meta(newest)
    assert meta["supervisor"]["topology"] == [1, 2, 2]
    assert meta["supervisor"]["topology_rung"] == 1

    # BIT-VALID: identical to the uninterrupted unsharded run (the
    # 24-cell grid keeps every topology on the same CPML slab path)
    import dataclasses

    from fdtd3d_tpu.config import ParallelConfig
    from fdtd3d_tpu.sim import Simulation
    ref = Simulation(dataclasses.replace(
        _cfg3d_sharded(tmp_path / "ref", checkpoint_every=0),
        parallel=ParallelConfig()))
    ref.advance(24)
    got = sim.fields()
    for comp, v in ref.fields().items():
        assert np.array_equal(np.asarray(v), np.asarray(got[comp])), comp


def test_transient_exhaustion_walks_topology_ladder(tmp_path):
    """Retries exhausted on the current topology: shed a topology rung
    (with a fresh retry budget) instead of giving up — the recovery
    for a persistently failing chip/link."""
    from fdtd3d_tpu.config import ParallelConfig
    cfg = SimConfig(
        scheme="2D_TMz", size=(24, 24, 1), time_steps=24, dx=1e-3,
        courant_factor=0.5, wavelength=10e-3,
        pml=PmlConfig(size=(4, 4, 0)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(12, 12, 0)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(2, 2, 1)),
        output=OutputConfig(save_dir=str(tmp_path), checkpoint_every=8,
                            telemetry_path=str(tmp_path / "t.jsonl")))
    faults.install("error@t=8,times=2")
    sup = Supervisor(cfg, policy=RetryPolicy(max_retries=0,
                                             sleep=lambda _s: None))
    sim = sup.run(interval=8)
    sim.close()
    faults.clear()
    assert sim._t_host == 24
    assert tuple(sim.topology) == (1, 1, 1)
    assert sup.topology_rung == 2 and sup.retries == 0
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    tc = [r for r in recs if r["type"] == "topology_change"]
    assert [(r["old_topology"], r["new_topology"]) for r in tc] == \
        [([2, 2, 1], [1, 2, 1]), ([1, 2, 1], [1, 1, 1])]


def test_topology_degrade_stays_on_tb_after_reshard(tmp_path):
    """ISSUE-10 satellite: a supervised sharded run on the
    temporal-blocked kernel that sheds a TOPOLOGY rung (transient
    exhaustion — the kernel ladder is not walked on this path) must
    come back on the smaller decomposition STILL dispatching
    pallas_packed_tb: since round 11 every sharded topology is in tb
    scope, so resharding alone may never silently cost the run its
    24 B/cell kernel."""
    from fdtd3d_tpu.config import ParallelConfig
    cfg = SimConfig(
        scheme="3D", size=(16, 16, 16), time_steps=24, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3, use_pallas=True,
        pml=PmlConfig(size=(2, 2, 2)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(8, 8, 8)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(2, 2, 2)),
        output=OutputConfig(save_dir=str(tmp_path), checkpoint_every=8,
                            telemetry_path=str(tmp_path / "t.jsonl")))
    faults.install("error@t=8,times=1")
    sup = Supervisor(cfg, policy=RetryPolicy(max_retries=0,
                                             sleep=lambda _s: None))
    sim = sup.run(interval=8)
    sim.close()
    faults.clear()
    assert sim._t_host == 24
    assert tuple(sim.topology) == (1, 2, 2)
    assert sim.step_kind == "pallas_packed_tb", sim.step_kind
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    tc = [r for r in recs if r["type"] == "topology_change"]
    assert [(r["old_topology"], r["new_topology"]) for r in tc] == \
        [([2, 2, 2], [1, 2, 2])]
    for comp, v in sim.fields().items():
        assert np.isfinite(np.asarray(v, np.float32)).all(), comp


def test_supervised_resume_adopts_persisted_degraded_state(tmp_path,
                                                           monkeypatch):
    """A preemption mid-degrade: the next supervised --resume reads the
    persisted supervisor state from the snapshot and resumes DEGRADED
    — on the smaller topology, counters seeded — rather than
    re-tripping on the original plan."""
    from fdtd3d_tpu.cli import main
    d = tmp_path / "run"
    argv = ["--3d", "--same-size", "24", "--time-steps", "24",
            "--use-pml", "--pml-size", "3", "--point-source", "Ez",
            "--courant-factor", "0.4", "--wavelength", "0.008",
            "--manual-topology", "2x2x2", "--checkpoint-every", "8",
            "--save-dir", str(d), "--supervise", "--log-level", "0"]
    # NaN at t=8 trips at 16 -> topology degrade to (1,2,2) + rollback
    # to t=8; the re-advanced boundary at t=16 commits a snapshot
    # carrying the supervisor state, then the preemption kills the run.
    monkeypatch.setenv("FDTD3D_FAULT_PLAN",
                       "nan@t=8,field=Ez,chip=3; preempt@t=16")
    with pytest.raises(faults.SimulatedPreemption):
        main(argv)
    monkeypatch.delenv("FDTD3D_FAULT_PLAN")
    faults.clear()
    newest = io.find_latest_checkpoint(str(d))
    meta = io.read_checkpoint_meta(newest)
    assert meta["supervisor"]["topology"] == [1, 2, 2]

    # resume (no fault plan): must adopt the degraded topology
    assert main(argv + ["--resume", "auto"]) == 0
    _state, extra = io.load_checkpoint(
        os.path.join(str(d), "ckpt_t000024.npz"))
    assert extra["t"] == 24
    assert extra["topology"] == [1, 2, 2]       # resumed DEGRADED
    assert extra["supervisor"]["topology_rung"] == 1  # counters seeded
    # no new recovery events fired on the resumed leg
    assert extra["supervisor"]["rollbacks"] == 1


def test_supervised_resume_peek_ignores_foreign_snapshot(tmp_path):
    """A foreign run's leftover snapshot in the same save_dir (the
    stale-leftover fault model) must not donate its recovery state to
    a supervised resume: the peek applies the same scheme/size/dtype
    guards the restore loop does."""
    import numpy as np

    from fdtd3d_tpu.cli import _peek_supervisor_state
    foreign = {"t": 8, "scheme": "3D", "size": [32, 32, 32],
               "dtype": "float32",
               "supervisor": {"topology": [2, 2, 2],
                              "topology_rung": 1, "env_pins":
                              {"FDTD3D_NO_TEMPORAL": "1"}}}
    io.save_checkpoint({"E": {"Ez": np.zeros((4, 4), np.float32)}},
                       str(tmp_path / "ckpt_t000008.npz"),
                       extra=foreign)
    cfg = _cfg2d(tmp_path)          # 2D_TMz (24, 24, 1): incompatible
    state, path = _peek_supervisor_state(cfg, "auto")
    assert state is None and path is None
    # a COMPATIBLE snapshot's state IS adopted
    compatible = {**foreign, "scheme": cfg.scheme,
                  "size": list(cfg.size), "dtype": cfg.dtype}
    io.save_checkpoint({"E": {"Ez": np.zeros((4, 4), np.float32)}},
                       str(tmp_path / "ckpt_t000016.npz"),
                       extra=compatible)
    state, path = _peek_supervisor_state(cfg, "auto")
    assert state == compatible["supervisor"]
    assert path.endswith("ckpt_t000016.npz")


def test_rollback_without_checkpoints_uses_initial_snapshot(tmp_path):
    """No cadence configured: the supervisor's in-memory snapshot of
    the starting state is the rollback target of last resort."""
    faults.install("error@t=8,times=1")
    cfg = _cfg2d(tmp_path, checkpoint_every=0,
                 telemetry_path=str(tmp_path / "t.jsonl"))
    sup = Supervisor(cfg, policy=RetryPolicy(sleep=lambda _s: None))
    sim = sup.run(interval=8)
    sim.close()
    assert sim._t_host == 24
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    rb = [r for r in recs if r["type"] == "rollback"]
    assert rb and rb[0]["source"] == "initial-snapshot"
    assert rb[0]["t_restored"] == 0
