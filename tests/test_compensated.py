"""Kahan-compensated f32 updates (SimConfig.compensated).

The reference solver is f64 C++; plain f32 drifts past 1e-6 relative
error within ~1000 steps (BASELINE.md frontier table). The compensated
mode stores a bf16 residual of each family's accumulation add and must
(a) beat plain f32 against an f64 oracle by a clear margin on a long
horizon, (b) match bit-for-bit semantics between the jnp path and the
packed kernel at f32 roundoff, (c) reject invalid dtype combinations.

The f64 oracle runs in a subprocess: jax_enable_x64 is process-global
and would silently upgrade literals in every other test.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from fdtd3d_tpu.config import PmlConfig, PointSourceConfig, SimConfig
from fdtd3d_tpu.sim import Simulation

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, STEPS = 20, 400

CHILD = r"""
import json, sys
import numpy as np
import jax
dtype = sys.argv[1]
if dtype == "float64":
    jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", "cpu")
from fdtd3d_tpu.config import PmlConfig, PointSourceConfig, SimConfig
from fdtd3d_tpu.sim import Simulation
n, steps = int(sys.argv[2]), int(sys.argv[3])
cfg = SimConfig(
    scheme="3D", size=(n, n, n), time_steps=steps, dx=1e-3,
    courant_factor=0.5, wavelength=n * 1e-3 / 3.0,
    dtype="float32" if dtype == "float32c" else dtype,
    compensated=dtype == "float32c",
    pml=PmlConfig(size=(4, 4, 4)),
    point_source=PointSourceConfig(enabled=True, component="Ez",
                                   position=(n // 2,) * 3),
)
sim = Simulation(cfg)
sim.run()
np.savez(sys.argv[4], **{c: np.asarray(sim.field(c), np.float64)
                         for c in ("Ez", "Hy")})
print(json.dumps({"ok": True, "kind": sim.step_kind}))
"""


def _run_child(dtype, out, tmp_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(tmp_env or {})
    r = subprocess.run([sys.executable, "-c", CHILD, dtype, str(N),
                       str(STEPS), out], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, (r.stderr or r.stdout)[-800:]
    return json.loads([ln for ln in r.stdout.splitlines()
                       if ln.startswith("{")][0])


def test_f32_source_accuracy_vs_f64():
    """rel-err vs the f64 oracle after 400 driven steps stays under
    1e-6 (measured 3.6e-7). Before the fixed-point source phase
    (ops/sources._phase_frac) this was 2.1e-5 — f32's eps*omega*t
    phase loss in sin(omega*t) grew linearly and dominated everything;
    this test pins the 58x win."""
    import tempfile
    tmp = tempfile.mkdtemp(prefix="comp_")
    outs = {}
    for dt in ("float64", "float32"):
        out = os.path.join(tmp, f"{dt}.npz")
        _run_child(dt, out)
        outs[dt] = np.load(out)
    ref = outs["float64"]
    err = max(np.abs(outs["float32"][c] - ref[c]).max()
              / np.abs(ref[c]).max() for c in ("Ez", "Hy"))
    assert err < 1e-6, err


def test_compensated_improves_cavity_drift():
    """Pure eigenmode rotation (no source, no PML) vs the machine-exact
    discrete oracle at 1000 steps: the Kahan + double-single-coefficient
    update must beat plain f32 (measured 1.95e-6 vs 2.62e-6 — the
    remaining floor is the f32 curl arithmetic's systematic
    eigenfrequency shift, reachable only with double-single FIELDS;
    docs/PHYSICS.md precision section)."""
    from fdtd3d_tpu import exact

    def run(compensated):
        cfg = SimConfig(scheme="3D", size=(17, 17, 17), time_steps=1000,
                        dx=1e-3, courant_factor=0.5, wavelength=8e-3,
                        pml=PmlConfig(size=(0, 0, 0)),
                        compensated=compensated, use_pallas=False)
        sim = Simulation(cfg)
        shapes, omega = exact.cavity_mode((17, 17, 17), (2, 3, 1),
                                          cfg.dx, cfg.dt)
        for c, v in shapes.items():
            sim.set_field(c, v.astype(np.float32))
        sim.run()
        return max(
            np.abs(np.asarray(sim.field(c), np.float64)
                   - exact.cavity_expectation(s, omega, cfg.dt, 1000)
                   ).max() / np.abs(s).max()
            for c, s in shapes.items())

    e32, e32c = run(False), run(True)
    assert e32c < e32 * 0.9, (e32, e32c)
    assert e32c < 2.5e-6, e32c


def test_compensated_packed_matches_jnp():
    def run(use_pallas):
        cfg = SimConfig(
            scheme="3D", size=(16, 16, 16), time_steps=30, dx=1e-3,
            courant_factor=0.5, wavelength=6e-3, compensated=True,
            pml=PmlConfig(size=(3, 3, 3)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(8, 8, 8)),
            use_pallas=use_pallas)
        sim = Simulation(cfg)
        sim.run()
        return sim
    j = run(False)
    p = run(True)
    assert p.step_kind == "pallas_packed", p.step_kind
    assert "rE" in p.state and "rH" in p.state
    for c in ("Ez", "Hy"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"


def test_compensated_requires_f32():
    base = dict(scheme="3D", size=(16, 16, 16), time_steps=2, dx=1e-3,
                courant_factor=0.5, wavelength=8e-3, compensated=True)
    with pytest.raises(ValueError, match="compensated"):
        Simulation(SimConfig(**base, dtype="bfloat16"))
    with pytest.raises(ValueError, match="compensated"):
        Simulation(SimConfig(**base, dtype="float64"))


def test_compensated_sharded_falls_back_to_jnp():
    from fdtd3d_tpu.config import ParallelConfig
    sim = Simulation(SimConfig(
        scheme="3D", size=(16, 16, 16), time_steps=2, dx=1e-3,
        courant_factor=0.5, wavelength=8e-3, compensated=True,
        pml=PmlConfig(size=(0, 3, 3)), use_pallas=True,
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(1, 2, 2))))
    assert sim.step_kind == "jnp"
    sim.advance(2)
    assert np.isfinite(np.asarray(sim.field("Ez"))).all()


def test_phase_frac_exact_modular():
    """_phase_frac must track frac(t*f) to ~2^-24 at ANY step count —
    the property that keeps source phase error constant instead of
    growing as eps*omega*t."""
    import math

    import jax.numpy as jnp

    from fdtd3d_tpu.ops.sources import _phase_frac

    f = 0.04283919274719  # generic cycles-per-step
    steps = np.concatenate([np.arange(0, 4096),
                            np.arange(10 ** 6, 10 ** 6 + 64),
                            np.arange(2 ** 24 - 32, 2 ** 24 + 32)])
    got = np.asarray(_phase_frac(jnp.asarray(steps.astype(np.int32)), f),
                     np.float64)
    want = (steps.astype(np.float64) * f) % 1.0
    d = np.abs(got - want)
    d = np.minimum(d, 1.0 - d)  # wrap-around distance
    assert d.max() < 2.0 ** -23, d.max()
