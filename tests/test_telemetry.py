"""Flight recorder (fdtd3d_tpu/telemetry.py): in-graph health counters,
structured JSONL sink, trace spans, and the observability satellites.

The load-bearing claims under test (ISSUE 2 acceptance):

* a tiny CPU run with telemetry emits schema-valid per-chunk JSONL
  (energy, div·E residual, max|E|/|H|, finite flag, wall time,
  provenance);
* the counters are computed IN-GRAPH: advance() performs NO full-field
  host transfer and ≤1 extra scalar-tuple readback per chunk;
* the non-finite tripwire works on the PACKED path and raises
  FloatingPointError naming the chunk + the first-bad-step bound;
* VMEM-ladder downgrades produce a structured ladder_downgrade event;
* telemetry costs ≤2% throughput on a chunked run (in-graph reduction
  amortized over the chunk);
* StepClock gains p50/p95/max per-chunk percentiles.
"""

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from fdtd3d_tpu import telemetry
from fdtd3d_tpu.config import (OutputConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig)
from fdtd3d_tpu.sim import Simulation

BASE3D = dict(scheme="3D", size=(16, 16, 16), time_steps=8, dx=1e-3,
              courant_factor=0.4, wavelength=8e-3)


def _cfg3d(tmp_path=None, **kw):
    out = kw.pop("output", {})
    if tmp_path is not None:
        out.setdefault("telemetry_path",
                       str(tmp_path / "telemetry.jsonl"))
    return SimConfig(
        **BASE3D,
        pml=PmlConfig(size=(3, 3, 3)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(8, 8, 8)),
        output=OutputConfig(**out), **kw)


# -------------------------------------------------------------------------
# JSONL schema + contents
# -------------------------------------------------------------------------

def test_telemetry_jsonl_schema_and_contents(tmp_path):
    cfg = _cfg3d(tmp_path)
    sim = Simulation(cfg)
    sim.advance(4)
    sim.advance(4)
    sim.close_telemetry()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)  # validates
    types = [r["type"] for r in recs]
    assert types == ["run_start", "chunk", "chunk", "run_end"]
    start = recs[0]
    # provenance: git sha, jax version, platform, topology, dtype
    assert start["jax_version"] == jax.__version__
    assert start["platform"] == jax.default_backend()
    assert start["topology"] == [1, 1, 1]
    assert start["dtype"] == "float32"
    assert start["grid"] == [16, 16, 16]
    assert start["step_kind"] == sim.step_kind
    assert start["vmem_rung"] == 0
    for i, c in enumerate(recs[1:3]):
        assert c["chunk"] == i + 1
        assert c["steps"] == 4
        assert c["t"] == 4 * (i + 1)
        assert c["wall_s"] > 0.0
        assert c["mcells_per_s"] > 0.0
        assert c["finite"] is True
        for k in ("energy", "div_l2", "div_linf", "max_e", "max_h"):
            assert np.isfinite(c[k]), k
    # the source has injected energy by chunk 2
    assert recs[2]["energy"] > 0.0
    assert recs[2]["max_e"] > 0.0


def test_run_start_records_comm_strategy_when_sharded(tmp_path):
    """Round 11: a sharded run's run_start carries the planner's
    communication-strategy record (the ledger comm lane's `strategy`
    twin — the run's exchange posture is auditable from telemetry
    alone); unsharded runs omit the key."""
    from fdtd3d_tpu.config import ParallelConfig
    cfg = _cfg3d(tmp_path)
    import dataclasses
    cfg = dataclasses.replace(
        cfg, pml=PmlConfig(size=(2, 2, 2)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(1, 2, 2)))
    sim = Simulation(cfg)
    sim.advance(4)
    sim.close_telemetry()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    start = recs[0]
    strat = start["comm_strategy"]
    assert strat is not None
    assert strat["step_kind"] == sim.step_kind
    assert strat["topology"] == [1, 2, 2]
    assert strat["split"] in ("fused", "per-plane")
    assert strat["schedule"] in ("async", "sync")


def test_run_end_and_counters_match_diag(tmp_path):
    cfg = _cfg3d(tmp_path)
    sim = Simulation(cfg)
    sim.advance(4)
    sim.advance(4)
    sim.close_telemetry()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    end = recs[3]
    assert end["steps"] == 8 and end["t"] == 8
    assert end["first_unhealthy_t"] is None
    # the in-graph counters must agree with diag's independent device
    # pass (vacuum materials, so the energy weighting coincides)
    from fdtd3d_tpu import diag
    m = diag.metrics(sim)
    chunk = recs[2]
    assert chunk["energy"] == pytest.approx(m["energy"], rel=1e-4)
    assert chunk["div_l2"] == pytest.approx(m["div_l2"], rel=1e-4)
    assert chunk["div_linf"] == pytest.approx(m["div_linf"], rel=1e-4)
    assert chunk["max_e"] == pytest.approx(
        max(v for k, v in m.items() if k.startswith("max_E")), rel=1e-5)
    assert chunk["max_h"] == pytest.approx(
        max(v for k, v in m.items() if k.startswith("max_H")), rel=1e-5)


def test_validate_record_rejects_malformed():
    with pytest.raises(ValueError, match="version"):
        telemetry.validate_record({"v": 99, "type": "chunk"})
    with pytest.raises(ValueError, match="unknown record type"):
        telemetry.validate_record({"v": 1, "type": "nope"})
    with pytest.raises(ValueError, match="missing"):
        telemetry.validate_record({"v": 1, "type": "chunk", "chunk": 1})


def test_schema_v3_recovery_records():
    """The supervisor's retry/rollback/degrade records (round 9): valid
    at v3, unknown at v1/v2 (old files must keep validating cleanly)."""
    recs = {
        "retry": {"t": 8, "attempt": 1, "delay_s": 1.0,
                  "error": "RuntimeError: x"},
        "rollback": {"t_failed": 16, "t_restored": 8,
                     "source": "out/ckpt_t000008.npz",
                     "reason": "FloatingPointError: y"},
        "degrade": {"t": 8, "old_kind": "pallas_packed_tb",
                    "new_kind": "pallas_packed",
                    "reason": "FloatingPointError: y"},
    }
    for rtype, fields in recs.items():
        telemetry.validate_record({"v": 3, "type": rtype, **fields})
        for v_old in (1, 2):
            with pytest.raises(ValueError, match="unknown record type"):
                telemetry.validate_record({"v": v_old, "type": rtype,
                                           **fields})
    with pytest.raises(ValueError, match="missing"):
        telemetry.validate_record({"v": 3, "type": "degrade", "t": 8})


def test_schema_v5_topology_and_chip_host():
    """v5 (ISSUE 8): topology_change joins the schema, and the
    recovery records carry chip/host stamps — REQUIRED (nullable) at
    v5, skipped when validating v3/v4 files."""
    tc = {"t": 8, "old_topology": [2, 2, 2],
          "new_topology": [1, 2, 2], "reason": "chip 3 diverged",
          "chip": 3, "host": 0}
    telemetry.validate_record({"v": 5, "type": "topology_change", **tc})
    for v_old in (1, 2, 3, 4):
        with pytest.raises(ValueError, match="unknown record type"):
            telemetry.validate_record({"v": v_old,
                                       "type": "topology_change", **tc})
    # chip/host: required at v5 (null allowed), absent pre-v5 is fine
    base = {"t": 8, "old_kind": "jnp", "new_kind": "jnp", "reason": "x"}
    telemetry.validate_record({"v": 3, "type": "degrade", **base})
    with pytest.raises(ValueError, match="missing 'chip'"):
        telemetry.validate_record({"v": 5, "type": "degrade", **base})
    telemetry.validate_record({"v": 5, "type": "degrade", **base,
                               "chip": None, "host": None})
    telemetry.validate_record({"v": 5, "type": "degrade", **base,
                               "chip": 3, "host": 1})


def test_schema_v10_health_records():
    """v10 (ISSUE 18): heartbeat + liveness join the schema — valid at
    v10, unknown at every earlier version (old files keep validating
    cleanly; a v9 reader meeting a heartbeat fails loudly)."""
    hb = {"emitter": "run", "pid": 4242, "host": "worker-0", "seq": 3,
          "unix": 1786100000.0, "t": 8, "cadence_s": 5.0,
          "run_id": "r1", "trace_id": "t-00", "job_id": "j1"}
    lv = {"emitter": "scheduler", "status": "stuck",
          "last_unix": 1786100000.0, "last_t": None,
          "deadline_s": 15.0, "silent_s": 20.0,
          "message": "scheduler silent 20.0s"}
    for rtype, fields in (("heartbeat", hb), ("liveness", lv)):
        telemetry.validate_record({"v": 10, "type": rtype, **fields})
        for v_old in range(1, 10):
            with pytest.raises(ValueError, match="unknown record type"):
                telemetry.validate_record({"v": v_old, "type": rtype,
                                           **fields})
    with pytest.raises(ValueError, match="missing 'seq'"):
        telemetry.validate_record(
            {"v": 10, "type": "heartbeat", "emitter": "run",
             "pid": 1, "host": "h", "unix": 1.0, "t": None})


def test_schema_v11_lease_records():
    """v11 (ISSUE 20): the fenced-lease lifecycle rows join the
    schema — valid at v11, unknown at every earlier version (a v10
    reader meeting a lease row fails loudly, never misfolds)."""
    ident = {"sched": "worker-0:7001:1786100000", "pid": 7001,
             "host": "worker-0", "start": 1786100000.0, "token": 1,
             "unix": 1786100000.0, "ttl_s": 30.0}
    recs = {
        "lease_acquire": {**ident,
                          "takeover_from": "worker-1:7000:1786099000"},
        "lease_renew": dict(ident),
        "lease_release": {**ident, "ttl_s": 0.0,
                          "reason": "serve loop exited"},
    }
    for rtype, fields in recs.items():
        telemetry.validate_record({"v": 11, "type": rtype, **fields})
        for v_old in range(1, 11):
            with pytest.raises(ValueError, match="unknown record type"):
                telemetry.validate_record({"v": v_old, "type": rtype,
                                           **fields})
    with pytest.raises(ValueError, match="missing 'token'"):
        telemetry.validate_record(
            {"v": 11, "type": "lease_acquire",
             **{k: v for k, v in ident.items() if k != "token"}})


def test_heartbeater_emits_at_chunk_boundaries(tmp_path, monkeypatch):
    """FDTD3D_HEARTBEAT_S=0 (every-boundary mode): each advance()
    chunk appends one heartbeat row onto the SAME telemetry stream —
    monotonic seq, the last committed step t, the declared cadence."""
    monkeypatch.setenv("FDTD3D_HEARTBEAT_S", "0")
    cfg = _cfg3d(tmp_path)
    sim = Simulation(cfg)
    sim.advance(4)
    sim.advance(4)
    sim.close_telemetry()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)  # validates
    beats = [r for r in recs if r["type"] == "heartbeat"]
    assert [(b["seq"], b["t"]) for b in beats] == [(1, 4), (2, 8)]
    for b in beats:
        assert b["emitter"] == "run"
        assert b["cadence_s"] == 0.0
        assert b["pid"] == os.getpid()
        # no registry configured -> no run_id identity to stamp (the
        # None key is dropped, not emitted as null)
        assert b.get("run_id") == sim.run_id
    # the surrounding stream is undisturbed
    assert [r["type"] for r in recs if r["type"] != "heartbeat"] == \
        ["run_start", "chunk", "chunk", "run_end"]


def test_heartbeater_rate_limits_on_cadence(tmp_path, monkeypatch):
    """A long cadence suppresses boundary beats inside the window: two
    back-to-back chunks yield exactly one heartbeat."""
    monkeypatch.setenv("FDTD3D_HEARTBEAT_S", "3600")
    cfg = _cfg3d(tmp_path)
    sim = Simulation(cfg)
    sim.advance(4)
    sim.advance(4)
    sim.close_telemetry()
    beats = [r for r in telemetry.read_jsonl(cfg.output.telemetry_path)
             if r["type"] == "heartbeat"]
    assert [(b["seq"], b["t"]) for b in beats] == [(1, 4)]


def test_heartbeat_off_is_a_strict_noop(tmp_path, monkeypatch):
    """Without FDTD3D_HEARTBEAT_S the stream is byte-identical to a
    v9-shaped run: zero heartbeat rows, zero extra bytes — the knob
    gates construction, not just emission."""
    monkeypatch.delenv("FDTD3D_HEARTBEAT_S", raising=False)
    cfg = _cfg3d(tmp_path)
    sim = Simulation(cfg)
    assert sim._heartbeat is None
    sim.advance(4)
    sim.advance(4)
    sim.close_telemetry()
    raw = open(cfg.output.telemetry_path, "rb").read()
    assert b"heartbeat" not in raw
    types = [r["type"]
             for r in telemetry.read_jsonl(cfg.output.telemetry_path)]
    assert types == ["run_start", "chunk", "chunk", "run_end"]


def test_heartbeat_cadence_bad_values_are_named(monkeypatch):
    """Garbage/negative FDTD3D_HEARTBEAT_S is a NAMED config error
    (the registered-knob convention), not a raw float() traceback."""
    monkeypatch.setenv("FDTD3D_HEARTBEAT_S", "fast")
    with pytest.raises(ValueError, match="FDTD3D_HEARTBEAT_S='fast'"):
        telemetry.heartbeat_cadence_s()
    monkeypatch.setenv("FDTD3D_HEARTBEAT_S", "-5")
    with pytest.raises(ValueError, match="must be >= 0"):
        telemetry.heartbeat_cadence_s()


# -------------------------------------------------------------------------
# in-graph guarantee: no full-field host transfer, ≤1 scalar readback
# -------------------------------------------------------------------------

def test_advance_readback_is_one_scalar_tuple(tmp_path, monkeypatch):
    cfg = _cfg3d(tmp_path)
    sim = Simulation(cfg)
    sim.advance(3)  # compile the n=3 chunk outside the counting window

    calls = []
    real_get = jax.device_get

    def counting_get(tree):
        sizes = [int(np.size(x)) for x in jax.tree.leaves(tree)]
        calls.append(sizes)
        return real_get(tree)

    monkeypatch.setattr(jax, "device_get", counting_get)
    sim.advance(3)
    monkeypatch.undo()
    # exactly ONE device_get — the health scalar tuple — and every leaf
    # of it is a scalar (no field array ever crosses to host)
    assert len(calls) == 1, f"device_get calls: {calls}"
    assert all(s == 1 for s in calls[0]), calls[0]
    assert len(calls[0]) == len(telemetry.HEALTH_KEYS)


def test_no_health_graph_without_telemetry():
    """Default path: no counters wired, no sink (and therefore no
    readback branch — advance() leaves the chunk output untouched)."""
    sim = Simulation(_cfg3d())
    assert sim._runner_health is False
    assert sim.telemetry is None


# -------------------------------------------------------------------------
# non-finite tripwire (packed path included)
# -------------------------------------------------------------------------

def _nan_trip(sim):
    sim.advance(4)  # healthy chunk passes
    bad = np.full(sim.state["E"]["Ez"].shape, np.nan, np.float32)
    sim.set_field("Ez", bad)
    with pytest.raises(FloatingPointError) as ei:
        sim.advance(4)
    msg = str(ei.value)
    # names the chunk and bounds the first bad step
    assert "chunk 2" in msg, msg
    assert "(4, 8]" in msg, msg
    assert "Ez" in msg, msg


def test_nan_tripwire_jnp(tmp_path):
    cfg = _cfg3d(tmp_path, output={"check_finite": True})
    sim = Simulation(cfg)
    _nan_trip(sim)
    sim.close_telemetry()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    chunks = [r for r in recs if r["type"] == "chunk"]
    assert [c["finite"] for c in chunks] == [True, False]
    # the record of the unhealthy chunk is written BEFORE the raise
    assert recs[-1]["first_unhealthy_t"] == 8


def test_nan_tripwire_packed_pallas():
    """ISSUE 2 satellite: inject a NaN mid-run on the PACKED path and
    assert the in-graph flag trips with the chunk + step bound. Since
    round 8 the sourceless packed hot path is the temporal-blocked
    kernel — the tripwire must unpack ITS carry in-graph too."""
    cfg = SimConfig(
        **BASE3D, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
        output=OutputConfig(check_finite=True))
    sim = Simulation(cfg)
    assert sim.step_kind == "pallas_packed_tb", sim.step_kind
    assert sim._runner_health is True
    _nan_trip(sim)


# -------------------------------------------------------------------------
# ladder_downgrade event
# -------------------------------------------------------------------------

def test_ladder_downgrade_event(tmp_path, monkeypatch):
    """Force one rung of the VMEM ladder and check the structured event
    lands in the JSONL next to the (still-present) stderr warning."""
    cfg = _cfg3d(tmp_path)
    sim = Simulation(cfg)
    sim.step_kind = "pallas_packed"   # enter the ladder's guard
    sim.step_diag = {"tile": {"EH": 8}}

    import fdtd3d_tpu.solver as solver_mod

    def fake_runner(static, mesh_axes, mesh_shape, health=False,
                    per_chip=False):
        r = lambda state, coeffs, n: state  # noqa: E731
        r.kind = "pallas_packed"
        r.diag = {"tile": {"EH": 4}}
        r.health = False
        return r

    monkeypatch.setattr(solver_mod, "make_chunk_runner", fake_runner)
    monkeypatch.setattr("fdtd3d_tpu.sim.make_chunk_runner", fake_runner)
    sim._vmem_fallback(RuntimeError("mosaic vmem overflow"))
    sim.close_telemetry()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    ev = [r for r in recs if r["type"] == "ladder_downgrade"]
    assert len(ev) == 1
    assert ev[0]["old_budget_mb"] is None       # first rung: model pick
    assert ev[0]["new_budget_mb"] == Simulation._VMEM_LADDER_MB[0]
    assert ev[0]["old_tile"] == 8 and ev[0]["new_tile"] == 4
    assert ev[0]["vmem_rung"] == 1


# -------------------------------------------------------------------------
# overhead guard (≤2% on a chunked run) + StepClock percentiles
# -------------------------------------------------------------------------

def _chunk_cost(static, coeffs, state, n_steps, health):
    """XLA cost-model (flops, bytes accessed) of one compiled chunk."""
    import functools

    from fdtd3d_tpu.solver import make_chunk_runner
    runner = make_chunk_runner(static, health=health)
    compiled = jax.jit(functools.partial(runner, n=n_steps)).lower(
        state, coeffs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):  # older jax returns [dict]
        ca = ca[0]
    return float(ca["flops"]), float(ca["bytes accessed"])


def test_telemetry_overhead_guard():
    """The ≤2% overhead guarantee, asserted deterministically.

    Wall-clock at the 2% level is unmeasurable on a loaded CI box
    (chunk times here swing 3x between iterations — the slow-lane
    test below takes the measured route on quiet machines/TPU). The
    deterministic form uses XLA's cost model on the SAME compiled
    chunk executables Simulation runs:

    1. the health counters add a FIXED per-chunk cost — one reduction
       over the final state, NOT a per-step term: the cost model
       (which counts the scan body once, independent of trip count —
       asserted below so the arithmetic stays honest) reports the same
       extra bytes/flops for a 16-step and a 128-step chunk;
    2. that fixed cost is ≤ 0.16 step-equivalents in bytes (what
       bounds this HBM-bound stencil) AND flops — so for every chunk
       of ≥ 8 steps the overhead is ≤ 2%, production chunks are
       60-120+ steps (bench stages; Simulation.run defaults to the
       WHOLE horizon in one scan) where it is ≤ 0.3%. The model
       over-counts fused temporaries; measured wall cost of the
       reduction is even lower (~0.008 chunk-equivalents at 48³x64,
       slow-lane test below).
    """
    import jax.numpy as jnp

    from fdtd3d_tpu.solver import build_coeffs, build_static, init_state
    cfg = SimConfig(scheme="3D", size=(32, 32, 32), time_steps=128,
                    dx=1e-3, courant_factor=0.4, wavelength=8e-3,
                    pml=PmlConfig(size=(4, 4, 4)))
    st = build_static(cfg)
    coeffs = jax.tree.map(jnp.asarray, build_coeffs(st))
    state = init_state(st)
    f16, b16 = _chunk_cost(st, coeffs, state, 16, health=False)
    f16h, b16h = _chunk_cost(st, coeffs, state, 16, health=True)
    f128h, b128h = _chunk_cost(st, coeffs, state, 128, health=True)
    # invariant the arithmetic relies on: the model counts the scan
    # body once, so a chunk's cost ~= one step's cost and the health
    # extra is per-CHUNK, not per-step
    assert b128h == pytest.approx(b16h, rel=0.01), \
        "cost model scales with trip count; rederive the bound"
    # ≤ 0.16 step-equivalents => ≤2% for every chunk of ≥8 steps
    extra_b, extra_f = b16h - b16, f16h - f16
    assert extra_b <= 0.16 * b16, \
        f"health reduction costs {extra_b / b16:.3f} step-equivalents " \
        f"of bytes (> 0.16): >2% at 8-step chunks"
    assert extra_f <= 0.16 * f16, \
        f"health reduction costs {extra_f / f16:.3f} step-equivalents " \
        f"of flops (> 0.16)"


@pytest.mark.slow
def test_telemetry_overhead_guard_wallclock(tmp_path):
    """Measured form of the ≤2% guard for quiet machines / the chip
    lane: interleaved min-of-N chunk timings (load drift hits both
    sims instead of whichever ran second), one re-measure, and a small
    absolute epsilon for timer noise."""
    n, steps, repeats = 48, 64, 7
    base = dict(scheme="3D", size=(n, n, n), time_steps=steps, dx=1e-3,
                courant_factor=0.4, wavelength=8e-3,
                pml=PmlConfig(size=(4, 4, 4)))
    off = Simulation(SimConfig(**base))
    on = Simulation(SimConfig(
        **base, output=OutputConfig(
            telemetry_path=str(tmp_path / "t.jsonl"))))
    off.advance(steps)  # warm-up/compile outside the timing
    on.advance(steps)

    def timed(sim):
        sim.block_until_ready()
        t0 = time.perf_counter()
        sim.advance(steps)
        sim.block_until_ready()
        return time.perf_counter() - t0

    def pair():
        t_off = t_on = float("inf")
        for _ in range(repeats):
            t_off = min(t_off, timed(off))
            t_on = min(t_on, timed(on))
        return t_off, t_on

    t_off, t_on = pair()
    if t_on > t_off * 1.02 + 0.002:  # one retry before failing
        t_off, t_on = pair()
    on.close_telemetry()
    assert t_on <= t_off * 1.02 + 0.002, \
        f"telemetry overhead {t_on / t_off - 1:.1%} " \
        f"(on {t_on * 1e3:.1f}ms vs off {t_off * 1e3:.1f}ms)"


def test_step_clock_percentiles():
    from fdtd3d_tpu.profiling import StepClock
    clk = StepClock()
    for sec in (1.0, 2.0, 4.0):
        clk.record(10, sec, 1e6)  # 10, 5, 2.5 Mcells/s chunks
    s = clk.summary()
    assert s["chunks"] == 3
    assert s["p50_mcells_per_s"] == pytest.approx(5.0)
    assert s["max_mcells_per_s"] == pytest.approx(10.0)
    assert s["p95_mcells_per_s"] == pytest.approx(
        float(np.percentile([10.0, 5.0, 2.5], 95)))
    rep = clk.report()
    assert "p50" in rep and "p95" in rep and "max" in rep
    empty = StepClock().summary()
    assert empty["p50_mcells_per_s"] == 0.0


# -------------------------------------------------------------------------
# CLI smoke + report tool
# -------------------------------------------------------------------------

def test_cli_telemetry_smoke(tmp_path, capsys):
    """ISSUE 2 satellite: CLI --telemetry on a tiny 3D case; every
    record validates against the schema."""
    from fdtd3d_tpu import cli
    path = str(tmp_path / "flight.jsonl")
    rc = cli.main(["--3d", "--same-size", "12", "--time-steps", "6",
                   "--use-pml", "--pml-size", "3",
                   "--point-source", "Ez",
                   "--metrics-every", "3",   # forces chunked advance
                   "--save-dir", str(tmp_path),
                   "--telemetry", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "telemetry:" in out
    recs = telemetry.read_jsonl(path)  # validates every record
    types = [r["type"] for r in recs]
    assert types[0] == "run_start" and types[-1] == "run_end"
    assert types.count("chunk") == 2  # 6 steps at interval 3


def test_report_tool(tmp_path):
    cfg = _cfg3d(tmp_path)
    sim = Simulation(cfg)
    for _ in range(4):
        sim.advance(2)
    sim.close_telemetry()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(root, "tools", "telemetry_report.py")
    proc = subprocess.run(
        [sys.executable, tool, cfg.output.telemetry_path],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    assert "Mcells/s" in proc.stdout
    assert "healthy: finite throughout" in proc.stdout
    tr = _load_report_tool()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    summaries = [tr.summarize_run(r) for r in tr.split_runs(recs)]
    assert summaries[0]["chunks"] == 4
    assert summaries[0]["complete"] is True
    assert summaries[0]["first_unhealthy_t"] is None


def _load_report_tool():
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(root, "tools", "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_report_tool_first_unhealthy(tmp_path):
    """An unhealthy run's summary carries the first-bad-step bound."""
    tr = _load_report_tool()
    cfg = _cfg3d(tmp_path, output={"check_finite": False})
    sim = Simulation(cfg)
    sim.advance(4)
    sim.set_field("Ez", np.full(sim.state["E"]["Ez"].shape, np.nan,
                                np.float32))
    sim.advance(4)  # check_finite off: records, does not raise
    sim.close_telemetry()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    s = tr.summarize_run(tr.split_runs(recs)[0])
    assert s["first_unhealthy_t"] == 8
    assert s["first_unhealthy_bound"] == [4, 8]


# -------------------------------------------------------------------------
# sharded + paired-complex coverage
# -------------------------------------------------------------------------

def _cfg2d(tmp_path, **kw):
    # 2D keeps the compile cheap (tier-1 wall budget); the collective /
    # paired-leg health plumbing is scheme-independent
    return SimConfig(
        scheme="2D_TMz", size=(32, 32, 1), time_steps=8, dx=1e-3,
        courant_factor=0.4, wavelength=10e-3,
        pml=PmlConfig(size=(4, 4, 0)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(16, 16, 0)),
        output=OutputConfig(
            telemetry_path=str(tmp_path / "telemetry.jsonl")), **kw)


def test_health_counters_sharded_match_single(tmp_path):
    """Counters psum/pmax to GLOBAL values under shard_map: a sharded
    run must report the same energy/max as the single-device run."""
    k1 = _cfg2d(tmp_path)
    s1 = Simulation(k1)
    s1.advance(8)
    s1.close_telemetry()
    r1 = [r for r in telemetry.read_jsonl(k1.output.telemetry_path)
          if r["type"] == "chunk"][-1]
    p2 = tmp_path / "sharded"
    p2.mkdir()
    k2 = _cfg2d(p2, parallel=ParallelConfig(topology="manual",
                                            manual_topology=(2, 2, 1)))
    s2 = Simulation(k2)
    assert s2.mesh is not None
    s2.advance(8)
    s2.close_telemetry()
    r2 = [r for r in telemetry.read_jsonl(k2.output.telemetry_path)
          if r["type"] == "chunk"][-1]
    assert r2["energy"] == pytest.approx(r1["energy"], rel=1e-4)
    assert r2["max_e"] == pytest.approx(r1["max_e"], rel=1e-5)
    assert r2["max_h"] == pytest.approx(r1["max_h"], rel=1e-5)
    assert r2["finite"] is True


def test_check_finite_paired_complex(tmp_path, monkeypatch):
    """The paired-complex path reduces its legs in-graph (health_view);
    the tripwire still works there."""
    monkeypatch.setenv("FDTD3D_FORCE_PAIRED_COMPLEX", "1")
    cfg = _cfg2d(tmp_path, complex_fields=True)
    cfg.output.check_finite = True
    sim = Simulation(cfg)
    assert sim.step_kind.startswith("complex2x")
    assert sim._runner_health is True
    sim.advance(4)  # healthy (packs the real legs, compiles the chunk)
    # the health reduction must not inject complex ops into the chunk:
    # the legs are real precisely because the backend may lack complex
    # arithmetic (the CPU test would otherwise mask an astype(c64))
    hlo = sim._compiled[4].as_text()
    assert "c64[" not in hlo and "c128[" not in hlo, \
        "complex ops in the paired-real chunk graph"
    bad = np.full(np.asarray(sim.state["E"]["Ez"]).shape, np.nan,
                  np.complex64)
    sim.set_field("Ez", bad)
    with pytest.raises(FloatingPointError, match="chunk 2"):
        sim.advance(4)
