"""Leveled logging helper (fdtd3d_tpu/log.py)."""

import contextlib
import io

from fdtd3d_tpu import log as flog


def test_log_levels(capsys):
    old = flog.get_level()
    try:
        flog.set_level(1)
        flog.log("visible")
        flog.log("hidden", level=2)
        out = capsys.readouterr().out
        assert "visible" in out and "hidden" not in out
        flog.set_level(0)
        flog.log("silenced")
        assert capsys.readouterr().out == ""
        flog.warn("always")
        assert "WARNING: always" in capsys.readouterr().err
    finally:
        flog.set_level(old)
