"""Complex-field mode (reference COMPLEX_FIELD_VALUES) end-to-end.

The solver is linear, so a complex-field run must equal the real-part run
plus 1j times the imag-part run — this superposition identity exercises
every op in the step (curl, CPML psi recursion, Drude ADE, TFSF, sources,
walls) under a complex dtype. A complex cavity phasor additionally pins
the time evolution to the machine-precision discrete oracle.
"""

import contextlib
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fdtd3d_tpu import exact, solver
from fdtd3d_tpu.config import (MaterialsConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation


def _superpose(scheme, size, steps, **extra):
    """complex run == real(Re init) + 1j * real(Im init).

    Sources (TFSF, point) inject REAL values, so they belong to the real
    part of the superposition only: the imaginary leg runs source-free.
    """
    def cfg(complex_fields, sources=True):
        kw = dict(extra)
        if not sources:
            kw.pop("tfsf", None)
            kw.pop("point_source", None)
        return SimConfig(scheme=scheme, size=size, time_steps=steps,
                         dx=1e-3, courant_factor=0.4, wavelength=8e-3,
                         complex_fields=complex_fields, **kw)

    key = jax.random.PRNGKey(7)
    sim_c = Simulation(cfg(True))
    sim_re = Simulation(cfg(False))
    sim_im = Simulation(cfg(False, sources=False))
    for grp in ("E", "H"):
        for comp in sim_c.state[grp]:
            key, k1, k2 = jax.random.split(key, 3)
            shape = sim_c.state[grp][comp].shape
            re = 0.01 * jax.random.normal(k1, shape, jnp.float32)
            im = 0.01 * jax.random.normal(k2, shape, jnp.float32)
            sim_c.set_field(comp, np.asarray(re) + 1j * np.asarray(im))
            sim_re.set_field(comp, np.asarray(re))
            sim_im.set_field(comp, np.asarray(im))
    sim_c.run(); sim_re.run(); sim_im.run()
    for grp in ("E", "H"):
        for comp in sim_c.state[grp]:
            want = sim_re.field(comp) + 1j * sim_im.field(comp)
            got = sim_c.field(comp)
            assert np.iscomplexobj(got), f"{comp} lost complex dtype"
            scale = np.abs(want).max() + 1e-30
            err = np.abs(got - want).max() / scale
            assert err < 1e-5, f"{scheme}/{comp}: rel {err:.2e}"


def test_superposition_1d():
    _superpose("1D_EzHy", (64, 1, 1), 40,
               pml=PmlConfig(size=(6, 0, 0)))


def test_superposition_2d_full_physics():
    _superpose("2D_TMz", (24, 24, 1), 25,
               pml=PmlConfig(size=(4, 4, 0)),
               point_source=PointSourceConfig(enabled=True, component="Ez",
                                              position=(12, 12, 0)))


def test_superposition_3d_full_physics():
    _superpose("3D", (16, 16, 16), 12,
               pml=PmlConfig(size=(3, 3, 3)),
               tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                               angle_teta=30.0, angle_phi=40.0,
                               angle_psi=15.0),
               materials=MaterialsConfig(
                   use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
                   drude_sphere=SphereConfig(enabled=True,
                                             center=(8.0, 8.0, 8.0),
                                             radius=3.0)))


def test_complex_cavity_phasor_exact():
    """Complex-amplitude cavity mode: phasor evolution to machine eps."""
    n, steps = 21, 150
    cfg = SimConfig(scheme="2D_TMz", size=(n, n, 1), time_steps=steps,
                    dx=1e-3, courant_factor=0.6, wavelength=10e-3,
                    dtype="float64", complex_fields=True)
    sim = Simulation(cfg)
    shape, omega = exact.cavity_mode_tmz((n, n), 2, 3, cfg.dx, cfg.dt)
    amp = 1.0 + 0.5j
    sim.set_field("Ez", amp * shape[:, :, None])
    sim.run()
    expected = amp * exact.cavity_expectation(shape, omega, cfg.dt, steps)
    err = np.max(np.abs(sim.field("Ez")[:, :, 0] - expected))
    assert err < 1e-10, f"complex cavity drifted: {err:.2e}"


def test_paired_complex_matches_native(monkeypatch):
    """The paired-real step (the TPU route for COMPLEX_FIELD_VALUES —
    the axon backend lacks complex arithmetic) must reproduce the
    native complex run: re leg sourced, im leg source-free, combined
    on the host. Forced on CPU via the test hook env var."""
    def build(paired):
        if paired:
            monkeypatch.setenv("FDTD3D_FORCE_PAIRED_COMPLEX", "1")
        else:
            monkeypatch.delenv("FDTD3D_FORCE_PAIRED_COMPLEX",
                               raising=False)
        cfg = SimConfig(scheme="3D", size=(16, 16, 16), time_steps=10,
                        dx=1e-3, courant_factor=0.4, wavelength=8e-3,
                        complex_fields=True,
                        pml=PmlConfig(size=(3, 3, 3)),
                        tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                                        angle_teta=30.0, angle_phi=40.0,
                                        angle_psi=15.0))
        sim = Simulation(cfg)
        key = jax.random.PRNGKey(3)
        for grp in ("E", "H"):
            for comp in list(sim.state[grp]):
                key, k1, k2 = jax.random.split(key, 3)
                shape = sim.state[grp][comp].shape
                re = 0.01 * np.asarray(jax.random.normal(k1, shape))
                im = 0.01 * np.asarray(jax.random.normal(k2, shape))
                sim.set_field(comp, re + 1j * im)
        sim.run()
        return sim

    native = build(False)
    assert not native.static.paired_complex
    paired = build(True)
    assert paired.static.paired_complex
    assert paired.step_kind.startswith("complex2x_"), paired.step_kind
    for comp in ("Ez", "Hy"):
        a = np.asarray(native.field(comp))
        b = np.asarray(paired.field(comp))
        err = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert err < 2e-6, f"{comp}: rel {err:.2e}"
        assert np.iscomplexobj(b)


def test_paired_complex_packed_legs(monkeypatch):
    """With use_pallas forced, the paired legs ride the packed kernel
    (interpret mode on CPU) — the path real TPU complex runs take."""
    monkeypatch.setenv("FDTD3D_FORCE_PAIRED_COMPLEX", "1")
    cfg = SimConfig(scheme="3D", size=(16, 16, 16), time_steps=6,
                    dx=1e-3, courant_factor=0.4, wavelength=8e-3,
                    complex_fields=True, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)))
    sim = Simulation(cfg)
    assert sim.step_kind == "complex2x_pallas_packed", sim.step_kind
    sim.run()
    monkeypatch.delenv("FDTD3D_FORCE_PAIRED_COMPLEX")
    ref = Simulation(dataclasses_replace_native(cfg))
    ref.run()
    a = np.asarray(ref.field("Ez"))
    b = np.asarray(sim.field("Ez"))
    err = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
    assert err < 2e-6, err


def dataclasses_replace_native(cfg):
    import dataclasses
    return dataclasses.replace(cfg, use_pallas=False)


def test_complex_falls_back_from_pallas():
    from fdtd3d_tpu.ops import pallas3d
    cfg = SimConfig(scheme="3D", size=(16, 16, 16), complex_fields=True)
    static = solver.build_static(cfg)
    assert pallas3d.make_pallas_step(static) is None


def test_complex_cli_black_box():
    from fdtd3d_tpu import cli
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(["--2d", "TMz", "--sizex", "24", "--sizey", "24",
                       "--sizez", "1", "--time-steps", "20",
                       "--complex-field-values", "--use-pml",
                       "--pml-size", "4", "--point-source", "Ez",
                       "--norms-every", "20"])
    assert rc == 0
    assert "[t=20]" in buf.getvalue()


def test_paired_complex_sharded_loudly_rejects(monkeypatch):
    """Paired-complex + a sharded topology cannot work (the
    complex<->paired conversion routes through host numpy, which
    cannot run inside shard_map) — it must fail at construction with
    an actionable error, not an obscure trace failure (VERDICT r4
    missing item 5)."""
    from fdtd3d_tpu.config import ParallelConfig
    monkeypatch.setenv("FDTD3D_FORCE_PAIRED_COMPLEX", "1")
    cfg = SimConfig(scheme="3D", size=(16, 16, 16), time_steps=4,
                    dx=1e-3, courant_factor=0.4, wavelength=8e-3,
                    complex_fields=True,
                    parallel=ParallelConfig(topology="manual",
                                            manual_topology=(1, 2, 2)))
    with pytest.raises(ValueError, match="native complex"):
        Simulation(cfg)
