"""Drude dispersive-media tests.

Physics oracle (SURVEY.md §4 posture): a Drude metal driven below its
plasma frequency has eps(w) = eps_inf - wp^2/(w^2 + i g w) < 0 — waves
must reflect off it and decay evanescently inside, at the analytic skin
depth. Reference parity: the dispersive (Drude "metamaterial") update with
OmegaPE/GammaE grids (SURVEY.md §2 InternalScheme row; BASELINE config #5).
"""

import math

import numpy as np

from fdtd3d_tpu import physics
from fdtd3d_tpu.config import (MaterialsConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation


def test_drude_metal_reflects_and_is_evanescent_inside():
    n = 160
    wavelength = 15e-3
    omega = 2 * math.pi * physics.C0 / wavelength
    wp = 3.0 * omega  # eps(omega) = 1 - 9 = -8: strongly metallic
    # "slab": a huge drude sphere centered deep in the right half.
    cfg = SimConfig(
        scheme="1D_EzHy", size=(n, 1, 1), time_steps=1600, dx=1e-3,
        courant_factor=0.5, wavelength=wavelength,
        # PML so the wave reflected off the metal is absorbed once it
        # leaves the TFSF box (a bare PEC wall would bounce it back in).
        pml=PmlConfig(size=(10, 0, 0)),
        tfsf=TfsfConfig(enabled=True, margin=(8, 0, 0),
                        angle_teta=90.0, angle_phi=0.0, angle_psi=180.0),
        materials=MaterialsConfig(
            use_drude=True, eps_inf=1.0, omega_p=wp, gamma=0.0,
            drude_sphere=SphereConfig(enabled=True, center=(n, 0.0, 0.0),
                                      radius=n - 100.0)),
    )
    sim = Simulation(cfg)
    sim.run()
    interface = 100  # drude region starts at x = 100

    # Standing wave in front of the metal: |Ez| has temporal nodes, so
    # sample several snapshots across one optical period (~33 steps) and
    # take the envelope; full reflection gives max approaching 2x incident.
    front_max, inside_max = 0.0, 0.0
    for _ in range(6):
        sim.advance(7)
        ez = sim.field("Ez")[:, 0, 0]
        front_max = max(front_max, np.abs(ez[40:interface - 5]).max())
        inside_max = max(inside_max,
                         np.abs(ez[interface + 12: interface + 18]).max())
    assert front_max > 1.5, f"no standing wave, max {front_max:.2f}"
    ez = sim.field("Ez")[:, 0, 0]

    # Evanescent decay inside: analytic kappa = k0 * sqrt(|eps|)
    k0 = omega / physics.C0 * cfg.dx  # per cell
    kappa = k0 * math.sqrt(8.0)
    depth = 12
    expected_bound = 2.0 * math.exp(-kappa * depth)
    assert inside_max < 3.0 * expected_bound + 0.02, (
        f"not evanescent: |Ez|={inside_max:.3f} at depth {depth}, "
        f"bound {expected_bound:.4f}")

    # And the fields stayed finite/stable over the whole run.
    assert np.isfinite(ez).all()


def test_drude_transparent_above_plasma_frequency():
    """wp << omega: eps -> eps_inf, the wave passes essentially unchanged."""
    n = 160
    wavelength = 15e-3
    omega = 2 * math.pi * physics.C0 / wavelength
    cfg = SimConfig(
        scheme="1D_EzHy", size=(n, 1, 1), time_steps=1100, dx=1e-3,
        courant_factor=0.5, wavelength=wavelength,
        tfsf=TfsfConfig(enabled=True, margin=(10, 0, 0),
                        angle_teta=90.0, angle_phi=0.0, angle_psi=180.0),
        materials=MaterialsConfig(
            use_drude=True, eps_inf=1.0, omega_p=0.05 * omega, gamma=0.0,
            drude_sphere=SphereConfig(enabled=True, center=(n, 0.0, 0.0),
                                      radius=n - 100.0)),
    )
    sim = Simulation(cfg)
    sim.run()
    ez = sim.field("Ez")[:, 0, 0]
    # Deep inside the weak plasma the CW amplitude stays near 1.
    inside = np.abs(ez[120:145]).max()
    assert 0.8 < inside < 1.3, f"transmission wrong: {inside:.3f}"


def _halfspace_cfg(wavelength, n, *, electric=False, magnetic=False,
                   wp_ratio=1.2, steps=1600, slab_hi=None):
    """TFSF plane wave onto a dispersive region starting at x=100.

    slab_hi: end of the dispersive region (default: the domain edge —
    fine for evanescent single-negative media). For PROPAGATING
    (double-negative) media the region must end before the CPML: a PML
    backed by a negative-index medium is a known instability.
    """
    omega = 2 * math.pi * physics.C0 / wavelength
    wp = wp_ratio * omega
    if slab_hi is None:
        sphere = SphereConfig(enabled=True, center=(n, 0.0, 0.0),
                              radius=n - 100.0)
    else:
        c = (100.0 + slab_hi) / 2.0
        sphere = SphereConfig(enabled=True, center=(c, 0.0, 0.0),
                              radius=(slab_hi - 100.0) / 2.0)
    return SimConfig(
        scheme="1D_EzHy", size=(n, 1, 1), time_steps=steps, dx=1e-3,
        courant_factor=0.5, wavelength=wavelength,
        pml=PmlConfig(size=(10, 0, 0)),
        tfsf=TfsfConfig(enabled=True, margin=(8, 0, 0),
                        angle_teta=90.0, angle_phi=0.0, angle_psi=180.0),
        materials=MaterialsConfig(
            use_drude=electric, eps_inf=1.0, omega_p=wp if electric else 0.0,
            gamma=0.0, drude_sphere=sphere,
            use_drude_m=magnetic, mu_inf=1.0,
            omega_pm=wp if magnetic else 0.0, gamma_m=0.0,
            drude_m_sphere=sphere),
    )


def test_magnetic_drude_mirror_below_plasma_frequency():
    """mu(w) < 0 single-negative half-space: reflective + evanescent —
    the magnetic dual of the electric Drude mirror above."""
    n, wavelength = 160, 15e-3
    sim = Simulation(_halfspace_cfg(wavelength, n, magnetic=True,
                                    wp_ratio=3.0))
    sim.run()
    front_max, inside_max = 0.0, 0.0
    for _ in range(6):
        sim.advance(7)
        ez = sim.field("Ez")[:, 0, 0]
        front_max = max(front_max, np.abs(ez[40:95]).max())
        inside_max = max(inside_max, np.abs(ez[112:118]).max())
    assert front_max > 1.5, f"no standing wave, max {front_max:.2f}"
    omega = 2 * math.pi * physics.C0 / wavelength
    k0 = omega / physics.C0 * 1e-3
    expected_bound = 2.0 * math.exp(-k0 * math.sqrt(8.0) * 12)
    assert inside_max < 3.0 * expected_bound + 0.02, (
        f"not evanescent: {inside_max:.3f}")


def _swr_probe(cells_per_wl, *, electric=False, magnetic=False,
               wp_ratio=1.2):
    """CW point source onto a dispersive slab; geometry fixed in physical
    wavelengths. Returns (reflection coefficient from the standing-wave
    ratio in front, transmitted envelope inside / incident).

    Point source, not TFSF (a penetrable slab crossing the TFSF exit
    face injects a spurious difference wave); SWR makes the measurement
    source-amplitude-free.
    """
    wavelength = 15e-3
    wl = cells_per_wl
    dx = wavelength / wl
    n = int(11 * wl)
    s_lo, s_hi = 4 * wl, 6.5 * wl
    omega = 2 * math.pi * physics.C0 / wavelength
    wp = wp_ratio * omega
    sphere = SphereConfig(enabled=True,
                          center=((s_lo + s_hi) / 2.0, 0.0, 0.0),
                          radius=(s_hi - s_lo) / 2.0)
    cfg = SimConfig(
        scheme="1D_EzHy", size=(n, 1, 1), time_steps=int(160 * wl), dx=dx,
        courant_factor=0.5, wavelength=wavelength,
        pml=PmlConfig(size=(wl, 0, 0)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(2 * wl, 0, 0)),
        materials=MaterialsConfig(
            use_drude=electric, eps_inf=1.0,
            omega_p=wp if electric else 0.0, gamma=0.0,
            drude_sphere=sphere,
            use_drude_m=magnetic, mu_inf=1.0,
            omega_pm=wp if magnetic else 0.0, gamma_m=0.0,
            drude_m_sphere=sphere))
    sim = Simulation(cfg)
    sim.run()
    env = np.zeros(n)
    stride = max(1, round(wl / 0.5 / 8))    # ~8 samples per period
    for _ in range(10):
        sim.advance(stride)
        env = np.maximum(env, np.abs(sim.field("Ez")[:, 0, 0]))
    front = env[int(2.6 * wl):int(3.8 * wl)]
    swr = front.max() / max(front.min(), 1e-12)
    refl = (swr - 1.0) / (swr + 1.0)
    inside = env[int(4.4 * wl):int(6.1 * wl)].max() / front.max()
    return refl, inside


def test_double_negative_medium_is_matched_and_transparent():
    """THE metamaterial oracle: with identical electric and magnetic
    plasma, eps(w) = mu(w) = -0.44 at the drive frequency, the impedance
    sqrt(mu/eps) = eta0 is MATCHED — the slab reflects ~nothing and the
    wave propagates inside (negative index), in stark contrast to the
    single-negative mirror. The residual reflection is the half-cell
    staggered-interface effect, first-order in dx — asserted to shrink
    with resolution. Gets the coupled J/K update signs right or fails."""
    r15, in15 = _swr_probe(15, electric=True, magnetic=True)
    r30, in30 = _swr_probe(30, electric=True, magnetic=True)
    assert r30 < 0.15, f"matched DNG slab reflected: R ~ {r30:.2f}"
    assert in30 > 0.8, f"wave did not propagate inside: {in30:.2f}"
    assert r30 < 0.75 * r15, (
        f"interface reflection not shrinking with dx: {r15:.3f} -> {r30:.3f}")


def test_single_negative_blocks_where_double_negative_passes():
    """Same plasma electric-only: eps < 0, mu = 1 -> mirror + evanescent.
    The contrast against the DNG case pins the physics, not just
    stability."""
    refl, inside = _swr_probe(15, electric=True)
    assert refl > 0.8, f"single-negative slab should reflect: {refl:.2f}"
    assert inside < 0.25, f"single-negative slab should block: {inside:.2f}"
