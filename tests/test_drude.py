"""Drude dispersive-media tests.

Physics oracle (SURVEY.md §4 posture): a Drude metal driven below its
plasma frequency has eps(w) = eps_inf - wp^2/(w^2 + i g w) < 0 — waves
must reflect off it and decay evanescently inside, at the analytic skin
depth. Reference parity: the dispersive (Drude "metamaterial") update with
OmegaPE/GammaE grids (SURVEY.md §2 InternalScheme row; BASELINE config #5).
"""

import math

import numpy as np

from fdtd3d_tpu import physics
from fdtd3d_tpu.config import (MaterialsConfig, PmlConfig, SimConfig,
                               SphereConfig, TfsfConfig)
from fdtd3d_tpu.sim import Simulation


def test_drude_metal_reflects_and_is_evanescent_inside():
    n = 160
    wavelength = 15e-3
    omega = 2 * math.pi * physics.C0 / wavelength
    wp = 3.0 * omega  # eps(omega) = 1 - 9 = -8: strongly metallic
    # "slab": a huge drude sphere centered deep in the right half.
    cfg = SimConfig(
        scheme="1D_EzHy", size=(n, 1, 1), time_steps=1600, dx=1e-3,
        courant_factor=0.5, wavelength=wavelength,
        # PML so the wave reflected off the metal is absorbed once it
        # leaves the TFSF box (a bare PEC wall would bounce it back in).
        pml=PmlConfig(size=(10, 0, 0)),
        tfsf=TfsfConfig(enabled=True, margin=(8, 0, 0),
                        angle_teta=90.0, angle_phi=0.0, angle_psi=180.0),
        materials=MaterialsConfig(
            use_drude=True, eps_inf=1.0, omega_p=wp, gamma=0.0,
            drude_sphere=SphereConfig(enabled=True, center=(n, 0.0, 0.0),
                                      radius=n - 100.0)),
    )
    sim = Simulation(cfg)
    sim.run()
    interface = 100  # drude region starts at x = 100

    # Standing wave in front of the metal: |Ez| has temporal nodes, so
    # sample several snapshots across one optical period (~33 steps) and
    # take the envelope; full reflection gives max approaching 2x incident.
    front_max, inside_max = 0.0, 0.0
    for _ in range(6):
        sim.advance(7)
        ez = sim.field("Ez")[:, 0, 0]
        front_max = max(front_max, np.abs(ez[40:interface - 5]).max())
        inside_max = max(inside_max,
                         np.abs(ez[interface + 12: interface + 18]).max())
    assert front_max > 1.5, f"no standing wave, max {front_max:.2f}"
    ez = sim.field("Ez")[:, 0, 0]

    # Evanescent decay inside: analytic kappa = k0 * sqrt(|eps|)
    k0 = omega / physics.C0 * cfg.dx  # per cell
    kappa = k0 * math.sqrt(8.0)
    depth = 12
    expected_bound = 2.0 * math.exp(-kappa * depth)
    assert inside_max < 3.0 * expected_bound + 0.02, (
        f"not evanescent: |Ez|={inside_max:.3f} at depth {depth}, "
        f"bound {expected_bound:.4f}")

    # And the fields stayed finite/stable over the whole run.
    assert np.isfinite(ez).all()


def test_drude_transparent_above_plasma_frequency():
    """wp << omega: eps -> eps_inf, the wave passes essentially unchanged."""
    n = 160
    wavelength = 15e-3
    omega = 2 * math.pi * physics.C0 / wavelength
    cfg = SimConfig(
        scheme="1D_EzHy", size=(n, 1, 1), time_steps=1100, dx=1e-3,
        courant_factor=0.5, wavelength=wavelength,
        tfsf=TfsfConfig(enabled=True, margin=(10, 0, 0),
                        angle_teta=90.0, angle_phi=0.0, angle_psi=180.0),
        materials=MaterialsConfig(
            use_drude=True, eps_inf=1.0, omega_p=0.05 * omega, gamma=0.0,
            drude_sphere=SphereConfig(enabled=True, center=(n, 0.0, 0.0),
                                      radius=n - 100.0)),
    )
    sim = Simulation(cfg)
    sim.run()
    ez = sim.field("Ez")[:, 0, 0]
    # Deep inside the weak plasma the CW amplitude stays near 1.
    inside = np.abs(ez[120:145]).max()
    assert 0.8 < inside < 1.3, f"transmission wrong: {inside:.3f}"
