"""Core solver tests: 1D exact propagation, all 13 modes, oracle checks.

Mirrors the reference acceptance strategy (SURVEY.md §4): physics is the
oracle — exact 1D propagation at the magic timestep, cross-checks against
an independent numpy implementation, PEC/energy sanity across every mode.
"""

import numpy as np
import pytest

from fdtd3d_tpu import diag
from fdtd3d_tpu.config import (PointSourceConfig, SimConfig, TfsfConfig)
from fdtd3d_tpu.layout import SCHEME_MODES
from fdtd3d_tpu.sim import Simulation

from oracle import run_3d, run_tmz


def test_1d_tfsf_exact_propagation():
    """1D EzHy at Courant factor 1 (magic timestep): TFSF injection is
    numerically exact — total field inside the box equals the incident
    line, scattered field outside is ~machine zero."""
    n = 200
    cfg = SimConfig(
        scheme="1D_EzHy", size=(n, 1, 1), time_steps=300, dx=1e-3,
        courant_factor=1.0, wavelength=30e-3, dtype="float32",
        tfsf=TfsfConfig(enabled=True, margin=(20, 0, 0),
                        angle_teta=90.0, angle_phi=0.0, angle_psi=180.0),
    )
    sim = Simulation(cfg)
    sim.run()
    ez = sim.field("Ez")[:, 0, 0]
    setup = sim.static.tfsf_setup
    lo, hi = setup.lo[0], setup.hi[0]

    # scattered region must be clean
    sf = np.concatenate([ez[: lo - 1], ez[hi + 2:]])
    assert np.max(np.abs(sf)) < 5e-6 * max(np.max(np.abs(ez)), 1e-30)

    # total field matches the incident line sampled at zeta(x)
    einc = np.asarray(sim.state["inc"]["Einc"])
    interior = np.arange(lo + 1, hi - 1)
    zeta = setup.zeta0 + (interior - setup.origin[0])  # khat = +x
    expect = setup.ehat[2] * einc[np.round(zeta).astype(int)]
    err = np.max(np.abs(ez[interior] - expect))
    assert err < 2e-5 * np.max(np.abs(einc) + 1e-30)


@pytest.mark.parametrize("name", sorted(SCHEME_MODES))
def test_all_modes_run_and_stay_finite(name):
    mode = SCHEME_MODES[name]
    size = tuple(24 if a in mode.active_axes else 1 for a in range(3))
    comp = mode.e_components[0]
    center = tuple(s // 2 for s in size)
    cfg = SimConfig(
        scheme=name, size=size, time_steps=25, dx=1e-3,
        courant_factor=0.5, wavelength=12e-3,
        point_source=PointSourceConfig(enabled=True, component=comp,
                                       position=center),
    )
    sim = Simulation(cfg)
    sim.run()
    norms = diag.field_norms(sim)
    assert set(norms) == set(mode.components)
    for c, v in norms.items():
        assert np.isfinite(v), f"{c} blew up"
    assert norms[comp] > 0.0, "source did not excite the field"


def test_2d_tmz_matches_numpy_oracle():
    n, steps = 32, 40
    dx = 1e-3
    cfg = SimConfig(
        scheme="2D_TMz", size=(n, n, 1), time_steps=steps, dx=dx,
        courant_factor=0.5, wavelength=10e-3,
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(n // 2, n // 2, 0)),
    )
    sim = Simulation(cfg)
    sim.run()
    ez_ref, hx_ref, hy_ref = run_tmz(
        n, steps, dx, cfg.dt, cfg.omega, (n // 2, n // 2))
    scale = np.max(np.abs(ez_ref))
    assert scale > 0
    assert np.max(np.abs(sim.field("Ez")[:, :, 0] - ez_ref)) < 2e-5 * scale
    hscale = max(np.max(np.abs(hx_ref)), 1e-30)
    assert np.max(np.abs(sim.field("Hx")[:, :, 0] - hx_ref)) < 2e-5 * hscale
    assert np.max(np.abs(sim.field("Hy")[:, :, 0] - hy_ref)) < 2e-5 * hscale


def test_3d_matches_numpy_oracle():
    n, steps = 16, 20
    dx = 1e-3
    cfg = SimConfig(
        scheme="3D", size=(n, n, n), time_steps=steps, dx=dx,
        courant_factor=0.5, wavelength=8e-3,
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(n // 2, n // 2, n // 2)),
    )
    sim = Simulation(cfg)
    sim.run()
    ref = run_3d(n, steps, dx, cfg.dt, cfg.omega,
                 (n // 2, n // 2, n // 2))
    for comp in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        scale = max(np.max(np.abs(ref["Ez"])), 1e-30)
        got = sim.field(comp)
        err = np.max(np.abs(got - ref[comp]))
        assert err < 3e-5 * scale, f"{comp}: {err/scale}"


def test_pec_energy_bounded_after_source_stops():
    """Gaussian pulse in a closed PEC box: energy settles and stays
    bounded (leapfrog is nondissipative; PEC reflects)."""
    n = 24
    cfg = SimConfig(
        scheme="2D_TMz", size=(n, n, 1), time_steps=200, dx=1e-3,
        courant_factor=0.5, wavelength=10e-3,
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(n // 2, n // 2, 0),
                                       waveform="ricker"),
    )
    sim = Simulation(cfg)
    sim.run()  # source fully decayed well before step 200
    samples = []
    for _ in range(8):
        samples.append(diag.em_energy(sim))
        sim.advance(25)
    # Leapfrog energy at equal-time sampling oscillates (E and H live at
    # staggered times) but must stay bounded: no growth, no decay.
    assert min(samples) > 0
    assert max(samples) / min(samples) < 1.10
