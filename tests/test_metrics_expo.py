"""OpenMetrics exposition (fdtd3d_tpu/metrics.py): the scraper-facing
counters/gauges/histograms fed from telemetry records, written
atomically at Simulation close.
"""

import os

from fdtd3d_tpu import metrics, telemetry
from fdtd3d_tpu.config import (OutputConfig, PmlConfig,
                               PointSourceConfig, SimConfig)
from fdtd3d_tpu.sim import Simulation

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "fixtures")


def test_counter_gauge_histogram_render():
    reg = metrics.MetricsRegistry()
    reg.inc("chunks_total", help_="chunks")
    reg.inc("chunks_total")
    reg.set_gauge("throughput_mcells_per_s", 5.5, help_="tp")
    reg.observe("chunk_wall_seconds", 0.02, help_="wall")
    reg.inc("lane_unhealthy_total", lane=1, help_="lanes")
    text = reg.render()
    assert "# TYPE fdtd3d_chunks_total counter" in text
    assert "fdtd3d_chunks_total 2" in text
    assert "fdtd3d_throughput_mcells_per_s 5.5" in text
    assert "# TYPE fdtd3d_chunk_wall_seconds histogram" in text
    assert 'fdtd3d_chunk_wall_seconds_bucket{le="0.05"} 1' in text
    assert 'fdtd3d_chunk_wall_seconds_bucket{le="+Inf"} 1' in text
    assert "fdtd3d_chunk_wall_seconds_count 1" in text
    assert 'fdtd3d_lane_unhealthy_total{lane="1"} 1' in text
    assert text.strip().endswith("# EOF")
    assert reg.value("chunks_total") == 2
    assert reg.value("lane_unhealthy_total", lane=1) == 1


def test_from_jsonl_v6_batch_fixture():
    reg = metrics.MetricsRegistry.from_jsonl(
        os.path.join(FIX, "telemetry_v6.jsonl"))
    assert reg.value("chunks_total") == 2
    assert reg.value("steps_total") == 8
    assert reg.value("unhealthy_chunks_total") == 1
    assert reg.value("lane_unhealthy_total", lane=1) == 1
    assert reg.value("lane_unhealthy_total", lane=0) is None
    assert reg.value("runs_finished_total") == 1
    assert reg.value("aot_cache_misses") == 1


def test_recovery_and_alert_feed():
    reg = metrics.MetricsRegistry.from_jsonl(
        os.path.join(FIX, "telemetry_v7.jsonl"))
    assert reg.value("recovery_events_total", kind="retry") == 1
    assert reg.value("alerts_total", rule="straggler-ratio") == 1
    assert reg.value("straggler_ratio") == 3.0
    assert reg.value("straggler_chip") == 5.0
    # registry rows feed the fleet-status counter
    reg2 = metrics.MetricsRegistry.from_jsonl(
        os.path.join(FIX, "registry_v7.jsonl"))
    assert reg2.value("runs_total", status="recovered") == 2


def test_sim_writes_exposition_without_telemetry_file(tmp_path):
    """--metrics without --telemetry: a file-less sink feeds the
    registry; the exposition is published at close; no JSONL is
    written."""
    mpath = str(tmp_path / "run.prom")
    cfg = SimConfig(
        scheme="3D", size=(12, 12, 12), time_steps=8, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        pml=PmlConfig(size=(3, 3, 3)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(6, 6, 6)),
        output=OutputConfig(save_dir=str(tmp_path / "out"),
                            metrics_path=mpath))
    sim = Simulation(cfg)
    try:
        assert sim.telemetry is not None
        assert sim.telemetry.path is None    # file-less event bus
        sim.advance(4)
        sim.advance(4)
    finally:
        sim.close()
    text = open(mpath).read()
    assert "fdtd3d_chunks_total 2" in text
    assert "fdtd3d_steps_total 8" in text
    assert "fdtd3d_runs_finished_total 1" in text
    assert text.strip().endswith("# EOF")
    # no telemetry JSONL anywhere (path was None)
    assert not os.path.exists(str(tmp_path / "t.jsonl"))


def test_metrics_mismatched_type_is_named_error():
    import pytest
    reg = metrics.MetricsRegistry()
    reg.inc("x_total")
    with pytest.raises(ValueError, match="counter"):
        reg.set_gauge("x_total", 1.0)


def test_pct_summary_shared_helper():
    """Satellite: the ONE percentile implementation — StepClock,
    telemetry_report and the fleet rollups all route through it."""
    from fdtd3d_tpu import profiling
    vals = [1.0, 2.0, 3.0, 4.0]
    out = telemetry.pct_summary(vals)
    assert out["p50"] == 2.5 and out["max"] == 4.0
    assert profiling.pct_summary is telemetry.pct_summary
    assert telemetry.pct_summary([]) == {"p50": 0.0, "p95": 0.0,
                                         "max": 0.0}
    # StepClock.summary derives its percentiles from the shared helper
    clock = profiling.StepClock()
    clock.record(4, 1.0, 1e6)
    clock.record(4, 2.0, 1e6)
    s = clock.summary()
    rates = [r.mcells_per_s for r in clock.records]
    assert s["p50_mcells_per_s"] == telemetry.pct_summary(rates)["p50"]
    assert s["max_mcells_per_s"] == telemetry.pct_summary(rates)["max"]
