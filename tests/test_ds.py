"""Double-single primitive correctness vs float64 (ops/ds.py).

Each primitive's (hi + lo) result must match the f64 computation to
~2^-45 relative — far below f32's 2^-24 — on adversarial operand mixes
(near-cancellation, wide magnitude spread). These are the invariants
the float32x2 field-storage mode (the reference's C++ double accuracy
class, SURVEY.md §2 FieldValue row) is built on.
"""

import numpy as np
import pytest

from fdtd3d_tpu.ops import ds

RNG = np.random.default_rng(7)


def _pairs(n=4096):
    """Adversarial operand set: magnitudes spread over ~2^40."""
    a64 = RNG.standard_normal(n) * np.exp2(RNG.integers(-20, 20, n))
    b64 = np.where(RNG.random(n) < 0.3,
                   -a64 * (1 + RNG.standard_normal(n) * 1e-6),  # cancels
                   RNG.standard_normal(n) * np.exp2(RNG.integers(-20, 20, n)))
    return a64, b64


def _ff(x64):
    hi, lo = ds.from_f64(x64)
    return hi, lo


def _err(got_pair, want64):
    got = np.asarray(got_pair[0], np.float64) \
        + np.asarray(got_pair[1], np.float64)
    scale = np.maximum(np.abs(want64), 1e-300)
    return np.max(np.abs(got - want64) / scale)


def test_from_f64_roundtrip():
    x = RNG.standard_normal(1000) * np.exp2(RNG.integers(-30, 30, 1000))
    hi, lo = ds.from_f64(x)
    back = hi.astype(np.float64) + lo.astype(np.float64)
    assert _err((hi, lo), x) < 2e-14
    assert np.all(np.abs(lo) <= np.spacing(np.abs(hi)) / 2 + 1e-300)
    assert np.allclose(back, x, rtol=2e-14)


def test_two_sum_exact():
    import jax.numpy as jnp
    a64, b64 = _pairs()
    a = jnp.asarray(a64, jnp.float32)
    b = jnp.asarray(b64, jnp.float32)
    s, e = ds.two_sum(a, b)
    # exactness: s + e == fl(a) + fl(b) in f64, bit-for-bit
    want = np.asarray(a, np.float64) + np.asarray(b, np.float64)
    got = np.asarray(s, np.float64) + np.asarray(e, np.float64)
    assert np.array_equal(got, want)


def test_two_diff_exact():
    import jax.numpy as jnp
    a64, b64 = _pairs()
    a = jnp.asarray(a64, jnp.float32)
    b = jnp.asarray(b64, jnp.float32)
    s, e = ds.two_diff(a, b)
    want = np.asarray(a, np.float64) - np.asarray(b, np.float64)
    got = np.asarray(s, np.float64) + np.asarray(e, np.float64)
    assert np.array_equal(got, want)


def test_two_prod_exact():
    import jax.numpy as jnp
    a64, b64 = _pairs()
    a = jnp.asarray(a64, jnp.float32)
    b = jnp.asarray(b64, jnp.float32)
    p, e = ds.two_prod(a, b)
    want = np.asarray(a, np.float64) * np.asarray(b, np.float64)
    got = np.asarray(p, np.float64) + np.asarray(e, np.float64)
    # a*b of two f32 is exactly representable in f64 -> exact equality
    assert np.array_equal(got, want)


@pytest.mark.parametrize("op,ref", [
    (ds.add_ff, lambda a, b: a + b),
    (ds.sub_ff, lambda a, b: a - b),
    (ds.mul_ff, lambda a, b: a * b),
])
def test_ff_ops(op, ref):
    a64, b64 = _pairs()
    ah, al = _ff(a64)
    bh, bl = _ff(b64)
    a_eff = ah.astype(np.float64) + al.astype(np.float64)
    b_eff = bh.astype(np.float64) + bl.astype(np.float64)
    got = op(ah, al, bh, bl)
    assert _err(got, ref(a_eff, b_eff)) < 1e-12


def test_add_f_and_scale_f():
    a64, b64 = _pairs()
    ah, al = _ff(a64)
    b = b64.astype(np.float32)
    a_eff = ah.astype(np.float64) + al.astype(np.float64)
    assert _err(ds.add_f(ah, al, b),
                a_eff + b.astype(np.float64)) < 1e-12
    assert _err(ds.scale_f(ah, al, b),
                a_eff * b.astype(np.float64)) < 1e-12


def test_sin2pi_vs_f64():
    """ds oscillator: ~2^-45 absolute error over the whole period, and
    over multi-million-step phases via the exact fixed-point frac."""
    import jax.numpy as jnp

    from fdtd3d_tpu.ops.sources import phase_frac_ds

    x = np.linspace(0.0, 2.0, 40001, endpoint=False)
    fh = x.astype(np.float32)
    fl = (x - fh.astype(np.float64)).astype(np.float32)
    sh, sl = ds.sin2pi(jnp.asarray(fh), jnp.asarray(fl))
    got = np.asarray(sh, np.float64) + np.asarray(sl, np.float64)
    want = np.sin(2.0 * np.pi * (fh.astype(np.float64)
                                 + fl.astype(np.float64)))
    assert np.abs(got - want).max() < 1e-12

    # long-horizon phase: steps up to 2^31, irrational-ish frequency
    f = 0.0137281964502347
    steps = jnp.asarray([1, 1000, 123457, 2 ** 27 + 5], jnp.int32)
    fh2, fl2 = phase_frac_ds(steps, f)
    got2 = np.asarray(*[np.asarray(v, np.float64) for v in [fh2]]) \
        + np.asarray(fl2, np.float64)
    q = int(round(f * 2.0 ** 64))
    want2 = np.array([((int(s) * q) % (1 << 64)) / 2.0 ** 64
                      for s in np.asarray(steps)])
    assert np.abs(got2 - want2).max() < 2 ** -46
    sh2, sl2 = ds.sin2pi(fh2, fl2)
    gots = np.asarray(sh2, np.float64) + np.asarray(sl2, np.float64)
    assert np.abs(gots - np.sin(2 * np.pi * want2)).max() < 1e-12


def test_pallas_eft_exactness():
    """EFT primitives inside a Pallas kernel (interpret mode on CPU;
    the same body was verified bit-exact compiled by Mosaic on the real
    chip, 2026-07-31) — the feasibility basis for the packed-ds kernel.
    Barriers must be off inside kernels (Mosaic has no
    optimization_barrier lowering): ds.no_barriers() scopes that."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(a_ref, b_ref, s_ref, e_ref, p_ref, pe_ref):
        with ds.no_barriers():
            a = a_ref[...]
            b = b_ref[...]
            s, e = ds.two_sum(a, b)
            p, pe = ds.two_prod(a, b)
        s_ref[...] = s
        e_ref[...] = e
        p_ref[...] = p
        pe_ref[...] = pe

    rng2 = np.random.default_rng(1)
    a64 = rng2.standard_normal((8, 128)) * np.exp2(
        rng2.integers(-18, 18, (8, 128)))
    b64 = rng2.standard_normal((8, 128)) * np.exp2(
        rng2.integers(-18, 18, (8, 128)))
    a = jnp.asarray(a64, jnp.float32)
    b = jnp.asarray(b64, jnp.float32)
    out = [jax.ShapeDtypeStruct(a.shape, jnp.float32)] * 4
    interpret = jax.default_backend() not in ("tpu", "axon")
    s, e, p, pe = pl.pallas_call(kernel, out_shape=out,
                                 interpret=interpret)(a, b)
    ws = np.asarray(a, np.float64) + np.asarray(b, np.float64)
    wp = np.asarray(a, np.float64) * np.asarray(b, np.float64)
    assert np.array_equal(np.asarray(s, np.float64)
                          + np.asarray(e, np.float64), ws)
    assert np.array_equal(np.asarray(p, np.float64)
                          + np.asarray(pe, np.float64), wp)
    assert not getattr(ds._TRACE_STATE, "no_barriers", False)  # restored


def test_accumulation_beats_f32():
    """1e5-term recurrence x += c*x + d: ds tracks f64 ~5 orders better
    than plain f32 — the property the float32x2 leapfrog rides."""
    import jax
    import jax.numpy as jnp

    n = 100_000
    c64 = 1e-5
    d64 = 1.0 / 3.0

    ch, cl = ds.from_f64(c64)   # c is not f32-representable: split it,
    dh, dl = ds.from_f64(d64)   # exactly as build_coeffs does (_cast_ds)

    def step_ds(carry, _):
        h, l = carry
        th, tl = ds.mul_ff(h, l, ch, cl)
        th, tl = ds.add_ff(th, tl, dh, dl)
        return ds.add_ff(h, l, th, tl), None

    def step_f32(x, _):
        return x + (np.float32(c64) * x + np.float32(d64)), None

    (h, l), _ = jax.lax.scan(step_ds, (jnp.float32(1.0), jnp.float32(0.0)),
                             None, length=n)
    xf, _ = jax.lax.scan(step_f32, jnp.float32(1.0), None, length=n)
    x64 = 1.0
    for _ in range(n):
        x64 = x64 + (c64 * x64 + d64)
    ds_err = abs((float(h) + float(l)) - x64) / abs(x64)
    f32_err = abs(float(xf) - x64) / abs(x64)
    assert ds_err < 1e-11
    assert ds_err < f32_err * 1e-3
