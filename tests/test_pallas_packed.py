"""Packed pipelined single-pass kernel (ops/pallas_packed.py) vs jnp.

The packed kernel stacks E/H (and the CPML psi) into single HBM arrays
and computes the H family one x-tile behind the E family on VMEM
scratch carry (grid-sequential pipelining). Parity with the jnp step
must hold at f32 roundoff INCLUDING the psi recursion state; the
Simulation keeps the packed carry across chunks, so the state
property, sample(), set_field and checkpointing are exercised against
it too. Out-of-scope configs (magnetic Drude, sharded) must fall back
to the recompute-fused / two-pass kernels rather than silently
degrade.
"""

import jax
import numpy as np
import pytest

from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation

BASE = dict(scheme="3D", size=(16, 16, 16), time_steps=8, dx=1e-3,
            courant_factor=0.4, wavelength=8e-3)


@pytest.fixture(autouse=True)
def _single_step_kernel(monkeypatch):
    """This file tests the SINGLE-step round-6 packed kernel. The
    round-8 temporal-blocked kernel (ops/pallas_packed_tb.py, covered
    by tests/test_pallas_packed_tb.py) outranks it in make_step's
    dispatch on most of these configs, so pin the production escape
    hatch that forces the round-6 kernel bit-for-bit."""
    monkeypatch.setenv("FDTD3D_NO_TEMPORAL", "1")


def _seed_fields(sim, seed=0):
    key = jax.random.PRNGKey(seed)
    for grp in ("E", "H"):
        for c in list(sim.state[grp]):
            key, k2 = jax.random.split(key)
            sim.set_field(c, 0.01 * np.asarray(
                jax.random.normal(k2, sim.state[grp][c].shape)))


def _run(use_pallas, **kw):
    sim = Simulation(SimConfig(**BASE, use_pallas=use_pallas, **kw))
    _seed_fields(sim)
    sim.run()
    return sim


def _parity(tol=2e-6, **kw):
    j = _run(False, **kw)
    p = _run(True, **kw)
    assert p.step_kind == "pallas_packed", p.step_kind
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < tol, f"{c}: rel {rel:.2e}"
    return j, p


def test_packed_vacuum_parity():
    _parity()


def test_packed_xyz_cpml_parity():
    _parity(pml=PmlConfig(size=(3, 3, 3)))


def test_packed_psi_state_parity():
    """The recursion state itself must match — errors there accumulate
    silently over long runs."""
    j, p = _parity(pml=PmlConfig(size=(3, 3, 3)))
    for grp in ("psi_E", "psi_H"):
        for k in j.state[grp]:
            a = np.asarray(j.state[grp][k])
            b = np.asarray(p.state[grp][k])
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < 2e-6, f"{grp}/{k}: rel {rel:.2e}"


def test_packed_tfsf_parity():
    _parity(pml=PmlConfig(size=(3, 3, 3)),
            tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                            angle_teta=30.0, angle_phi=40.0,
                            angle_psi=15.0))


def test_packed_point_source_drude_materials_parity():
    """Kitchen sink within packed scope: x/y/z CPML + TFSF + point
    source + electric Drude + a material grid (streamed array coeffs at
    the lagged H tile index)."""
    _parity(pml=PmlConfig(size=(3, 3, 3)),
            tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(5, 9, 7)),
            materials=MaterialsConfig(
                eps=2.0,
                eps_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                        radius=4, value=6.0),
                use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
                drude_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                          radius=3)))


def test_packed_fused_x_engages_and_legacy_path_parity():
    """Round 6: with sources inside the CPML identity region (or no
    sources) the x-slab CPML runs IN-KERNEL (diag fused_x=True, no hxs
    carry); a point source INSIDE the absorber fails the interior
    condition and keeps the legacy post-pass path — both must match
    the jnp step."""
    j, p = _parity(pml=PmlConfig(size=(3, 3, 3)),
                   point_source=PointSourceConfig(
                       enabled=True, component="Ez", position=(8, 8, 8)))
    assert p.step_diag["fused_x"] is True
    assert "hxs" not in p._pstate

    j2, p2 = _parity(pml=PmlConfig(size=(3, 3, 3)),
                     point_source=PointSourceConfig(
                         enabled=True, component="Ez",
                         position=(2, 8, 8)))  # x=2 < npml: in-absorber
    assert p2.step_diag["fused_x"] is False
    assert "hxs" in p2._pstate


def test_packed_uneven_tiles():
    """Non-power-of-two x extent (12 -> T=4, 3 tiles): exercises the
    lagged index maps and the last-tile jnp H pass on an odd tiling."""
    cfg = dict(BASE)
    cfg["size"] = (12, 16, 16)

    def run(up):
        sim = Simulation(SimConfig(**cfg, use_pallas=up,
                                   pml=PmlConfig(size=(2, 3, 3))))
        _seed_fields(sim, seed=2)
        sim.run()
        return sim
    j = run(False)
    p = run(True)
    assert p.step_kind == "pallas_packed"
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"


def test_two_pass_tile1_parity(monkeypatch):
    """x extent 17 forces T=1 in every kernel: the two-pass x-halo
    concat built a zero-size f[:-1] slice there (Mosaic rejects
    0-sized vectors — surfaced first at 640^3 on hardware, where the
    VMEM budget pushes the two-pass tile to 1). Parity guards the
    T==1 special case."""
    monkeypatch.setenv("FDTD3D_NO_PACKED", "1")
    monkeypatch.setenv("FDTD3D_NO_FUSED", "1")
    cfg = dict(BASE)
    cfg["size"] = (17, 16, 16)

    def run(up):
        sim = Simulation(SimConfig(**cfg, use_pallas=up,
                                   pml=PmlConfig(size=(3, 3, 3))))
        _seed_fields(sim, seed=5)
        sim.run()
        return sim
    j = run(False)
    p = run(True)
    assert p.step_kind == "pallas"
    assert p.step_diag["tile"] == {"E": 1, "H": 1}
    for c in ("Ex", "Ez", "Hy"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"


def test_packed_bf16_smoke():
    j = _run(False, dtype="bfloat16", pml=PmlConfig(size=(0, 3, 3)))
    p = _run(True, dtype="bfloat16", pml=PmlConfig(size=(0, 3, 3)))
    assert p.step_kind == "pallas_packed"
    for c in ("Ez", "Hy"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-2, f"{c}: rel {rel:.2e}"


def test_packed_multi_chunk_carry():
    """Several advance() calls reuse the packed carry; interleaved state
    reads (which unpack) must not fork it."""
    cfg = SimConfig(**BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)))
    one = Simulation(cfg)
    one.advance(8)
    many = Simulation(cfg)
    for _ in range(4):
        many.advance(2)
        _ = many.state["E"]["Ez"]  # force an unpack between chunks
    assert many.step_kind == "pallas_packed"
    a = np.asarray(one.field("Ez"))
    b = np.asarray(many.field("Ez"))
    assert np.abs(a - b).max() / (np.abs(a).max() + 1e-30) < 1e-6
    assert one.t == many.t == 8


def test_packed_sample_matches_state():
    cfg = SimConfig(**BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)))
    sim = Simulation(cfg)
    sim.advance(6)
    got = sim.sample("Ez", (8, 8, 9))
    want = float(np.asarray(sim.state["E"]["Ez"])[8, 8, 9])
    assert got == pytest.approx(want, rel=0, abs=0)


def test_packed_direct_state_mutation_adopted():
    """sim.state['E']['Ez'] = arr worked on every pre-packed path; the
    packed carry must leaf-identity-check the unpacked view and adopt
    such edits instead of silently dropping them."""
    import jax.numpy as jnp
    cfg = SimConfig(**BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)))
    sim = Simulation(cfg)
    sim.advance(2)
    sim.state["E"]["Ez"] = jnp.zeros(sim.state["E"]["Ez"].shape,
                                     jnp.float32)
    assert sim.sample("Ez", (8, 8, 9)) == 0.0  # adopted before the read
    sim.advance(1)  # re-packs from the edited dict
    other = Simulation(cfg)
    other.advance(2)
    other.set_field("Ez", np.zeros(other.state["E"]["Ez"].shape,
                                   np.float32))
    other.advance(1)
    a = np.asarray(sim.field("Ez"))
    b = np.asarray(other.field("Ez"))
    assert np.abs(a - b).max() == 0.0


def test_packed_set_field_after_advance():
    """set_field must invalidate the packed carry (re-packed next
    advance) — the edit, not the stale carry, is authoritative."""
    cfg = SimConfig(**BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)))
    sim = Simulation(cfg)
    _seed_fields(sim)
    sim.advance(2)
    sim.set_field("Ez", np.zeros(sim.state["E"]["Ez"].shape,
                                 np.float32))
    assert sim.sample("Ez", (8, 8, 8)) == 0.0
    sim.advance(1)  # must re-pack and keep running
    assert np.isfinite(np.asarray(sim.field("Ez"))).all()


def test_packed_checkpoint_roundtrip(tmp_path):
    cfg = SimConfig(**BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)))
    sim = Simulation(cfg)
    sim.advance(4)
    path = str(tmp_path / "ck.npz")
    sim.checkpoint(path)
    sim.advance(4)
    ref = np.asarray(sim.field("Ez"))

    res = Simulation(cfg)
    res.restore(path)
    assert res.t == 4
    res.advance(4)
    got = np.asarray(res.field("Ez"))
    assert np.abs(ref - got).max() == 0.0  # bit-exact resume


def test_packed_drude_m_in_scope():
    """Magnetic Drude joined the packed scope in round 5 (K rides
    lag-mapped operands in the lagged H phase; parity coverage in
    tests/test_packed_sourced_sharded.py); only compensated+K still
    falls back (K residuals are not Kahan-treated)."""
    mats = MaterialsConfig(
        use_drude_m=True, mu_inf=1.5, omega_pm=1e11, gamma_m=1e10,
        drude_m_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                    radius=3))
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(0, 3, 3)),
        materials=mats))
    assert sim.step_kind == "pallas_packed"
    comp = Simulation(SimConfig(
        **BASE, use_pallas=True, compensated=True,
        pml=PmlConfig(size=(0, 3, 3)), materials=mats))
    assert comp.step_kind in ("pallas_fused", "pallas", "jnp")


@pytest.mark.parametrize("topo", [(2, 1, 1), (1, 2, 1), (1, 2, 2),
                                  (2, 2, 2)])
def test_packed_sharded_parity(topo, monkeypatch):
    """The packed kernel is the single-step multi-chip path (round 4):
    E-phase halos ppermute in as ghost operands (x via the tile-0
    edge, y/z as thin blocks), the H phase's local hi-edge planes get
    the missing neighbor new-E contribution as a thin post-fix, and
    the x-slab patch curls ppermute their boundary plane. Parity vs
    the sharded jnp step at f32 roundoff on the 8-device virtual mesh.
    FDTD3D_NO_TEMPORAL pins the single-step kernel: since round 11 the
    temporal-blocked kernel outranks it on sharded topologies too
    (tests/test_pallas_packed_tb.py covers that path)."""
    monkeypatch.setenv("FDTD3D_NO_TEMPORAL", "1")

    def run(up):
        # use_pallas=False IS the jnp baseline (no env juggling needed:
        # _want_pallas short-circuits before any kernel dispatch)
        sim = Simulation(SimConfig(
            **BASE, use_pallas=up, pml=PmlConfig(size=(2, 2, 2)),
            parallel=ParallelConfig(topology="manual",
                                    manual_topology=topo)))
        _seed_fields(sim, seed=9)
        sim.run()
        return sim
    j = run(False)
    p = run(True)
    assert p.step_kind == "pallas_packed", p.step_kind
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"
    for grp in ("psi_E", "psi_H"):
        for k in j.state[grp]:
            a = np.asarray(j.state[grp][k])
            b = np.asarray(p.state[grp][k])
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < 2e-6, f"{grp}/{k}: rel {rel:.2e}"


def test_packed_sharded_with_sources_falls_back():
    """Sharded + TFSF/point source is out of packed scope -> the
    ownership-gated two-pass path."""
    from fdtd3d_tpu.config import TfsfConfig
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(0, 3, 3)),
        tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(1, 2, 2))))
    assert sim.step_kind == "pallas"


def test_vmem_fallback_ladder():
    """VERDICT r4 weak item 6: the packed tile picker's Mosaic-
    temporaries constant is calibrated on one v5e tunnel; on other
    hardware a model-picked tile may fail compile. A compile failure
    (caught in _chunk_fn's explicit AOT compile, before any donated
    buffer is consumed) must walk the VMEM-budget ladder to a SMALLER
    tile, loudly, and keep the run alive; rungs that re-pick the
    failed tile are skipped; exhaustion raises the actionable error;
    non-packed sims re-raise."""
    sim = Simulation(SimConfig(**BASE, use_pallas=True,
                               pml=PmlConfig(size=(3, 3, 3))))
    assert sim.step_kind == "pallas_packed"
    boom = RuntimeError("Mosaic scoped vmem overflow (simulated)")
    # pretend the model-picked tile was bigger than any rung re-pick
    sim.step_diag = dict(sim.step_diag, tile={"EH": 99})
    sim._vmem_fallback(boom)
    assert sim.step_kind == "pallas_packed"
    assert sim.step_diag["tile"]["EH"] < 99
    # the rebuilt runner still advances and matches the jnp reference
    sim.advance(4)
    ref = Simulation(SimConfig(**BASE, use_pallas=False,
                               pml=PmlConfig(size=(3, 3, 3))))
    ref.advance(4)
    for c, rv in ref.fields().items():
        got = np.asarray(sim.fields()[c])
        scale = np.abs(rv).max() + 1e-30
        assert np.abs(got - rv).max() < 1e-5 * scale, c
    # nothing smaller than tile 1 exists: the remaining rungs re-pick
    # >= tiles, are skipped, and the ladder exhausts with the
    # actionable error
    sim.step_diag = dict(sim.step_diag, tile={"EH": 1})
    with pytest.raises(RuntimeError, match="FDTD3D_NO_PACKED"):
        sim._vmem_fallback(boom)
    # non-packed sims re-raise the original failure untouched
    jnp_sim = Simulation(SimConfig(**BASE, use_pallas=False,
                                   pml=PmlConfig(size=(3, 3, 3))))
    with pytest.raises(RuntimeError, match="simulated"):
        jnp_sim._vmem_fallback(boom)
