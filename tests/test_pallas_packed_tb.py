"""Temporal-blocked packed kernel (ops/pallas_packed_tb.py) vs jnp.

Round 8: TWO Yee steps per HBM pass — the kernel deepens the packed
pipeline to four phases (E(t+1) on tile i, H(t+1) on i-1, E(t+2) on
i-2, H(t+2) on i-3 from VMEM ring scratch) and runs the CPML psi
recursion twice per pass, halving per-step field traffic (48 -> ~24
B/cell f32). Parity with the jnp step must hold at f32 roundoff
INCLUDING the psi recursion state, for even AND odd total step counts
(odd counts append one single-step ``pallas_packed`` tail built at the
SAME tile) and for odd / two-region tilings (pipeline-drain edges).
``FDTD3D_NO_TEMPORAL=1`` is the escape hatch that forces the round-6
single-step kernel bit-for-bit.
"""

import os

import jax
import numpy as np
import pytest

from fdtd3d_tpu.config import (MaterialsConfig, OutputConfig,
                               ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation

BASE = dict(scheme="3D", size=(16, 16, 16), time_steps=8, dx=1e-3,
            courant_factor=0.4, wavelength=8e-3)


def _seed_fields(sim, seed=0):
    key = jax.random.PRNGKey(seed)
    for grp in ("E", "H"):
        for c in list(sim.state[grp]):
            key, k2 = jax.random.split(key)
            sim.set_field(c, 0.01 * np.asarray(
                jax.random.normal(k2, sim.state[grp][c].shape)))


def _run(use_pallas, seed=0, **kw):
    cfg = dict(BASE, use_pallas=use_pallas, **kw)
    sim = Simulation(SimConfig(**cfg))
    _seed_fields(sim, seed=seed)
    sim.run()
    return sim


def _parity(tol=2e-6, seed=0, psi=True, **kw):
    j = _run(False, seed=seed, **kw)
    p = _run(True, seed=seed, **kw)
    assert p.step_kind == "pallas_packed_tb", p.step_kind
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < tol, f"{c}: rel {rel:.2e}"
    if psi and "psi_E" in j.state:
        for grp in ("psi_E", "psi_H"):
            for k in j.state[grp]:
                a = np.asarray(j.state[grp][k])
                b = np.asarray(p.state[grp][k])
                rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
                assert rel < tol, f"{grp}/{k}: rel {rel:.2e}"
    return j, p


def test_tb_vacuum_parity():
    _parity()


@pytest.mark.slow
def test_tb_cpml_parity_even():
    """Subsumed in tier-1 by test_tb_odd_ntiles_and_two_region_x_psi
    (even horizon + full CPML at a two-region tiling); kept in the slow
    lane as the minimal single-region repro."""
    _parity(pml=PmlConfig(size=(3, 3, 3)))


def test_tb_cpml_parity_odd_steps():
    """Odd horizon: n//2 blocked passes + ONE single-step tail on the
    identical packed-carry layout (solver.make_chunk_runner)."""
    _parity(pml=PmlConfig(size=(3, 3, 3)), time_steps=7)


def test_tb_odd_ntiles_and_two_region_x_psi():
    """48-long x at tile 16 -> 3 tiles with the two-region tile-aligned
    x-psi layout (interior tile pins its block; lag-2/lag-3 output
    maps): the pipeline-drain edges the ISSUE names."""
    j, p = _parity(pml=PmlConfig(size=(3, 3, 3)), size=(48, 16, 16))
    assert p.step_diag["temporal_block"] == 2


def test_tb_two_region_odd_steps_sourced():
    _parity(pml=PmlConfig(size=(3, 3, 3)), size=(48, 16, 16),
            time_steps=7,
            point_source=PointSourceConfig(enabled=True, component="Ey",
                                           position=(30, 8, 8)))


@pytest.mark.slow
def test_tb_point_source_parity_even():
    """The mid-grid injection rides IN-KERNEL (both E phases add the
    masked waveform term before ca/cb — a post-patch cannot reach the
    second step's curls). Tier-1 coverage of that path lives in
    test_tb_two_region_odd_steps_sourced, whose blocked passes inject
    in both phases too; this pure-even single-region variant rides the
    slow lane (tier-1 wall budget)."""
    src = PointSourceConfig(enabled=True, component="Ez",
                            position=(8, 8, 8))
    _parity(pml=PmlConfig(size=(3, 3, 3)), point_source=src)


@pytest.mark.slow
def test_tb_x_only_and_yz_only_pml():
    """Axis-isolated CPML parities — a debugging decomposition of the
    full-PML parity above (which exercises both mechanisms at once);
    slow lane for the tier-1 wall budget."""
    _parity(pml=PmlConfig(size=(3, 0, 0)))   # fused-x path alone
    _parity(pml=PmlConfig(size=(0, 3, 3)))   # y/z slab recursions alone


@pytest.mark.slow
def test_tb_bf16_smoke():
    """Slow lane (tier-1 wall budget): the acceptance parity gate is
    f32; bench's accuracy spot-check covers bf16 on chip windows."""
    _parity(tol=3e-2, psi=False, dtype="bfloat16",
            pml=PmlConfig(size=(3, 3, 3)))


def test_tb_escape_hatch_bit_for_bit(monkeypatch):
    """FDTD3D_NO_TEMPORAL must force the round-6 kernel: same kind and
    BIT-identical fields as a dispatch where the tb builder is absent
    entirely (the acceptance criterion's escape hatch)."""
    kw = dict(pml=PmlConfig(size=(3, 3, 3)))
    with monkeypatch.context() as m:
        m.setenv("FDTD3D_NO_TEMPORAL", "1")
        a = _run(True, **kw)
    assert a.step_kind == "pallas_packed", a.step_kind

    from fdtd3d_tpu.ops import pallas_packed_tb
    with monkeypatch.context() as m:
        m.setattr(pallas_packed_tb, "make_packed_tb_step",
                  lambda *args, **kwargs: None)
        b = _run(True, **kw)
    assert b.step_kind == "pallas_packed", b.step_kind
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        assert np.array_equal(np.asarray(a.field(c)),
                              np.asarray(b.field(c))), c


# -------------------------------------------------------------------------
# sharded: the depth-2 halo pipeline (round 11)
# -------------------------------------------------------------------------

def _sharded_parity(topo, steps, tol=2e-6, seed=0, **kw):
    """tb vs jnp on the SAME topology (per-shard slab-compacted psi
    layouts coincide), fields AND psi recursion state. Seeded fields +
    interior source: a bare Ez point source leaves Hz identically zero
    by symmetry, and comparing that component's roundoff noise against
    itself is a degenerate metric."""
    from fdtd3d_tpu.parallel import distributed as pdist
    par = ParallelConfig(topology="manual", manual_topology=topo)
    base = dict(BASE, time_steps=steps, pml=PmlConfig(size=(2, 2, 2)),
                point_source=PointSourceConfig(
                    enabled=True, component="Ez", position=(8, 8, 8)),
                parallel=par, **kw)
    j = Simulation(SimConfig(**dict(base, use_pallas=False)))
    _seed_fields(j, seed=seed)
    j.run()
    p = Simulation(SimConfig(**dict(base, use_pallas=True)))
    _seed_fields(p, seed=seed)
    p.run()
    assert p.step_kind == "pallas_packed_tb", p.step_kind
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < tol, f"{c}: rel {rel:.2e} on {topo}"
    for grp in ("psi_E", "psi_H"):
        for k in j.state[grp]:
            a = np.asarray(pdist.gather_to_host(j.state[grp][k]))
            b = np.asarray(pdist.gather_to_host(p.state[grp][k]))
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < tol, f"{grp}/{k}: rel {rel:.2e} on {topo}"
    return j, p


def test_tb_sharded_parity_222_even():
    """ISSUE-10 acceptance: sharded tb vs sharded jnp on the (2,2,2)
    CPU interpret mesh, even horizon, CPML + interior source."""
    _sharded_parity((2, 2, 2), steps=8)


def test_tb_sharded_parity_222_odd():
    """Odd horizon: n//2 blocked passes + ONE single-step sharded
    pallas_packed tail on the same packed carry inside one chunk."""
    _sharded_parity((2, 2, 2), steps=7)


def test_tb_sharded_parity_122_even_and_odd():
    _sharded_parity((1, 2, 2), steps=8)
    _sharded_parity((1, 2, 2), steps=7)


def test_tb_sharded_odd_ntiles_drain_edges():
    """Odd-ntiles two-region tiling UNDER sharding: 48-long x sharded
    by 2 -> 24 local at tile 8 (3 tiles, two-region x-psi) — the
    pipeline-drain edges now masked against the two-deep ghost region
    (the exchanged generation ghosts replace the PEC zeros at i==0 /
    i==2 / i==ntiles). x-sharded (2,1,1) isolates the xgh0/xgh1/xe1
    operands; (2,2,2) composes them with the y/z thin-block ghosts."""
    from fdtd3d_tpu.parallel import distributed as pdist  # noqa: F401
    for topo in ((2, 1, 1), (2, 2, 2)):
        par = ParallelConfig(topology="manual", manual_topology=topo)
        base = dict(BASE, size=(48, 16, 16), time_steps=7,
                    pml=PmlConfig(size=(2, 2, 2)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ey",
                        position=(30, 8, 8)),
                    parallel=par)
        j = Simulation(SimConfig(**dict(base, use_pallas=False)))
        _seed_fields(j, seed=3)
        j.run()
        p = Simulation(SimConfig(**dict(base, use_pallas=True)))
        _seed_fields(p, seed=3)
        p.run()
        assert p.step_kind == "pallas_packed_tb", (topo, p.step_kind)
        nt = (48 // topo[0]) // p.step_diag["tile"]["EH"]
        assert nt == 3, nt   # odd ntiles: real drain-edge coverage
        for c in ("Ey", "Hz", "Hx"):
            a = np.asarray(j.field(c), np.float32)
            b = np.asarray(p.field(c), np.float32)
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < 2e-6, f"{c}: rel {rel:.2e} on {topo}"


def test_tb_sharded_comm_strategy_in_diag():
    """The step's diag carries the planned CommStrategy record (what
    telemetry run_start and the ledger comm lane echo)."""
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(2, 2, 2)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(2, 2, 2))))
    assert sim.step_kind == "pallas_packed_tb"
    strat = sim.step_diag["comm_strategy"]
    assert strat["ghost_depth"] == 2
    assert strat["split"] == "fused" and strat["schedule"] == "async"


def test_tb_sharded_strategy_override_parity(monkeypatch):
    """FDTD3D_COMM_STRATEGY=per-plane,sync must change the message
    plan WITHOUT changing the physics: parity still holds and the
    strategy records the env source."""
    monkeypatch.setenv("FDTD3D_COMM_STRATEGY", "per-plane,sync")
    _, p = _sharded_parity((1, 2, 2), steps=4)
    strat = p.step_diag["comm_strategy"]
    assert strat["split"] == "per-plane"
    assert strat["schedule"] == "sync"
    assert strat["source"] == "env:FDTD3D_COMM_STRATEGY"


# -------------------------------------------------------------------------
# eligibility: the scope is a strict subset of the packed kernel's
# -------------------------------------------------------------------------

def test_tb_fallbacks_stay_on_packed():
    """Out-of-tb-scope configs must land on the round-6 packed kernel
    (never jnp, never silently tb): TFSF (sharded or not), in-absorber
    source, Drude. Sharded topologies are IN tb scope since round 11
    (the depth-2 halo pipeline) — asserted here so the dispatch can
    never silently regress to the single-step kernel."""
    tfsf = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
        tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2))))
    assert tfsf.step_kind == "pallas_packed", tfsf.step_kind

    absorber = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(2, 8, 8))))
    assert absorber.step_kind == "pallas_packed", absorber.step_kind

    sharded = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(2, 2, 2)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(1, 2, 2))))
    assert sharded.step_kind == "pallas_packed_tb", sharded.step_kind

    tfsf_sharded = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(2, 2, 2)),
        tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(1, 2, 2))))
    assert tfsf_sharded.step_kind == "pallas_packed", \
        tfsf_sharded.step_kind

    drude = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(0, 3, 3)),
        materials=MaterialsConfig(
            use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
            drude_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                      radius=3))))
    assert drude.step_kind == "pallas_packed", drude.step_kind


def test_tb_material_grid_falls_back():
    """A material grid would need each coefficient streamed at two tile
    lags: out of scope, packed kernel covers it."""
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
        materials=MaterialsConfig(
            eps=2.0, eps_sphere=SphereConfig(enabled=True,
                                             center=(8, 8, 8),
                                             radius=4, value=6.0))))
    assert sim.step_kind == "pallas_packed", sim.step_kind


def test_tb_paired_complex_legs_stay_single_step(monkeypatch):
    """The paired-complex wrapper calls each leg once per step — a
    two-steps-per-call leg would silently double-advance
    (make_step(allow_multistep=False))."""
    monkeypatch.setenv("FDTD3D_FORCE_PAIRED_COMPLEX", "1")
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
        complex_fields=True))
    assert sim.step_kind == "complex2x_pallas_packed", sim.step_kind


def test_tb_force_tile_validation():
    """make_packed_eh_step(force_tile=...) (the tb tail builder's hook)
    rejects non-divisor / too-thin tiles instead of building a
    mismatched carry layout."""
    from fdtd3d_tpu import solver
    from fdtd3d_tpu.ops import pallas_packed
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    static = solver.build_static(cfg)
    assert pallas_packed.make_packed_eh_step(static, force_tile=5) is None
    assert pallas_packed.make_packed_eh_step(static, force_tile=16) is None
    ok = pallas_packed.make_packed_eh_step(static, force_tile=8)
    assert ok is not None and ok.diag["tile"]["EH"] == 8


def test_tb_step_contract():
    """The multi-step step object's contract with make_chunk_runner:
    steps_per_call=2, a single-step tail at the SAME tile, shared
    pack/unpack/prepare."""
    from fdtd3d_tpu import solver
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    static = solver.build_static(cfg)
    step = solver.make_step(static)
    assert step.kind == "pallas_packed_tb"
    assert step.steps_per_call == 2
    tail = step.tail_step
    assert tail.kind == "pallas_packed"
    assert tail.diag["tile"]["EH"] == step.diag["tile"]["EH"]
    assert step.pack is tail.pack and step.unpack is tail.unpack
    assert step.prepare is tail.prepare
    # the one-step contract escape for wrappers
    single = solver.make_step(static, allow_multistep=False)
    assert single.kind == "pallas_packed"
    # a chunk runner built on the tb step reports the multi-step shape
    runner = solver.make_chunk_runner(static)
    assert runner.kind == "pallas_packed_tb"
    assert runner.steps_per_call == 2


# -------------------------------------------------------------------------
# donation safety (structural, mirrors test_h_inputs_never_donated)
# -------------------------------------------------------------------------

def test_tb_donation_fetch_before_write(monkeypatch):
    """Structural donation-safety: every ALIASED operand's in-map must
    be monotone (each HBM block fetched once) and fetch each block no
    later than the out-map's first visit of it — backward-read state
    never sees a block its own (masked or real) output writes could
    already have flushed. Non-field operands (profiles, source, walls)
    must not be donated at all. Interpreter mode cannot surface the
    hazard at runtime — assert the structure."""
    from jax.experimental import pallas as pl

    from fdtd3d_tpu import solver
    from fdtd3d_tpu.ops import pallas_packed_tb

    captured = {}
    real_call = pl.pallas_call

    def spy(kernel, **kw):
        captured["aliases"] = dict(kw.get("input_output_aliases") or {})
        captured["in_specs"] = list(kw.get("in_specs"))
        captured["out_specs"] = list(kw.get("out_specs"))
        captured["grid"] = kw.get("grid")
        return real_call(kernel, **kw)

    monkeypatch.setattr(pallas_packed_tb.pl, "pallas_call", spy)
    cfg = SimConfig(**dict(BASE, size=(48, 16, 16)), use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez",
                        position=(24, 8, 8)))
    static = solver.build_static(cfg)
    step = pallas_packed_tb.make_packed_tb_step(static)
    assert step is not None and captured

    aliases = captured["aliases"]
    n_in = len(captured["in_specs"])
    n_out = len(captured["out_specs"])
    # every output is fed by a donated input with the same position;
    # everything else (profiles/source/walls) is NOT donated
    assert aliases == {j: j for j in range(n_out)}, aliases
    assert n_in > n_out

    (n_iters,) = captured["grid"]

    def blocks(spec):
        # x-block index per grid iteration (index maps are pure)
        return [int(spec.index_map(i)[1]) for i in range(n_iters)]

    for j in sorted(aliases):
        fetches = blocks(captured["in_specs"][j])
        visits = blocks(captured["out_specs"][aliases[j]])
        assert fetches == sorted(fetches), \
            f"operand {j}: non-monotone in-map {fetches}"
        first_fetch = {}
        for i, b in enumerate(fetches):
            first_fetch.setdefault(b, i)
        first_visit = {}
        for i, b in enumerate(visits):
            first_visit.setdefault(b, i)
        for b, fi in first_fetch.items():
            assert fi <= first_visit.get(b, n_iters), (
                f"operand {j}: block {b} fetched at iteration {fi} "
                f"after its first out visit {first_visit.get(b)} — "
                f"donation hazard")


# -------------------------------------------------------------------------
# chunk runner / carry / flight recorder integration
# -------------------------------------------------------------------------

def test_tb_multi_chunk_odd_chunks_carry():
    """Odd-length chunks run blocked passes + the single-step tail
    INSIDE one compiled chunk; several such chunks must compose to the
    same answer as one even scan."""
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)))
    one = Simulation(cfg)
    one.advance(6)
    many = Simulation(cfg)
    many.advance(3)   # 1 blocked + 1 tail
    _ = many.state["E"]["Ez"]      # force an unpack between chunks
    many.advance(3)   # odd again (re-uses the compiled length)
    assert many.step_kind == "pallas_packed_tb"
    assert one.t == many.t == 6
    a = np.asarray(one.field("Ez"))
    b = np.asarray(many.field("Ez"))
    assert np.abs(a - b).max() / (np.abs(a).max() + 1e-30) < 2e-6


@pytest.mark.slow
def test_tb_checkpoint_roundtrip():
    """Bit-exact resume across the tb carry; the tile-dependent unpack
    it depends on is covered in tier-1 by
    test_tb_multi_chunk_odd_chunks_carry (tier-1 wall budget)."""
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)))
    import tempfile
    sim = Simulation(cfg)
    sim.advance(4)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ck.npz")
        sim.checkpoint(path)
        sim.advance(4)
        ref = np.asarray(sim.field("Ez"))
        res = Simulation(cfg)
        res.restore(path)
        assert res.t == 4
        res.advance(4)
        got = np.asarray(res.field("Ez"))
    assert np.abs(ref - got).max() == 0.0   # bit-exact resume


def test_tb_health_counters_unpack_blocked_carry(tmp_path):
    """The flight recorder's in-graph health counters must unpack the
    tb packed carry (telemetry satellite): finite energy per chunk,
    matching the jnp run's counters, odd chunk included."""
    from fdtd3d_tpu import telemetry

    def run(up):
        cfg = SimConfig(
            **BASE, use_pallas=up, pml=PmlConfig(size=(3, 3, 3)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(8, 8, 8)),
            output=OutputConfig(
                telemetry_path=str(tmp_path / f"t_{up}.jsonl"),
                check_finite=True))
        sim = Simulation(cfg)
        sim.advance(5)   # odd: blocked passes + tail inside the chunk
        sim.close_telemetry()
        return sim, telemetry.read_jsonl(cfg.output.telemetry_path)

    sim_p, recs_p = run(True)
    assert sim_p.step_kind == "pallas_packed_tb"
    sim_j, recs_j = run(False)
    chunks_p = [r for r in recs_p if r["type"] == "chunk"]
    chunks_j = [r for r in recs_j if r["type"] == "chunk"]
    assert [c["t"] for c in chunks_p] == [5]
    for cp, cj in zip(chunks_p, chunks_j):
        assert cp["finite"] is True
        assert cp["energy"] == pytest.approx(cj["energy"], rel=1e-4)
        assert cp["max_e"] == pytest.approx(cj["max_e"], rel=1e-4)


def test_tb_vmem_ladder_downgrade_to_packed(monkeypatch):
    """A VMEM-ladder rebuild that falls out of tb scope down to the
    single-step packed kernel is SOUND (same packed-carry family,
    re-packed through the dict form) and must keep the run alive."""
    from fdtd3d_tpu import solver
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    sim = Simulation(cfg)
    assert sim.step_kind == "pallas_packed_tb"
    _seed_fields(sim, seed=3)
    sim.advance(2)   # materialize the packed carry

    real = solver.make_chunk_runner

    def forced_packed(static, mesh_axes=None, mesh_shape=None,
                      health=False, per_chip=False):
        saved = os.environ.get("FDTD3D_NO_TEMPORAL")
        os.environ["FDTD3D_NO_TEMPORAL"] = "1"
        try:
            return real(static, mesh_axes, mesh_shape, health=health,
                        per_chip=per_chip)
        finally:
            if saved is None:
                os.environ.pop("FDTD3D_NO_TEMPORAL", None)
            else:
                os.environ["FDTD3D_NO_TEMPORAL"] = saved

    monkeypatch.setattr(solver, "make_chunk_runner", forced_packed)
    sim.step_diag = dict(sim.step_diag, tile={"EH": 99})
    sim._vmem_fallback(RuntimeError("mosaic vmem overflow (simulated)"))
    assert sim.step_kind == "pallas_packed"
    sim.advance(6)

    ref = Simulation(cfg.__class__(**dict(BASE, use_pallas=False,
                                          pml=PmlConfig(size=(3, 3, 3)))))
    _seed_fields(ref, seed=3)
    ref.advance(8)
    for c in ("Ez", "Hy"):
        a = np.asarray(ref.field(c), np.float32)
        b = np.asarray(sim.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"
